"""Managed processes: real, unmodified Linux executables inside the sim.

Reference analog: SURVEY.md §2 "Process / ManagedThread" + §3.2/3.3 (spawn
handshake, seccomp trap, strict turn-taking). The division of labor is
deliberately different from upstream: the C shim (native/shim/shim.c) is
DUMB — it forwards trapped syscalls verbatim over a fixed-fd socketpair —
and this module owns every bit of emulation: the descriptor table, the
socket bridge onto the simulated transport, the emulated clock, blocking
semantics, and guest memory access (native/memory.py, process_vm_readv).

Turn-taking: the managed process is *always* blocked except between our
reply and its next request. The pump loop services syscalls at the current
sim instant (app compute costs zero sim time, upstream's default model);
a syscall that must wait (nanosleep, connect, recv on an empty buffer,
send into a full buffer) parks the process — no reply — and a host event
or transport callback later resumes the pump. The blocking socket read
releases the GIL, so hosts running managed processes get real OS-thread
parallelism under thread_per_core — the phase-4 payoff promised in
core/scheduler.py.

v1 emulation surface (grown as workloads need): write/read on stdio and
virtual sockets, socket/connect/send/recv/close/shutdown + sockname peers
+ sockopt stubs, nanosleep/clock_nanosleep, clock_gettime/gettimeofday/
time, getrandom (deterministic, per-host RNG), stdin EOF. bind/listen/
accept (server side) intentionally return -ENOSYS until implemented.
"""

from __future__ import annotations

import hashlib
import mmap
import os
import socket
import struct
import subprocess
import threading
from pathlib import Path
from typing import Optional

from shadow_tpu.core.time import NS_PER_SEC, SimTime, emulated
from shadow_tpu.host.process import ProcessLifecycle
from shadow_tpu.native.memory import ProcessMemory
from shadow_tpu.native.vfs import RETRY_NATIVE, HostVFS

SHIM_IPC_FD = 995
IPC_LOW = 932  # per-thread channel window [IPC_LOW, SHIM_IPC_FD]
VFD_BASE = 0x100000
HELLO = 0xFFFFFFFF
# thread-management pseudo-syscalls (shim-side analogs in native/shim/shim.c)
SPAWN_THREAD = 0xFFFFFFF0  # -> reply carries slot + SCM_RIGHTS channel fd
THREAD_HELLO = 0xFFFFFFF1  # new thread checks in; reply is its first turn
THREAD_JOIN = 0xFFFFFFF2   # arg0 = slot; reply is the thread's retval
THREAD_EXIT = 0xFFFFFFF3   # arg0 = retval; thread finishes dying natively
FORK_INTENT = 0xFFFFFFF4   # -> reply carries embryo id + SCM_RIGHTS fd
FORK_COMMIT = 0xFFFFFFF5   # args = (embryo id, real child pid) -> vpid
RESOLVE = 0xFFFFFFF6       # arg0 = guest ptr to a hostname -> IPv4 (u32)
AUDIT_NOTE = 0xFFFFFFF7    # arg0 = unemulated syscall nr, first native use
#: reply sentinel: "a ring memfd + role follows, then the real result"
#: (native/shring.h shared-memory pipe fast path; outside the errno
#: range, distinct from vfs.RETRY_NATIVE's -1000000)
MAPRING = -1000001

# -- shim fast-plane ABI (C twin: native/shring.h; tools/twincheck audits
# every constant below against the header — drift cannot merge) ----------
SHIM_PAGE_FLAGS = 4         # clock-page u64 word indices
SHIM_PAGE_CLS_TIME = 5
SHIM_PAGE_CLS_IDENT = 6
SHIM_PAGE_CLS_RING_R = 7
SHIM_PAGE_CLS_RING_W = 8
SHIM_PAGE_CLS_READY = 9
SHIM_PAGE_OPLOG_N = 15
SHIM_PAGE_F_FAST = 1        # flags word bit0: fast plane enabled
SHIM_READY_OFF = 256        # per-vfd readiness bytes [OFF, OFF+LEN)
SHIM_READY_LEN = 768
SHIM_READY_VALID = 1
SHIM_READY_IN = 2
SHIM_READY_OUT = 4
SHIM_READY_HUP = 8
SHIM_READY_ERR = 16
SHIM_OPLOG_OFF = 1024       # socket-op log [OFF, OFF + 8*MAX)
SHIM_OPLOG_MAX = 383
SHIM_OP_RECV = 1
SHIM_OP_SEND = 2
SHRING_OFF_FLAGS = 44       # struct shring field offsets (new fields)
SHRING_OFF_WBUDGET = 56
SHRING_F_HUP = 1
SHRING_F_ERR = 2
SHRING_F_SOCK = 4
SHRING_CAP_MIN = 4096
SHRING_CAP_MAX = 1 << 24

#: clock-page class word -> host counter (fold reads then zeroes, in
#: this order; the per-class counters are informational — the "syscalls"
#: fold uses the total in word [2], so totals stay mode-invariant)
_SHIM_CLASS_COUNTERS = (
    (SHIM_PAGE_CLS_TIME, "shim_fast_time"),
    (SHIM_PAGE_CLS_IDENT, "shim_fast_identity"),
    (SHIM_PAGE_CLS_RING_R, "shim_fast_ring_read"),
    (SHIM_PAGE_CLS_RING_W, "shim_fast_ring_write"),
    (SHIM_PAGE_CLS_READY, "shim_fast_readiness"),
)

# operator escape hatch for A/B determinism runs: with the fast plane
# forced off, every guest op takes the worker round trip and all
# simulated observables must stay byte-identical (tools/ci.sh gates it)
# detlint: ok(envread): host-side A/B switch, never sim state
_FASTPATH_ON = os.environ.get("SHADOW_TPU_SHIM_FASTPATH", "1") != "0"


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length()


SYS_wait4, SYS_exit_group, SYS_pipe, SYS_pipe2 = 61, 231, 22, 293
SYS_dup, SYS_dup2, SYS_dup3 = 32, 33, 292
SYS_fstat, SYS_lseek, SYS_newfstatat = 5, 8, 262
SYS_sendfile, SYS_sigaltstack = 40, 131
SYS_getrlimit, SYS_setrlimit, SYS_prlimit64 = 97, 160, 302
SYS_signalfd, SYS_signalfd4 = 282, 289
SYS_splice, SYS_tee = 275, 276
SYS_inotify_init, SYS_inotify_add_watch = 253, 254
SYS_inotify_rm_watch, SYS_inotify_init1 = 255, 294
# the virtual file surface (native/vfs.py)
SYS_pread64, SYS_pwrite64 = 17, 18
SYS_open, SYS_stat, SYS_lstat, SYS_access = 2, 4, 6, 21
SYS_fsync, SYS_fdatasync, SYS_truncate, SYS_ftruncate = 74, 75, 76, 77
SYS_getcwd, SYS_chdir, SYS_fchdir, SYS_rename, SYS_mkdir = 79, 80, 81, 82, 83
SYS_rmdir, SYS_creat, SYS_unlink, SYS_readlink = 84, 85, 87, 89
SYS_getdents64, SYS_openat, SYS_mkdirat, SYS_unlinkat = 217, 257, 258, 263
SYS_renameat, SYS_readlinkat, SYS_faccessat = 264, 267, 269
SYS_renameat2, SYS_statx, SYS_faccessat2 = 316, 332, 439
AT_FDCWD = -100
AT_REMOVEDIR = 0x200
AT_SYMLINK_NOFOLLOW = 0x100


def _sfd(v: int) -> int:
    """Sign-extend a syscall fd argument. AT_FDCWD arrives either as a
    full u64 pattern (0xFFFF...FF9C) or as a 32-bit one (0xFFFFFF9C)
    when the libc wrapper writes the int arg with a 32-bit mov and the
    upper register half happens to be zero. No legitimate fd lives in
    [2^31, 2^32) (vfds start at 0x100000), so both decode safely."""
    if v >= (1 << 63):
        return v - (1 << 64)
    if 0x80000000 <= v <= 0xFFFFFFFF:
        return v - (1 << 32)
    return v
SYS_close_range = 436
SYS_select, SYS_pselect6 = 23, 270
SYS_kill = 62
SYS_socketpair = 53
SYS_uname = 63
SYS_times, SYS_clock_getres = 100, 229
SYS_sched_getaffinity, SYS_sysinfo = 204, 99
SYS_mmap = 9
SYS_getrusage = 98
from shadow_tpu.native.identity import SIM_CPUS  # noqa: E402 (why 1
# CPU: see identity.py — the spin-free machine identity)
# default-terminate signals the worker emulates for guest-to-guest kill
# every Linux default-terminate signal (+ realtime 34..64, all default-
# terminate); STOP/CONT/TSTP (19,18,20..22) and default-ignores excluded
_TERM_SIGS = ({1, 2, 3, 4, 5, 6, 7, 8, 10, 11, 12, 13, 14, 15, 16,
               24, 25, 26, 27, 29, 30, 31} | set(range(34, 65)))
_IGN_SIGS = {17, 23, 28}  # CHLD URG WINCH: default-ignore
WNOHANG, ECHILD, ESRCH = 1, 10, 3
MAX_THREADS = 64           # slots 1..63 map to shim fds 994..932
SYS_futex = 202
FUTEX_WAIT, FUTEX_WAKE, FUTEX_REQUEUE, FUTEX_CMP_REQUEUE = 0, 1, 3, 4
FUTEX_WAKE_OP, FUTEX_WAIT_BITSET, FUTEX_WAKE_BITSET = 5, 9, 10
FUTEX_CLOCK_REALTIME = 256
FUTEX_BITSET_ALL = 0xFFFFFFFF

# x86-64 syscall numbers
SYS_read, SYS_write, SYS_close = 0, 1, 3
SYS_readv, SYS_writev = 19, 20
SYS_nanosleep = 35
SYS_socket, SYS_connect, SYS_accept, SYS_sendto, SYS_recvfrom = 41, 42, 43, 44, 45
SYS_sendmsg, SYS_recvmsg, SYS_shutdown, SYS_bind, SYS_listen = 46, 47, 48, 49, 50
SYS_getsockname, SYS_getpeername = 51, 52
SYS_setsockopt, SYS_getsockopt = 54, 55
SYS_gettimeofday, SYS_time = 96, 201
SYS_clock_gettime, SYS_clock_nanosleep = 228, 230
SYS_getrandom = 318
SYS_accept4 = 288
SYS_poll, SYS_ppoll = 7, 271
SYS_ioctl, SYS_fcntl = 16, 72
SYS_epoll_create, SYS_epoll_create1 = 213, 291
SYS_epoll_ctl, SYS_epoll_wait, SYS_epoll_pwait = 233, 232, 281
SYS_getpid, SYS_getppid, SYS_gettid = 39, 110, 186
SYS_timerfd_create, SYS_timerfd_settime, SYS_timerfd_gettime = 283, 286, 287
SYS_eventfd, SYS_eventfd2 = 284, 290
TFD_TIMER_ABSTIME = 1
#: clock ids whose origin is boot == sim start (monotonic + cputime
#: families); the realtime family stays epoch-based (core/time.EMULATED_EPOCH)
MONO_CLOCKS = (1, 2, 3, 4, 6, 7)

POLLIN, POLLOUT, POLLERR, POLLHUP = 0x001, 0x004, 0x008, 0x010
EPOLL_CTL_ADD, EPOLL_CTL_DEL, EPOLL_CTL_MOD = 1, 2, 3
EPOLLIN, EPOLLOUT, EPOLLERR, EPOLLHUP = 0x001, 0x004, 0x008, 0x010
F_GETFD, F_SETFD, F_GETFL, F_SETFL = 1, 2, 3, 4
O_NONBLOCK = 0o4000
O_CLOEXEC = 0o2000000
FIONREAD, FIONBIO = 0x541B, 0x5421
SYS_clone, SYS_fork, SYS_vfork, SYS_execve, SYS_clone3 = 56, 57, 58, 59, 435

EPERM, EBADF, EAGAIN, EFAULT, EINVAL, EPIPE = 1, 9, 11, 14, 22, 32
ESPIPE = 29
E2BIG = 7
ENOSYS, ENOTCONN, ECONNRESET, ETIMEDOUT, EAFNOSUPPORT, ENETUNREACH = (
    38, 107, 104, 110, 97, 101)

def _zeroed_sets(sets, nbytes: int):
    """Fresh all-zero fd_set buffers shaped like ``sets``."""
    return [bytearray(nbytes) if s is not None else None for s in sets]


#: _read_req sentinel: the guest held its turn past the watchdog deadline
#: (experimental.guest_turn_timeout) without making a syscall. Shaped like
#: a (nr, args) tuple so handshake sites treat it as a plain failure.
_TIMEDOUT = (-2, ())

_BLOCK = object()  # service() sentinel: no reply yet, process parked
_DETACH = object()  # service() sentinel: reply 0, then stop reading this
                    # thread's channel forever (it announced its exit)
_REPLIED = object()  # service() sentinel: reply already sent inline
_EMBRYO = object()  # ready-queue sentinel: read THREAD_HELLO before granting
_EXITGROUP = object()  # service() sentinel: reply, SIGKILL the whole
                       # process (exit_group semantics), reap immediately
_EXECED = object()  # service() sentinel: execve succeeded — the OLD real
                    # process was killed and replaced; no reply, stop
                    # reading the dead channel

#: spawn serialization: the child end of the socketpair rides a FIXED fd
#: number (the seccomp filter bakes it in), so concurrent spawns on
#: different scheduler threads must not interleave the dup2/Popen window
_SPAWN_LOCK = threading.Lock()

#: how long (real seconds) to wait for the shim's HELLO before concluding
#: LD_PRELOAD injection failed (statically linked binary, setuid, ...)
HANDSHAKE_TIMEOUT_S = 30.0

_reserved_ipc_slot = False


def _reserve_ipc_slot() -> None:
    """Pin /dev/null onto SHIM_IPC_FD so the process-wide fd allocator can
    never hand that number to an unrelated file; spawns dup2 over it and
    restore it afterwards. Without this, a large sim would eventually
    allocate fd 995 to some live object and the next spawn's dup2 would
    silently destroy it."""
    global _reserved_ipc_slot
    if _reserved_ipc_slot:
        return
    try:
        os.fstat(SHIM_IPC_FD)
        raise RuntimeError(
            f"fd {SHIM_IPC_FD} (SHIM_IPC_FD) is already in use in this "
            f"process; managed processes need it reserved")
    except OSError:
        pass
    devnull = os.open(os.devnull, os.O_RDWR)
    os.dup2(devnull, SHIM_IPC_FD)
    os.close(devnull)
    _reserved_ipc_slot = True

TIMER_ABSTIME = 1


def _shim_lib() -> Path:
    override = os.environ.get("SHADOW_SHIM_LIB")
    if override:
        return Path(override)
    return Path(__file__).resolve().parents[2] / "native" / "build" / "libshadow_shim.so"


class VSocket:
    """One virtual descriptor: a simulated socket (stream, listener,
    datagram) or an epoll instance."""

    __slots__ = ("vfd", "kind", "endpoint", "rxbuf", "peer_closed",
                 "connected", "connect_err", "bound_port", "listening",
                 "accept_q", "nonblock", "dgram_q", "udp", "dgram_peer",
                 "interest",
                 "expirations", "interval_ns", "deadline", "timer_handle",
                 "evt_counter", "refs", "pipe", "pipe_out", "timer_clock",
                 "vfile", "sig_mask", "sig_q", "watches", "next_wd",
                 "ino_q", "sockring")

    def __init__(self, vfd: int, kind: str = "stream") -> None:
        self.vfd = vfd
        self.kind = kind  # stream | dgram | epoll | timer | event | pipe_r/w
        self.endpoint = None
        self.rxbuf = bytearray()
        self.peer_closed = False
        self.connected = False
        self.connect_err = 0
        self.bound_port = 0
        self.listening = False
        self.accept_q: list = []  # pre-wired VSockets awaiting accept()
        self.nonblock = False
        self.dgram_q: list = []  # (payload bytes|b"", nbytes, src, sport)
        self.udp = None  # DatagramSocket when bound
        self.dgram_peer = None  # connected-UDP default peer: (host_id, port)
        self.interest: dict = {}  # epoll: vfd -> (events, userdata)
        # timerfd state
        self.expirations = 0
        self.interval_ns = 0
        self.deadline = 0
        self.timer_handle = None
        # eventfd state
        self.evt_counter = 0
        self.timer_clock = 0  # timerfd: clockid the deadlines are based on
        self.vfile = None  # VFile when kind is file/dir (native/vfs.py)
        self.sockring = None  # SockRing once ESTABLISHED + offered
        # fork support: open-file-description refcount (a forked child's fd
        # table shares VSocket objects; the backing object closes when the
        # LAST table entry referencing it closes, like the kernel's)
        self.refs = 1
        self.pipe = None  # PipeBuf when kind is pipe_r/pipe_w (read side
        # for "spair" duplex ends)
        self.pipe_out = None  # "spair": the buffer this end WRITES
        self.sig_mask = 0  # signalfd: u64 signal mask
        self.sig_q: list = []  # signalfd: pending (signo, sender_vpid)
        self.watches: dict = {}  # inotify: wd -> watched real path
        self.next_wd = 1  # inotify: next watch descriptor
        self.ino_q: list = []  # inotify: pending packed events


class PipeBuf:
    """The shared buffer behind a pipe's two ends — usable from EITHER
    process of a forked pair (reference analog: cross-process pipes of the
    descriptor table, SURVEY.md §2 row 12). Readers/writers park with their
    owning (process, thread) recorded here so wakeups cross processes.

    Byte storage is behind the avail/room/append_bytes/take/peek accessors
    so RingPipeBuf can back them with a guest-shared memory ring."""

    CAP = 65536

    __slots__ = ("buf", "r_end", "w_end", "waiting", "procs")

    def __init__(self) -> None:
        self.buf = bytearray()
        self.r_end = None  # the pipe_r VSocket (refs==0 -> no readers)
        self.w_end = None  # the pipe_w VSocket (refs==0 -> EOF)
        self.waiting: list = []  # (proc, thread) parked on this pipe
        self.procs: set = set()  # processes holding an end (poll wakeups)

    @property
    def readers(self) -> int:
        return self.r_end.refs if self.r_end is not None else 0

    @property
    def writers(self) -> int:
        return self.w_end.refs if self.w_end is not None else 0

    # -- byte storage ------------------------------------------------------
    def avail(self) -> int:
        return len(self.buf)

    def room(self) -> int:
        return self.CAP - len(self.buf)

    def append_bytes(self, data: bytes) -> None:
        self.buf += data

    def take(self, k: int) -> bytes:
        out = bytes(self.buf[:k])
        del self.buf[:k]
        return out

    def peek(self, k: int) -> bytes:
        return bytes(self.buf[:k])

    def sync_refs(self) -> None:
        pass  # ring variant mirrors readers/writers into the shared header

    def set_waiters(self, on: bool) -> None:
        pass  # ring variant flags the shared header for the shim

    def maybe_retire(self) -> None:
        pass  # ring variant releases the mmap/memfd when fully done

    def wake(self) -> None:
        self.sync_refs()
        parked, self.waiting = self.waiting, []
        for proc, th in parked:
            w = th.waiting
            if not w or th.dead or w[0] not in ("pipe_r", "pipe_w",
                                                "sendfile", "splice"):
                continue
            proc._pipe_retry(th, w)
        self.set_waiters(bool(self.waiting))
        # retire only AFTER the retry loop: a parked thread re-delivered
        # above (e.g. EOF) must not find a closed ring under its accessors
        self.maybe_retire()
        for proc in list(self.procs):
            if proc.running:
                proc._notify()  # pollers (possibly in the other process)


class RingPipeBuf(PipeBuf):
    """A PipeBuf whose bytes live in a guest-shared memory ring
    (native/shring.h) — the reference's shared-memory data channel
    (SURVEY.md §2 ⭐Shmem allocator / shim-side service): the worker
    SCM_RIGHTS-mints the memfd to each guest that touches an end, and the
    shim then serves non-blocking reads/writes entirely locally (zero
    worker round trips); only blocking edges (empty read, full or
    atomic-split write, EPIPE) forward here. Strict turn-taking makes the
    shared state race-free: exactly one of {worker, any guest thread}
    runs at any instant.

    Header layout (struct shring): magic u32, cap u32, rpos u64, wpos
    u64, readers u32, writers u32, has_waiters u32, dirty u32, fast_ok
    u32, pad u32, shim_ops u64. rpos/wpos are free-running counters."""

    __slots__ = ("memfd", "mm", "registry", "attached")
    HDR = 4096
    MAGIC = 0x53524E47

    def __init__(self, registry: dict) -> None:
        super().__init__()
        self.buf = None  # storage is the ring, not the bytearray
        self.memfd = os.memfd_create("shring", 0)
        os.ftruncate(self.memfd, self.HDR + self.CAP)
        self.mm = mmap.mmap(self.memfd, self.HDR + self.CAP)
        struct.pack_into("<II", self.mm, 0, self.MAGIC, self.CAP)
        struct.pack_into("<I", self.mm, 40, 1)  # fast_ok
        #: controller-scoped registry of live rings, INSERTION-ORDERED
        #: (a dict used as an ordered set): the wake scan walks it when a
        #: guest's fast-op counter moved, and multi-ring wake order must
        #: be deterministic run-to-run. Retired when both ends close, so
        #: one sim's rings never leak into the next.
        self.registry = registry
        registry[self] = None
        #: set once both ends are wired (sync_refs at creation); the
        #: retire/fast-off guards key on THIS, not r_end — a spair
        #: shutdown(SHUT_RD) nulls r_end and must not defeat them
        self.attached = False

    # positions
    def _rw(self):
        return struct.unpack_from("<QQ", self.mm, 8)

    def avail(self) -> int:
        if self.mm.closed:  # retired ring: nothing readable
            return 0
        r, w = self._rw()
        return w - r

    def room(self) -> int:
        if self.mm.closed:
            return self.CAP
        r, w = self._rw()
        return self.CAP - (w - r)

    def append_bytes(self, data: bytes) -> None:
        r, w = self._rw()
        off = w % self.CAP
        first = min(self.CAP - off, len(data))
        self.mm[self.HDR + off:self.HDR + off + first] = data[:first]
        if len(data) > first:
            rest = len(data) - first
            self.mm[self.HDR:self.HDR + rest] = data[first:]
        struct.pack_into("<Q", self.mm, 16, w + len(data))

    def peek(self, k: int) -> bytes:
        r, _w = self._rw()
        off = r % self.CAP
        first = min(self.CAP - off, k)
        out = self.mm[self.HDR + off:self.HDR + off + first]
        if k > first:
            out += self.mm[self.HDR:self.HDR + (k - first)]
        return out

    def take(self, k: int) -> bytes:
        out = self.peek(k)
        r, _w = self._rw()
        struct.pack_into("<Q", self.mm, 8, r + k)
        return out

    def sync_refs(self) -> None:
        if self.mm.closed:
            return
        if self.r_end is not None or self.w_end is not None:
            self.attached = True
        struct.pack_into("<II", self.mm, 24, self.readers, self.writers)
        if self.attached and self.readers == 0:
            # nobody may read (last close OR shutdown(SHUT_RD)): local
            # service must stop — a fork sibling's installed mapping
            # would otherwise fast-read bytes that must EOF
            struct.pack_into("<I", self.mm, 40, 0)  # fast_ok off

    def maybe_retire(self) -> None:
        """Release the mmap/memfd once both ends are closed AND nothing
        is parked here (wake() calls this after its retry loop — closing
        earlier would yank the ring from under a parked thread's EOF
        delivery; VERDICT r5 review finding)."""
        if (not self.mm.closed and self.r_end is not None
                and self.readers == 0 and self.writers == 0
                and not self.waiting):
            self.registry.pop(self, None)
            self.mm.close()
            os.close(self.memfd)

    def set_waiters(self, on: bool) -> None:
        if not self.mm.closed:  # wake() may have just retired the ring
            struct.pack_into("<I", self.mm, 32, 1 if on else 0)

    def dirty(self) -> bool:
        return (not self.mm.closed
                and struct.unpack_from("<I", self.mm, 36)[0] != 0)

    def clear_dirty(self) -> None:
        if not self.mm.closed:
            struct.pack_into("<I", self.mm, 36, 0)


class SockRing:
    """Per-connection RX/TX ring pair for an ESTABLISHED managed stream
    socket (native/shring.h with SHRING_F_SOCK set). Unlike RingPipeBuf,
    these rings MIRROR authoritative transport state rather than store
    it: the worker appends every delivered payload to RX (invariant: RX
    unread == len(vs.rxbuf)) and refreshes the TX ring's wbudget =
    send_buffer - buffered before every service reply, while the shim
    consumes RX / fills TX locally and logs each op in the clock-page
    oplog. The worker replays that log IN ORDER at the next fold, so the
    simulated transport sees the exact slow-path call sequence and every
    observable is byte-identical with the fast plane on or off. Exact
    because of strict turn-taking: transport state is frozen for the
    whole guest turn, so budgets/readiness published at reply time
    cannot go stale mid-turn."""

    __slots__ = ("cap", "rx_fd", "tx_fd", "rx", "tx", "dead")
    HDR = RingPipeBuf.HDR
    MAGIC = RingPipeBuf.MAGIC

    def __init__(self, cap: int) -> None:
        self.cap = cap
        self.dead = False
        self.rx_fd, self.rx = self._mk(cap)
        self.tx_fd, self.tx = self._mk(cap)

    def _mk(self, cap: int):
        fd = os.memfd_create("sockring", 0)
        os.ftruncate(fd, self.HDR + cap)
        mm = mmap.mmap(fd, self.HDR + cap)
        struct.pack_into("<II", mm, 0, self.MAGIC, cap)
        struct.pack_into("<II", mm, 24, 1, 1)  # readers/writers: wired
        struct.pack_into("<I", mm, 40, 1)      # fast_ok
        struct.pack_into("<I", mm, SHRING_OFF_FLAGS, SHRING_F_SOCK)
        return fd, mm

    # -- RX mirror: worker appends on delivery; rpos advances either
    #    in-shim (local read, oplogged) or here (slow-path consume) -----
    def rx_unread(self) -> int:
        r, w = struct.unpack_from("<QQ", self.rx, 8)
        return w - r

    def rx_room(self) -> int:
        return self.cap - self.rx_unread()

    def rx_append(self, data) -> None:
        r, w = struct.unpack_from("<QQ", self.rx, 8)
        off = w % self.cap
        first = min(self.cap - off, len(data))
        self.rx[self.HDR + off:self.HDR + off + first] = data[:first]
        if len(data) > first:
            rest = len(data) - first
            self.rx[self.HDR:self.HDR + rest] = data[first:]
        struct.pack_into("<Q", self.rx, 16, w + len(data))

    def rx_advance(self, k: int) -> None:
        r = struct.unpack_from("<Q", self.rx, 8)[0]
        struct.pack_into("<Q", self.rx, 8, r + k)

    # -- TX: shim appends within wbudget (oplogged); replay takes -------
    def tx_take(self, k: int) -> bytes:
        r, _w = struct.unpack_from("<QQ", self.tx, 8)
        off = r % self.cap
        first = min(self.cap - off, k)
        out = self.tx[self.HDR + off:self.HDR + off + first]
        if k > first:
            out += self.tx[self.HDR:self.HDR + (k - first)]
        struct.pack_into("<Q", self.tx, 8, r + k)
        return out

    def set_wbudget(self, n: int) -> None:
        struct.pack_into("<Q", self.tx, SHRING_OFF_WBUDGET, n)

    def sync_flags(self, vs) -> None:
        if self.rx.closed:
            return
        fl = SHRING_F_SOCK
        if vs.peer_closed:
            fl |= SHRING_F_HUP
        if vs.connect_err:
            fl |= SHRING_F_ERR
        struct.pack_into("<I", self.rx, SHRING_OFF_FLAGS, fl)
        struct.pack_into("<I", self.tx, SHRING_OFF_FLAGS, fl)

    def kill(self) -> None:
        """Permanent fast-off (mirror overflow, shutdown, socket error,
        teardown): the shim checks fast_ok on every local op, so any
        still-installed alias mapping stops serving immediately and all
        traffic takes the worker round trip again."""
        self.dead = True
        if not self.rx.closed:
            struct.pack_into("<I", self.rx, 40, 0)
            struct.pack_into("<I", self.tx, 40, 0)

    def retire(self) -> None:
        """Last fd-table reference is gone (every shim mapping was
        dropped before its close forwarded): release the mappings."""
        if self.rx.closed:
            return
        self.kill()
        self.rx.close()
        self.tx.close()
        os.close(self.rx_fd)
        os.close(self.tx_fd)


class GuestThread:
    """One thread of a managed guest: its IPC channel + scheduling state.

    Reference analog: ManagedThread (SURVEY.md §2 "Process / ManagedThread").
    Exactly one thread of a process runs at a time (strict turn-taking);
    the rest are parked either on a sim continuation (``waiting``) or in
    the ready queue awaiting their turn grant.
    """

    __slots__ = ("slot", "sock", "waiting", "dead", "retval", "joiners",
                 "joined", "altstack")

    def __init__(self, slot: int, sock: socket.socket) -> None:
        self.slot = slot
        self.sock = sock
        self.altstack = None  # sigaltstack bookkeeping: (sp, flags, size)
        self.waiting = None  # (kind, ...) while parked on a continuation
        self.dead = False
        self.retval = 0  # pthread-style exit value (int64, reply-ready)
        self.joiners: list = []  # GuestThreads parked in join on this one
        self.joined = False  # slot recyclable only once dead AND joined


class GuestJournal:
    """Append-only record of one managed guest's observation stream: every
    worker reply (turn grant) with its result, issuing thread slot, and the
    emulated clock word published alongside it. Two consumers: (a) the
    running ``(n, sha256)`` cursor is the guest's position in its
    replayable history — recorded in v5 re-execution snapshots
    (shadow_tpu/checkpoint.py) and verified when a restore's re-executed
    prefix reaches the snapshot boundary; (b) the jsonl file itself
    (``<data_dir>/guest_oplogs/``) is byte-identical run to run, so a
    cursor mismatch can be diffed down to the first divergent grant. Pure
    side plane: nothing here feeds simulation state, so journaling on/off
    cannot change results (the bench's ``managed_ckpt_overhead`` row
    measures its wall cost)."""

    __slots__ = ("path", "n", "_h", "_f")

    def __init__(self, path: Path) -> None:
        path.parent.mkdir(parents=True, exist_ok=True)
        self.path = path
        self.n = 0
        self._h = hashlib.sha256()
        self._f = open(path, "a")

    def record(self, slot: int, ret: int, clk: int) -> None:
        self.n += 1
        self._h.update(b"%d|%d|%d|%d\n" % (self.n, slot, ret, clk))
        if self._f is not None:
            self._f.write('{"n":%d,"slot":%d,"ret":%d,"clk":%d}\n'
                          % (self.n, slot, ret, clk))

    def cursor(self) -> dict:
        if self._f is not None:
            self._f.flush()
        return {"n": self.n, "sha": self._h.hexdigest()}

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class ManagedProcess(ProcessLifecycle):
    """Lifecycle + syscall service for one real executable in the sim.

    Mirrors PluginProcess's surface (spawn/shutdown/finish/check_final_state)
    so the controller treats both uniformly.
    """

    def __init__(self, host, opts, index: int) -> None:
        self.host = host
        self.opts = opts
        self.name = f"{Path(opts.path).name}.{index}"
        self.exit_code: Optional[int] = None
        self.running = False
        self.spawned = False  # ever spawned (host reboot respects start_time)
        #: observation journal (GuestJournal) when the controller armed
        #: re-execution snapshots; survives crash/respawn — one stream
        #: per record
        self._journal = None
        self.app = None  # parity with PluginProcess (no plugin object)
        self.proc: Optional[subprocess.Popen] = None
        self.mem: Optional[ProcessMemory] = None
        self.sock: Optional[socket.socket] = None
        self._time_map: Optional[mmap.mmap] = None
        self._time_path: Optional[Path] = None
        self.fds: dict[int, VSocket] = {}
        self._next_vfd = VFD_BASE
        self._files: dict[int, object] = {}  # 1/2 -> open capture files
        # threading state: slot -> GuestThread; _cur = thread being serviced
        self.threads: dict[int, GuestThread] = {}
        self._cur: Optional[GuestThread] = None
        self._next_slot = 1
        self._ready: list = []  # (thread, reply) queue awaiting turn grants
        self._pumping = False
        self.futexes: dict[int, list] = {}  # uaddr -> [(thread, mask), ...]
        self.fd_cloexec: set[int] = set()  # vfds closed at execve
        self._strace = None  # open file when strace_logging_mode != off
        #: guest fds already offered their ring mapping (per process
        #: image; cleared at execve — the replacement shim starts empty)
        self._ring_offered: set[int] = set()
        gen = host.controller.cfg.general
        self._syscall_latency = 1000 if gen.model_unblocked_syscall_latency else 0
        #: master gate for every worker-GRANTED shim fast path (socket
        #: rings, in-shim poll, raw-time service via the clock-page flags
        #: word); identity + libc-time interposition are shim-intrinsic
        #: and stay on in every mode. Off under strace (which must see
        #: every call), modeled syscall latency (each call must cost sim
        #: time), or the SHADOW_TPU_SHIM_FASTPATH=0 escape hatch.
        self._fast_plane = (
            _FASTPATH_ON and self._syscall_latency == 0
            and host.controller.cfg.experimental.strace_logging_mode == "off")
        #: ESTABLISHED-socket ring pairs by guest fd (page-owner process
        #: only: vfd numbering is per-process, so a fork child's fds
        #: could collide with the parent's — children service sockets on
        #: the slow path and the shim drops SOCK rings at fork)
        self._sock_rings: dict[int, SockRing] = {}
        #: oplog replay map: (vfd - VFD_BASE) -> VSocket (owner process)
        self._oplog_vs: dict[int, VSocket] = {}
        #: vfds a worker-serviced poll referenced; their readiness bytes
        #: are published on the clock page at every reply (non-ring fds
        #: only — ring-backed readiness is computed in-shim, live)
        self._ready_watch: set[int] = set()
        #: per-syscall-number worker round-trip census for the bench
        #: audit table (controller-scoped, NOT host.counters: it must
        #: stay out of determinism fingerprints)
        self._slow_nrs = host.controller.__dict__.setdefault(
            "_shim_slow_nrs", {})
        #: guest watchdog (experimental.guest_turn_timeout): wall seconds a
        #: turn may last without a syscall before the guest is killed and
        #: the host downed (spin-wait livelock containment; 0 = off)
        self._turn_timeout = float(
            host.controller.cfg.experimental.guest_turn_timeout or 0.0)
        #: shim-fastpath liveness: SockRing positions at the last watchdog
        #: timeout. A guest streaming through its rings in-shim makes no
        #: syscalls for whole timeout windows, yet is doing real work —
        #: the watchdog only fires once the rings are frozen across a full
        #: window too (ring movement, NOT the clock-page ops counter: a
        #: spin-wait livelock advances ops forever and would never fire)
        self._shim_prog = None
        # reference: max_unapplied_cpu_latency — modeled syscall latency
        # accumulates and is applied to the clock in batches of this size
        # (fewer, coarser clock bumps; 0 = apply each immediately)
        self._max_unapplied = host.controller.cfg.experimental.\
            max_unapplied_cpu_latency
        self._unapplied = 0
        self._spin_t = -1  # busy-loop detector: syscalls at one sim instant
        self._spin_n = 0
        #: experimental.native_audit: syscall numbers this process ran
        #: against the host kernel (reported once each by the shim)
        self.audit_native: set[int] = set()
        #: default-on reality boundary: syscall numbers the worker sent
        #: back for native re-issue (RETRY_NATIVE) in THIS process
        self.native_vfs: set[int] = set()
        #: the per-host virtual file surface (native/vfs.py): synthesized
        #: /etc files, host-data-dir tree, native passthrough elsewhere
        self.vfs = HostVFS(self)
        self.vfs.on_mutate = self._ino_mutate  # inotify bridge
        # deterministic virtual pid (real pids would leak host scheduling
        # nondeterminism into any guest that prints or hashes its pid)
        self.vpid = 1000 + host.id * 64 + index
        # fork support
        self._exit_hint = None  # true exit code captured from exit_group
        self._signal_hint = None  # -signum from an emulated kill(2)
        self.children: list = []  # forked ManagedProcess records
        self.parent_proc = None
        self.reaped = False  # consumed by the parent's wait4
        self.real_pid = None  # adopted children: kernel pid (no Popen)
        self._embryos: dict = {}  # embryo id -> worker-side channel sock

    # the syscall-service sites park/peek the CURRENT thread's wait state;
    # continuations instead search all threads via _find_waiter
    @property
    def _waiting(self):
        return self._cur.waiting if self._cur is not None else None

    @_waiting.setter
    def _waiting(self, value):
        self._cur.waiting = value

    def _find_waiter(self, *preds):
        """First parked thread (slot order) whose wait matches: each pred is
        (kinds, obj) — wait[0] in kinds and (obj is None or wait[1] is obj)."""
        for slot in sorted(self.threads):
            th = self.threads[slot]
            w = th.waiting
            if w is None or th.dead:
                continue
            for kinds, obj in preds:
                if w[0] in kinds and (obj is None or w[1] is obj):
                    return th, w
        return None, None

    def _open_strace(self) -> None:
        # reference analog: strace_logging (SURVEY.md §5.1): every
        # emulated syscall with args and result. "deterministic" omits
        # the sim timestamp so logs diff clean across configs whose
        # timing legitimately differs.
        mode = self.host.controller.cfg.experimental.strace_logging_mode
        if mode != "off":
            ddir = Path(self.host.controller.data_dir) / "hosts" / self.host.name
            ddir.mkdir(parents=True, exist_ok=True)
            self._strace = open(ddir / f"{self.name}.strace", "w")
            self._strace_times = mode != "deterministic"

    def _new_clock_page(self) -> None:
        """Create (or replace) this record's guest-shared clock page.
        Page layout: [0:8] emulated ns, [8:16] vpid (the shim's identity
        fast path serves getpid/gettid from here — no worker round trip;
        forked children share the parent's page and keep forwarding),
        [16:24] shim fast-op counter, [24:32] the worker's fold cursor
        (native/shring.h). Used by spawn and by execve (the replacement
        image owns a fresh page — a fork-child record has none)."""
        old = getattr(self, "_time_map", None)
        ddir = Path(self.host.controller.data_dir) / "hosts" / self.host.name
        ddir.mkdir(parents=True, exist_ok=True)
        self._time_path = ddir / f"{self.name}.clock"
        with open(self._time_path, "wb") as f:
            f.write(b"\0" * 4096)
        tf = open(self._time_path, "r+b")
        self._time_map = mmap.mmap(tf.fileno(), 4096)
        tf.close()
        self._time_map[8:16] = struct.pack("<q", self.vpid)
        if self._fast_plane:
            # arm the shim's worker-granted fast paths (raw time, local
            # poll, socket rings); zero = forward everything (strace /
            # modeled latency / SHADOW_TPU_SHIM_FASTPATH=0)
            struct.pack_into("<q", self._time_map, 8 * SHIM_PAGE_FLAGS,
                             SHIM_PAGE_F_FAST)
        if old is not None and self.parent_proc is None:
            # repeated execs: release the superseded mapping (fork-child
            # records borrow the parent's map — never close that one)
            old.close()

    # -- lifecycle ---------------------------------------------------------
    def _reset_for_respawn(self) -> None:
        """A host reboot respawns this record as a fresh instance
        (Host.reboot -> spawn after kill): drop every per-life table the
        crashed guest left behind, exactly as __init__ built them. The
        observation journal (if any) deliberately survives — a respawned
        guest's grants continue the same per-record stream, which is what
        makes crash/reboot runs re-execution-checkpointable."""
        self.proc = None
        self.real_pid = None
        self.mem = None
        self.sock = None
        self.fds = {}
        self._next_vfd = VFD_BASE
        self._files = {}
        self.threads = {}
        self._cur = None
        self._next_slot = 1
        self._ready = []
        self._pumping = False
        self.futexes = {}
        self.fd_cloexec = set()
        self._ring_offered = set()
        self._sock_rings = {}
        self._oplog_vs = {}
        self._ready_watch = set()
        self._spin_t = -1
        self._spin_n = 0
        self._exit_hint = None
        self._signal_hint = None
        self.children = []
        self._embryos = {}
        self._unapplied = 0
        self.audit_native = set()
        self.native_vfs = set()
        self.vfs = HostVFS(self)
        self.vfs.on_mutate = self._ino_mutate

    def spawn(self) -> None:
        lib = _shim_lib()
        if not lib.exists():
            raise FileNotFoundError(
                f"{lib} missing — build the native shim first: make -C native")
        if self.spawned:
            self._reset_for_respawn()
        self._new_clock_page()
        ddir = self._time_path.parent  # hosts/<name>/ (capture files etc.)
        # detlint: ok(envread): guests inherit the operator environment
        env = dict(os.environ)
        env.update(self.opts.environment)
        env.update({
            "LD_PRELOAD": str(lib),
            "SHADOW_SHIM": "1",
            "SHADOW_TIME_SHM": str(self._time_path),
        })
        if self.host.controller.cfg.experimental.native_audit:
            env["SHADOW_AUDIT"] = "1"
        with _SPAWN_LOCK:
            _reserve_ipc_slot()
            parent, child = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
            os.dup2(child.fileno(), SHIM_IPC_FD)
            child.close()
            try:
                # the guest's REAL stdio points at the capture files,
                # unbuffered (the shared file description means worker-
                # trapped writes and any native slip-throughs interleave
                # at one offset). Not /dev/null: tools fstat their stdout
                # and change behavior on it — GNU grep goes quiet-mode on
                # a devnull stdout. Opened into _files as each succeeds so
                # a failing spawn cannot leak handles.
                self._files[1] = open(ddir / f"{self.name}.stdout", "wb",
                                      buffering=0)
                self._files[2] = open(ddir / f"{self.name}.stderr", "wb",
                                      buffering=0)
                try:
                    self.proc = subprocess.Popen(
                        [self.opts.path] + list(self.opts.args),
                        env=env,
                        pass_fds=(SHIM_IPC_FD,),
                        stdout=self._files[1],
                        stderr=self._files[2],
                        cwd=str(ddir),
                    )
                except BaseException:
                    for f in self._files.values():
                        f.close()
                    self._files.clear()
                    raise
            finally:
                devnull = os.open(os.devnull, os.O_RDWR)
                os.dup2(devnull, SHIM_IPC_FD)  # restore the reservation
                os.close(devnull)
        self.sock = parent
        self.threads = {0: GuestThread(0, parent)}
        self._cur = self.threads[0]
        self.mem = ProcessMemory(self.proc.pid)
        self.running = True
        self.spawned = True
        self.host.counters.add("processes_spawned", 1)
        self._open_strace()
        ctl = self.host.controller
        jdir = getattr(ctl, "guest_journal_dir", None)
        if jdir is not None and self._journal is None:
            self._journal = GuestJournal(
                jdir / f"{self.host.name}.{self.name}.guest_oplog.jsonl")
        note = getattr(ctl, "note_guest_pid", None)
        if note is not None:  # hand-rolled controllers in tests lack it
            note(self)

        # handshake with a real-time bound: a binary the preload cannot
        # enter (static link, setuid) would otherwise hang the scheduler
        main = self.threads[0]
        self.sock.settimeout(HANDSHAKE_TIMEOUT_S)
        try:
            req = self._read_req(main)
        finally:
            self.sock.settimeout(None)
        if req is None or req[0] != HELLO:
            self.proc.kill()
            self._exited()
            raise RuntimeError(
                f"{self.host.name}/{self.name}: shim handshake failed — is "
                f"{self.opts.path!r} dynamically linked? (LD_PRELOAD cannot "
                f"enter static or setuid binaries)")
        self._resume(main, 0)  # grant the first turn and pump

    def shutdown(self) -> None:
        if self.running and self.proc is not None:
            import signal as _signal

            sig = getattr(_signal, self.opts.shutdown_signal, _signal.SIGKILL)
            self.proc.send_signal(sig)
            try:
                # whenever this event can run, the process is parked on the
                # IPC channel (not mid-turn), so a termination signal takes
                # effect without a grant; handlers that ignore it get the
                # reference's escalation: SIGKILL
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
            self._exited()

    def reap(self) -> None:
        """Sim over (reference §3.5): kill and reap a still-running child."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self._exited()
        elif self.proc is None and self.real_pid is not None and self.running:
            try:
                os.kill(self.real_pid, 9)
            except ProcessLookupError:
                pass
            self._exited()

    # -- execve: worker-mediated respawn -----------------------------------
    def _read_ptr_array(self, ptr: int, cap: int = 65536):
        """Read a NULL-terminated array of C-string pointers (argv/envp).
        Returns the list, None on a bad read (EFAULT), or the string
        "2BIG" when the array exceeds ``cap`` entries (real kernels bound
        the TOTAL argv+envp bytes, not the entry count; 64k entries is
        far past any real environment, so hitting it means a runaway or
        unterminated array — report E2BIG like the kernel's limit)."""
        out = []
        for i in range(cap):
            v = struct.unpack("<Q", self.mem.read(ptr + 8 * i, 8))[0]
            if v == 0:
                return out
            cs = self._read_cstr(v)
            if cs is None:
                return None
            out.append(cs)
        return "2BIG"

    def _do_exec(self, args):
        """execve as a respawn: spawn a fresh managed process (clean
        filter stack — the old in-place re-exec died in the new image's
        dynamic linker once file syscalls started trapping) into THIS
        record: same vpid, vfd table, stdio captures, clock page, strace
        stream, and audit set. The old real process is killed while its
        shim blocks in the forward — success never returns, like the real
        execve. Works from any thread and under audit mode."""
        path = self._read_cstr(args[0])
        if path is None:
            return -EFAULT
        argv = self._read_ptr_array(args[1]) if args[1] else None
        envp = self._read_ptr_array(args[2]) if args[2] else []
        if argv is None and args[1]:
            return -EFAULT
        if envp is None:
            return -EFAULT
        if argv == "2BIG" or envp == "2BIG":
            return -E2BIG
        if not argv:
            argv = [path]
        real = path
        r = self.vfs.resolve(AT_FDCWD, path)
        if r is not None:
            if r[0] == "synth":
                return -EACCES  # synthesized files are not executable
            # "host" (data-dir) and "wnative" (worker-tracked cwd outside
            # the root) both carry the absolute real path — exec either;
            # relative paths after a chdir outside the data dir resolve
            # to "wnative" and must keep working
            real = r[1]
        if not os.path.isfile(real):
            return -2  # ENOENT
        if not os.access(real, os.X_OK):
            return -EACCES
        env = {}
        for e in envp:
            k, _, v = e.partition("=")
            env[k] = v
        # the replacement image gets its OWN clock page: a fork-child
        # record shares the parent's page (parent's vpid; no _time_path
        # at all, which used to leak "None" into the env and silently
        # cost exec'd pipeline stages every shim fast path — found in
        # round 5 when the ring counter stayed at zero)
        self._new_clock_page()
        env.update({
            "LD_PRELOAD": str(_shim_lib()),
            "SHADOW_SHIM": "1",
            "SHADOW_TIME_SHM": str(self._time_path),
        })
        if self.host.controller.cfg.experimental.native_audit:
            env["SHADOW_AUDIT"] = "1"
        cwd = self.vfs.cwd if os.path.isdir(self.vfs.cwd) else None
        # spawn the replacement FIRST: a failed execve must leave the
        # calling process unchanged (POSIX), so nothing destructive
        # happens until the new image exists
        with _SPAWN_LOCK:
            _reserve_ipc_slot()
            parent, child = socket.socketpair(socket.AF_UNIX,
                                              socket.SOCK_STREAM)
            os.dup2(child.fileno(), SHIM_IPC_FD)
            child.close()
            try:
                try:
                    newproc = subprocess.Popen(
                        argv, executable=real, env=env,
                        pass_fds=(SHIM_IPC_FD,),
                        stdout=self._files.get(1),
                        stderr=self._files.get(2),
                        cwd=cwd,
                    )
                except OSError as exc:
                    parent.close()
                    return -(exc.errno or EACCES)
            finally:
                devnull = os.open(os.devnull, os.O_RDWR)
                os.dup2(devnull, SHIM_IPC_FD)
                os.close(devnull)
        # point of no return: reap sibling-thread records (exec kills the
        # real siblings), sweep FD_CLOEXEC vfds, retire the old process
        cur = self._cur
        old_threads = self.threads
        for t in list(old_threads.values()):
            if t is not cur and not t.dead:
                t.retval = 0
                self._thread_gone(t)
            if t is not cur:
                t.joined = True
        for fd in sorted(self.fd_cloexec):  # FD_CLOEXEC sweep
            vs = self.fds.pop(fd, None)
            if vs is not None:
                self._close_vs(vs)
        self.fd_cloexec.clear()
        old_proc, old_pid, old_sock = self.proc, self.real_pid, self.sock
        if old_proc is not None:
            old_proc.kill()
            old_proc.wait()
        elif old_pid is not None:
            try:
                os.kill(old_pid, 9)
            except ProcessLookupError:
                pass
        for t in old_threads.values():  # close every per-thread channel
            if t.sock is not None and t.sock is not old_sock:
                try:
                    t.sock.close()
                except OSError:
                    pass
        if old_sock is not None:
            old_sock.close()
        self.proc = newproc
        self.real_pid = None
        self.mem = ProcessMemory(newproc.pid)
        self.sock = parent
        self.threads = {0: GuestThread(0, parent)}
        main = self.threads[0]
        note = getattr(self.host.controller, "note_guest_pid", None)
        if note is not None:
            note(self)
        self._ring_offered.clear()  # the replacement shim starts unmapped
        self._sock_rings.clear()  # re-offered on first use (same rings)
        self._ready_watch.clear()  # fresh page: readiness region is zero
        self.host.counters.add("execs", 1)
        if self._strace is not None:
            self._strace.write(f"+++ execve {real} +++\n")
        # fresh-image handshake, then queue its first turn grant (drained
        # when the old thread's pump returns)
        parent.settimeout(HANDSHAKE_TIMEOUT_S)
        try:
            req = self._read_req(main)
        finally:
            parent.settimeout(None)
        if req is None or req[0] != HELLO:
            newproc.kill()
            self._exited()
            return _EXECED
        self._resume(main, 0)
        return _EXECED

    # -- IPC ---------------------------------------------------------------
    def _read_req(self, th: GuestThread):
        buf = b""
        while len(buf) < 56:
            try:
                chunk = th.sock.recv(56 - len(buf))
            except socket.timeout:
                return _TIMEDOUT
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        nr = struct.unpack_from("<Q", buf, 0)[0]
        args = struct.unpack_from("<6Q", buf, 8)
        return nr, args

    def _reply(self, th: GuestThread, ret: int) -> None:
        clk = emulated(self.host.now)
        self._time_map[:8] = struct.pack("<q", clk)
        if self._fast_plane and self.parent_proc is None:
            self._refresh_fast_state()
        if self._journal is not None:
            # every grant the guest will ever observe passes through here
            # (strict turn-taking): the journal cursor after this record
            # IS the guest's position in its replayable history
            self._journal.record(th.slot, ret, clk)
        th.sock.sendall(struct.pack("<q", ret))

    def _refresh_fast_state(self) -> None:
        """Re-arm the shim's local world view before handing the turn
        back: per-connection TX budgets + HUP/ERR flags, and readiness
        bytes for watched non-ring vfds. Exact for the whole guest turn
        because transport state is frozen while the guest runs (strict
        turn-taking); every worker-serviced op ends here, so the view is
        refreshed before the guest can consult it again."""
        for fd, sr in self._sock_rings.items():
            if sr.dead:
                continue
            vs = self.fds.get(fd)
            if vs is None or vs.endpoint is None:
                continue
            snd = vs.endpoint.sender
            sr.set_wbudget(max(0, snd.send_buffer - snd.buffered))
            sr.sync_flags(vs)
        if self._ready_watch:
            tm = self._time_map
            for fd in self._ready_watch:
                vs = self.fds.get(fd)
                idx = fd - VFD_BASE
                if vs is None or not self._ready_byte_ok(vs):
                    tm[SHIM_READY_OFF + idx] = 0  # shim must forward
                    continue
                b = SHIM_READY_VALID
                if self._readable(vs):
                    b |= SHIM_READY_IN
                if self._writable(vs):
                    b |= SHIM_READY_OUT
                if vs.peer_closed:
                    b |= SHIM_READY_HUP
                if vs.connect_err:
                    b |= SHIM_READY_ERR
                tm[SHIM_READY_OFF + idx] = b

    def _drop_fast_fd(self, fd: int) -> None:
        """This fd number is going away (close / dup-over): forget its
        fast-plane state. The VSocket's SockRing itself survives while
        other references (dup aliases) remain; _close_vs retires it when
        the LAST reference goes."""
        self._sock_rings.pop(fd, None)
        self._ready_watch.discard(fd)
        if (self.parent_proc is None and self._time_map is not None
                and 0 <= fd - VFD_BASE < SHIM_READY_LEN):
            self._time_map[SHIM_READY_OFF + (fd - VFD_BASE)] = 0

    @staticmethod
    def _ready_byte_ok(vs: VSocket) -> bool:
        """Publish a page readiness byte only for fds with NO ring-
        capable backing: the shim's own local ring ops would make a
        published byte stale mid-turn, so ring-backed fds are evaluated
        from live ring state in-shim instead (shim_poll_local)."""
        if vs.sockring is not None:
            return False
        for pb in (vs.pipe, vs.pipe_out):
            if isinstance(pb, RingPipeBuf):
                return False
        return True

    def _maybe_offer_ring(self, fd: int, vs: VSocket, role: int, ret):
        """First read/write on a ring-pipe end from this process image:
        piggyback the ring's memfd on the reply (MAPRING sentinel +
        SCM_RIGHTS + the real result) so the shim serves subsequent
        non-blocking ops on this fd locally (native/shring.h). ``fd`` is
        the guest's actual fd (dup aliases each get their own offer)."""
        pb = self._wbuf(vs) if role else vs.pipe
        # offer only for fds whose read/write actually TRAPS (gen_bpf.py:
        # read traps fd 0 + vfds, write traps fd 1/2 + vfds) — a pipe on
        # fd 3..931 never reaches the worker, so a mapping there would be
        # inert and leak a shim table slot
        traps = fd >= VFD_BASE or (fd == 0 if role == 0 else fd in (1, 2))
        if (not traps or not isinstance(pb, RingPipeBuf) or pb.mm.closed
                or not isinstance(ret, int)
                or (fd, role) in self._ring_offered):
            return ret
        self._ring_offered.add((fd, role))
        th = self._cur
        try:
            th.sock.sendall(struct.pack("<q", MAPRING))
            th.sock.sendmsg([struct.pack("<q", role)],
                            [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                              struct.pack("<i", pb.memfd))])
            self._reply(th, ret)
        except OSError:
            return ret  # channel died; the pump notices on its next read
        return _REPLIED

    def _maybe_offer_sock(self, fd: int, ret):
        """First serviced read/write/recv/send on an ESTABLISHED stream
        from the page-owner image: publish the per-connection RX/TX ring
        pair over the service reply (two MAPRING offers + the real
        result — the same wire mechanism as pipe rings), so subsequent
        ready-data ops on this fd complete in-shim. Socket rings are
        MIRRORS of transport state; see SockRing."""
        if (not self._fast_plane or self.parent_proc is not None
                or not isinstance(ret, int)
                or fd < VFD_BASE or fd - VFD_BASE >= (1 << 24)
                or (fd, 0) in self._ring_offered):
            return ret
        vs = self.fds.get(fd)
        if (vs is None or vs.kind != "stream" or vs.endpoint is None
                or not vs.connected or vs.peer_closed or vs.connect_err
                or vs.listening):
            return ret
        sr = vs.sockring
        if sr is None:
            ep = vs.endpoint
            cap = _next_pow2(max(ep.receiver.recv_buffer,
                                 ep.sender.send_buffer, SHRING_CAP_MIN))
            if cap > SHRING_CAP_MAX:
                return ret
            sr = vs.sockring = SockRing(cap)
            if vs.rxbuf:  # mirror invariant holds from birth
                sr.rx_append(bytes(vs.rxbuf))
        if sr.dead:
            return ret
        self._ring_offered.add((fd, 0))
        self._ring_offered.add((fd, 1))
        # register BEFORE the reply: _reply's refresh must arm wbudget
        self._sock_rings[fd] = sr
        self._oplog_vs[fd - VFD_BASE] = vs
        th = self._cur
        try:
            for role, memfd in ((0, sr.rx_fd), (1, sr.tx_fd)):
                th.sock.sendall(struct.pack("<q", MAPRING))
                th.sock.sendmsg([struct.pack("<q", role)],
                                [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                                  struct.pack("<i", memfd))])
            self._reply(th, ret)
        except OSError:
            return ret  # channel died; the pump notices on its next read
        return _REPLIED

    def _fold_fast_ops(self) -> None:
        """Fold shim-local ring ops into the syscall counters and wake
        anything parked on a ring the guest touched. The op counter lives
        on the clock page (slot [2]; the fold cursor in [3] — ON the page
        so fork-shared pages fold each op exactly once, to whichever
        related process traps first; deterministic under strict
        turn-taking). Called on every received request: any shim-local
        activity strictly precedes the guest's next trap."""
        tm = self._time_map
        # socket oplog FIRST: replaying the shim's in-shim recv/send
        # sequence against the simulated transport — in arrival order,
        # before the trapped syscall is serviced — reproduces the slow
        # path's exact event schedule (window-update acks, drain wakes)
        nlog = struct.unpack_from("<q", tm, 8 * SHIM_PAGE_OPLOG_N)[0]
        if nlog:
            self._replay_oplog(tm, nlog)
            struct.pack_into("<q", tm, 8 * SHIM_PAGE_OPLOG_N, 0)
        ops, cur = struct.unpack_from("<qq", tm, 16)
        if ops == cur:
            return
        struct.pack_into("<q", tm, 24, ops)
        d = ops - cur
        c = self.host.counters
        c.add("syscalls", d)
        c.add("shim_fast_syscalls", d)
        # per-class census (informational: host.state_fingerprint filters
        # the shim_fast_ prefix, so these never gate determinism)
        for word, name in _SHIM_CLASS_COUNTERS:
            k = struct.unpack_from("<q", tm, 8 * word)[0]
            if k:
                struct.pack_into("<q", tm, 8 * word, 0)
                c.add(name, k)
        reg = self.host.controller.__dict__.get("_ring_registry")
        if reg:
            for pb in [p for p in reg if p.dirty()]:
                pb.clear_dirty()
                pb.wake()

    def _replay_oplog(self, tm, nlog: int) -> None:
        """Apply the shim's logged in-shim socket ops to the simulated
        transport, in order. Socket rings belong to the page OWNER (the
        root of a fork chain) — a child's trap folds the shared page, so
        replay resolves vfds through the owner's map."""
        owner = self
        while owner.parent_proc is not None:
            owner = owner.parent_proc
        for i in range(min(nlog, SHIM_OPLOG_MAX)):
            word = struct.unpack_from("<Q", tm, SHIM_OPLOG_OFF + 8 * i)[0]
            nbytes = word & 0xFFFFFFFF
            op = word >> 56
            idx = (word >> 32) & 0xFFFFFF
            vs = owner._oplog_vs.get(idx)
            sr = None if vs is None else vs.sockring
            if vs is None or sr is None:
                continue  # ring retired with ops in flight: cannot happen
                # mid-turn (close traps AFTER the fold); tolerated anyway
            if op == SHIM_OP_RECV:
                # the shim consumed nbytes from the RX ring (rpos already
                # advanced); drop the same prefix from the authoritative
                # buffer and let the receiver ack the window update
                del vs.rxbuf[:nbytes]
                owner._rx_consumed(vs)
            elif op == SHIM_OP_SEND:
                data = sr.tx_take(nbytes)
                accepted = 0
                if vs.endpoint is not None:
                    accepted = vs.endpoint.send(payload=bytes(data))
                if accepted != nbytes:
                    # the wbudget contract (send always accepts in full)
                    # broke — fail LOUDLY and fall back to the slow path
                    # forever on this connection
                    import sys as _sys

                    print(
                        f"shadow_tpu: {self.host.name}/{self.name} socket"
                        f" ring replay short ({accepted}/{nbytes} vfd"
                        f" {VFD_BASE + idx}) — wbudget contract violated;"
                        f" ring disabled", file=_sys.stderr)
                    sr.kill()

    def _pump(self, th: GuestThread) -> None:
        """Service one thread's syscalls until it blocks in sim time, yields
        the turn, or the process exits."""
        self._cur = th
        if self._turn_timeout:
            # every read below is a turn-wait (the guest is never blocked
            # on US between our reply and its next request), so one socket
            # timeout covers the whole pump
            th.sock.settimeout(self._turn_timeout)
        while True:
            req = self._read_req(th)
            if req is _TIMEDOUT:
                prog = self._ring_progress()
                if prog is not None and prog != self._shim_prog:
                    # the shim moved its fast-plane rings (consumed RX /
                    # filled TX in-shim) during the window: the guest is
                    # streaming without syscalls, not livelocked — re-arm
                    # and keep waiting
                    self._shim_prog = prog
                    continue
                self._watchdog_fire(th)
                return
            if req is None:
                if th.slot == 0:
                    self._exited()  # main channel EOF == process death
                else:
                    self._thread_gone(th)
                return
            self._fold_fast_ops()
            nr, args = req
            # worker round-trip census by syscall number (bench audit
            # table; controller-scoped so fingerprints never see it)
            self._slow_nrs[nr] = self._slow_nrs.get(nr, 0) + 1
            try:
                ret = self._service(nr, args)
            except OSError:
                ret = -EFAULT  # guest memory went away (racing exit)
            if ret is _BLOCK:
                self._flush_cpu_lat()  # timeouts must see consumed CPU time
                self._trace(nr, args, "<blocked>")
                return
            if ret in (_DETACH, _EXITGROUP):
                self._flush_cpu_lat()
            if ret is _DETACH:
                # thread announced exit: reply so it can finish dying
                # natively, then never read its channel again
                self._trace(nr, args, 0)
                try:
                    self._reply(th, 0)
                except OSError:
                    pass
                return
            if ret is _EXECED:
                # the record now fronts the REPLACEMENT process; the old
                # image (this channel) is gone. The new main's first turn
                # grant is queued and drains when we return.
                self._trace(nr, args, "<execed>")
                return
            if ret is _REPLIED:
                # service sent its own (ancillary-carrying) reply inline
                self._trace(nr, args, "<inline>")
                self.host.counters.add("syscalls", 1)
                continue
            if ret is _EXITGROUP:
                self._trace(nr, args, 0)
                try:
                    self._reply(th, 0)
                except OSError:
                    pass
                self._kill_now()  # before any reap: the pid is still ours
                self._exited()
                return
            self._trace(nr, args, ret)
            if ret == RETRY_NATIVE and nr not in self.native_vfs:
                # reality boundary, default-on (VERDICT r3 item #7): the
                # worker declined this path/syscall and the shim re-issues
                # it against the host kernel — record the number even
                # without audit mode (audit mode additionally observes
                # the never-trapped numbers via the gadget-IP filter)
                self.native_vfs.add(nr)
                self.host.counters.add("native_passthrough_syscalls", 1)
            if self._syscall_latency == 0:
                # livelock detector: a guest spinning on nonblocking
                # syscalls at a frozen sim instant (e.g. sloppy epoll
                # usage) would hang the simulation silently
                if self.host.now != self._spin_t:
                    self._spin_t, self._spin_n = self.host.now, 0
                self._spin_n += 1
                if self._spin_n == 200_000:
                    import sys as _sys

                    print(
                        f"shadow_tpu: {self.host.name}/{self.name} has made "
                        f"200000 syscalls without sim time advancing — guest "
                        f"busy-loop? Set general."
                        f"model_unblocked_syscall_latency: true to break it",
                        file=_sys.stderr)
            if self._syscall_latency:
                # model_unblocked_syscall_latency: each serviced syscall
                # advances this host's clock slightly, so busy-loops spin
                # forward in sim time instead of livelocking the round
                self._unapplied += self._syscall_latency
                if self._unapplied > self._max_unapplied:
                    self.host._now += self._unapplied
                    self._unapplied = 0
            try:
                self._reply(th, ret)
            except OSError:
                self._exited()
                return
            self.host.counters.add("syscalls", 1)

    def _ring_progress(self):
        """Shim-side SockRing cursor snapshot for the watchdog: RX read
        positions and TX write positions are the two cursors ONLY the shim
        advances (in-shim reads/writes, oplogged for later replay), so a
        change between two timeout windows proves the guest is alive in
        the fast plane. None when no live rings exist — then a silent
        guest has no syscall-free way to make progress and the watchdog
        fires on the first timeout, exactly as before the fast plane."""
        snap = None
        for fd, sr in self._sock_rings.items():
            if sr.dead:
                continue
            rx_r = struct.unpack_from("<Q", sr.rx, 8)[0]
            tx_w = struct.unpack_from("<Q", sr.tx, 16)[0]
            if snap is None:
                snap = []
            snap.append((fd, rx_r, tx_w))
        return None if snap is None else tuple(snap)

    def _watchdog_fire(self, th: GuestThread) -> None:
        """The guest held its turn past experimental.guest_turn_timeout
        wall seconds without making a syscall — a userspace spin-wait
        livelock (the README's declared turn-taking limitation). Kill the
        guest and convert the stall into the same host_down teardown the
        fault injector uses, so the simulation keeps its round loop (and
        its determinism for every OTHER host) instead of hanging forever.
        A stalled guest stalls every run, so the conversion is observed
        reproducibly; only the wall instant of detection varies."""
        host = self.host
        ctl = host.controller
        if getattr(ctl, "_supervised", False):
            # supervised run (shadow_tpu/supervise.py): escalate instead of
            # degrading in-sim. Kill the guest to unblock the pump, park
            # the named reason on the controller — it raises GuestStallError
            # at the next round boundary, and the supervisor restarts the
            # whole run from its re-execution snapshot (or scratch), which
            # regenerates every stream byte-identically. No in-sim
            # accounting (counters, host.crash) may record the stall: the
            # restarted run never saw it.
            msg = (f"guest watchdog: {host.name}/{self.name} held its turn "
                   f"for more than {self._turn_timeout:g}s wall without a "
                   f"syscall or fast-plane ring progress (wedged guest) — "
                   f"escalating to the supervisor")
            ctl.log.error(msg)
            ctl._stall_escalate = msg
            self._kill_now()
            self._exited()
            return
        msg = (f"guest watchdog: {host.name}/{self.name} held its turn for "
               f"more than {self._turn_timeout:g}s wall without a syscall "
               f"or fast-plane ring progress (spin-wait livelock?) — "
               f"killing the guest and downing the host (host_down)")
        host.controller.log.error(msg)
        host.log(msg, level="error")
        host.counters.add("guest_watchdog_kills", 1)
        self._signal_hint = -9  # killed by the watchdog
        self._kill_now()
        self._exited()
        # the host is going down: reap sibling MANAGED guests first, with
        # exit accounting — the stall killed the whole host, and a
        # sibling's live OS process must not outlive it (Host.crash's
        # .kill sweep would leave them respawnable, but a watchdog-downed
        # host records its guests as dead, not power-cycled)
        for p in host.processes:
            if p is not self:
                reap = getattr(p, "reap", None)
                if reap is not None:
                    reap()
        host.crash(host.now)

    def _resume(self, th: GuestThread, ret: int) -> None:
        """A continuation fired for a parked thread: queue its turn grant,
        and drain the grant queue unless a thread is already being pumped
        (then the drain happens when the active thread yields)."""
        if not self.running or th.dead:
            return
        th.waiting = None
        self._ready.append((th, ret))
        if not self._pumping:
            self._drain_ready()

    def _drain_ready(self) -> None:
        self._pumping = True
        try:
            while self._ready and self.running:
                th, ret = self._ready.pop(0)
                if th.dead:
                    continue
                self._cur = th
                if ret is _EMBRYO:
                    # first grant of a freshly spawned thread: read its
                    # THREAD_HELLO (blocks in real time only, bounded —
                    # the guest's real pthread_create may have FAILED
                    # after the slot was minted, and then nobody ever
                    # speaks on this channel)
                    th.sock.settimeout(HANDSHAKE_TIMEOUT_S)
                    try:
                        req = self._read_req(th)
                    finally:
                        if th.sock is not None:
                            th.sock.settimeout(None)
                    if req is None or req[0] != THREAD_HELLO:
                        self._thread_gone(th)
                        continue
                    th.waiting = None
                    ret = 0
                self._trace(-1, (), f"<resumed> = {ret}")
                try:
                    self._reply(th, ret)
                except OSError:
                    self._exited()
                    return
                self.host.counters.add("syscalls", 1)
                self._pump(th)
        finally:
            self._pumping = False

    def _close_vs(self, vs: VSocket) -> None:
        """Drop one fd-table reference; tear down the backing object only
        when the last reference (across forked processes) goes away."""
        vs.refs -= 1
        if vs.refs > 0:
            return
        if vs.kind in ("file", "dir"):
            self.vfs.close(vs)
            return
        if vs.listening:
            self.host.unlisten(vs.bound_port)
        if vs.sockring is not None:
            # every fd-table reference closed -> every shim mapping was
            # dropped before its close trap forwarded; safe to unmap
            vs.sockring.retire()
            vs.sockring = None
        if vs.endpoint is not None:
            vs.endpoint.close()
        if vs.pipe is not None:
            vs.pipe.wake()  # refs hit 0: EOF readers / EPIPE writers
        if vs.pipe_out is not None:
            vs.pipe_out.wake()

    def _thread_gone(self, th: GuestThread) -> None:
        """A non-main thread announced exit (or its channel died)."""
        th.dead = True
        for q in list(self.futexes.values()):
            q[:] = [(t, m) for (t, m) in q if t is not th]
        if th.joiners:
            th.joined = True
        for j in th.joiners:
            self._resume(j, th.retval)
        th.joiners = []

    # -- guest threads (reference analog: Process/ManagedThread ------------
    #    per SURVEY.md §2; strict one-runnable-thread turn-taking) ---------
    def _spawn_thread(self):
        slot = None
        # recycle a dead, fully-joined slot first (its worker-side socket
        # closes here; the guest's dup2 onto the reserved fd replaces the
        # stale guest end) — the 31-slot window caps CONCURRENT threads,
        # not threads-over-a-lifetime
        for s in sorted(self.threads):
            t = self.threads[s]
            if s != 0 and t.dead and t.joined and not t.joiners:
                if t.sock is not None:
                    t.sock.close()
                slot = s
                break
        if slot is None:
            if self._next_slot >= MAX_THREADS:
                return -EAGAIN
            slot = self._next_slot
            self._next_slot += 1
        parent, child = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
        nt = GuestThread(slot, parent)
        nt.waiting = ("embryo",)  # until its THREAD_HELLO is read
        self.threads[slot] = nt
        # reply carries the slot in-band plus the new channel's guest end
        # as SCM_RIGHTS ancillary data (the shim recvmsg's this one reply)
        self._time_map[:8] = struct.pack("<q", emulated(self.host.now))
        socket.send_fds(self._cur.sock, [struct.pack("<q", slot)],
                        [child.fileno()])
        child.close()
        # grant the embryo its first turn once the spawner yields
        self._ready.append((nt, _EMBRYO))
        return _REPLIED

    def _mmap_vfd(self, args):
        """mmap over a virtualized file (the arg4-conditional trap): reply
        with a real kernel fd as SCM_RIGHTS — the host-tree backing fd, or
        a memfd snapshot for synthesized content — and the shim re-issues
        the map with it through the gadget, then closes the temporary fd.
        Deterministic: only this simulation writes the backing files, and
        synthesized snapshots are pure functions of the config."""
        fd = _sfd(args[4])
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        if vs.kind != "file" or vs.vfile is None:
            return -19  # ENODEV: directories/sockets are not mappable
        vf = vs.vfile
        if vf.fd is not None:
            send = vf.fd
            tmp = None
        else:
            tmp = os.memfd_create("shadow-synth")
            os.write(tmp, vf.data)
            send = tmp
        self._time_map[:8] = struct.pack("<q", emulated(self.host.now))
        try:
            socket.send_fds(self._cur.sock, [struct.pack("<q", 0)], [send])
        finally:
            if tmp is not None:
                os.close(tmp)
        return _REPLIED

    def _join_thread(self, slot: int):
        target = self.threads.get(slot)
        if target is None or target is self._cur:
            return -EINVAL
        if target.dead:
            target.joined = True
            return target.retval
        target.joiners.append(self._cur)
        self._waiting = ("join", target)
        return _BLOCK

    # -- fork (reference analog: Process::spawn's sibling path — a managed
    #    guest forking a managed child, SURVEY.md §3.2; the real fork runs
    #    SHIM-side, the worker mints the child's channel and adopts it) ----
    def _fork_intent(self):
        eid = len(self._embryos)
        parent_sock, child_sock = socket.socketpair(
            socket.AF_UNIX, socket.SOCK_STREAM)
        self._embryos[eid] = parent_sock
        self._time_map[:8] = struct.pack("<q", emulated(self.host.now))
        socket.send_fds(self._cur.sock, [struct.pack("<q", eid)],
                        [child_sock.fileno()])
        child_sock.close()
        return _REPLIED

    def _fork_commit(self, eid: int, real_pid: int):
        sock = self._embryos.pop(eid, None)
        if sock is None:
            return -EINVAL
        child = ManagedProcess.adopt(self, sock, real_pid)
        self.children.append(child)
        # grant the child its first turn once the parent yields; the child
        # parks in THREAD_HELLO inside its (copied) SIGSYS frame
        child._ready.append((child.threads[0], _EMBRYO))
        self.host.schedule_in(0, child._kick)
        return child.vpid

    @classmethod
    def adopt(cls, parent: "ManagedProcess", sock, real_pid: int):
        """Register a forked guest as a managed process of the same host.

        Built on __init__ (which is side-effect-free) so new runtime fields
        never need mirroring here; only the fork-specific identity and the
        fd-table snapshot are overridden."""
        import copy
        from dataclasses import replace as dc_replace

        host = parent.host
        ctl = host.controller
        seq = getattr(ctl, "_fork_seq", 0)
        ctl._fork_seq = seq + 1
        opts = dc_replace(copy.copy(parent.opts), expected_final_state=None)
        self = cls(host, opts, 0)
        self.name = f"{Path(parent.opts.path).name}.f{seq}"
        seqv = getattr(ctl, "_vpid_seq", 40000)
        ctl._vpid_seq = seqv + 1
        self.vpid = seqv  # deterministic: fork order is deterministic
        self.proc = None  # not our OS child — the guest parent's
        self.real_pid = real_pid
        self.mem = ProcessMemory(real_pid)
        self.sock = sock
        self._time_map = parent._time_map  # same mapped clock page
        # fork semantics: the fd table is a snapshot sharing open file
        # descriptions (refcounted); per-process capture files stay fresh
        self.fds = dict(parent.fds)
        self.fd_cloexec = set(parent.fd_cloexec)
        if hasattr(parent, "_rlimits"):  # setrlimit overrides inherit
            self._rlimits = dict(parent._rlimits)
        for vs in self.fds.values():
            vs.refs += 1
            if vs.pipe is not None:
                vs.pipe.procs.add(self)
            if vs.pipe_out is not None:
                vs.pipe_out.procs.add(self)
        self._next_vfd = parent._next_vfd
        self.vfs.cwd = parent.vfs.cwd
        self.threads = {0: GuestThread(0, sock)}
        self._cur = self.threads[0]
        self.parent_proc = parent
        self.running = True
        self.spawned = True
        self._open_strace()
        jdir = getattr(ctl, "guest_journal_dir", None)
        if jdir is not None:
            self._journal = GuestJournal(
                jdir / f"{host.name}.{self.name}.guest_oplog.jsonl")
        host.processes.append(self)
        ctl.processes.append(self)
        host.counters.add("processes_spawned", 1)
        note = getattr(ctl, "note_guest_pid", None)
        if note is not None:
            note(self)
        return self

    def _kick(self) -> None:
        if self.running and not self._pumping:
            self._drain_ready()

    def _flush_cpu_lat(self) -> None:
        """Apply accumulated-but-unapplied modeled CPU latency. Reference
        semantics: unapplied latency flushes at blocking points so sleep/
        poll timeouts are computed against the true consumed-CPU clock."""
        if self._unapplied:
            self.host._now += self._unapplied
            self._unapplied = 0

    def _kill_now(self) -> None:
        """SIGKILL the guest synchronously (exit_group: sibling threads
        must die too). Safe against pid reuse: called before the pid is
        reaped, so it is at worst a zombie that still belongs to us."""
        pid = self.proc.pid if self.proc is not None else self.real_pid
        if pid is not None:
            try:
                os.kill(pid, 9)
            except ProcessLookupError:
                pass

    # -- round-5 syscall-family breadth (SURVEY §2 SyscallHandler) ---------
    #: deterministic resource limits, part of the virtual identity
    #: (res -> (cur, max)); RLIM_INFINITY for everything unlisted
    _RLIM_INF = (1 << 64) - 1
    _RLIMITS_DEFAULT = {
        3: (8 << 20, _RLIM_INF),   # RLIMIT_STACK
        4: (0, _RLIM_INF),         # RLIMIT_CORE
        6: (4096, 4096),           # RLIMIT_NPROC
        7: (1024, 1 << 20),        # RLIMIT_NOFILE
        12: (819200, 819200),      # RLIMIT_MSGQUEUE
    }

    def _rlimit_get(self, res: int):
        ovr = getattr(self, "_rlimits", None)
        if ovr and res in ovr:
            return ovr[res]
        return self._RLIMITS_DEFAULT.get(res, (self._RLIM_INF,
                                               self._RLIM_INF))

    def _rlimit(self, nr: int, args):
        """getrlimit/setrlimit/prlimit64: a deterministic limit table
        (virtual identity) with per-process overrides. prlimit64 serves
        self (pid 0 or own vpid) only."""
        if nr == SYS_prlimit64:
            pid, res, newp, oldp = args[0], args[1], args[2], args[3]
            pid &= 0xFFFFFFFF
            if pid not in (0, self.vpid):
                return -EPERM
        else:
            res, ptr = args[0], args[1]
            newp = ptr if nr == SYS_setrlimit else 0
            oldp = ptr if nr == SYS_getrlimit else 0
        if res > 15:
            return -EINVAL
        if oldp:
            cur, mx = self._rlimit_get(res)
            self.mem.write(oldp, struct.pack("<QQ", cur, mx))
        if newp:
            cur, mx = struct.unpack("<QQ", self.mem.read(newp, 16))
            if cur > mx:
                return -EINVAL
            if not hasattr(self, "_rlimits"):
                self._rlimits = {}
            self._rlimits[res] = (cur, mx)
        return 0

    def _sigaltstack(self, th: GuestThread, args):
        """Bookkeeping + native passthrough: the record keeps strace and
        determinism surfaces coherent, while the real kernel stack switch
        still happens (genuine faults — e.g. the TSC SIGSEGV service —
        must honor the guest's alternate stack)."""
        ss_ptr, old_ptr = args[0], args[1]
        if old_ptr:
            sp, fl, sz = th.altstack or (0, 2, 0)  # SS_DISABLE when unset
            self.mem.write(old_ptr, struct.pack("<QiiQ", sp, fl, 0, sz))
        if ss_ptr:
            sp, fl, _pad, sz = struct.unpack("<QiiQ",
                                             self.mem.read(ss_ptr, 24))
            if not (fl & 2) and sz < 2048:  # MINSIGSTKSZ
                return -12  # ENOMEM
            th.altstack = (sp, fl, sz)
        return RETRY_NATIVE

    def _sendfile(self, args, th: GuestThread = None):
        """sendfile(2): virtual file -> simulated socket or pipe. Reads
        at the explicit offset (or the file position), sends what the
        destination accepts NOW, and advances by exactly the returned
        count (POSIX); a blocking socket with no room parks and retries
        whole (see _on_drain). All-real-fd calls pass through native."""
        out_fd, in_fd, off_ptr, count = args[0], args[1], args[2], args[3]
        out_vs = self.fds.get(out_fd)
        in_vs = self.fds.get(in_fd)
        if in_vs is None and out_vs is None:
            return RETRY_NATIVE
        if in_vs is None or in_vs.kind != "file":
            return -EINVAL
        if out_vs is None:
            return -EBADF
        count = min(count, 1 << 20)
        off = None
        if off_ptr:
            off = struct.unpack("<q", self.mem.read(off_ptr, 8))[0]
            data = self.vfs.pread(in_vs, count, off)
        else:
            data = self.vfs.pread(in_vs, count, in_vs.vfile.off)
        if isinstance(data, int):
            return data
        if not data:
            return 0
        if out_vs.kind in ("pipe_w", "spair"):
            pb = self._wbuf(out_vs)
            if pb is None or pb.readers == 0:
                return -EPIPE
            k = min(len(data), pb.room())
            if k <= 0:
                if out_vs.nonblock:
                    return -EAGAIN
                tgt = th if th is not None else self._cur
                tgt.waiting = ("sendfile", out_vs, args)
                self._park_on(pb, tgt)
                return _BLOCK
            pb.append_bytes(data[:k])
            pb.wake()
        elif out_vs.endpoint is not None and out_vs.connected:
            if out_vs.peer_closed:
                return -EPIPE
            k = out_vs.endpoint.send(payload=data)
            if k <= 0:
                if out_vs.nonblock:
                    return -EAGAIN
                tgt = th if th is not None else self._cur
                tgt.waiting = ("sendfile", out_vs, args)
                return _BLOCK
        else:
            return -EINVAL
        if off_ptr:
            self.mem.write(off_ptr, struct.pack("<q", off + k))
        else:
            in_vs.vfile.off += k
        return k

    def _signalfd(self, args, four: bool):
        """signalfd(4): a virtual signal fd. Model: an emulated kill(2)
        whose signal is in a signalfd's mask is captured there (the
        blocked-signal semantics real callers set up; per-thread signal
        masks are not otherwise modeled — documented scope)."""
        fd, mask_ptr = _sfd(args[0]), args[1]
        mask = struct.unpack("<Q", self.mem.read(mask_ptr, 8))[0]
        flags = args[3] if four else 0
        if fd == -1:
            vs = VSocket(self._next_vfd, "sigfd")
            self._next_vfd += 1
            vs.sig_mask = mask
            if flags & 0o4000:  # SFD_NONBLOCK
                vs.nonblock = True
            if flags & O_CLOEXEC:
                self.fd_cloexec.add(vs.vfd)
            self.fds[vs.vfd] = vs
            return vs.vfd
        vs = self.fds.get(fd)
        if vs is None or vs.kind != "sigfd":
            return -EINVAL
        vs.sig_mask = mask
        return fd

    _SFD_SIZE = 128  # sizeof(struct signalfd_siginfo)

    def _sigfd_read(self, vs: VSocket, bufaddr: int, buflen: int):
        if buflen < self._SFD_SIZE:
            return -EINVAL
        if not vs.sig_q:
            if vs.nonblock:
                return -EAGAIN
            self._waiting = ("sigread", vs, bufaddr, buflen)
            return _BLOCK
        out = b""
        while vs.sig_q and len(out) + self._SFD_SIZE <= buflen:
            signo, spid = vs.sig_q.pop(0)
            rec = bytearray(self._SFD_SIZE)
            struct.pack_into("<IiiII", rec, 0, signo, 0, 0, spid, 0)
            out += bytes(rec)
        self.mem.write(bufaddr, out)
        return len(out)

    def _sigfd_deliver(self, sig: int, sender_vpid: int) -> bool:
        """Queue sig on the first signalfd whose mask has it; wake its
        reader/pollers. Returns True if captured."""
        for vs in self.fds.values():
            if vs.kind == "sigfd" and (vs.sig_mask >> (sig - 1)) & 1:
                vs.sig_q.append((sig, sender_vpid))
                th, w = self._find_waiter((("sigread",), vs))
                if th is not None:
                    self._resume(th, self._sigfd_read(vs, w[2], w[3]))
                else:
                    self._notify()
                return True
        return False

    def _splice(self, args, tee: bool, th: GuestThread = None):
        """splice/tee between virtual pipes (and file->pipe for splice).
        Progress-now semantics with parking on an empty blocking input;
        all-real-fd calls pass through native."""
        if tee:
            fd_in, fd_out, count = args[0], args[1], args[2]
            off_in = off_out = 0
        else:
            fd_in, off_in, fd_out, off_out, count = (
                args[0], args[1], args[2], args[3], args[4])
        in_vs = self.fds.get(fd_in)
        out_vs = self.fds.get(fd_out)
        if in_vs is None and out_vs is None:
            return RETRY_NATIVE
        count = min(count, 1 << 20)
        # destination must be a virtual pipe (or file for splice-out)
        if out_vs is not None and out_vs.kind == "pipe_w":
            pb_out = out_vs.pipe
        else:
            pb_out = None
        if in_vs is not None and in_vs.kind == "pipe_r":
            pb_in = in_vs.pipe
            if pb_in is None:
                return 0
            if off_in:
                return -ESPIPE
            avail = pb_in.avail()
            if avail == 0:
                if pb_in.writers == 0:
                    return 0
                if in_vs.nonblock:
                    return -EAGAIN
                tgt = th if th is not None else self._cur
                tgt.waiting = ("splice", in_vs, args, tee)
                self._park_on(pb_in, tgt)
                return _BLOCK
            if tee:
                if pb_out is None or pb_out.readers == 0:
                    return -EINVAL if pb_out is None else -EPIPE
                k = min(avail, count, pb_out.room())
                if k <= 0:  # output full: block like tee(2), never 0
                    if out_vs.nonblock:
                        return -EAGAIN
                    tgt = th if th is not None else self._cur
                    tgt.waiting = ("splice", out_vs, args, tee)
                    self._park_on(pb_out, tgt)
                    return _BLOCK
                pb_out.append_bytes(pb_in.peek(k))  # tee: no consume
                pb_out.wake()
                return k
            if pb_out is not None:
                if pb_out.readers == 0:
                    return -EPIPE
                k = min(avail, count, pb_out.room())
                if k <= 0:  # output full: block like splice(2)
                    if out_vs.nonblock:
                        return -EAGAIN
                    tgt = th if th is not None else self._cur
                    tgt.waiting = ("splice", out_vs, args, tee)
                    self._park_on(pb_out, tgt)
                    return _BLOCK
                pb_out.append_bytes(pb_in.take(k))
                pb_in.wake()
                pb_out.wake()
                return k
            if out_vs is not None and out_vs.kind == "file":
                # write FIRST, consume what was actually written (an
                # error or short write must not lose pipe bytes)
                k = min(avail, count)
                data = pb_in.peek(k)
                if off_out:
                    off = struct.unpack("<q",
                                        self.mem.read(off_out, 8))[0]
                    r = self.vfs.pwrite(out_vs, data, off)
                else:
                    r = self.vfs.write(out_vs, data)
                if r > 0:
                    pb_in.take(r)
                    pb_in.wake()
                    if off_out:
                        self.mem.write(off_out,
                                       struct.pack("<q", off + r))
                return r
            return -EINVAL
        if (not tee and in_vs is not None and in_vs.kind == "file"
                and pb_out is not None):
            if pb_out.readers == 0:
                return -EPIPE
            k = min(count, pb_out.room())
            if k <= 0:
                return -EAGAIN
            if off_in:
                off = struct.unpack("<q", self.mem.read(off_in, 8))[0]
                data = self.vfs.pread(in_vs, k, off)
            else:
                data = self.vfs.pread(in_vs, k, in_vs.vfile.off)
            if isinstance(data, int):
                return data
            if not data:
                return 0
            if off_in:
                self.mem.write(off_in,
                               struct.pack("<q", off + len(data)))
            else:
                in_vs.vfile.off += len(data)
            pb_out.append_bytes(data)
            pb_out.wake()
            return len(data)
        return -EINVAL

    # -- inotify (directory watches over the virtual file surface) ---------
    _INO_HDR = struct.Struct("<iIII")  # wd, mask, cookie, len

    def _inotify_init(self, flags: int):
        vs = VSocket(self._next_vfd, "inotify")
        self._next_vfd += 1
        if flags & 0o4000:  # IN_NONBLOCK
            vs.nonblock = True
        if flags & O_CLOEXEC:
            self.fd_cloexec.add(vs.vfd)
        self.fds[vs.vfd] = vs
        return vs.vfd

    def _inotify_add(self, args):
        """Watches on DIRECTORIES within the worker-served tree; events
        are generated for direct children at the vfs mutation points
        (create/delete/move/modify — the families real build tools and
        event loops watch for). Self-events and native-passthrough paths
        are out of scope (documented)."""
        vs = self.fds.get(args[0])
        if vs is None or vs.kind != "inotify":
            return -EINVAL
        path = self.vfs._path_arg(args[1])
        if path is None:
            return -EFAULT
        r = self.vfs.resolve(AT_FDCWD, path)
        if r is None or r[0] == "synth":
            return -EPERM  # only the worker-served tree is watchable
        real = r[1]
        if not os.path.isdir(real):
            return -20  # ENOTDIR (file watches: out of scope)
        real = real.rstrip("/")
        wmask = args[2] & 0xFFFFFFFF
        for wd, (p, _m) in vs.watches.items():
            if p == real:
                vs.watches[wd] = (real, wmask)
                return wd
        wd = vs.next_wd
        vs.next_wd += 1
        vs.watches[wd] = (real, wmask)
        return wd

    def _inotify_rm(self, args):
        vs = self.fds.get(args[0])
        if vs is None or vs.kind != "inotify":
            return -EINVAL
        if args[1] not in vs.watches:
            return -EINVAL
        del vs.watches[args[1]]
        return 0

    def _ino_read(self, vs: VSocket, bufaddr: int, buflen: int):
        if not vs.ino_q:
            if vs.nonblock:
                return -EAGAIN
            self._waiting = ("inoread", vs, bufaddr, buflen)
            return _BLOCK
        out = b""
        while vs.ino_q and len(out) + len(vs.ino_q[0]) <= buflen:
            out += vs.ino_q.pop(0)
        if not out:
            return -EINVAL  # buffer smaller than the next event
        self.mem.write(bufaddr, out)
        return len(out)

    def _ino_mutate(self, real_path: str, mask: int, cookie: int = 0):
        """A vfs mutation happened at ``real_path``: deliver an event to
        every inotify watch (ANY process on this host — the tree is
        shared) whose directory is the path's parent."""
        parent = os.path.dirname(real_path.rstrip("/"))
        name = os.path.basename(real_path.rstrip("/"))
        nb = name.encode()
        pad = (-(len(nb) + 1)) % 8 + 1  # NUL + align to 8
        seen: set = set()  # fork/dup share VSockets: queue + wake ONCE
        for proc in self.host.processes:
            for vs in getattr(proc, "fds", {}).values():
                if vs.kind != "inotify" or id(vs) in seen:
                    continue
                seen.add(id(vs))
                for wd, (wpath, wmask) in vs.watches.items():
                    if wpath != parent or not (wmask & mask & 0xFFF):
                        continue
                    rec = (self._INO_HDR.pack(wd, mask, cookie,
                                              len(nb) + pad)
                           + nb + b"\0" * pad)
                    # coalesce identical consecutive unread events
                    # (kernel behavior for e.g. repeated IN_MODIFY)
                    if vs.ino_q and vs.ino_q[-1] == rec:
                        continue
                    vs.ino_q.append(rec)
                    # the blocked reader may be ANY process sharing the
                    # fd (fork); wake the first match, notify the rest
                    for p2 in self.host.processes:
                        fw = getattr(p2, "_find_waiter", None)
                        if fw is None:
                            continue
                        th, w = fw((("inoread",), vs))
                        if th is not None:
                            p2._resume(th, p2._ino_read(vs, w[2], w[3]))
                            break
                    else:
                        for p2 in self.host.processes:
                            if getattr(p2, "running", False):
                                p2._notify()

    def _kill(self, args):
        """kill(2) between managed guests of one simulated host: vpid
        resolution + DEFAULT dispositions emulated worker-side (terminate /
        ignore). Real in-guest handler delivery is out of scope — the
        turn-taking protocol admits no out-of-turn syscalls (a handler
        firing inside a parked syscall would corrupt the channel)."""
        pid = args[0] & 0xFFFFFFFF
        if pid >= (1 << 31):
            pid -= 1 << 32
        sig = args[1] & 0xFFFFFFFF
        if sig > 64:
            return -EINVAL
        if pid <= 0:
            return -EPERM  # process groups: not modeled
        target = None
        for p in self.host.processes:
            if getattr(p, "vpid", None) == pid and p.running:
                target = p
                break
        if target is None:
            return -ESRCH
        if sig == 0:
            return 0  # existence probe
        if sig != 9 and target._sigfd_deliver(sig, self.vpid):
            return 0  # captured by a signalfd (blocked-signal semantics)
        if sig in _IGN_SIGS or sig not in _TERM_SIGS and sig != 9:
            return 0  # default-ignore, or dispositions we don't model
        target._signal_hint = -sig
        if target is self:
            # self-signal with a fatal default: terminate after the reply
            self._exit_hint = None
            return _EXITGROUP
        target._kill_now()
        # the victim's channel EOF is collected here so its death (and
        # any wait4 wakeup) lands at THIS sim instant, deterministically
        target._exited()
        return 0

    def _wait4(self, args):
        # pid is a C int: only the low 32 bits are defined (the ABI leaves
        # the upper half of the register unspecified for int args)
        pid = args[0] & 0xFFFFFFFF
        if pid >= (1 << 31):
            pid -= 1 << 32
        status_ptr, options = args[1], args[2]
        matches = [c for c in self.children
                   if not c.reaped and (pid in (-1, 0) or c.vpid == pid)]
        if not matches:
            return -ECHILD
        dead = [c for c in matches if not c.running]
        if dead:
            return self._reap_child(dead[0], status_ptr)
        if options & WNOHANG:
            return 0
        self._waiting = ("waitchild", pid, status_ptr)
        return _BLOCK

    def _reap_child(self, c: "ManagedProcess", status_ptr: int) -> int:
        c.reaped = True
        code = c.exit_code if c.exit_code is not None else 0
        status = (-code if code < 0 else (code & 0xFF) << 8)  # signal|exit
        if status_ptr:
            self.mem.write(status_ptr, struct.pack("<i", status))
        return c.vpid

    def _child_exited(self, c: "ManagedProcess") -> None:
        """A forked child died: wake a parked wait4 if it matches."""
        for slot in sorted(self.threads):
            th = self.threads[slot]
            w = th.waiting
            if (w and not th.dead and w[0] == "waitchild"
                    and (w[1] in (-1, 0) or w[1] == c.vpid)):
                self._resume(th, self._reap_child(c, w[2]))
                return

    def _fstat(self, fd: int, buf: int):
        """struct stat for a virtual descriptor: enough for stdio/io.open
        (st_mode by kind, st_blksize, zero size)."""
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        if vs.kind in ("file", "dir"):
            self.mem.write(buf, self.vfs.fstat_bytes(vs))
            return 0
        mode = {"pipe_r": 0o010600, "pipe_w": 0o010600,  # S_IFIFO
                "stream": 0o140777, "dgram": 0o140777,   # S_IFSOCK
                "spair": 0o140777,
                }.get(vs.kind, 0o0600)  # epoll/timer/event: anon inode
        st = bytearray(144)  # struct stat, x86-64 layout
        struct.pack_into("<QQQIII", st, 0, 0, fd, 1, mode, 0, 0)
        struct.pack_into("<qq", st, 48, 0, 4096)  # st_size, st_blksize
        sec = emulated(self.host.now) // NS_PER_SEC
        for off in (72, 88, 104):  # st_atime / st_mtime / st_ctime .tv_sec
            struct.pack_into("<q", st, off, sec)
        self.mem.write(buf, bytes(st))
        return 0

    # -- pipes + dup (descriptor-table breadth; pipes work across fork) ----
    def _ring_bufs(self, n: int) -> list:
        """``n`` guest-shared memory rings (native/shring.h — the shim
        serves non-blocking ops locally, zero worker round trips) when
        eligible; plain worker-side buffers under strace / modeled
        syscall latency, which must see every call."""
        if (_FASTPATH_ON and self._strace is None
                and self._syscall_latency == 0):
            reg = self.host.controller.__dict__.setdefault(
                "_ring_registry", {})
            return [RingPipeBuf(reg) for _ in range(n)]
        return [PipeBuf() for _ in range(n)]

    def _pipe(self, fds_ptr: int, flags: int):
        (pb,) = self._ring_bufs(1)
        pb.procs.add(self)
        r = VSocket(self._next_vfd, "pipe_r")
        w = VSocket(self._next_vfd + 1, "pipe_w")
        self._next_vfd += 2
        r.pipe = w.pipe = pb
        pb.r_end, pb.w_end = r, w
        pb.sync_refs()  # the shared header must see readers/writers NOW:
        # the shim's local-write gate checks readers == 0 (EPIPE path)
        if flags & 0o4000:  # O_NONBLOCK
            r.nonblock = w.nonblock = True
        if flags & O_CLOEXEC:
            self.fd_cloexec.update((r.vfd, w.vfd))
        self.fds[r.vfd] = r
        self.fds[w.vfd] = w
        self.mem.write(fds_ptr, struct.pack("<ii", r.vfd, w.vfd))
        return 0

    def _socketpair(self, args):
        """AF_UNIX socketpair(2): a duplex pair modeled as two cross-
        wired PipeBufs (bash process substitution, mux/IPC idioms; works
        across fork like pipes)."""
        if args[0] != 1:  # AF_UNIX only
            return -EAFNOSUPPORT
        if (args[1] & 0xFF) != 1:  # SOCK_STREAM only — datagram pairs
            return -93  # EPROTONOSUPPORT: fail loudly, not mis-frame
        a = VSocket(self._next_vfd, "spair")
        b = VSocket(self._next_vfd + 1, "spair")
        self._next_vfd += 2
        # each direction is one ring; an end maps its read ring (role 0)
        # and its write ring (role 1) via separate offers
        ab, ba = self._ring_bufs(2)
        ab.procs.add(self)
        ba.procs.add(self)
        ab.w_end, ab.r_end = a, b
        ba.w_end, ba.r_end = b, a
        a.pipe, a.pipe_out = ba, ab
        b.pipe, b.pipe_out = ab, ba
        ab.sync_refs()  # headers must see readers/writers NOW (shim gate)
        ba.sync_refs()
        if args[1] & 0o4000:  # SOCK_NONBLOCK
            a.nonblock = b.nonblock = True
        if args[1] & O_CLOEXEC:
            self.fd_cloexec.update((a.vfd, b.vfd))
        self.fds[a.vfd] = a
        self.fds[b.vfd] = b
        self.mem.write(args[3], struct.pack("<ii", a.vfd, b.vfd))
        return 0

    def _dup(self, oldfd: int, newfd):
        vs = self.fds.get(oldfd)
        if vs is None:
            return -EBADF
        if newfd == oldfd:
            return newfd  # dup2(x, x): POSIX no-op, closes nothing
        if newfd is None:
            newfd = self._next_vfd
            self._next_vfd += 1
        else:
            old = self.fds.pop(newfd, None)
            if old is not None:
                self._close_vs(old)
            self._ring_offered.discard((newfd, 0))  # rebound fd number
            self._ring_offered.discard((newfd, 1))
            self._drop_fast_fd(newfd)
        vs.refs += 1
        self.fds[newfd] = vs
        self.fd_cloexec.discard(newfd)  # dup/dup2 clear FD_CLOEXEC
        return newfd

    def _pipe_read(self, vs: VSocket, iovs, peek: bool = False):
        pb = vs.pipe
        if pb is None:  # SHUT_RD half of a shutdown socketpair
            return 0
        if pb.avail():
            k = min(pb.avail(), sum(ln for _, ln in iovs))
            if peek:  # MSG_PEEK: leave the data in place
                self._scatter(iovs, pb.peek(k))
                return k
            self._scatter(iovs, pb.take(k))
            pb.wake()  # writers may have room now
            return k
        if pb.writers == 0:
            return 0  # EOF
        if vs.nonblock:
            return -EAGAIN
        self._cur.waiting = ("pipe_r", vs, iovs, peek)
        self._park_on(pb)
        return _BLOCK

    PIPE_BUF = 4096  # POSIX atomicity bound for pipe writes

    def _wbuf(self, vs: VSocket):
        return vs.pipe_out if vs.kind == "spair" else vs.pipe

    def _park_on(self, pb: PipeBuf, th: GuestThread = None) -> None:
        """Park a thread (default: the current one) on a pipe; ring pipes
        flag the shared header so the shim marks local ops dirty for the
        wake scan."""
        pb.waiting.append((self, th if th is not None else self._cur))
        pb.set_waiters(True)

    def _pipe_write(self, vs: VSocket, data: bytes):
        pb = self._wbuf(vs)
        if pb is None:  # SHUT_WR half of a shutdown socketpair
            return -EPIPE
        if pb.readers == 0:
            return -EPIPE
        room = pb.room()
        atomic = len(data) <= self.PIPE_BUF  # never split small writes
        if room <= 0 or (atomic and room < len(data)):
            if vs.nonblock:
                return -EAGAIN
            self._cur.waiting = ("pipe_w", vs, data, 0)
            self._park_on(pb)
            return _BLOCK
        k = min(room, len(data))
        pb.append_bytes(data[:k])
        pb.wake()
        if k == len(data) or vs.nonblock:
            return k  # nonblocking large writes may be short, as on Linux
        # blocking write(2) returns only once ALL bytes are transferred
        self._cur.waiting = ("pipe_w", vs, data[k:], k)
        self._park_on(pb)
        return _BLOCK

    def _pipe_retry(self, th: GuestThread, w) -> None:
        """Re-attempt a parked pipe op (called from PipeBuf.wake)."""
        if w[0] == "sendfile":
            r = self._sendfile(w[2], th=th)
            if r is not _BLOCK:
                self._resume(th, r)
            return
        if w[0] == "splice":
            r = self._splice(w[2], w[3], th=th)
            if r is not _BLOCK:
                self._resume(th, r)
            return
        vs = w[1]
        pb = vs.pipe
        if w[0] == "pipe_r":
            if pb.avail():
                k = min(pb.avail(), sum(ln for _, ln in w[2]))
                if len(w) > 3 and w[3]:  # MSG_PEEK leaves the data
                    self._scatter(w[2], pb.peek(k))
                else:
                    self._scatter(w[2], pb.take(k))
                    pb.wake()
                self._resume(th, k)
            elif pb.writers == 0:
                self._resume(th, 0)
            else:
                self._park_on(pb, th)
            return
        data, done = w[2], w[3]
        pb = self._wbuf(vs)
        if pb.readers == 0:
            self._resume(th, done if done else -EPIPE)
            return
        room = pb.room()
        atomic = done == 0 and len(data) <= self.PIPE_BUF
        if room <= 0 or (atomic and room < len(data)):
            self._park_on(pb, th)
            return
        k = min(room, len(data))
        pb.append_bytes(data[:k])
        if k == len(data):
            self._resume(th, done + k)
        else:
            th.waiting = ("pipe_w", vs, data[k:], done + k)
            pb.waiting.append((self, th))
        pb.wake()

    # -- futex emulation (reference analog: syscall handler futex family;
    #    required so lock handoffs between parked threads cannot deadlock
    #    the strict turn-taking protocol) ----------------------------------
    def _futex(self, args):
        uaddr, val = args[0], args[2] & 0xFFFFFFFF
        op = args[1] & 0x7F
        abs_realtime = bool(args[1] & FUTEX_CLOCK_REALTIME)
        if op in (FUTEX_WAIT, FUTEX_WAIT_BITSET):
            cur = struct.unpack("<I", self.mem.read(uaddr, 4))[0]
            if cur != val:
                return -EAGAIN
            mask = (args[5] & 0xFFFFFFFF if op == FUTEX_WAIT_BITSET
                    else FUTEX_BITSET_ALL)
            if mask == 0:
                return -EINVAL
            th = self._cur
            token = object()
            if args[3]:  # timeout pointer
                sec, nsec = struct.unpack("<qq", self.mem.read(args[3], 16))
                t = sec * NS_PER_SEC + nsec
                # WAIT: relative. WAIT_BITSET: absolute against
                # CLOCK_MONOTONIC (origin = sim start) unless
                # FUTEX_CLOCK_REALTIME selects the epoch clock
                if op == FUTEX_WAIT_BITSET or abs_realtime:
                    base = (emulated(self.host.now) if abs_realtime
                            else self.host.now)
                    delay = max(0, t - base)
                else:
                    delay = max(0, t)

                def fire():
                    w = th.waiting
                    if w and w[0] == "futex" and w[1] is token:
                        # w[2], not the original uaddr: a REQUEUE may have
                        # moved this waiter to another queue since parking
                        self._futex_remove(w[2], th)
                        self._resume(th, -ETIMEDOUT)

                self.host.schedule_in(delay, fire)
            th.waiting = ("futex", token, uaddr)
            self.futexes.setdefault(uaddr, []).append((th, mask))
            return _BLOCK
        if op in (FUTEX_WAKE, FUTEX_WAKE_BITSET):
            mask = (args[5] & 0xFFFFFFFF if op == FUTEX_WAKE_BITSET
                    else FUTEX_BITSET_ALL)
            return self._futex_wake(uaddr, args[2], mask)
        if op in (FUTEX_REQUEUE, FUTEX_CMP_REQUEUE):
            if op == FUTEX_CMP_REQUEUE:
                cur = struct.unpack("<I", self.mem.read(uaddr, 4))[0]
                if cur != (args[5] & 0xFFFFFFFF):
                    return -EAGAIN
            woken = self._futex_wake(uaddr, args[2], FUTEX_BITSET_ALL)
            moved = 0
            q = self.futexes.get(uaddr, [])
            dst = self.futexes.setdefault(args[4], [])
            while q and moved < args[3]:  # timeout slot doubles as val2
                t, m = q.pop(0)
                if t.waiting and t.waiting[0] == "futex":
                    # retag so timeouts/removals target the new queue
                    t.waiting = ("futex", t.waiting[1], args[4])
                dst.append((t, m))
                moved += 1
            if not q:
                self.futexes.pop(uaddr, None)
            return woken + (moved if op == FUTEX_CMP_REQUEUE else 0)
        if op == FUTEX_WAKE_OP:
            enc, uaddr2 = args[5], args[4]
            o, cmp = (enc >> 28) & 0xF, (enc >> 24) & 0xF
            oparg, cmparg = (enc >> 12) & 0xFFF, enc & 0xFFF
            if o & 8:  # FUTEX_OP_OPARG_SHIFT
                oparg = 1 << (oparg & 31)
            o &= 7
            old = struct.unpack("<I", self.mem.read(uaddr2, 4))[0]
            new = {0: oparg, 1: old + oparg, 2: old | oparg,
                   3: old & ~oparg, 4: old ^ oparg}.get(o, old)
            self.mem.write(uaddr2, struct.pack("<I", new & 0xFFFFFFFF))
            woken = self._futex_wake(uaddr, args[2], FUTEX_BITSET_ALL)
            hit = {0: old == cmparg, 1: old != cmparg, 2: old < cmparg,
                   3: old <= cmparg, 4: old > cmparg,
                   5: old >= cmparg}.get(cmp, False)
            if hit:
                woken += self._futex_wake(uaddr2, args[3], FUTEX_BITSET_ALL)
            return woken
        return -ENOSYS  # PI / robust futexes: not modeled

    def _futex_wake(self, uaddr: int, nmax: int, mask: int) -> int:
        q = self.futexes.get(uaddr)
        if not q:
            return 0
        woken, i = 0, 0
        while i < len(q) and woken < nmax:
            th, m = q[i]
            if (m & mask) and not th.dead:
                q.pop(i)
                woken += 1
                self._resume(th, 0)
            else:
                i += 1
        if not q:
            self.futexes.pop(uaddr, None)
        return woken

    def _futex_remove(self, uaddr: int, th: GuestThread) -> None:
        q = self.futexes.get(uaddr)
        if q:
            q[:] = [(t, m) for (t, m) in q if t is not th]
            if not q:
                self.futexes.pop(uaddr, None)

    def _trace(self, nr: int, args, ret) -> None:
        if self._strace is None:
            return
        ts = f"{self.host.now} " if self._strace_times else ""
        if self._cur is not None and self._cur.slot:
            ts += f"[t{self._cur.slot}] "
        if nr < 0:
            self._strace.write(f"{ts}{ret}\n")
        else:
            # deterministic mode omits raw args: they carry ASLR'd guest
            # pointers that legitimately differ between runs
            a = ",".join(hex(x) for x in args) if self._strace_times else "..."
            self._strace.write(f"{ts}syscall_{nr}({a}) = {ret}\n")

    def _exited(self) -> None:
        if self.proc is None and self.real_pid is None:
            return
        if not self.running:
            return
        if self.proc is not None:
            code = self.proc.wait()
            if code < 0 and self._exit_hint is not None:
                # exit_group path: the shim raw-exits / worker SIGKILLs,
                # but the TRUE code was captured at the trap
                code = self._exit_hint
            if code < 0 and self._signal_hint is not None:
                code = self._signal_hint  # the signal the guest was sent
        else:
            # adopted (forked) guest: not our OS child, no waitpid — the
            # captured exit_group code is authoritative; EOF without it
            # means a signal death (attributed when an emulated kill sent it)
            if self._exit_hint is not None:
                code = self._exit_hint
            elif self._signal_hint is not None:
                code = self._signal_hint
            else:
                code = -9
        if self.audit_native:
            # the reality boundary, surfaced (VERDICT r2 item #5): which
            # syscalls this guest ran against the HOST kernel
            self.host.log(
                f"{self.name}: {len(self.audit_native)} unemulated "
                f"syscalls ran natively: {sorted(self.audit_native)}")
        if self.native_vfs:
            # default-on flavor (VERDICT r3 item #7): numbers the worker
            # explicitly re-issued natively (virtual-FS policy and
            # unemulated trapped calls), observed in EVERY run
            self.host.log(
                f"{self.name}: guest used {len(self.native_vfs)} "
                f"native-passthrough syscalls: {sorted(self.native_vfs)}")
        if self._strace is not None:
            if self.audit_native:
                self._strace.write(
                    f"+++ native passthrough: {sorted(self.audit_native)} "
                    "+++\n")
            self._strace.write(f"+++ exited with {code} +++\n")
        self._teardown()
        if self._journal is not None:
            # terminal: exit_code is about to be set, so this record can
            # never respawn (Host.reboot skips exited processes) — the
            # journal stream is complete
            self._journal.close()
        self.finish(code)
        if (self.parent_proc is not None and self.parent_proc.running):
            self.parent_proc._child_exited(self)

    def _teardown(self) -> None:
        """Release every worker-side runtime handle of the current guest
        life: capture files, strace stream, fd-table references, thread
        channels, embryo channels, the IPC socket. Shared by ``_exited``
        (process death — exit accounting follows) and ``kill`` (host
        crash — no exit status, the record stays respawnable)."""
        if self._strace is not None:
            self._strace.close()
            self._strace = None
        for f in self._files.values():
            f.close()
        self._files.clear()
        for vs in list(self.fds.values()):  # one ref per table entry
            self._close_vs(vs)
        self.fds.clear()
        for th in self.threads.values():
            th.dead = True
            if th.sock is not None and th.sock is not self.sock:
                th.sock.close()
                th.sock = None
        self._ready.clear()
        self.futexes.clear()
        for s in self._embryos.values():  # forks that never committed
            s.close()
        self._embryos.clear()
        if self.sock is not None:
            self.sock.close()
            self.sock = None

    def kill(self) -> None:
        """Host crash (shadow_tpu/faults.py host_down/churn, live
        ``host_down``): the guest dies with its host. SIGKILL + reap the
        real OS process, release every worker-side handle, and record NO
        exit status — in the simulated world the host lost power, the
        process neither exited nor was signaled (the same contract as
        PluginProcess.kill), so ``Host.reboot`` respawns a fresh instance
        via spawn(). Deterministic: crashes apply at round boundaries,
        where every guest is parked between turns, and ``Host.crash`` has
        already torn down the transport side before processes are killed
        (endpoint.close on a crashed endpoint is a no-op)."""
        if not self.running:
            return
        self._kill_now()
        if self.proc is not None:
            self.proc.wait()  # reap now: the zombie pid was ours until here
            self.proc = None
        if self._strace is not None:
            self._strace.write("+++ killed: host crash +++\n")
        self._teardown()
        self.running = False
        if self.parent_proc is not None:
            # a fork child dies for good with its host: the rebooted
            # PARENT re-forks deterministically, so this record must not
            # respawn as a fresh top-level guest — record the signal
            # death (exit_code set directly: nothing "exited" in the
            # simulated world, so no processes_exited accounting)
            self.exit_code = -9

    # -- syscall emulation -------------------------------------------------
    def _service(self, nr: int, args):
        h = self.host
        if nr == SYS_write:
            fd, addr, n = args[0], args[1], args[2]
            # a dup2'd vfd on 0/1/2 takes precedence over stdio capture
            if fd in (1, 2) and fd not in self.fds:
                data = self.mem.read(addr, min(n, 1 << 20))
                self._capture(fd).write(data)
                return len(data)
            vs = self.fds.get(fd)
            if vs is not None and vs.kind == "event":
                if n < 8:
                    return -EINVAL
                val = struct.unpack("<Q", self.mem.read(addr, 8))[0]
                vs.evt_counter += val
                th, w = self._find_waiter((("cread",), vs))
                if th is not None:
                    self._resume(th, self._counter_read(vs, w[2], w[3]))
                else:
                    self._notify()
                return 8
            if vs is not None and vs.kind in ("pipe_w", "spair"):
                ret = self._pipe_write(
                    vs, self.mem.read(addr, min(n, 1 << 20)))
                return self._maybe_offer_ring(fd, vs, 1, ret)
            if vs is not None and vs.kind == "pipe_r":
                return -EBADF  # write on the read end
            if vs is not None and vs.kind in ("file", "dir"):
                return self.vfs.write(vs, self.mem.read(addr, min(n, 1 << 20)))
            return self._maybe_offer_sock(fd, self._vfd_send(fd, addr, n))
        if nr == SYS_read:
            if args[0] == 0 and 0 not in self.fds:
                return 0  # stdin: EOF (unless a vfd was dup2'd onto it)
            vs = self.fds.get(args[0])
            if vs is not None and vs.kind in ("file", "dir"):
                data = self.vfs.read(vs, min(args[2], 1 << 20))
                if isinstance(data, int):
                    return data
                self.mem.write(args[1], data)
                return len(data)
            if vs is not None and vs.kind in ("timer", "event"):
                return self._counter_read(vs, args[1], args[2])
            if vs is not None and vs.kind == "sigfd":
                return self._sigfd_read(vs, args[1], args[2])
            if vs is not None and vs.kind == "inotify":
                return self._ino_read(vs, args[1], args[2])
            if vs is not None and vs.kind in ("pipe_r", "spair"):
                ret = self._pipe_read(vs, [(args[1], args[2])])
                return self._maybe_offer_ring(args[0], vs, 0, ret)
            if vs is not None and vs.kind == "pipe_w":
                return -EBADF  # read on the write end
            return self._maybe_offer_sock(
                args[0], self._vfd_recv(args[0], args[1], args[2]))
        if nr == SYS_close:
            if IPC_LOW <= args[0] <= SHIM_IPC_FD:
                # a guest sweeping "all fds" (subprocess close_fds) must
                # not sever its own management channels; pretend success
                return 0
            vs = self.fds.pop(args[0], None)
            if vs is None:
                return -EBADF
            self.fd_cloexec.discard(args[0])
            self._ring_offered.discard((args[0], 0))  # fd may be reused
            self._ring_offered.discard((args[0], 1))
            self._drop_fast_fd(args[0])
            self._close_vs(vs)
            return 0
        if nr == SYS_clock_gettime:
            if args[0] == 2**64 - 1:  # shim slow-path sentinel: raw ns
                return emulated(h.now)
            # monotonic/cputime-family clock ids originate at boot == sim
            # start; realtime family stays epoch-based — matching the
            # shim's libc interposition and sysinfo's uptime
            ns = h.now if args[0] in MONO_CLOCKS else emulated(h.now)
            self.mem.write(args[1], struct.pack(
                "<qq", ns // NS_PER_SEC, ns % NS_PER_SEC))
            return 0
        if nr == SYS_gettimeofday:
            if args[0]:
                ns = emulated(h.now)
                self.mem.write(args[0], struct.pack(
                    "<qq", ns // NS_PER_SEC, (ns % NS_PER_SEC) // 1000))
            return 0
        if nr == SYS_time:
            secs = emulated(h.now) // NS_PER_SEC
            if args[0]:
                self.mem.write(args[0], struct.pack("<q", secs))
            return secs
        if nr in (SYS_nanosleep, SYS_clock_nanosleep):
            ts_addr = args[0] if nr == SYS_nanosleep else args[2]
            sec, nsec = struct.unpack("<qq", self.mem.read(ts_addr, 16))
            dur = sec * NS_PER_SEC + nsec
            if nr == SYS_clock_nanosleep and args[1] & TIMER_ABSTIME:
                # absolute deadline in the REQUESTED clock's base:
                # monotonic family originates at sim start
                base = h.now if args[0] in MONO_CLOCKS else emulated(h.now)
                dur = max(0, sec * NS_PER_SEC + nsec - base)
            self._waiting = ("sleep",)
            th = self._cur
            h.schedule_in(max(dur, 0), lambda: self._resume(th, 0))
            return _BLOCK
        if nr == SYS_getrandom:
            n = min(args[1], 1 << 16)
            self.mem.write(args[0], h.rng.bytes(n))
            return n
        if nr == SYS_socket:
            domain, typ = args[0], args[1] & 0xFF
            if domain != socket.AF_INET or typ not in (socket.SOCK_STREAM,
                                                       socket.SOCK_DGRAM):
                return -EAFNOSUPPORT
            vfd = self._next_vfd
            self._next_vfd += 1
            kind = "stream" if typ == socket.SOCK_STREAM else "dgram"
            vs = VSocket(vfd, kind)
            if args[1] & 0o4000:  # SOCK_NONBLOCK
                vs.nonblock = True
            if args[1] & O_CLOEXEC:  # SOCK_CLOEXEC
                self.fd_cloexec.add(vfd)
            self.fds[vfd] = vs
            return vfd
        if nr == SYS_connect:
            return self._connect(args[0], args[1], args[2])
        if nr == SYS_sendto:
            vs = self.fds.get(args[0])
            if vs is not None and vs.kind == "dgram":
                return self._dgram_sendto(vs, args)
            return self._maybe_offer_sock(
                args[0], self._vfd_send(args[0], args[1], args[2]))
        if nr == SYS_recvfrom:
            vs = self.fds.get(args[0])
            if vs is not None and vs.kind == "dgram":
                return self._dgram_recvfrom(vs, args,
                                            peek=bool(args[3] & 2))
            return self._maybe_offer_sock(args[0], self._vfd_recv(
                args[0], args[1], args[2], peek=bool(args[3] & 2)))
        if nr == SYS_shutdown:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            if vs.kind == "spair":
                how = args[1]
                if how in (1, 2) and vs.pipe_out is not None:  # SHUT_WR
                    pb, vs.pipe_out = vs.pipe_out, None
                    pb.w_end = None  # writers -> 0: peer reads see EOF
                    pb.wake()
                if how in (0, 2) and vs.pipe is not None:  # SHUT_RD
                    pb, vs.pipe = vs.pipe, None
                    pb.r_end = None  # readers -> 0: peer writes see EPIPE
                    pb.wake()
                return 0
            if vs.endpoint is not None:
                if vs.sockring is not None:
                    # full close of the connection: every alias mapping
                    # (the shim only dropped THIS fd's) must stop serving
                    vs.sockring.kill()
                vs.endpoint.close()
            return 0
        if nr in (SYS_setsockopt,):
            return 0
        if nr == SYS_getsockopt:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            err = 0
            if args[1] == 1 and args[2] == 4:  # SOL_SOCKET, SO_ERROR
                err = vs.connect_err
                vs.connect_err = 0  # SO_ERROR reads clear the error
            if args[3] and args[4]:
                self.mem.write(args[3], struct.pack("<i", err))
                self.mem.write(args[4], struct.pack("<i", 4))
            return 0
        if nr in (SYS_getsockname, SYS_getpeername):
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            vs = self.fds[args[0]]
            port = vs.endpoint.local_port if vs.endpoint is not None else 0
            sa = (struct.pack("<H", socket.AF_INET)
                  + struct.pack(">H", port)
                  + socket.inet_aton(h.ip) + b"\0" * 8)
            if args[1] and args[2]:
                self.mem.write(args[1], sa)
                self.mem.write(args[2], struct.pack("<i", len(sa)))
            return 0
        if nr == SYS_bind:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            raw = self.mem.read(args[1], min(max(args[2], 16), 128))
            vs.bound_port = struct.unpack_from(">H", raw, 2)[0]
            if vs.kind == "dgram":
                return self._dgram_bind(vs)
            return 0
        if nr == SYS_listen:
            return self._listen(args[0])
        if nr in (SYS_accept, SYS_accept4):
            flags = args[3] if nr == SYS_accept4 else 0
            return self._accept(args[0], args[1], args[2], flags)
        if nr in (SYS_poll, SYS_ppoll):
            return self._poll(args[0], args[1], args[2], nr == SYS_ppoll)
        if nr in (SYS_select, SYS_pselect6):
            return self._select(args, nr == SYS_pselect6)
        if nr in (SYS_epoll_create, SYS_epoll_create1):
            vfd = self._next_vfd
            self._next_vfd += 1
            self.fds[vfd] = VSocket(vfd, "epoll")
            if nr == SYS_epoll_create1 and args[0] & O_CLOEXEC:
                self.fd_cloexec.add(vfd)
            return vfd
        if nr == SYS_epoll_ctl:
            return self._epoll_ctl(args[0], args[1], args[2], args[3])
        if nr in (SYS_epoll_wait, SYS_epoll_pwait):
            return self._epoll_wait(args[0], args[1], args[2], args[3])
        if nr == SYS_fcntl:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            cmd = args[1]
            if cmd == F_GETFL:
                return 0o2 | (O_NONBLOCK if vs.nonblock else 0)  # O_RDWR
            if cmd == F_SETFL:
                vs.nonblock = bool(args[2] & O_NONBLOCK)
                return 0
            if cmd == F_GETFD:
                return 1 if args[0] in self.fd_cloexec else 0
            if cmd == F_SETFD:
                if args[2] & 1:  # FD_CLOEXEC
                    self.fd_cloexec.add(args[0])
                else:
                    self.fd_cloexec.discard(args[0])
                return 0
            if cmd in (0, 1030):  # F_DUPFD / F_DUPFD_CLOEXEC
                newfd = self._dup(args[0], None)
                if newfd >= 0 and cmd == 1030:
                    self.fd_cloexec.add(newfd)
                return newfd
            return 0  # other fcntl cmds: benign
        if nr == SYS_ioctl:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            if args[1] == FIONBIO:
                flag = struct.unpack("<i", self.mem.read(args[2], 4))[0]
                vs.nonblock = bool(flag)
                return 0
            if args[1] == FIONREAD:
                if vs.kind in ("pipe_r", "spair"):
                    avail = vs.pipe.avail() if vs.pipe is not None else 0
                elif vs.kind == "stream":
                    avail = len(vs.rxbuf)
                else:
                    avail = vs.dgram_q[0][1] if vs.dgram_q else 0
                self.mem.write(args[2], struct.pack("<i", avail))
                return 0
            return 0
        if nr == SYS_getpid:
            return self.vpid
        if nr == SYS_gettid:
            return self.vpid
        if nr == SYS_getppid:
            return 1  # the "init" of the simulated world
        if nr == SYS_timerfd_create:
            vfd = self._next_vfd
            self._next_vfd += 1
            vs = VSocket(vfd, "timer")
            vs.timer_clock = args[0] & 0xFFFFFFFF  # clockid: deadline base
            self.fds[vfd] = vs
            if args[1] & 0o2000000:  # TFD_CLOEXEC
                self.fd_cloexec.add(vfd)
            return vfd
        if nr == SYS_timerfd_settime:
            return self._timerfd_settime(args[0], args[1], args[2], args[3])
        if nr == SYS_timerfd_gettime:
            vs = self.fds.get(args[0])
            if vs is None or vs.kind != "timer":
                return -EBADF
            tnow = (h.now if vs.timer_clock in MONO_CLOCKS
                    else emulated(h.now))
            left = max(vs.deadline - tnow, 0) if vs.timer_handle else 0
            self.mem.write(args[1], struct.pack(
                "<qqqq", vs.interval_ns // NS_PER_SEC,
                vs.interval_ns % NS_PER_SEC,
                left // NS_PER_SEC, left % NS_PER_SEC))
            return 0
        if nr in (SYS_eventfd, SYS_eventfd2):
            vfd = self._next_vfd
            self._next_vfd += 1
            vs = VSocket(vfd, "event")
            vs.evt_counter = args[0]
            if nr == SYS_eventfd2 and args[1] & 0o4000:  # EFD_NONBLOCK
                vs.nonblock = True
            if nr == SYS_eventfd2 and args[1] & O_CLOEXEC:  # EFD_CLOEXEC
                self.fd_cloexec.add(vfd)
            self.fds[vfd] = vs
            return vfd
        if nr == SYS_sendmsg:
            return self._sendmsg(args[0], args[1])
        if nr == SYS_recvmsg:
            return self._recvmsg(args[0], args[1],
                                 peek=bool(args[2] & 2))  # MSG_PEEK
        if nr == SYS_writev:
            return self._writev(args[0], args[1], args[2])
        if nr == SYS_readv:
            return self._readv(args[0], args[1], args[2])
        if nr == HELLO:
            # mid-life HELLO == the guest execve'd a new image: same process
            # record and channel, fresh shim. The kernel killed any sibling
            # threads at exec; reap their records. vfds survive (exec keeps
            # fds), as do stdout/stderr captures and the strace stream.
            cur = self._cur
            for t in list(self.threads.values()):
                if t is not cur and not t.dead:
                    t.retval = 0
                    self._thread_gone(t)
                if t is not cur:
                    t.joined = True  # kernel-reaped at exec: recyclable
            for fd in sorted(self.fd_cloexec):  # FD_CLOEXEC semantics
                vs = self.fds.pop(fd, None)
                if vs is not None:
                    self._close_vs(vs)
            self.fd_cloexec.clear()
            self.host.counters.add("execs", 1)
            return 0  # the reply is the new image's first turn grant
        if nr == SPAWN_THREAD:
            return self._spawn_thread()
        if nr == THREAD_HELLO:
            return 0  # the reply itself is this thread's first turn grant
        if nr == THREAD_JOIN:
            return self._join_thread(args[0])
        if nr == THREAD_EXIT:
            th = self._cur
            # retval crosses the wire as int64 (negative-encoded pointers
            # like (void*)-1 are common); store it reply-ready
            th.retval = (args[0] - (1 << 64) if args[0] >= (1 << 63)
                         else args[0])
            self._thread_gone(th)
            return _DETACH
        if nr == SYS_futex:
            return self._futex(args)
        if nr == FORK_INTENT:
            return self._fork_intent()
        if nr == FORK_COMMIT:
            return self._fork_commit(args[0], args[1])
        if nr == AUDIT_NOTE:
            # reality boundary (experimental.native_audit): the shim passed
            # an unemulated syscall through to the host kernel; record the
            # number (once per number per process)
            self.audit_native.add(int(args[0]))
            self.host.counters.add("audit_native_syscalls", 1)
            if self._strace is not None:
                self._strace.write(
                    f"native-passthrough first use: syscall_{args[0]}\n")
            return 0
        if nr == RESOLVE:
            # simulated name resolution (shim-interposed getaddrinfo):
            # config host names map to their simulated IPv4
            name = self._read_cstr(args[0])
            if name is not None:
                ctl = self.host.controller
                hid = ctl._by_name.get(name)
                if hid is not None:
                    return int.from_bytes(
                        socket.inet_aton(ctl.hosts[hid].ip), "big")
            return -1  # unknown: the shim falls through to the real resolver
        if nr == SYS_wait4:
            return self._wait4(args)
        if nr == SYS_kill:
            return self._kill(args)
        if nr == SYS_sched_getaffinity:
            # deterministic virtual CPU count: guests sizing thread pools
            # by core count behave identically on every real machine (and
            # stay inside the 31-thread channel window)
            size = min(args[1], 128)
            if size < 8 or size % 8:  # kernel: multiple of sizeof(long)
                return -EINVAL
            mask = ((1 << SIM_CPUS) - 1).to_bytes(8, "little")
            self.mem.write(args[2], mask + b"\0" * (size - 8))
            return 8  # kernel returns the mask size it wrote
        if nr == SYS_sysinfo:
            # deterministic virtual machine: 2 GB RAM, sim uptime
            si = bytearray(112)  # sizeof(struct sysinfo) on x86-64
            struct.pack_into("<q", si, 0, emulated(h.now) // NS_PER_SEC)
            struct.pack_into("<QQ", si, 32,
                             2 << 30, (2 << 30) - (256 << 20))
            struct.pack_into("<H", si, 80, 1)  # procs
            struct.pack_into("<I", si, 104, 1)  # mem_unit = 1 byte
            self.mem.write(args[0], bytes(si))
            return 0
        if nr == SYS_getrusage:
            # sim-time resource usage: utime = simulated elapsed, the rest
            # zero (per-process CPU accounting is not modeled)
            ru = bytearray(144)  # struct rusage
            ns = emulated(h.now)
            struct.pack_into("<qq", ru, 0, ns // NS_PER_SEC,
                             (ns % NS_PER_SEC) // 1000)
            self.mem.write(args[1], bytes(ru))
            return 0
        if nr == SYS_times:
            # clock ticks (100/s) of SIM time; per-process CPU split is
            # not modeled — report elapsed in utime, zeros elsewhere
            ticks = emulated(h.now) * 100 // NS_PER_SEC
            if args[0]:
                self.mem.write(args[0], struct.pack("<qqqq", ticks, 0, 0, 0))
            return ticks & 0x7FFFFFFFFFFFFFFF
        if nr == SYS_clock_getres:
            if args[1]:
                self.mem.write(args[1], struct.pack("<qq", 0, 1))  # 1 ns
            return 0
        if nr == SYS_uname:
            # identity virtualization: nodename is the SIMULATED host name
            # (gethostname() routes through uname in glibc)
            u = os.uname()
            buf = b"".join(
                s.encode()[:64].ljust(65, b"\0")
                for s in ("Linux", self.host.name, u.release, u.version,
                          u.machine, ""))
            self.mem.write(args[0], buf)
            return 0
        if nr == SYS_exit_group:
            # record the true exit code; _pump then replies, SIGKILLs the
            # process synchronously (sibling threads must not outlive an
            # exit_group, and the pid is still ours — unreaped), and reaps
            self._exit_hint = args[0] & 0xFF
            return _EXITGROUP
        if nr in (SYS_pipe, SYS_pipe2):
            return self._pipe(args[0], args[1] if nr == SYS_pipe2 else 0)
        if nr == SYS_socketpair:
            return self._socketpair(args)
        if nr == SYS_sendfile:
            return self._sendfile(args)
        if nr == SYS_sigaltstack:
            return self._sigaltstack(self._cur, args)
        if nr in (SYS_getrlimit, SYS_setrlimit, SYS_prlimit64):
            return self._rlimit(nr, args)
        if nr in (SYS_signalfd, SYS_signalfd4):
            return self._signalfd(args, nr == SYS_signalfd4)
        if nr in (SYS_splice, SYS_tee):
            return self._splice(args, nr == SYS_tee)
        if nr in (SYS_inotify_init, SYS_inotify_init1):
            return self._inotify_init(args[0] if nr == SYS_inotify_init1
                                      else 0)
        if nr == SYS_inotify_add_watch:
            return self._inotify_add(args)
        if nr == SYS_inotify_rm_watch:
            return self._inotify_rm(args)
        if nr == SYS_close_range:
            # close the range's VFDS only; real fds — including the shim's
            # reserved IPC window — survive (the guest can't be allowed to
            # sever its own management channel; leaked real fds are benign
            # under the sim). CLOSE_RANGE_CLOEXEC degrades to close.
            lo, hi = args[0], min(args[1], 1 << 62)
            if args[2] & 4:  # CLOSE_RANGE_CLOEXEC: mark, don't close
                self.fd_cloexec.update(
                    f for f in self.fds if lo <= f <= hi)
                return 0
            for fd in [f for f in self.fds if lo <= f <= hi]:
                self.fd_cloexec.discard(fd)
                self._ring_offered.discard((fd, 0))
                self._ring_offered.discard((fd, 1))
                self._close_vs(self.fds.pop(fd))
            return 0
        if nr == SYS_mmap:
            return self._mmap_vfd(args)
        if nr == SYS_fstat:
            return self._fstat(args[0], args[1])
        if nr == SYS_newfstatat:
            return self.vfs.statat(_sfd(args[0]), args[1], args[2],
                                   args[3])
        if nr == SYS_lseek:
            vs = self.fds.get(args[0])
            if vs is not None and vs.kind in ("file", "dir"):
                return self.vfs.lseek(vs, args[1], args[2])
            return -29 if args[0] in self.fds else -EBADF  # ESPIPE
        if nr == SYS_pread64:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            if vs.kind not in ("file", "dir"):
                return -29  # ESPIPE
            data = self.vfs.pread(vs, min(args[2], 1 << 20), _sfd(args[3]))
            if isinstance(data, int):
                return data
            self.mem.write(args[1], data)
            return len(data)
        if nr == SYS_pwrite64:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            if vs.kind not in ("file", "dir"):
                return -29  # ESPIPE
            return self.vfs.pwrite(
                vs, self.mem.read(args[1], min(args[2], 1 << 20)),
                _sfd(args[3]))
        if nr in (SYS_open, SYS_creat):
            flags = (0o1101 if nr == SYS_creat  # O_WRONLY|O_CREAT|O_TRUNC
                     else args[1])
            return self.vfs.openat(AT_FDCWD, args[0], flags, args[2])
        if nr == SYS_openat:
            return self.vfs.openat(_sfd(args[0]), args[1], args[2], args[3])
        if nr in (SYS_stat, SYS_lstat):
            return self.vfs.statat(
                AT_FDCWD, args[0], args[1],
                AT_SYMLINK_NOFOLLOW if nr == SYS_lstat else 0)
        if nr == SYS_statx:
            return self.vfs.statx(_sfd(args[0]), args[1], args[2], args[4])
        if nr == SYS_access:
            return self.vfs.access(AT_FDCWD, args[0], args[1])
        if nr in (SYS_faccessat, SYS_faccessat2):
            return self.vfs.access(_sfd(args[0]), args[1], args[2])
        if nr == SYS_unlink:
            return self.vfs.unlinkat(AT_FDCWD, args[0], 0)
        if nr == SYS_rmdir:
            return self.vfs.unlinkat(AT_FDCWD, args[0], AT_REMOVEDIR)
        if nr == SYS_unlinkat:
            return self.vfs.unlinkat(_sfd(args[0]), args[1], args[2])
        if nr == SYS_mkdir:
            return self.vfs.mkdirat(AT_FDCWD, args[0], args[1])
        if nr == SYS_mkdirat:
            return self.vfs.mkdirat(_sfd(args[0]), args[1], args[2])
        if nr == SYS_rename:
            return self.vfs.renameat(AT_FDCWD, args[0], AT_FDCWD, args[1])
        if nr in (SYS_renameat, SYS_renameat2):
            if nr == SYS_renameat2 and args[4]:
                return -EINVAL  # RENAME_* flags not modeled
            return self.vfs.renameat(_sfd(args[0]), args[1],
                                     _sfd(args[2]), args[3])
        if nr == SYS_readlink:
            return self.vfs.readlinkat(AT_FDCWD, args[0], args[1], args[2])
        if nr == SYS_readlinkat:
            return self.vfs.readlinkat(_sfd(args[0]), args[1], args[2],
                                       args[3])
        if nr == SYS_chdir:
            return self.vfs.chdir(args[0])
        if nr == SYS_fchdir:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            return self.vfs.fchdir(vs)
        if nr == SYS_getcwd:
            return self.vfs.getcwd(args[0], args[1])
        if nr == SYS_truncate:
            return self.vfs.truncate(args[0], args[1])
        if nr == SYS_ftruncate:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            return self.vfs.ftruncate(vs, args[1])
        if nr in (SYS_fsync, SYS_fdatasync):
            return 0 if args[0] in self.fds else -EBADF
        if nr == SYS_getdents64:
            vs = self.fds.get(args[0])
            if vs is None:
                return -EBADF
            if vs.kind != "dir":
                return -20  # ENOTDIR
            data = self.vfs.getdents64(vs, min(args[2], 1 << 16))
            if isinstance(data, int):
                return data
            self.mem.write(args[1], data)
            return len(data)
        if nr == SYS_dup:
            return self._dup(args[0], None)
        if nr in (SYS_dup2, SYS_dup3):
            if args[0] not in self.fds:
                # REAL source fd: the kernel will do the dup — but a dup2
                # onto a number we map virtually (a shell restoring its
                # saved stdout) must drop our mapping first, or writes to
                # that number keep landing in the old virtual file
                vs = self.fds.pop(args[1], None)
                if vs is not None:
                    self.fd_cloexec.discard(args[1])
                    self._close_vs(vs)
                return RETRY_NATIVE
            if args[0] == args[1]:
                # dup2(x, x): POSIX no-op; dup3 must fail (Linux EINVAL)
                return -EINVAL if nr == SYS_dup3 else args[1]
            r = self._dup(args[0], args[1])
            if r >= 0 and nr == SYS_dup3 and args[2] & O_CLOEXEC:
                self.fd_cloexec.add(r)
            return r
        if nr == SYS_execve:
            return self._do_exec(args)
        if nr in (SYS_clone, SYS_fork, SYS_vfork, SYS_clone3):
            # CLONE_THREAD clones run natively; fork-style clones are
            # executed SHIM-side (FORK_INTENT/COMMIT protocol) and never
            # reach here; vfork (shared-VM) stays rejected
            return -ENOSYS
        return -ENOSYS

    # -- readiness (poll/epoll) --------------------------------------------
    def _readable(self, vs: VSocket) -> bool:
        if vs.kind == "timer":
            return vs.expirations > 0
        if vs.kind == "event":
            return vs.evt_counter > 0
        if vs.kind == "sigfd":
            return bool(vs.sig_q)
        if vs.kind == "inotify":
            return bool(vs.ino_q)
        if vs.kind in ("pipe_r", "spair"):
            if vs.pipe is None:
                return True  # SHUT_RD: reads return EOF immediately
            return vs.pipe.avail() > 0 or vs.pipe.writers == 0
        if vs.kind == "pipe_w":
            return False
        if vs.kind == "dgram":
            return bool(vs.dgram_q)
        if vs.listening:
            return bool(vs.accept_q)
        return bool(vs.rxbuf) or vs.peer_closed

    def _writable(self, vs: VSocket) -> bool:
        if vs.kind in ("dgram", "event"):
            return True
        if vs.kind == "pipe_w":
            return vs.pipe.room() > 0 or vs.pipe.readers == 0
        if vs.kind == "spair":
            pb = vs.pipe_out
            if pb is None:
                return True  # SHUT_WR: writes fail fast with EPIPE
            return pb.room() > 0 or pb.readers == 0
        if vs.kind == "pipe_r":
            return False
        ep = vs.endpoint
        if ep is None or not vs.connected or vs.peer_closed:
            return bool(vs.connect_err)  # error state is "writable" (POLLERR)
        return ep.sender.buffered < ep.sender.send_buffer

    def _revents(self, vs: VSocket, want: int) -> int:
        r = 0
        if want & POLLIN and self._readable(vs):
            r |= POLLIN
        if want & POLLOUT and self._writable(vs):
            r |= POLLOUT
        if vs.peer_closed:
            r |= POLLHUP
        if vs.connect_err:
            r |= POLLERR
        return r

    def _notify(self) -> None:
        """Some vfd's state changed: re-evaluate every parked poll/epoll."""
        for slot in sorted(self.threads):
            th = self.threads[slot]
            w = th.waiting
            if not w or th.dead:
                continue
            if w[0] == "poll":
                n = self._poll_scan(w[2], w[3])
                if n:
                    self._resume(th, n)
            elif w[0] == "epoll":
                n = self._epoll_scan(w[2], w[3], w[4])
                if n:
                    self._resume(th, n)
            elif w[0] == "select":
                n = self._select_scan(w[2], w[3], w[4], w[5])
                if n:
                    self._select_timeleft(w)
                    self._resume(th, n)

    def _poll_scan(self, entries, fds_ptr) -> int:
        """Write revents for ready entries; returns the ready count."""
        n = 0
        for i, (fd, want) in enumerate(entries):
            if fd < 0:  # poll(2): negative fds are ignored, revents = 0
                r = 0
            else:
                vs = self.fds.get(fd)
                r = (self._revents(vs, want) if vs is not None
                     else 0x20)  # POLLNVAL
            if r:
                n += 1
            self.mem.write(fds_ptr + 8 * i + 6, struct.pack("<h", r))
        return n

    def _epoll_scan(self, ep_vs: VSocket, events_ptr: int, maxev: int) -> int:
        n = 0
        for fd, (want, data) in list(ep_vs.interest.items()):
            vs = self.fds.get(fd)
            if vs is None:
                continue
            r = self._revents(vs, want)
            if r and n < maxev:
                self.mem.write(events_ptr + 12 * n,
                               struct.pack("<I", r) + struct.pack("<Q", data))
                n += 1
        return n

    def _arm_wait_timeout(self, timeout_ns: int):
        token = object()
        if timeout_ns >= 0:
            def fire():
                th, w = self._find_waiter((("poll", "epoll", "select"),
                                           token))
                if th is not None:
                    if w[0] == "select":  # Linux zeroes the sets on timeout
                        self._select_write(w[3], _zeroed_sets(w[4], w[5]),
                                           w[5])
                        self._select_timeleft(w)
                    self._resume(th, 0)

            self.host.schedule_in(timeout_ns, fire)
        return token

    # -- socket bridge -----------------------------------------------------
    def _wire_endpoint(self, vs: VSocket, ep) -> None:
        vs.endpoint = ep
        ep.on_data = lambda n, payload, now: self._on_net_data(vs, n, payload)
        ep.on_close = lambda now: self._on_net_close(vs)
        ep.on_error = lambda msg: self._on_net_error(vs)
        ep.on_drain = lambda room: self._on_drain(vs)
        # flow control sees the guest's unread backlog: a guest that never
        # reads closes the advertised window instead of growing rxbuf
        # without bound (transport.StreamReceiver.window)
        ep.receiver.app_unread = lambda: len(vs.rxbuf)

    def _on_drain(self, vs: VSocket) -> None:
        th, w = self._find_waiter((("send", "smsg", "sendfile"), vs))
        if th is not None:
            if w[0] == "sendfile":
                r = self._sendfile(w[2], th=th)
                if r is not _BLOCK:
                    self._resume(th, r)
                return
            if w[0] == "send":
                data = self.mem.read(w[2], min(w[3], 1 << 20))
            else:
                data = w[2]
            accepted = vs.endpoint.send(payload=data)
            if accepted > 0:
                self._resume(th, accepted)
            return
        self._notify()

    def _listen(self, fd: int):
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        if not vs.bound_port:
            return -EINVAL
        if vs.listening:
            return 0

        def on_accept(ep, now):
            # wire rx buffering IMMEDIATELY: the peer's first data can land
            # before the app calls accept() (SYNACK already went out)
            conn = VSocket(-1)
            conn.connected = True
            self._wire_endpoint(conn, ep)
            th, w = self._find_waiter((("accept",), vs))
            if th is not None:
                self._finish_accept(th, vs, conn, w[2], w[3],
                                    w[4] if len(w) > 4 else 0)
            else:
                vs.accept_q.append(conn)
                self._notify()

        try:
            self.host.listen(vs.bound_port, on_accept)
        except ValueError:
            return -98  # EADDRINUSE
        vs.listening = True
        return 0

    def _accept(self, fd: int, addr: int, addrlen: int, flags: int = 0):
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        if not vs.listening:
            return -EINVAL
        if vs.accept_q:
            return self._do_accept(vs, vs.accept_q.pop(0), addr, addrlen,
                                   flags)
        if vs.nonblock:
            return -EAGAIN
        self._waiting = ("accept", vs, addr, addrlen, flags)
        return _BLOCK

    def _do_accept(self, vs: VSocket, conn: VSocket, addr: int,
                   addrlen: int, flags: int = 0):
        conn.vfd = self._next_vfd
        self._next_vfd += 1
        self.fds[conn.vfd] = conn
        if flags & 0o4000:  # SOCK_NONBLOCK
            conn.nonblock = True
        if flags & O_CLOEXEC:  # SOCK_CLOEXEC
            self.fd_cloexec.add(conn.vfd)
        if addr and addrlen:
            peer = self.host.controller.hosts[conn.endpoint.remote_host]
            sa = (struct.pack("<H", socket.AF_INET)
                  + struct.pack(">H", conn.endpoint.remote_port)
                  + socket.inet_aton(peer.ip) + b"\0" * 8)
            self.mem.write(addr, sa)
            self.mem.write(addrlen, struct.pack("<i", len(sa)))
        return conn.vfd

    def _finish_accept(self, th: GuestThread, vs: VSocket, conn: VSocket,
                       addr: int, addrlen: int, flags: int = 0) -> None:
        self._resume(th, self._do_accept(vs, conn, addr, addrlen, flags))

    def _connect(self, fd: int, addr: int, addrlen: int):
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        if vs.endpoint is not None:  # the re-connect completion idiom
            if vs.connect_err:
                return -vs.connect_err
            return -106 if vs.connected else -114  # EISCONN / EALREADY
        raw = self.mem.read(addr, min(max(addrlen, 16), 128))
        family = struct.unpack_from("<H", raw, 0)[0]
        if vs.kind == "dgram":
            # connected UDP (DNS/stub-resolver idiom): record the default
            # peer, filter inbound to it, and return instantly — Linux
            # performs no handshake for SOCK_DGRAM connect(2)
            if family == socket.AF_UNSPEC:  # dissolve the association
                vs.dgram_peer = None
                vs.connected = False
                return 0
            if family != socket.AF_INET:
                return -EAFNOSUPPORT
            port = struct.unpack_from(">H", raw, 2)[0]
            ip = socket.inet_ntoa(raw[4:8])
            try:
                peer = self.host.controller.resolve(ip)
            except KeyError:
                return -ENETUNREACH
            if vs.udp is None:
                r = self._dgram_bind(vs)  # connect auto-binds, like the kernel
                if r != 0:
                    return r
            vs.dgram_peer = (peer, port)
            vs.connected = True
            return 0
        if family != socket.AF_INET:
            return -EAFNOSUPPORT
        port = struct.unpack_from(">H", raw, 2)[0]
        ip = socket.inet_ntoa(raw[4:8])
        try:
            peer = self.host.controller.resolve(ip)
        except KeyError:
            return -ENETUNREACH
        ep = self.host.connect(peer, port)
        self._wire_endpoint(vs, ep)
        ep.on_connected = lambda now: self._on_connected(vs)
        if vs.nonblock:
            ep.connect()
            return -115  # EINPROGRESS; completion via POLLOUT + SO_ERROR
        self._waiting = ("connect", vs)
        ep.connect()
        return _BLOCK

    def _on_connected(self, vs: VSocket) -> None:
        vs.connected = True
        th, _ = self._find_waiter((("connect",), vs))
        if th is not None:
            self._resume(th, 0)
            return
        self._notify()

    def _on_net_data(self, vs: VSocket, n: int, payload) -> None:
        data = payload if payload is not None else b"\0" * n
        vs.rxbuf += data
        sr = vs.sockring
        if sr is not None and not sr.dead:
            if len(data) <= sr.rx_room():
                sr.rx_append(data)  # mirror: RX unread == len(rxbuf)
            else:
                # mirror overflow (rxbuf grew past the ring's slack over
                # recv_buffer): permanent slow path; rxbuf stays
                # authoritative, so nothing is lost
                sr.kill()
        # wake every satisfiable waiter: a fulfilled MSG_PEEK leaves the
        # data in place, so another thread's recv may also be servable
        while vs.rxbuf:
            th, w = self._find_waiter((("recv", "rmsg"), vs))
            if th is None:
                break
            if w[0] == "recv":
                self._fulfill_recv(th, vs, w[2], w[3], w[4])
            else:  # rmsg: a parked MSG_PEEK must not consume on wakeup
                peek = len(w) > 3 and w[3]
                self._resume(th, self._scatter_rx(vs, w[2],
                                                  consume=not peek))
        self._notify()

    def _on_net_close(self, vs: VSocket) -> None:
        vs.peer_closed = True
        if vs.sockring is not None:
            # HUP now: the shim serves EOF-once-drained locally and
            # forwards writes (the worker twin returns -EPIPE)
            vs.sockring.sync_flags(vs)
        woke = False
        while not vs.rxbuf:  # terminal event: EVERY reader gets EOF
            th, _ = self._find_waiter((("recv", "rmsg"), vs))
            if th is None:
                break
            self._resume(th, 0)
            woke = True
        if not woke:
            self._notify()

    def _on_net_error(self, vs: VSocket) -> None:
        vs.connect_err = ETIMEDOUT if not vs.connected else ECONNRESET
        if vs.sockring is not None:
            # error delivery ordering is worker business: flag + kill so
            # the shim forwards everything on this connection from now on
            vs.sockring.sync_flags(vs)
            vs.sockring.kill()
        woke = False
        while True:  # terminal event: EVERY waiter on this socket errors
            th, w = self._find_waiter((("connect",), vs))
            if th is not None:
                self._resume(th, -ETIMEDOUT)
                woke = True
                continue
            th, w = self._find_waiter(
                (("recv", "send", "rmsg", "smsg", "dmsg"), vs))
            if th is not None:
                self._resume(th, -ECONNRESET)
                woke = True
                continue
            break
        if not woke:
            self._notify()

    def _vfd_send(self, fd: int, addr: int, n: int):
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        if vs.kind == "spair":
            return self._pipe_write(vs, self.mem.read(addr, min(n, 1 << 20)))
        if vs.kind == "dgram":  # send/write(2) on a connected-UDP socket
            return self._dgram_sendto(vs, (fd, addr, n, 0, 0, 0))
        if vs.endpoint is None or not vs.connected:
            return -ENOTCONN
        if vs.peer_closed:
            return -EPIPE
        data = self.mem.read(addr, min(n, 1 << 20))
        accepted = vs.endpoint.send(payload=data)
        if accepted > 0:
            return accepted
        if vs.nonblock:
            return -EAGAIN
        # send buffer full: park until acks drain it (_on_drain resumes)
        self._waiting = ("send", vs, addr, n)
        return _BLOCK

    def _vfd_recv(self, fd: int, bufaddr: int, buflen: int,
                  peek: bool = False):
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        if vs.kind == "spair":
            return self._pipe_read(vs, [(bufaddr, buflen)], peek=peek)
        if vs.kind == "dgram":  # recv/read(2) on a (connected-)UDP socket
            return self._dgram_recvfrom(vs, (fd, bufaddr, buflen, 0, 0, 0),
                                        peek=peek)
        if vs.endpoint is None:
            return -ENOTCONN
        if vs.rxbuf:
            return self._take_rx(vs, bufaddr, buflen, consume=not peek)
        if vs.peer_closed:
            return 0
        if vs.nonblock:
            return -EAGAIN
        self._waiting = ("recv", vs, bufaddr, buflen, peek)
        return _BLOCK

    def _fulfill_recv(self, th: GuestThread, vs: VSocket, bufaddr: int,
                      buflen: int, peek: bool = False) -> None:
        # a parked MSG_PEEK must not consume on wakeup
        self._resume(th, self._take_rx(vs, bufaddr, buflen,
                                       consume=not peek))

    def _take_rx(self, vs: VSocket, bufaddr: int, buflen: int,
                 consume: bool = True) -> int:
        k = min(len(vs.rxbuf), buflen)
        self.mem.write(bufaddr, bytes(vs.rxbuf[:k]))
        if consume:
            del vs.rxbuf[:k]
            sr = vs.sockring
            if sr is not None and not sr.dead and k:
                sr.rx_advance(k)  # keep the mirror invariant
            self._rx_consumed(vs)
        return k

    def _rx_consumed(self, vs: VSocket) -> None:
        """The guest read from rxbuf: let the receiver send a window-update
        ack if the sender was throttled by our advertised window."""
        if vs.endpoint is not None:
            vs.endpoint.receiver.on_app_read()

    # -- select -------------------------------------------------------------
    def _select(self, args, is_pselect: bool):
        """select/pselect6 over fd_set bitmaps. Only reachable for fds the
        guest can legally FD_SET (< FD_SETSIZE): vfds land there via dup2
        (shell redirections, inetd-style servers). Real fds in the sets
        count as always-ready, like regular files."""
        nfds = min(args[0] & 0xFFFFFFFF, 1024)
        nbytes = (nfds + 7) // 8
        sets = []
        for ptr in (args[1], args[2], args[3]):
            if ptr:
                sets.append(bytearray(self.mem.read(ptr, nbytes)))
            else:
                sets.append(None)
        want_of = (POLLIN, POLLOUT, 0)  # exceptfds: never signaled here
        entries = []  # (fd, set_index, want_mask)
        for si, bits in enumerate(sets):
            if bits is None:
                continue
            for fd in range(nfds):
                if bits[fd >> 3] & (1 << (fd & 7)):
                    entries.append((fd, si, want_of[si]))
        n = self._select_scan(entries, args, sets, nbytes)
        if n:
            return n
        if args[4] == 0:  # NULL timeout pointer = infinite
            timeout_ns = -1
        else:
            # timespec (pselect) and timeval (select) are both two int64s
            sec, frac = struct.unpack("<qq", self.mem.read(args[4], 16))
            timeout_ns = sec * NS_PER_SEC + (frac if is_pselect
                                             else frac * 1000)
            if sec < 0 or frac < 0:
                return -EINVAL  # Linux rejects negative timeouts
        if timeout_ns == 0:
            # nothing ready and a zero timeout: clear every set and return
            self._select_write(args, _zeroed_sets(sets, nbytes), nbytes)
            return 0
        token = self._arm_wait_timeout(timeout_ns)
        # select (not pselect) updates the guest's timeval with the time
        # remaining; remember the deadline for the writeback
        deadline = (None if is_pselect or timeout_ns < 0
                    else emulated(self.host.now) + timeout_ns)
        self._waiting = ("select", token, entries, args, sets, nbytes,
                        deadline)
        return _BLOCK

    def _select_timeleft(self, w) -> None:
        """Linux select(2) semantics: write the unslept remainder back
        into the guest's timeval on every blocking return."""
        deadline = w[6] if len(w) > 6 else None
        if deadline is None:
            return
        left = max(0, deadline - emulated(self.host.now))
        self.mem.write(w[3][4], struct.pack(
            "<qq", left // NS_PER_SEC, (left % NS_PER_SEC) // 1000))

    def _select_scan(self, entries, args, sets, nbytes: int) -> int:
        out = _zeroed_sets(sets, nbytes)
        n = 0
        for fd, si, want in entries:
            vs = self.fds.get(fd)
            if vs is None:
                ready = si != 2  # real fd (file-like): always read/write-ready
            elif si == 2:
                ready = False
            else:
                ready = bool(self._revents(vs, want) & want)
            if ready:
                out[si][fd >> 3] |= 1 << (fd & 7)
                n += 1
        if n:
            self._select_write(args, out, nbytes)
        return n

    def _select_write(self, args, out, nbytes: int) -> None:
        for ptr, bits in zip((args[1], args[2], args[3]), out):
            if ptr and bits is not None:
                self.mem.write(ptr, bytes(bits))

    # -- poll / epoll -------------------------------------------------------
    def _poll(self, fds_ptr: int, nfds: int, timeout, is_ppoll: bool):
        nfds = min(nfds, 1024)
        raw = self.mem.read(fds_ptr, 8 * nfds)
        entries = []
        for i in range(nfds):
            fd = struct.unpack_from("<i", raw, 8 * i)[0]
            want = struct.unpack_from("<h", raw, 8 * i + 4)[0]
            entries.append((fd, want))
        if self._fast_plane and self.parent_proc is None:
            # this poll reached the worker: publish readiness bytes for
            # its fds from the next reply on, so repeats complete in-shim
            for fd, _w in entries:
                if 0 <= fd - VFD_BASE < SHIM_READY_LEN:
                    self._ready_watch.add(fd)
        n = self._poll_scan(entries, fds_ptr)
        if n:
            return n
        if is_ppoll:  # timeout is a timespec pointer (NULL = infinite)
            if timeout == 0:
                timeout_ns = -1
            else:
                sec, nsec = struct.unpack("<qq", self.mem.read(timeout, 16))
                timeout_ns = sec * NS_PER_SEC + nsec
        else:  # poll: signed ms (negative = infinite)
            tmo = timeout if timeout < (1 << 63) else timeout - (1 << 64)
            timeout_ns = -1 if tmo < 0 else int(tmo) * 1_000_000
        if timeout_ns == 0:
            return 0
        token = self._arm_wait_timeout(timeout_ns)
        self._waiting = ("poll", token, entries, fds_ptr)
        return _BLOCK

    def _epoll_ctl(self, epfd: int, op: int, fd: int, event_ptr: int):
        ep_vs = self.fds.get(epfd)
        if ep_vs is None or ep_vs.kind != "epoll":
            return -EBADF
        if op == EPOLL_CTL_DEL:
            ep_vs.interest.pop(fd, None)
            return 0
        if fd not in self.fds:
            # real (non-virtual) fds can't be multiplexed by the simulated
            # epoll — fail loudly instead of silently never firing
            return -EPERM
        raw = self.mem.read(event_ptr, 12)
        events = struct.unpack_from("<I", raw, 0)[0]
        data = struct.unpack_from("<Q", raw, 4)[0]
        ep_vs.interest[fd] = (events, data)
        return 0

    def _epoll_wait(self, epfd: int, events_ptr: int, maxev: int, timeout):
        ep_vs = self.fds.get(epfd)
        if ep_vs is None or ep_vs.kind != "epoll":
            return -EBADF
        n = self._epoll_scan(ep_vs, events_ptr, maxev)
        if n:
            return n
        tmo = timeout if timeout < (1 << 63) else timeout - (1 << 64)
        if tmo == 0:
            return 0
        timeout_ns = -1 if tmo < 0 else int(tmo) * 1_000_000
        token = self._arm_wait_timeout(timeout_ns)
        self._waiting = ("epoll", token, ep_vs, events_ptr, maxev)
        return _BLOCK

    # -- scatter-gather (msghdr/iovec walking via guest memory) --------------
    def _read_cstr(self, ptr: int, limit: int = 256):
        try:
            return self.mem.read_cstr(ptr, limit).decode()
        except (OSError, UnicodeDecodeError):
            return None

    def _read_iovec(self, iov_ptr: int, iovcnt: int):
        """Reads a struct iovec[] from guest memory → [(base, len)]."""
        iovs = []
        n = min(iovcnt, 1024)  # IOV_MAX
        if iov_ptr and n:
            raw = self.mem.read(iov_ptr, 16 * n)
            for i in range(n):
                iovs.append(struct.unpack_from("<QQ", raw, 16 * i))
        return iovs

    def _read_msghdr(self, msg_ptr: int):
        """Returns (name_ptr, namelen, iov list[(base, len)])."""
        raw = self.mem.read(msg_ptr, 56)  # struct msghdr on x86-64
        # msg_namelen is a 4-byte socklen_t at offset 8 (then 4 pad bytes)
        name, namelen, iov, iovlen = struct.unpack_from("<QIxxxxQQ", raw, 0)
        return name, namelen, self._read_iovec(iov, iovlen)

    def _stream_send(self, vs: VSocket, data: bytes):
        """Send gathered bytes on a stream socket; park replaying the same
        staged buffer if the send buffer is full (sendmsg/writev path)."""
        if vs.kind != "stream" or vs.endpoint is None or not vs.connected:
            return -ENOTCONN
        if vs.peer_closed:
            return -EPIPE
        accepted = vs.endpoint.send(payload=data)
        if accepted > 0:
            return accepted
        if vs.nonblock:
            return -EAGAIN
        self._waiting = ("smsg", vs, data)
        return _BLOCK

    def _scatter_rx(self, vs: VSocket, iovs, consume: bool = True) -> int:
        """Move bytes from vs.rxbuf into the guest's iovecs (MSG_PEEK:
        copy without consuming)."""
        k = min(len(vs.rxbuf), sum(ln for _, ln in iovs))
        self._scatter(iovs, bytes(vs.rxbuf[:k]))
        if consume:
            del vs.rxbuf[:k]
            sr = vs.sockring
            if sr is not None and not sr.dead and k:
                sr.rx_advance(k)  # keep the mirror invariant
            self._rx_consumed(vs)
        return k

    def _sendmsg(self, fd: int, msg_ptr: int):
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        name, namelen, iovs = self._read_msghdr(msg_ptr)
        data = b"".join(self.mem.read(b, min(ln, 1 << 20))
                        for b, ln in iovs if ln)
        if vs.kind == "dgram":
            # NULL name falls back to the connected-UDP default peer
            return self._dgram_sendto(vs, (fd, 0, len(data), 0, name, namelen),
                                      staged=data)
        if vs.kind == "spair":
            return self._pipe_write(vs, data)
        return self._stream_send(vs, data)

    def _recvmsg(self, fd: int, msg_ptr: int, peek: bool = False):
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        name, namelen, iovs = self._read_msghdr(msg_ptr)
        if vs.kind == "spair":
            return self._pipe_read(vs, iovs, peek=peek)
        if vs.kind == "dgram":
            if not vs.dgram_q:
                if vs.nonblock:
                    return -EAGAIN
                self._waiting = ("dmsg", vs, iovs, (msg_ptr, name, namelen),
                                 peek)
                return _BLOCK
            return self._recvmsg_take(vs, iovs, (msg_ptr, name, namelen),
                                      consume=not peek)
        if vs.rxbuf:
            return self._scatter_rx(vs, iovs, consume=not peek)
        if vs.peer_closed:
            return 0
        if vs.nonblock:
            return -EAGAIN
        self._waiting = ("rmsg", vs, iovs, peek)
        return _BLOCK

    def _recvmsg_take(self, vs: VSocket, iovs, where,
                      consume: bool = True) -> int:
        # MSG_PEEK (consume=False) copies the head datagram without
        # dequeuing it, matching the recvfrom path (_dgram_take)
        if consume:
            payload, nbytes, src, sport = vs.dgram_q.pop(0)
        else:
            payload, nbytes, src, sport = vs.dgram_q[0]
        data = payload if payload is not None else b"\0" * nbytes
        msg_ptr, name_ptr, namelen = where if where else (0, 0, 0)
        if name_ptr and namelen:
            ip = self.host.controller.hosts[src].ip
            sa = (struct.pack("<H", socket.AF_INET) + struct.pack(">H", sport)
                  + socket.inet_aton(ip) + b"\0" * 8)
            # kernel semantics: truncate to the caller's buffer, then
            # write the un-truncated length back into msg_namelen
            self.mem.write(name_ptr, sa[:namelen])
            self.mem.write(msg_ptr + 8, struct.pack("<I", len(sa)))
        return self._scatter(iovs, data)

    def _writev(self, fd: int, iov_ptr: int, iovcnt: int):
        iovs = self._read_iovec(iov_ptr, iovcnt)
        data = b"".join(self.mem.read(b, min(ln, 1 << 20))
                        for b, ln in iovs if ln)
        if fd in (1, 2) and fd not in self.fds:
            self._capture(fd).write(data)
            return len(data)
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        if vs.kind == "event":
            if len(data) < 8:
                return -EINVAL
            vs.evt_counter += struct.unpack("<Q", data[:8])[0]
            self._notify()
            return 8
        if vs.kind in ("pipe_w", "spair"):
            return self._pipe_write(vs, data)
        if vs.kind in ("file", "dir"):
            return self.vfs.write(vs, data)
        return self._stream_send(vs, data)

    def _readv(self, fd: int, iov_ptr: int, iovcnt: int):
        if fd == 0 and 0 not in self.fds:
            return 0  # stdin: EOF, matching the read path
        vs = self.fds.get(fd)
        if vs is None:
            return -EBADF
        iovs = self._read_iovec(iov_ptr, iovcnt)
        if vs.kind in ("timer", "event"):
            if not iovs:
                return -EINVAL
            return self._counter_read(vs, iovs[0][0], iovs[0][1])
        if vs.kind in ("file", "dir"):
            data = self.vfs.read(vs, sum(ln for _, ln in iovs))
            if isinstance(data, int):
                return data
            return self._scatter(iovs, data)
        if vs.kind in ("pipe_r", "spair"):
            return self._pipe_read(vs, iovs)
        if vs.kind == "dgram":
            if not vs.dgram_q:
                if vs.nonblock:
                    return -EAGAIN
                self._waiting = ("dmsg", vs, iovs, None)
                return _BLOCK
            return self._recvmsg_take(vs, iovs, None)
        if vs.rxbuf:
            return self._scatter_rx(vs, iovs)
        if vs.peer_closed:
            return 0
        if vs.nonblock:
            return -EAGAIN
        self._waiting = ("rmsg", vs, iovs)
        return _BLOCK

    def _scatter(self, iovs, data: bytes) -> int:
        off = 0
        for base, ln in iovs:
            if off >= len(data):
                break
            k = min(ln, len(data) - off)
            self.mem.write(base, data[off:off + k])
            off += k
        return off

    # -- timerfd / eventfd ---------------------------------------------------
    def _counter_read(self, vs: VSocket, buf: int, buflen: int):
        if buflen < 8:
            return -EINVAL
        val = vs.expirations if vs.kind == "timer" else vs.evt_counter
        if val > 0:
            if vs.kind == "timer":
                vs.expirations = 0
            else:
                vs.evt_counter = 0
            self.mem.write(buf, struct.pack("<Q", val))
            return 8
        if vs.nonblock:
            return -EAGAIN
        self._waiting = ("cread", vs, buf, buflen)
        return _BLOCK

    def _timerfd_settime(self, fd: int, flags: int, new_ptr: int, old_ptr: int):
        vs = self.fds.get(fd)
        if vs is None or vs.kind != "timer":
            return -EBADF
        isec, insec, vsec, vnsec = struct.unpack(
            "<qqqq", self.mem.read(new_ptr, 32))
        # deadlines live in the timerfd's OWN clock base (timerfd_create
        # clockid): monotonic family counts from sim start
        now = (self.host.now if vs.timer_clock in MONO_CLOCKS
               else emulated(self.host.now))
        if old_ptr:
            left = max(vs.deadline - now, 0) if vs.timer_handle else 0
            self.mem.write(old_ptr, struct.pack(
                "<qqqq", vs.interval_ns // NS_PER_SEC,
                vs.interval_ns % NS_PER_SEC,
                left // NS_PER_SEC, left % NS_PER_SEC))
        if vs.timer_handle is not None:
            self.host.cancel(vs.timer_handle)
            vs.timer_handle = None
        vs.interval_ns = isec * NS_PER_SEC + insec
        first = vsec * NS_PER_SEC + vnsec
        if first == 0:
            return 0  # disarm
        if flags & TFD_TIMER_ABSTIME:
            delay = max(first - now, 0)
            vs.deadline = first
        else:
            delay = first
            vs.deadline = now + first
        vs.timer_handle = self.host.schedule_in(delay, lambda: self._timer_fire(vs))
        return 0

    def _timer_fire(self, vs: VSocket) -> None:
        if vs.vfd not in self.fds or not self.running:
            return
        vs.expirations += 1
        if vs.interval_ns > 0:
            vs.deadline += vs.interval_ns
            vs.timer_handle = self.host.schedule_in(
                vs.interval_ns, lambda: self._timer_fire(vs))
        else:
            vs.timer_handle = None
        th, w = self._find_waiter((("cread",), vs))
        if th is not None:
            self._resume(th, self._counter_read(vs, w[2], w[3]))
        else:
            self._notify()

    # -- datagram bridge ----------------------------------------------------
    def _dgram_bind(self, vs: VSocket):
        try:
            sock = self.host.udp_socket(vs.bound_port or None)
        except ValueError:
            return -98  # EADDRINUSE
        vs.udp = sock
        vs.bound_port = sock.local_port

        def on_datagram(nbytes, payload, src_addr, now):
            if vs.dgram_peer is not None and src_addr != vs.dgram_peer:
                return  # connected UDP filters inbound to the peer
            vs.dgram_q.append((payload, nbytes, src_addr[0], src_addr[1]))
            # wake every satisfiable waiter: a fulfilled MSG_PEEK leaves
            # the datagram queued for the next reader
            while vs.dgram_q:
                th, w = self._find_waiter((("drecv", "dmsg"), vs))
                if th is None:
                    break
                if w[0] == "drecv":
                    self._resume(
                        th, self._dgram_take(vs, w[2], w[3], w[4], w[5],
                                             consume=not (len(w) > 6
                                                          and w[6])))
                else:
                    self._resume(th, self._recvmsg_take(
                        vs, w[2], w[3],
                        consume=not (len(w) > 4 and w[4])))
            self._notify()

        sock.on_datagram = on_datagram
        return 0

    def _dgram_sendto(self, vs: VSocket, args, staged: bytes = None):
        if not args[4] and vs.dgram_peer is None:
            # NULL addr needs a connected socket; error BEFORE the
            # auto-bind so the failed send leaves the socket unbound,
            # like the kernel
            return -89  # EDESTADDRREQ
        if vs.udp is None:
            r = self._dgram_bind(vs)  # auto-bind an ephemeral port
            if r != 0:
                return r
        if not args[4]:
            peer, port = vs.dgram_peer
        else:
            raw = self.mem.read(args[4], min(max(args[5], 16), 128))
            port = struct.unpack_from(">H", raw, 2)[0]
            ip = socket.inet_ntoa(raw[4:8])
            try:
                peer = self.host.controller.resolve(ip)
            except KeyError:
                return -ENETUNREACH
        if staged is not None:
            data = staged
        else:
            data = self.mem.read(args[1], min(args[2], 1 << 16))
        vs.udp.sendto(peer, port, payload=data)
        return len(data)

    def _dgram_recvfrom(self, vs: VSocket, args, peek: bool = False):
        if vs.udp is None:
            return -ENOTCONN
        if vs.dgram_q:
            return self._dgram_take(vs, args[1], args[2], args[4], args[5],
                                    consume=not peek)
        if vs.nonblock:
            return -EAGAIN
        self._waiting = ("drecv", vs, args[1], args[2], args[4], args[5],
                         peek)
        return _BLOCK

    def _dgram_take(self, vs: VSocket, buf: int, buflen: int,
                    src_ptr: int, srclen_ptr: int,
                    consume: bool = True) -> int:
        if consume:
            payload, nbytes, src, sport = vs.dgram_q.pop(0)
        else:  # MSG_PEEK: inspect without dequeuing
            payload, nbytes, src, sport = vs.dgram_q[0]
        data = payload if payload is not None else b"\0" * nbytes
        k = min(len(data), buflen)
        self.mem.write(buf, data[:k])
        if src_ptr and srclen_ptr:
            ip = self.host.controller.hosts[src].ip
            sa = (struct.pack("<H", socket.AF_INET) + struct.pack(">H", sport)
                  + socket.inet_aton(ip) + b"\0" * 8)
            self.mem.write(src_ptr, sa)
            self.mem.write(srclen_ptr, struct.pack("<i", len(sa)))
        return k

    # -- stdio capture -----------------------------------------------------
    def _capture(self, fd: int):
        f = self._files.get(fd)
        if f is None:
            ddir = Path(self.host.controller.data_dir) / "hosts" / self.host.name
            ddir.mkdir(parents=True, exist_ok=True)
            suffix = "stdout" if fd == 1 else "stderr"
            f = open(ddir / f"{self.name}.{suffix}", "wb")  # fresh per run
            self._files[fd] = f
        return f
