"""Fleet mode: M seeded simulations per box behind one shared device plane.

"Once is Never Enough" (Jansen/Tracey/Goldberg, USENIX Security '21 —
PAPERS.md) is the methodology this simulator pairs with: no conclusion
from one run, only from N seeded runs with confidence intervals. This
module is the throughput layer that makes N-seed sweeps cheaper than N
serial walls on one box, plus the statistics layer that turns the per-seed
telemetry into cross-run aggregates:

**The sweep runner** (``FleetRunner`` / ``python -m shadow_tpu.fleet sweep
config.yaml --seeds 10 --jobs M``) packs M concurrent seeded simulations:

- ``jobs`` persistent worker processes, pinned to cores (best-effort
  ``sched_setaffinity``), each running its assigned seeds SEQUENTIALLY in
  one interpreter — so the Python/numpy import wall, the APSP cache, and
  the JAX persistent compile cache amortize across seeds instead of being
  paid ``N`` times (DeviceDrawPlane.attach_cached's per-process discipline,
  one level up).
- Bounded admission: never more than ``jobs`` resident simulations, and an
  RSS guard that delays handing the next seed to an idle worker while the
  fleet's resident-set total is over budget (a big topology's build spike
  should not land while every sibling is at peak).
- ONE process-group device attach: the parent owns a
  ``ops.propagate.DrawServer`` — a single attach+calibrate+warm_shapes —
  and members route their draw windows to it through ``FleetDrawClient``
  (published into the existing ``network/devroute.py`` window machinery
  via ``SHADOW_TPU_DRAW_SERVICE``). The draw kernels take the threefry
  key as *data*, so every member seed shares the same compiled programs.
  Routing is wall-clock policy: the proxy's results are bit-identical to
  the in-process twins, and any transport failure falls back to the
  local numpy twin — a dead server can never change results.
- Per-seed isolation: each seed runs with
  ``data_directory = <sweep_dir>/seed_<s>`` — its host log tree, flow and
  metric streams, and digest stream land there, byte-identical to the
  same seed run standalone (tests/test_fleet.py).
- Failure containment with bounded retries: a seed that raises, a worker
  process that dies, and a member that wedges past the EMA-derived stall
  deadline (``_check_members``) are all routed through one retry budget
  (``--retries``, supervise.py discipline) before counting as failed in
  the manifest; the sweep continues either way. A member over the
  per-member RSS ceiling (``--member-max-rss-mb``) is killed and NOT
  retried — a leak leaks again. SIGINT mid-sweep tears down coherently:
  in-flight members killed, leaked guests reaped, seeds recorded
  ``interrupted``, and the partial summary stays a valid artifact.
- ``--resume``: a partially-completed sweep re-runs only the seeds whose
  per-seed manifest is missing, failed, or was produced under a different
  config (checkpoint.config_digest identity).

**The reducer** (``reduce_sweep`` / ``... report <sweep-dir>``) k-way
merges the per-seed ``LogHistogram`` states (mergeable by construction —
fixed bucket layout, bucket-wise addition) into ``sweep_summary.json``:

- pooled percentiles (all seeds' samples in one histogram), and
- per-seed percentile vectors with t-based 95% confidence intervals per
  flow group — the run-level statistic is computed per seed first, then
  the CI is taken ACROSS seeds (the "repeated experiments" discipline of
  the methodology paper; seeds are the independent unit, not samples).

Nothing here touches simulation semantics: the fleet is process
orchestration plus statistics over streams the runs already produce.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import shutil
import sys
import time as _walltime  # detlint: ok(wallclock): worker admission, RSS polling, sweep wall report
from pathlib import Path

import numpy as np

SEED_MANIFEST = "fleet_manifest.json"
TEL_STATE_FILE = "telemetry_state.json"
SWEEP_SUMMARY = "sweep_summary.json"
MANIFEST_FORMAT = "shadow_tpu-fleet-seed"
SUMMARY_FORMAT = "shadow_tpu-sweep-summary"

#: chaos hook for the failure-path gates (tests/test_fleet.py, ci.sh):
#: comma-separated seeds that raise instead of running — exercising the
#: crashed-member path without needing a genuinely broken config. Unlike
#: the KILL/WEDGE hooks below this one fires on EVERY attempt, so a
#: chaos-failed seed exhausts its retry budget and lands in ``failed``.
CHAOS_ENV = "SHADOW_TPU_FLEET_CHAOS_SEEDS"

#: harder chaos hooks (shadow_tpu/supervise.py discipline): the worker
#: SIGKILLs itself / wedges forever just before running the listed seed.
#: Once-only via an O_EXCL marker under <sweep_dir>/chaos/, so the
#: retried attempt runs clean and the sweep converges — this is how
#: ci.sh proves detection + retry, not just failure accounting.
CHAOS_KILL_ENV = "SHADOW_TPU_FLEET_CHAOS_KILL_SEEDS"
CHAOS_WEDGE_ENV = "SHADOW_TPU_FLEET_CHAOS_WEDGE_SEEDS"

#: fixed member-stall deadline override (wall seconds). Default policy is
#: EMA-derived: max(supervise.stall_deadline_s(completed-seed wall EMA),
#: 60) once at least one seed has completed — before that there is no
#: basis for a deadline and members may run arbitrarily long.
FLEET_STALL_ENV = "SHADOW_TPU_FLEET_STALL_S"

#: member-side service discovery (read by network/devroute.py)
SERVICE_ENV = "SHADOW_TPU_DRAW_SERVICE"


def _dumps(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


# -- the member-side draw proxy ----------------------------------------------
#
# Quacks like ops.propagate.DeviceDrawPlane (dispatch / dispatch_min /
# SPEC_BUCKET) without importing jax: the member ships its draw batches to
# the fleet parent's DrawServer and reads the flags back through handle
# objects that satisfy the window machinery's read()/is_ready() contract.
# Responses arrive FIFO per member but are demuxed by request id, since
# the window pipeline + speculative waves read out of order.

def _min_draw_np(seed: int, uid_lo, uid_hi, npkts, width: int):
    """numpy twin of ops.propagate._min_draw_kernel (prefix-min 24-bit
    draw per unit; 0xFFFFFFFF for npkts == 0) — the dead-service fallback
    for speculative waves. Same integer math as fluid.loss_flags."""
    from shadow_tpu.network.fluid import PKT_SHIFT
    from shadow_tpu.ops.prng import threefry2x32

    pkt = np.arange(width, dtype=np.uint32)[None, :]
    c0 = np.broadcast_to(uid_lo[:, None], (uid_lo.shape[0], width))
    c1 = uid_hi[:, None] | (pkt << np.uint32(PKT_SHIFT))
    k0 = np.uint32(seed & 0xFFFFFFFF)
    k1 = np.uint32((seed >> 32) & 0xFFFFFFFF)
    draws, _ = threefry2x32(k0, k1, c0, c1, xp=np)
    draws = (draws >> np.uint32(8)).astype(np.uint32)
    return np.where(pkt < npkts[:, None], draws,
                    np.uint32(0xFFFFFFFF)).min(axis=1)


class _LocalFallbackHandle:
    """Handle whose result is computed in-process by the bit-identical
    numpy twin (service unreachable). Lazy: computed at first read."""

    __slots__ = ("_fn", "_out")

    def __init__(self, fn) -> None:
        self._fn = fn
        self._out = None

    def read(self):
        if self._out is None:
            self._out = self._fn()
        return self._out

    def is_ready(self) -> bool:
        return True


class _RemoteHandle:
    """An in-flight request to the fleet draw server."""

    __slots__ = ("_cl", "_rid", "_fallback")

    def __init__(self, cl, rid: int, fallback) -> None:
        self._cl = cl
        self._rid = rid
        self._fallback = fallback  # () -> twin result, on transport death

    def read(self):
        out = self._cl._wait(self._rid)
        if out is None:  # connection died mid-flight: twin carries it
            return self._fallback()
        return out

    def is_ready(self) -> bool:
        return self._cl._check(self._rid)


class FleetDrawClient:
    """Member-side proxy for the fleet parent's DrawServer (see module
    doc). Single-threaded by contract: the simulation round loop is the
    only caller (devroute publishes it like a device plane)."""

    name = "fleet"

    def __init__(self, conn, seed: int, dev_s: float, np_per_unit: float,
                 spec_bucket: int, max_batch: int, max_pkts: int) -> None:
        self._conn = conn
        self.seed = int(seed)
        self.dev_s = dev_s
        self.np_per_unit = np_per_unit
        self.SPEC_BUCKET = spec_bucket
        self.max_batch = max_batch
        self.max_pkts = max_pkts
        self._rid = 0
        self._results: dict = {}
        self._dead = False

    @classmethod
    def connect(cls, address: str, seed: int, max_batch: int,
                max_pkts: int, timeout: float = 60.0,
                abort=None) -> "FleetDrawClient":
        """Connect to the fleet draw service. The socket handshake is
        immediate (the parent accepts before its attach finishes); the
        hello REPLY may take as long as the attach, so it is waited with
        an abortable poll — ``abort()`` returning True (e.g. the member
        run is tearing down) raises instead of blocking. Raises on a
        server that never comes up within ``timeout``."""
        from multiprocessing.connection import Client

        from shadow_tpu.ops.propagate import DRAW_SERVICE_AUTHKEY

        t0 = _walltime.monotonic()
        deadline = t0 + timeout
        # a MISSING socket gets a shorter window than a busy one: the
        # parent publishes the socket path at spawn but only binds it
        # once its jax import finishes (~seconds), and a socket that
        # never appears means the service died
        missing_deadline = t0 + 20.0
        last = None
        while True:
            try:
                conn = Client(address, family="AF_UNIX",
                              authkey=DRAW_SERVICE_AUTHKEY)
                break
            except (FileNotFoundError, ConnectionError, OSError) as exc:
                last = exc
                now = _walltime.monotonic()
                if now > (missing_deadline
                          if isinstance(exc, FileNotFoundError)
                          else deadline):
                    raise TimeoutError(
                        f"fleet draw service at {address!r} not reachable"
                        f": {last}") from last
                if abort is not None and abort():
                    raise TimeoutError("member aborted service connect")
                _walltime.sleep(0.25)
        try:
            conn.send(("hello", int(seed)))
            while not conn.poll(0.25):
                if abort is not None and abort():
                    raise TimeoutError("member aborted service connect")
                if _walltime.monotonic() > deadline:
                    raise TimeoutError(
                        f"fleet draw service at {address!r}: no hello "
                        f"reply within {timeout}s (attach stuck?)")
            op, dev_s, np_per_unit, spec_bucket, srv_max_batch = \
                conn.recv()
        except BaseException:
            conn.close()
            raise
        if op != "ok":
            conn.close()
            raise RuntimeError(f"draw service refused: {op!r}")
        return cls(conn, seed, dev_s, np_per_unit, spec_bucket,
                   min(int(max_batch), int(srv_max_batch)), max_pkts)

    # -- plane interface (devroute window machinery) -----------------------
    def dispatch(self, uid_lo, uid_hi, npkts, thresh):
        def twin():
            from shadow_tpu.network.fluid import loss_flags

            return loss_flags(self.seed, uid_lo, uid_hi, npkts, thresh)

        if self._dead:
            return _LocalFallbackHandle(twin)
        rid = self._rid = self._rid + 1
        try:
            self._conn.send(("draw", rid, self.seed, uid_lo, uid_hi,
                             npkts, thresh))
        except (OSError, ValueError, BrokenPipeError):
            self._dead = True
            return _LocalFallbackHandle(twin)
        return _RemoteHandle(self, rid, twin)

    def dispatch_min(self, uid_lo, uid_hi, npkts, min_bucket: int = 0):
        def twin():
            return _min_draw_np(self.seed, uid_lo, uid_hi, npkts,
                                self.max_pkts)

        if self._dead:
            return _LocalFallbackHandle(twin)
        rid = self._rid = self._rid + 1
        try:
            self._conn.send(("min", rid, self.seed, uid_lo, uid_hi,
                             npkts, min_bucket))
        except (OSError, ValueError, BrokenPipeError):
            self._dead = True
            return _LocalFallbackHandle(twin)
        return _RemoteHandle(self, rid, twin)

    # -- response demux ----------------------------------------------------
    def _pump(self) -> None:
        """Drain whatever responses already landed (never blocks)."""
        try:
            while self._conn.poll(0):
                rid, out = self._conn.recv()
                self._results[rid] = out
        except (OSError, EOFError, BrokenPipeError):
            self._dead = True

    def _check(self, rid: int) -> bool:
        if rid in self._results:
            return True
        self._pump()
        return rid in self._results or self._dead

    def _wait(self, rid: int):
        """Block until response ``rid`` arrives (stashing any siblings
        that land first). Returns None if the connection died — the
        handle's twin closure takes over."""
        while rid not in self._results:
            if self._dead:
                return None
            try:
                got, out = self._conn.recv()
                self._results[got] = out
            except (OSError, EOFError, BrokenPipeError):
                self._dead = True
                return None
        return self._results.pop(rid)

    def close_client(self) -> None:
        try:
            self._conn.send(("bye",))
        except (OSError, ValueError, BrokenPipeError):
            pass
        try:
            self._conn.close()
        except OSError:
            pass


# -- per-seed execution (worker side) -----------------------------------------

def seed_dir(sweep_dir, seed) -> Path:
    """Per-task directory. A task is a seed (int — a sweep member) or a
    fork branch name (str — shadow_tpu/forks.py); the fleet's dispatch,
    watchdog, and retry machinery treat both as opaque keys."""
    if isinstance(seed, str):
        return Path(sweep_dir) / f"branch_{seed}"
    return Path(sweep_dir) / f"seed_{int(seed)}"


def output_tree_digest(data_dir) -> str:
    """One sha256 over the per-host output tree (path + content, sorted)
    — the identity the fleet gates on: in-fleet == standalone. A raw
    os.scandir walk: the tor-scale tree is ~1000 small files and the
    pathlib rglob + per-file Path machinery cost 3x the actual
    hashing."""
    base = str(data_dir)
    hosts = os.path.join(base, "hosts")
    files = []
    stack = [hosts]
    while stack:
        d = stack.pop()
        try:
            # detlint: ok(unordered-iter): list is .sort()ed before hashing
            with os.scandir(d) as it:
                for e in it:
                    if e.is_dir(follow_symlinks=False):
                        stack.append(e.path)
                    elif e.is_file(follow_symlinks=False):
                        files.append(e.path)
        except FileNotFoundError:
            pass
    files.sort()
    h = hashlib.sha256()
    pfx = len(base) + 1
    for p in files:
        h.update(p[pfx:].encode())
        h.update(b"\0")
        with open(p, "rb") as f:
            h.update(hashlib.sha256(f.read()).digest())
    return h.hexdigest()


def _stream_digests(data_dir) -> dict:
    out = {}
    for name in ("flows.jsonl", "metrics.jsonl", "state_digests.jsonl"):
        p = Path(data_dir) / name
        if p.is_file():
            out[name] = hashlib.sha256(p.read_bytes()).hexdigest()
    return out


def _write_json(path: Path, doc: dict) -> None:
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(json.dumps(doc, sort_keys=True, indent=1))
    os.replace(tmp, path)


def _member_config(config_path: str, overrides: dict, sweep_dir,
                   seed: int):
    from shadow_tpu.config import load_config

    over = dict(overrides or {})
    over["general.seed"] = int(seed)
    over["general.data_directory"] = str(seed_dir(sweep_dir, seed))
    # members never bind a live endpoint: M concurrent seeds would race
    # on one socket path, and a sweep is a batch artifact. The sweep
    # itself can expose a status-only endpoint (--live-endpoint).
    over["general.live_endpoint"] = None
    # cache_doc: one worker parses the (possibly multi-hundred-host)
    # YAML once per process, not once per seed — the compose step alone
    # cost more than the tor_400 round loop
    return load_config(config_path, over, cache_doc=True)


def _reap_stale_guests(d) -> int:
    """SIGKILL real-binary guests leaked by an interrupted managed member
    run. A worker that died mid-run (SIGKILL, OOM) never reaped the
    executables it spawned; their pids live in the seed directory's
    ``guest_pids.jsonl`` side plane. Pids get recycled on a busy box, so
    each one is verified against the recorded clock-page path via
    ``/proc/<pid>/environ`` before the kill: only a process that still
    carries OUR shm path in its environment is one of ours."""
    import signal

    p = Path(d) / "guest_pids.jsonl"
    if not p.is_file():
        return 0
    killed = 0
    for raw in p.read_text().splitlines():
        try:
            rec = json.loads(raw)
            pid, shm = int(rec["pid"]), str(rec.get("shm") or "")
        except (ValueError, KeyError, TypeError):
            continue
        if pid <= 1 or not shm:
            continue
        try:
            env = Path(f"/proc/{pid}/environ").read_bytes()
        except OSError:
            continue  # already gone (the common case)
        if shm.encode() not in env:
            continue  # pid recycled by an unrelated process: hands off
        try:
            os.kill(pid, signal.SIGKILL)
            killed += 1
        except OSError:
            pass
    return killed


def _fleet_chaos(sweep_dir, seed: int) -> None:
    """Worker-side hard-failure injection (CHAOS_KILL_ENV/CHAOS_WEDGE_ENV):
    die or hang just before running the listed seed, once per sweep. The
    O_EXCL marker is claimed BEFORE firing so recovery converges — the
    parent detects the dead/wedged member, respawns, retries the seed,
    and the second attempt finds the marker already claimed."""
    import signal as _signal

    for env, kind in ((CHAOS_KILL_ENV, "kill"), (CHAOS_WEDGE_ENV, "wedge")):
        spec = os.environ.get(env, "")  # detlint: ok(envread): loop var over the SHADOW_TPU_FLEET_CHAOS_* module constants
        if not spec or str(seed) not in spec.split(","):
            continue
        mark_dir = Path(sweep_dir) / "chaos"
        mark_dir.mkdir(parents=True, exist_ok=True)
        try:
            fd = os.open(mark_dir / f"{kind}.s{seed}.fired",
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
        except FileExistsError:
            continue  # fired on an earlier attempt: this retry runs clean
        print(f"fleet: CHAOS {kind} firing in worker for seed {seed}",
              file=sys.stderr, flush=True)
        if kind == "kill":
            os.kill(os.getpid(), _signal.SIGKILL)
        while True:  # wedge: hold the seed forever without progress
            _walltime.sleep(3600)


def _run_one_seed(config_path: str, overrides: dict, sweep_dir,
                  seed: int) -> dict:
    """Run one member simulation into its per-seed directory and write
    its manifest + mergeable telemetry state. Raises on failure (the
    worker loop converts that into a failed manifest + report)."""
    from shadow_tpu import checkpoint as _ckpt
    from shadow_tpu.core.controller import (VOLATILE_SUMMARY_KEYS,
                                            Controller)

    chaos = os.environ.get(CHAOS_ENV, "")
    if chaos and str(seed) in chaos.split(","):
        raise RuntimeError(
            f"chaos hook: seed {seed} configured to fail ({CHAOS_ENV})")
    _fleet_chaos(sweep_dir, seed)
    d = seed_dir(sweep_dir, seed)
    # a fresh member run owns its directory: stale partial output from an
    # earlier attempt must not survive into the hashes — and a managed
    # attempt that died mid-run may have leaked real guest processes
    # that would fight the re-run for ptrace/SIGSTOP control; reap them
    # before the tree goes away (the pid registry lives in it)
    stale = _reap_stale_guests(d)
    if stale:
        print(f"fleet: seed {seed}: reaped {stale} stale guest "
              f"process(es) from an interrupted earlier attempt",
              file=sys.stderr, flush=True)
    shutil.rmtree(d, ignore_errors=True)
    t0 = _walltime.perf_counter()
    cfg = _member_config(config_path, overrides, sweep_dir, seed)
    # mark the attempt in-flight BEFORE spawning anything: if this worker
    # dies mid-run, --resume sees status "running" (not "ok") and treats
    # the seed as failed instead of trusting the partial tree
    d.mkdir(parents=True, exist_ok=True)
    _write_json(d / SEED_MANIFEST, {
        "format": MANIFEST_FORMAT,
        "seed": int(seed),
        "status": "running",
        "config_digest": _ckpt.config_digest(cfg),
    })
    ctl = Controller(cfg, mirror_log=False)
    result = ctl.run()
    if ctl.telemetry is not None:
        (d / TEL_STATE_FILE).write_text(
            ctl.telemetry.export_state_json())
    wall = _walltime.perf_counter() - t0
    summary = {k: v for k, v in result.items()
               if k not in VOLATILE_SUMMARY_KEYS}
    man = {
        "format": MANIFEST_FORMAT,
        "seed": int(seed),
        "status": "ok",
        "config_digest": _ckpt.config_digest(cfg),
        "wall_seconds": round(wall, 3),
        "loop_wall_seconds": round(result["wall_seconds"], 3),
        "events": result["events"],
        "rounds": result["rounds"],
        "exit_reason": result["exit_reason"],
        "process_errors": result["process_errors"],
        "tree_sha256": output_tree_digest(d),
        "streams_sha256": _stream_digests(d),
        "summary": summary,
    }
    _write_json(d / SEED_MANIFEST, man)
    return man


def _write_failed_manifest(sweep_dir, seed, error: str,
                           tb: str = "") -> dict:
    if isinstance(seed, str):  # a fork branch failed, not a sweep seed
        from shadow_tpu import forks as _forks

        return _forks.write_failed_branch_manifest(sweep_dir, seed,
                                                   error, tb)
    d = seed_dir(sweep_dir, seed)
    d.mkdir(parents=True, exist_ok=True)
    man = {
        "format": MANIFEST_FORMAT,
        "seed": int(seed),
        "status": "failed",
        "error": error,
        "traceback": tb,
    }
    _write_json(d / SEED_MANIFEST, man)
    return man


def _fleet_worker_main(conn, config_path: str, overrides: dict,
                       sweep_dir: str, worker_idx: int,
                       service_addr, pin: bool, fork: dict = None) -> None:
    """Worker process entry: run seeds sequentially as they arrive. One
    interpreter for many seeds is the amortization lever (module doc)."""
    import gc as _gc
    import signal as _signal
    import traceback

    try:  # the parent owns signal policy (the sharded-worker discipline)
        _signal.signal(_signal.SIGINT, _signal.SIG_IGN)
    except (ValueError, OSError):
        pass
    if pin:
        try:
            ncpu = os.cpu_count() or 1
            os.sched_setaffinity(0, {worker_idx % ncpu})
        except (AttributeError, OSError):
            pass  # pinning is a locality hint, never a requirement
    if service_addr:
        os.environ[SERVICE_ENV] = str(service_addr)
    seeds_run = 0
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        if msg[0] == "exit":
            break
        seed = str(msg[1]) if fork is not None else int(msg[1])
        try:
            if fork is not None:
                from shadow_tpu import forks as _forks

                man = _forks.run_branch(fork, seed)
            else:
                man = _run_one_seed(config_path, overrides, sweep_dir,
                                    seed)
            conn.send(("done", seed, man))
        except BaseException as exc:
            tb = traceback.format_exc()
            try:
                _write_failed_manifest(sweep_dir, seed, str(exc), tb)
            except OSError:
                pass
            try:
                conn.send(("failed", seed, str(exc), tb))
            except (OSError, ValueError):
                break
            if not isinstance(exc, Exception):
                break  # KeyboardInterrupt/SystemExit: stop the worker
        seeds_run += 1
        if seeds_run % 3 == 0:
            # dead Controller graphs are mostly refcount-reclaimed; a
            # full cycle collection every few seeds bounds the rest
            # without paying ~0.1 s per seed
            _gc.collect()
    # everything durable is already on disk (manifests via os.replace)
    # and every protocol message is sent: skip the interpreter teardown
    # of a multi-GB simulation heap — the kernel reclaims it faster
    try:
        conn.close()
    except OSError:
        pass
    os._exit(0)


# -- the sweep runner (parent side) -------------------------------------------

def _proc_rss_mb(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / (1 << 20)
    except (OSError, ValueError, IndexError):
        return 0.0


def _default_rss_cap_mb() -> int:
    """80% of MemTotal — the admission guard's default budget."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(int(line.split()[1]) * 0.8) // 1024
    except (OSError, ValueError):
        pass
    return 0  # unknown: guard disabled


class FleetRunner:
    """Parent orchestrator: admission-bounded seed dispatch over ``jobs``
    pinned persistent workers + the shared DrawServer (module doc)."""

    def __init__(self, config_path: str, seeds: list, jobs: int,
                 sweep_dir, overrides: dict = None, resume: bool = False,
                 max_rss_mb: int = None, pin_cores: bool = True,
                 device_service: bool = True, quiet: bool = False,
                 live_endpoint: str = None, retries: int = 1,
                 member_max_rss_mb: int = 0, fork: dict = None) -> None:
        self.config_path = str(config_path)
        #: a validated fork plan (shadow_tpu.forks.plan_fork) turns the
        #: fleet into a fork orchestrator: ``seeds`` become branch names
        #: and every worker runs branches of ONE trunk checkpoint
        self.fork = fork
        if fork is not None:
            self.seeds = [str(s) for s in seeds]
            if resume:
                raise ValueError(
                    "a fork cannot --resume: branches are planned from "
                    "the trunk checkpoint each time — just re-run the "
                    "fork (completed branch directories are rebuilt)")
        else:
            self.seeds = [int(s) for s in seeds]
        if not self.seeds:
            raise ValueError("a sweep needs at least one seed")
        if len(set(self.seeds)) != len(self.seeds):
            raise ValueError(f"duplicate seeds in sweep: {self.seeds}")
        self.jobs = max(1, int(jobs))
        self.sweep_dir = Path(sweep_dir)
        self.overrides = dict(overrides or {})
        self.resume = bool(resume)
        self.max_rss_mb = (_default_rss_cap_mb() if max_rss_mb is None
                           else int(max_rss_mb))
        self.pin_cores = bool(pin_cores)
        self.device_service = bool(device_service)
        self.quiet = bool(quiet)
        self._server = None
        self._procs: list = []
        self._conns: list = []
        self._inflight: dict = {}  # worker idx -> seed
        self._respawns = 0
        #: bounded retry budget per seed (the supervisor discipline —
        #: supervise.run_supervised): a crashed, wedged, or raising seed
        #: is requeued up to ``retries`` times before it counts as failed
        self.retries = max(0, int(retries))
        #: per-member RSS ceiling (MB, 0 = off): a member over it is
        #: KILLED (failed manifest + crash report, no retry — a leak
        #: leaks again), unlike max_rss_mb which only delays admission
        self.member_max_rss_mb = max(0, int(member_max_rss_mb or 0))
        self._attempts: dict = {}  # seed -> dispatch attempts so far
        self._inflight_t: dict = {}  # worker idx -> dispatch monotonic
        self._seed_wall_ema = 0.0  # completed-seed wall EMA (stall basis)
        self._interrupted = False
        # sweep-level live endpoint (shadow_tpu/live.py): STATUS ONLY —
        # per-seed lifecycle records for dashboards. Runtime commands are
        # refused by name: a sweep is a batch of independent replayable
        # runs, and mutating one seed mid-sweep would fork its identity.
        self.live = None
        if live_endpoint:
            from shadow_tpu import live as _live

            self.live = _live.LiveServer(
                _live.resolve_endpoint(live_endpoint, self.sweep_dir),
                refuse=lambda norm: (
                    f"sweep endpoint is status-only: {norm['cmd']!r} "
                    f"would fork one seed's identity mid-sweep — attach "
                    f"to a single run's live_endpoint instead"))

    def _publish(self, rec: dict) -> None:
        if self.live is None:
            return
        if self.fork is not None and isinstance(rec.get("seed"), str):
            # forked sweeps stream per-BRANCH progress: same lifecycle
            # records, branch-keyed (branch_dispatched/branch_done/...)
            rec = dict(rec)
            rec["branch"] = rec.pop("seed")
            if isinstance(rec.get("type"), str):
                rec["type"] = rec["type"].replace("seed_", "branch_")
        self.live.publish(rec)

    def _log(self, msg: str) -> None:
        if not self.quiet:
            print(f"fleet: {msg}", file=sys.stderr, flush=True)

    # -- resume ------------------------------------------------------------
    def _completed_seeds(self) -> dict:
        """seed -> manifest for every seed already completed under THIS
        config (status ok + config_digest match); everything else
        re-runs."""
        from shadow_tpu import checkpoint as _ckpt

        done = {}
        for seed in self.seeds:
            p = seed_dir(self.sweep_dir, seed) / SEED_MANIFEST
            if not p.is_file():
                continue
            try:
                man = json.loads(p.read_text())
            except ValueError:
                continue
            if (man.get("format") != MANIFEST_FORMAT
                    or man.get("status") != "ok"):
                continue
            cfg = _member_config(self.config_path, self.overrides,
                                 self.sweep_dir, seed)
            if man.get("config_digest") == _ckpt.config_digest(cfg):
                done[seed] = man
        return done

    # -- workers -----------------------------------------------------------
    def _mp_ctx(self):
        """fork when safe (jax not yet imported in this process — the
        parent deliberately defers the DrawServer's jax import until
        after the workers exist), else spawn. A forked worker inherits
        the parsed-config cache and every pre-imported simulation
        module, which removes the per-worker cold start entirely."""
        import multiprocessing as mp

        if "jax" in sys.modules or not hasattr(os, "fork"):
            return mp.get_context("spawn")
        return mp.get_context("fork")

    def _spawn_worker(self, idx: int):
        ctx = self._mp_ctx()
        parent_conn, child_conn = ctx.Pipe()
        p = ctx.Process(
            target=_fleet_worker_main,
            args=(child_conn, self.config_path, self.overrides,
                  str(self.sweep_dir), idx, self._service_addr,
                  self.pin_cores, self.fork),
            name=f"shadow-fleet-{idx}", daemon=True)
        p.start()
        child_conn.close()
        return p, parent_conn

    def _rss_ok(self) -> bool:
        if not self.max_rss_mb or not self._inflight:
            return True  # nothing resident (or guard off): always admit
        total = sum(_proc_rss_mb(p.pid) for p in self._procs
                    if p is not None and p.is_alive())
        return total < self.max_rss_mb

    # -- the sweep ---------------------------------------------------------
    def run(self) -> dict:
        t_sweep = _walltime.perf_counter()
        self.sweep_dir.mkdir(parents=True, exist_ok=True)
        # validate the config up front: a typo should fail the sweep in
        # milliseconds, not once per worker (a fork plan was already
        # validated end to end by forks.plan_fork)
        if self.fork is None:
            _member_config(self.config_path, self.overrides,
                           self.sweep_dir, self.seeds[0])
        failed: dict = {}
        skipped: list = []
        pending = list(self.seeds)
        if self.resume:
            done = self._completed_seeds()
            skipped = sorted(done)
            pending = [s for s in pending if s not in done]
            self._log(f"resume: {len(skipped)} seed(s) already complete, "
                      f"{len(pending)} to run")
        self._service_addr = None
        server_thread = None
        if self.device_service and pending:
            # choose the socket path NOW (workers need it at spawn) but
            # build the server — which imports jax — on a background
            # thread AFTER the workers exist: with jax unimported the
            # workers fork instantly and start their first seeds while
            # the parent compiles. Members retry a not-yet-listening
            # socket (FleetDrawClient.connect), running the numpy twin
            # until the shared plane publishes.
            import tempfile
            import threading

            d = tempfile.mkdtemp(prefix="stpu_draw_")
            os.chmod(d, 0o700)
            self._service_addr = os.path.join(d, "sock")
        # pre-import the simulation stack (no jax in any of it): forked
        # workers inherit warm modules + the parsed-config doc cache
        import shadow_tpu.checkpoint  # noqa: F401
        import shadow_tpu.core.controller  # noqa: F401
        import shadow_tpu.faults  # noqa: F401
        import shadow_tpu.models.echo  # noqa: F401
        import shadow_tpu.models.gossip  # noqa: F401
        import shadow_tpu.models.tgen  # noqa: F401
        import shadow_tpu.models.tor  # noqa: F401
        import shadow_tpu.network.colplane  # noqa: F401
        import shadow_tpu.network.engine  # noqa: F401
        import shadow_tpu.telemetry.collector  # noqa: F401
        try:
            from shadow_tpu.native import _colcore  # noqa: F401
        except ImportError:
            pass
        try:
            if pending:
                n_workers = min(self.jobs, len(pending))
                for k in range(n_workers):
                    p, conn = self._spawn_worker(k)
                    self._procs.append(p)
                    self._conns.append(conn)
                if self._service_addr is not None:
                    def _build_server():
                        try:
                            # the jax import is background amortization:
                            # take it mildly off the members' first
                            # seeds (per-thread nice; the serving path
                            # resets itself — see DrawServer)
                            os.setpriority(os.PRIO_PROCESS,
                                           threading.get_native_id(), 5)
                        except (AttributeError, OSError):
                            pass
                        try:
                            from shadow_tpu.config import load_config
                            from shadow_tpu.ops.propagate import DrawServer

                            if self.fork is not None:
                                # branches share the trunk's plane shape
                                cfg0 = load_config(
                                    self.config_path,
                                    dict(self.fork["overrides"]),
                                    cache_doc=True)
                            else:
                                cfg0 = _member_config(
                                    self.config_path, self.overrides,
                                    self.sweep_dir, self.seeds[0])
                            self._server = DrawServer(
                                cfg0.general.seed,
                                cfg0.experimental.tpu_max_batch,
                                cfg0.experimental.tpu_mesh_shards,
                                cfg0.experimental.unit_mtus,
                                address=self._service_addr)
                        except Exception as exc:
                            self._log(f"draw service unavailable "
                                      f"({exc}); members attach locally")

                    server_thread = threading.Thread(
                        target=_build_server, name="fleet-draw-server",
                        daemon=True)
                    server_thread.start()
                try:
                    self._dispatch_loop(pending, failed)
                except KeyboardInterrupt:
                    # mid-sweep interrupt: tear down coherently instead
                    # of unwinding through worker pipes — kill in-flight
                    # members, reap the guests they leaked, record their
                    # seeds as interrupted. The summary below is a valid
                    # partial artifact; --resume finishes the sweep.
                    self._interrupted = True
                    self._log("interrupted — tearing down in-flight "
                              "members")
                    for k in list(self._inflight):
                        seed = self._inflight[k]
                        self._kill_member(k)
                        try:
                            _write_failed_manifest(self.sweep_dir, seed,
                                                   "interrupted")
                        except OSError:
                            pass
                        failed[seed] = "interrupted"
                    self._inflight.clear()
                    self._inflight_t.clear()
        finally:
            if server_thread is not None:
                server_thread.join(timeout=120)
            for k, conn in enumerate(self._conns):
                if conn is not None:
                    try:
                        conn.send(("exit",))
                    except (OSError, ValueError):
                        pass
            for p in self._procs:
                if p is not None:
                    p.join(timeout=10)
                    if p.is_alive():
                        p.terminate()
            if self._server is not None:
                self._server.close()
        wall = _walltime.perf_counter() - t_sweep
        service_doc = ({"draw_service": {
            "served_batches": self._server.served_batches,
            "served_units": self._server.served_units,
            "attach_wall_seconds": round(self._server.attach_wall, 3),
        }} if self._server is not None else {})
        if self.fork is not None:
            from shadow_tpu import forks as _forks

            fork_doc = {
                "config": self.config_path,
                "jobs": self.jobs,
                "branches_planned": self.seeds,
                "trunk_checkpoint": self.fork["ckpt"],
                "trunk_dir": self.fork["trunk_dir"],
                "failed": {str(s): failed[s] for s in sorted(failed)},
                "fork_wall_seconds": round(wall, 3),
                "exit_reason": ("interrupted" if self._interrupted
                                else "completed"),
                "retries": self.retries,
                "respawns": self._respawns,
                **service_doc,
            }
            summary = _forks.reduce_fork(self.sweep_dir, extra=fork_doc)
            n_ok = len(summary["completed"])
            self._log(f"fork done: {n_ok}/{len(self.seeds)} branch(es) "
                      f"ok, {len(failed)} failed, wall {wall:.1f}s -> "
                      f"{self.sweep_dir / _forks.FORK_SUMMARY}")
            if self.live is not None:
                self._publish({"type": "end", "ok": n_ok,
                               "failed": len(failed),
                               "wall_seconds": round(wall, 1)})
                self.live.close()
            return summary
        sweep_doc = {
            "config": self.config_path,
            "jobs": self.jobs,
            "seeds": self.seeds,
            "skipped_resume": sorted(skipped),
            "failed": {str(s): failed[s] for s in sorted(failed)},
            "sweep_wall_seconds": round(wall, 3),
            "exit_reason": ("interrupted" if self._interrupted
                            else "completed"),
            "retries": self.retries,
            "respawns": self._respawns,
            **service_doc,
        }
        summary = reduce_sweep(self.sweep_dir, extra=sweep_doc)
        n_ok = len(summary["completed"])
        self._log(f"sweep done: {n_ok}/{len(self.seeds)} seeds ok, "
                  f"{len(failed)} failed, wall {wall:.1f}s -> "
                  f"{self.sweep_dir / SWEEP_SUMMARY}")
        if self.live is not None:
            self._publish({"type": "end", "ok": n_ok,
                           "failed": len(failed),
                           "wall_seconds": round(wall, 1)})
            self.live.close()
        return summary

    def _dispatch_loop(self, pending: list, failed: dict) -> None:
        from multiprocessing.connection import wait as _mpwait

        idle = list(range(len(self._procs)))
        rss_note = 0.0
        while pending or self._inflight:
            # admission: one seed per idle worker, RSS-guarded
            while pending and idle:
                if not self._rss_ok():
                    now = _walltime.monotonic()
                    if now - rss_note > 10:
                        rss_note = now
                        self._log(
                            f"admission paused: fleet RSS over "
                            f"{self.max_rss_mb} MB "
                            f"({len(self._inflight)} resident)")
                    break
                k = idle.pop(0)
                seed = pending.pop(0)
                try:
                    self._conns[k].send(("run", seed))
                except (OSError, ValueError):
                    # worker died before taking the seed: requeue it,
                    # replace the worker, and return the slot to the
                    # idle pool
                    pending.insert(0, seed)
                    self._on_worker_death(k, pending, failed, idle)
                    continue
                self._inflight[k] = seed
                self._inflight_t[k] = _walltime.monotonic()
                self._attempts[seed] = self._attempts.get(seed, 0) + 1
                self._log(f"seed {seed} -> worker {k} "
                          f"({len(pending)} queued, "
                          f"{len(self._inflight)} resident)")
                self._publish({"type": "seed_dispatched", "seed": seed,
                               "worker": k, "queued": len(pending),
                               "resident": len(self._inflight)})
            live = [c for c in self._conns if c is not None]
            if not live:
                break
            ready = _mpwait(live, timeout=0.5)
            self._check_members(pending, failed, idle)
            for conn in ready:
                k = self._conns.index(conn)
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    self._on_worker_death(k, pending, failed,
                                          idle)
                    continue
                op = msg[0]
                if op == "done":
                    _, seed, man = msg
                    self._inflight.pop(k, None)
                    t0 = self._inflight_t.pop(k, None)
                    if t0 is not None:
                        # completed-seed wall EMA: the basis the member
                        # stall deadline is derived from
                        dt = _walltime.monotonic() - t0
                        self._seed_wall_ema = (
                            dt if self._seed_wall_ema == 0.0
                            else 0.7 * self._seed_wall_ema + 0.3 * dt)
                    idle.append(k)
                    self._log(f"seed {seed} ok "
                              f"({man['wall_seconds']}s wall, "
                              f"{man['events']} events)")
                    self._publish({"type": "seed_done", "seed": seed,
                                   "wall_seconds": man["wall_seconds"],
                                   "events": man["events"],
                                   "rounds": man["rounds"]})
                elif op == "failed":
                    _, seed, err, tb = msg
                    self._inflight.pop(k, None)
                    self._inflight_t.pop(k, None)
                    idle.append(k)
                    self._seed_failed(seed, err, pending, failed)
                else:
                    self._inflight.pop(k, None)
                    self._inflight_t.pop(k, None)
                    idle.append(k)

    def _seed_failed(self, seed: int, err: str, pending: list,
                     failed: dict) -> None:
        """One attempt at a seed failed (member raised, died, wedged, or
        hit a ceiling). Bounded retry budget, the supervisor discipline:
        requeue while attempts remain, else record failed — the final
        failed manifest is whatever the last attempt wrote."""
        attempts = self._attempts.get(seed, 1)
        if attempts <= self.retries:
            left = self.retries - attempts + 1
            self._log(f"seed {seed} attempt {attempts} failed: {err} — "
                      f"retrying ({left} retr{'y' if left == 1 else 'ies'}"
                      f" left)")
            self._publish({"type": "seed_retry", "seed": seed,
                           "attempt": attempts, "error": err})
            pending.append(seed)
            return
        failed[seed] = err
        self._log(f"seed {seed} FAILED after {attempts} attempt(s): "
                  f"{err} — sweep continues")
        self._publish({"type": "seed_failed", "seed": seed, "error": err,
                       "attempts": attempts})

    def _member_deadline_s(self):
        """Wall seconds an in-flight member may run before it counts as
        wedged; None = no deadline yet (no completed-seed EMA basis)."""
        fixed = float(os.environ.get(FLEET_STALL_ENV, "0") or 0.0)
        if fixed > 0:
            return fixed
        if self._seed_wall_ema <= 0.0:
            return None
        from shadow_tpu.supervise import stall_deadline_s

        # the supervise deadline curve over the seed-wall EMA, floored at
        # a minute: seeds are whole simulations, not rounds
        return max(stall_deadline_s(self._seed_wall_ema), 60.0)

    def _kill_member(self, k: int) -> None:
        """SIGKILL worker k and reap any real-binary guests its in-flight
        managed seed leaked (guest_pids.jsonl side plane)."""
        p = self._procs[k]
        try:
            if p is not None and p.is_alive():
                p.kill()
        except (OSError, AttributeError):
            pass
        if p is not None:
            p.join(timeout=10)
        seed = self._inflight.get(k)
        if seed is not None:
            _reap_stale_guests(seed_dir(self.sweep_dir, seed))

    def _check_members(self, pending: list, failed: dict,
                       idle: list) -> None:
        """Liveness + resource policing of in-flight members, once per
        dispatch-loop tick: (a) a member past the stall deadline is
        wedged — kill it and retry the seed on a fresh worker; (b) a
        member over the per-member RSS ceiling is leaking — kill it,
        write a crash report, and do NOT retry (a leak leaks again)."""
        if not self._inflight:
            return
        deadline = self._member_deadline_s()
        now = _walltime.monotonic()
        for k in list(self._inflight):
            seed = self._inflight[k]
            p = self._procs[k]
            if self.member_max_rss_mb and p is not None and p.is_alive():
                rss = _proc_rss_mb(p.pid)
                if rss > self.member_max_rss_mb:
                    err = (f"member RSS {rss:.0f} MB over the per-member "
                           f"ceiling {self.member_max_rss_mb} MB — killed")
                    self._log(f"seed {seed}: {err}")
                    self._kill_member(k)
                    from shadow_tpu import supervise as _sup

                    d = seed_dir(self.sweep_dir, seed)
                    d.mkdir(parents=True, exist_ok=True)
                    try:
                        _sup.write_crash_report(
                            d, "member_rss_ceiling",
                            extra={"seed": seed if isinstance(seed, str)
                                   else int(seed),
                                   "rss_mb": round(rss, 1),
                                   "ceiling_mb": self.member_max_rss_mb})
                    except OSError:
                        pass
                    # exhaust the budget: an OOM-class failure is not
                    # transient, rerunning it just OOMs the box later
                    self._attempts[seed] = self.retries + 1
                    self._on_worker_death(k, pending, failed, idle,
                                          reason=err)
                    continue
            t0 = self._inflight_t.get(k)
            if deadline is None or t0 is None or now - t0 <= deadline:
                continue
            err = (f"member wedged: no completion after {now - t0:.1f}s "
                   f"(deadline {deadline:.1f}s) — killed by the fleet "
                   f"watchdog")
            self._log(f"seed {seed}: {err}")
            self._kill_member(k)
            self._on_worker_death(k, pending, failed, idle, reason=err)

    def _on_worker_death(self, k: int, pending: list,
                         failed: dict, idle: list,
                         reason: str = None) -> None:
        """A worker process died (hard crash, OOM kill, or the fleet
        watchdog killed it): route its in-flight seed through the retry
        budget and respawn so the rest of the sweep continues — one
        crashed seed never sinks the fleet."""
        p = self._procs[k]
        code = p.exitcode if p is not None else None
        seed = self._inflight.pop(k, None)
        self._inflight_t.pop(k, None)
        if seed is not None:
            err = reason or f"worker process died (exit code {code})"
            try:
                _write_failed_manifest(self.sweep_dir, seed, err)
            except OSError:
                pass
            self._seed_failed(seed, err, pending, failed)
        try:
            self._conns[k].close()
        except OSError:
            pass
        self._conns[k] = None
        self._procs[k] = None
        self._respawns += 1
        if self._respawns > 2 * (len(self.seeds) * (self.retries + 1)
                                 + self.jobs):
            raise RuntimeError(
                "fleet: worker respawn limit exceeded — the environment "
                "is killing workers faster than seeds can run")
        np_, nc = self._spawn_worker(k)
        self._procs[k] = np_
        self._conns[k] = nc
        if k not in idle:
            idle.append(k)


# -- the reducer --------------------------------------------------------------

#: two-sided 95% Student-t critical values by degrees of freedom (the
#: n<=31 sweep sizes this box runs; beyond that the normal 1.96 is within
#: rounding of t). Source: standard t tables, 3 decimals.
_T95 = {1: 12.706, 2: 4.303, 3: 3.182, 4: 2.776, 5: 2.571, 6: 2.447,
        7: 2.365, 8: 2.306, 9: 2.262, 10: 2.228, 11: 2.201, 12: 2.179,
        13: 2.160, 14: 2.145, 15: 2.131, 16: 2.120, 17: 2.110, 18: 2.101,
        19: 2.093, 20: 2.086, 21: 2.080, 22: 2.074, 23: 2.069, 24: 2.064,
        25: 2.060, 26: 2.056, 27: 2.052, 28: 2.048, 29: 2.045, 30: 2.042}


def t_ci95(vals: list) -> dict:
    """t-based 95% CI of the mean of per-seed statistics (the cross-run
    inference "Once is Never Enough" prescribes: the statistic is
    computed per run, the interval across runs)."""
    n = len(vals)
    if n == 0:
        return {"n": 0}
    mean = sum(vals) / n
    if n == 1:
        return {"n": 1, "mean": round(mean, 3)}
    var = sum((v - mean) ** 2 for v in vals) / (n - 1)
    s = math.sqrt(var)
    t = _T95.get(n - 1, 1.960)
    hw = t * s / math.sqrt(n)
    return {"n": n, "mean": round(mean, 3), "stdev": round(s, 3),
            "lo": round(mean - hw, 3), "hi": round(mean + hw, 3),
            "half_width": round(hw, 3)}


def reduce_sweep(sweep_dir, extra: dict = None) -> dict:
    """K-way merge the per-seed histogram states + manifests under
    ``sweep_dir`` into ``sweep_summary.json`` (module doc). Idempotent:
    pure function of the on-disk per-seed artifacts."""
    from shadow_tpu.telemetry.histogram import LogHistogram

    sweep_dir = Path(sweep_dir)
    if extra is None:
        # re-reduction (the report subcommand): carry the original run's
        # orchestration metadata forward instead of erasing it
        try:
            prev = json.loads((sweep_dir / SWEEP_SUMMARY).read_text())
            extra = {k: prev[k] for k in
                     ("config", "jobs", "seeds", "skipped_resume",
                      "sweep_wall_seconds", "draw_service")
                     if k in prev}
        except (OSError, ValueError):
            extra = None
    # a sweep's seed roster bounds the reduction: seed dirs left behind
    # by an earlier, differently-scoped sweep into the same directory
    # must not pollute the pooled histograms or inflate the CIs
    roster = set((extra or {}).get("seeds") or ()) or None
    manifests = []
    for p in sorted(sweep_dir.glob("seed_*/" + SEED_MANIFEST),
                    key=lambda p: int(p.parent.name.split("_", 1)[1])):
        try:
            man = json.loads(p.read_text())
        except ValueError:
            continue
        if man.get("format") != MANIFEST_FORMAT:
            continue
        if roster is not None and man.get("seed") not in roster:
            continue
        manifests.append(man)
    completed = [m for m in manifests if m.get("status") == "ok"]
    failed = {str(m["seed"]): m.get("error", "unknown")
              for m in manifests if m.get("status") != "ok"}
    # per-seed mergeable telemetry states, in seed order
    states = []  # (seed, state)
    for m in completed:
        p = seed_dir(sweep_dir, m["seed"]) / TEL_STATE_FILE
        if p.is_file():
            try:
                states.append((m["seed"], json.loads(p.read_text())))
            except ValueError:
                pass
    flows: dict = {}
    kinds = sorted({k for _s, st in states for k in st["flow_counts"]})
    labels = ("p50_ms", "p90_ms", "p99_ms", "p99_9_ms")
    for kind in kinds:
        pooled = LogHistogram.merged(
            [st["hist"][kind] for _s, st in states
             if kind in st["hist"]])
        per_seed = {lab: [] for lab in labels}
        seeds_with = []
        ok = failed_n = x_sum = x_n = 0
        for s, st in states:
            c = st["flow_counts"].get(kind)
            if c is not None:
                ok += c["ok"]
                failed_n += c["failed"]
                x_sum += c.get("x_sum", 0)
                x_n += c.get("x_n", 0)
            hs = st["hist"].get(kind)
            if hs is None:
                continue
            q = LogHistogram.from_state(hs).quantiles_ns_to_ms()
            seeds_with.append(s)
            for lab in labels:
                per_seed[lab].append(q[lab])
        flows[kind] = {
            "count": ok + failed_n,
            "ok": ok,
            "failed": failed_n,
            "pooled": pooled.quantiles_ns_to_ms(),
            "seeds": seeds_with,
            "per_seed": per_seed,
            "ci95": {lab: t_ci95(per_seed[lab]) for lab in labels},
        }
        if x_n:
            flows[kind]["x_mean"] = x_sum // x_n
    doc = {
        "format": SUMMARY_FORMAT,
        "n_seeds": len(manifests),
        "completed": [m["seed"] for m in completed],
        "failed": failed,
        "per_seed_wall_seconds": {
            str(m["seed"]): m.get("wall_seconds") for m in completed},
        "events_total": sum(m.get("events", 0) for m in completed),
        "flows": flows,
        **(extra or {}),
    }
    _write_json(sweep_dir / SWEEP_SUMMARY, doc)
    return doc


def render_report(summary: dict) -> str:
    """Human-readable sweep report (tools/metrics_report.py lineage)."""
    lines = []
    n_ok = len(summary.get("completed", []))
    failed = summary.get("failed", {})
    lines.append(
        f"sweep: {summary.get('n_seeds', n_ok)} seed(s), {n_ok} ok, "
        f"{len(failed)} failed"
        + (f", jobs={summary['jobs']}" if "jobs" in summary else "")
        + (f", wall {summary['sweep_wall_seconds']}s"
           if "sweep_wall_seconds" in summary else ""))
    for s, err in sorted(failed.items(), key=lambda kv: kv[0]):
        lines.append(f"  FAILED seed {s}: {err}")
    svc = summary.get("draw_service")
    if svc:
        lines.append(
            f"  shared draw service: {svc['served_batches']} batches / "
            f"{svc['served_units']} units served, one attach "
            f"({svc['attach_wall_seconds']}s)")
    flows = summary.get("flows", {})
    if not flows:
        lines.append("  (no flow telemetry recorded — enable telemetry "
                     "for cross-seed percentile CIs)")
        return "\n".join(lines)
    lines.append("")
    hdr = (f"  {'flow group':<18} {'n':>8} {'ok':>8} "
           f"{'pooled p50/p90/p99 ms':>26}   "
           f"{'p50 CI95':>20} {'p99 CI95':>20}")
    lines.append(hdr)
    lines.append("  " + "-" * (len(hdr) - 2))

    def ci_str(ci):
        if ci.get("n", 0) < 2:
            return f"{ci.get('mean', '-')} (n<2)"
        return f"{ci['mean']:.1f} ± {ci['half_width']:.1f}"

    for kind in sorted(flows):
        f = flows[kind]
        pooled = f["pooled"]
        lines.append(
            f"  {kind:<18} {f['count']:>8} {f['ok']:>8} "
            f"{pooled['p50_ms']:>8.1f}/{pooled['p90_ms']:>7.1f}/"
            f"{pooled['p99_ms']:>8.1f}   "
            f"{ci_str(f['ci95']['p50_ms']):>20} "
            f"{ci_str(f['ci95']['p99_ms']):>20}")
    lines.append("")
    lines.append("  CI95: t-based over per-seed percentiles (seeds are "
                 "the independent unit; pooled = all seeds merged into "
                 "one histogram)")
    return "\n".join(lines)


# -- CLI ----------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shadow_tpu.fleet",
        description="fleet mode: N-seed simulation sweeps with mergeable "
                    "cross-run statistics")
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("sweep", help="run an N-seed sweep")
    ps.add_argument("config", help="simulation YAML config file")
    ps.add_argument("--seeds", type=int, default=10, metavar="N",
                    help="number of seeds (base, base+1, ..., base+N-1); "
                    "default 10")
    ps.add_argument("--seed-base", type=int, default=None,
                    help="first seed (default: the config's general.seed)")
    ps.add_argument("--jobs", type=int, default=2, metavar="M",
                    help="concurrent member simulations (default 2)")
    ps.add_argument("--sweep-dir", default=None,
                    help="sweep output root (default: <config-stem>.sweep)")
    ps.add_argument("--resume", action="store_true",
                    help="skip seeds whose per-seed manifest is already "
                    "complete under this config")
    ps.add_argument("--stop-time", help="override general.stop_time")
    ps.add_argument("--set", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="override any config option by dotted path; "
                    "repeatable")
    ps.add_argument("--max-rss-mb", type=int, default=None,
                    help="admission guard: pause handing out new seeds "
                    "while fleet RSS exceeds this (default: 80%% of "
                    "MemTotal; 0 disables)")
    ps.add_argument("--member-max-rss-mb", type=int, default=0,
                    metavar="MB",
                    help="per-member RSS ceiling: a member over it is "
                    "killed, its seed recorded failed with a crash "
                    "report, and NOT retried (default 0 = off)")
    ps.add_argument("--retries", type=int, default=1, metavar="N",
                    help="bounded retry budget per seed: a crashed or "
                    "wedged seed is requeued up to N times before it "
                    "counts as failed (default 1; 0 disables)")
    ps.add_argument("--no-pin", action="store_true",
                    help="do not pin worker processes to cores")
    ps.add_argument("--no-device-service", action="store_true",
                    help="members attach the device individually instead "
                    "of sharing the parent's attach")
    ps.add_argument("--no-telemetry", action="store_true",
                    help="do not auto-enable telemetry (no flow "
                    "percentiles or CIs in the sweep summary)")
    ps.add_argument("--live-endpoint", metavar="PATH",
                    help="bind a STATUS-ONLY AF_UNIX endpoint streaming "
                    "per-seed lifecycle records (dispatched/done/failed); "
                    "runtime commands are refused — 'auto' = "
                    "<sweep-dir>/live.sock")
    ps.add_argument("--quiet", action="store_true",
                    help="no progress lines on stderr")
    ps.add_argument("--json", action="store_true",
                    help="print the sweep summary as one JSON line on "
                    "stdout instead of the report")
    ps.add_argument("--fork-from", metavar="CKPT", default=None,
                    help="fork mode (shadow_tpu/forks.py): restore this "
                    "trunk checkpoint into every worker and run the "
                    "--branches divergence specs instead of seeds")
    ps.add_argument("--branches", metavar="FILE", default=None,
                    help="branches.yaml for --fork-from: the per-branch "
                    "divergence specs")
    ps.add_argument("--trunk-dir", metavar="DIR", default=None,
                    help="the trunk run directory for --fork-from "
                    "(default: derived from the checkpoint path's "
                    "<trunk>/checkpoints/ layout)")
    pr = sub.add_parser("report",
                        help="re-reduce + render a sweep (or fork) "
                        "directory")
    pr.add_argument("sweep_dir")
    pr.add_argument("--json", action="store_true",
                    help="print the summary JSON instead of the report")
    pr.add_argument("--compare", action="store_true",
                    help="fork directories: render only the comparative "
                    "table (per-group percentile deltas vs the trunk "
                    "with CI95)")
    return p


def _sweep_overrides(args, fork: bool = False) -> dict:
    import yaml as _yaml

    over: dict = {}
    if args.stop_time:
        over["general.stop_time"] = args.stop_time
    for item in args.set:
        if "=" not in item:
            print(f"fleet: --set expects KEY=VALUE, got {item!r}",
                  file=sys.stderr)
            raise SystemExit(2)
        k, v = item.split("=", 1)
        over[k] = _yaml.safe_load(v)
    if fork:
        # a fork inherits the trunk's telemetry settings verbatim —
        # auto-enabling here would re-cadence streams the trunk already
        # started (forks.plan_fork refuses explicit telemetry overrides
        # with the full story)
        return over
    if not args.no_telemetry and not any(
            k.startswith("telemetry") for k in over):
        # the whole point of a sweep is cross-seed percentiles: enable
        # the telemetry subsystem (at its default cadence) unless the
        # config/overrides already speak for it — a standalone run with
        # the same telemetry settings stays byte-identical
        from shadow_tpu.config.schema import load_yaml_doc

        if "telemetry" not in (load_yaml_doc(args.config, cache=True)
                               or {}):
            over["telemetry.sample_every"] = "10s"
    return over


def _is_fork_dir(d) -> bool:
    from shadow_tpu import forks as _forks

    d = Path(d)
    return ((d / _forks.FORK_SUMMARY).is_file()
            or any(sorted(d.glob("branch_*/" + _forks.FORK_MANIFEST))))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "report":
        if _is_fork_dir(args.sweep_dir):
            from shadow_tpu import forks as _forks

            summary = _forks.reduce_fork(args.sweep_dir)
            print(json.dumps(summary) if args.json
                  else _forks.render_compare(summary) if args.compare
                  else _forks.render_fork_report(summary))
            return 0 if not summary["failed"] else 1
        if args.compare:
            print(f"fleet: {args.sweep_dir} is a seed sweep, not a fork "
                  f"— --compare diffs fork branches against their trunk",
                  file=sys.stderr)
            return 2
        summary = reduce_sweep(args.sweep_dir)
        print(json.dumps(summary) if args.json
              else render_report(summary))
        return 0 if not summary["failed"] else 1
    try:
        fork_plan = None
        if args.fork_from or args.branches:
            if not (args.fork_from and args.branches):
                print("fleet: --fork-from and --branches go together "
                      "(a fork needs both the trunk checkpoint and the "
                      "divergence specs)", file=sys.stderr)
                return 2
            if args.resume:
                print("fleet: a fork cannot --resume — just re-run it",
                      file=sys.stderr)
                return 2
        over = _sweep_overrides(args, fork=bool(args.fork_from))
        if args.fork_from:
            from shadow_tpu import forks as _forks

            sweep_dir = (args.sweep_dir
                         or (Path(args.config).stem + ".fork"))
            branches = _forks.load_branches(args.branches)
            fork_plan = _forks.plan_fork(
                args.config, args.fork_from, branches, sweep_dir,
                overrides=over, trunk_dir=args.trunk_dir)
            seeds = fork_plan["order"]
        else:
            if args.seed_base is not None:
                base = int(args.seed_base)
            else:
                from shadow_tpu.config.schema import load_yaml_doc

                doc = load_yaml_doc(args.config, cache=True)
                base = int(((doc or {}).get("general") or {})
                           .get("seed", 1))
            seeds = [base + i for i in range(int(args.seeds))]
            sweep_dir = (args.sweep_dir
                         or (Path(args.config).stem + ".sweep"))
        runner = FleetRunner(
            args.config, seeds, args.jobs, sweep_dir, overrides=over,
            resume=args.resume, max_rss_mb=args.max_rss_mb,
            pin_cores=not args.no_pin,
            device_service=not args.no_device_service, quiet=args.quiet,
            live_endpoint=args.live_endpoint, retries=args.retries,
            member_max_rss_mb=args.member_max_rss_mb, fork=fork_plan)
        summary = runner.run()
    except FileNotFoundError as exc:
        print(f"fleet: config file not found: "
              f"{getattr(exc, 'filename', None) or exc}", file=sys.stderr)
        return 2
    except (ValueError, OSError) as exc:
        print(f"fleet: {exc}", file=sys.stderr)
        return 2
    if fork_plan is not None:
        from shadow_tpu import forks as _forks

        print(json.dumps(summary) if args.json
              else _forks.render_fork_report(summary))
    else:
        print(json.dumps(summary) if args.json
              else render_report(summary))
    if summary.get("exit_reason") == "interrupted":
        return 130  # conventional SIGINT status; the summary above is a
        # valid partial artifact and --resume finishes the sweep
    return 0 if not summary["failed"] else 1


if __name__ == "__main__":
    sys.exit(main())
