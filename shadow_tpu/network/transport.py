"""Transports for plugin workloads: reliable streams (TCP-like) and datagrams.

Re-designs the reference's userspace TCP + UDP socket layer (SURVEY.md §1
layer 9, §2 "TCP stack") as a *fluid* model suited to batched per-round
simulation. Round 2 hardening (VERDICT.md item #5) made the stream layer a
real protocol; round 5 made loss recovery self-contained (dup-ack fast
retransmit), and the round 2-4 engine-notification loss model was deleted
per its COMPONENTS.md #13 retirement criterion:

- **Cumulative acks + sequence accounting.** Every DATA unit carries its
  byte offset; the receiver tracks ``rcv_nxt``, buffers out-of-order
  chunks (bounded by ``experimental.socket_recv_buffer``), discards
  duplicates, and acks cumulatively with its advertised window. A lost ACK
  is repaired by any later ACK — no cross-host bookkeeping (round 1's
  ``_peer_sender`` reach-across is gone).
- **Retransmission machinery with SACK.** Two layers, like TCP's
  fast-retransmit vs RTO: the receiver acks out-of-order data immediately
  and attaches SACK blocks (its merged received-unit ranges, up to 4,
  RFC 2018-shaped — encoded in the ACK's payload field, wire size
  unchanged); the sender keeps a scoreboard of SACKed segments, counts
  consecutive duplicate acks, and the 3rd enters recovery: multiplicative
  decrease + retransmission of EVERY un-SACKed hole below the highest
  SACKed byte in one burst — a multi-unit loss burst repairs in one RTT
  instead of the pre-PR-9 one-retransmit-per-RTT crawl. While in recovery,
  each partial ack or newly arrived SACK block retransmits newly exposed
  holes (each hole at most once per recovery episode); recovery ends when
  the cumulative ack reaches the recovery point. An RTO timer (2x path
  RTT, exponential backoff, RTO_MAX_NS ceiling) independently guarantees
  progress for every pattern duplicate acks do not cover (lost ACKs, lost
  retransmits, tail loss); an RTO discards the scoreboard (renege safety,
  RFC 2018 §8) and falls back to go-back-N from the oldest hole. Control
  units use pure timers: SYN and FIN retransmit
  on RTO with bounded retries; SYNACK loss is repaired by SYN retransmit +
  the server's duplicate-SYN re-ack; FINACK loss by FIN retransmit + the
  TIME_WAIT re-ack below.
- **Flow control.** Senders respect ``min(cwnd, peer advertised window)``;
  the handshake exchanges initial windows; ``send()`` accepts at most
  ``experimental.socket_send_buffer`` un-segmented bytes and returns the
  accepted count (POSIX write semantics), with ``on_drain`` callbacks as
  buffer space frees.
- **Orderly close with half-close.** FIN only after all of the closer's
  data is acked; a receiver still mid-stream defers its FINACK until its
  own outbound data drains (the FIN sender keeps receiving in FIN_SENT,
  like TCP's FIN_WAIT half-close). The FINACK side lingers in TIME_WAIT
  (2x RTO) to re-ack duplicate FINs, then the endpoint is dropped — no
  stranded connections (tests assert ``_conns`` empties; exhausted retries
  force-drop like TCP's orphan timeout).

Congestion control is pluggable behind the ``CongestionControl`` seam
(selected per host via ``experimental.congestion_control`` or the
per-host ``congestion_control`` key): ``newreno`` is the extracted
default (standard slow-start + AIMD, RFC 5681 shaped, in integer bytes —
bit-identical to the pre-seam behavior), ``cubic`` a CUBIC-shaped
variant (RFC 8312's time-based cubic window in pure integer arithmetic,
beta 0.7, C = 0.4 — every operation is int64-safe and
floor-division-free on negatives so the C twin computes the exact same
windows). Datagram sockets fragment payloads into units and reassemble
at the receiver; losing any fragment loses the datagram (IP semantics).

Telemetry contract (shadow_tpu/telemetry/): the sampler aggregates, per
host connection, ``sender.{snd_nxt, snd_una, cwnd, ssthresh, loss_events,
retries, rto_backoff, buffered}`` and models read
``sender.loss_events`` / ``receiver.bytes_received`` at flow close. Every
field in that set is exposed IDENTICALLY by the C endpoint twin
(native/colcore ``CEp`` getters) — extending the sampled set means adding
the matching C getter, or the telemetry streams stop being byte-identical
across the Python/C twins (tests/test_telemetry.py enforces this).
"""

from __future__ import annotations

from bisect import bisect_left, insort
from collections import deque
from typing import Callable, Optional

from shadow_tpu.core.time import NS_PER_MS, SimTime
from shadow_tpu.network.fluid import HEADER
from shadow_tpu.network import unit as U
from shadow_tpu.network.unit import Unit

MSS = 1460  # cwnd growth quantum (classic ethernet MSS)
INIT_CWND = 10 * MSS  # RFC 6928
MIN_CWND = 2 * MSS
RTO_MIN_NS = 200 * NS_PER_MS
#: RTO ceiling (TCP's conventional 60 s): a connection CREATED while its
#: path is cut (faults.py blackholes it with INF latency) derives its
#: timeout from the effective matrix, and an uncapped 2x-INF RTO both
#: stalls retries forever and overflows the C twin's int64 timer math.
#: Physical latencies are ms-scale, so the cap only binds on cut paths.
RTO_MAX_NS = 60_000 * NS_PER_MS
SYN_RETRIES = 5
FIN_RETRIES = 5
DATA_RETRIES = 8  # consecutive data RTOs before the connection resets

#: SACK blocks per ack (TCP fits 3-4 in the options space; we keep 4)
SACK_MAX_BLOCKS = 4


def _icbrt(x: int) -> int:
    """Floor integer cube root (binary search; operands stay < 2**60 so
    the C twin computes the identical result in int64)."""
    lo, hi = 0, 1 << 20
    while lo < hi:
        mid = (lo + hi + 1) >> 1
        if mid * mid * mid <= x:
            lo = mid
        else:
            hi = mid - 1
    return lo


def _sb_has(sb: list, seq: int) -> bool:
    """Sorted-scoreboard membership (bisect; the lists are tiny — entries
    are a subset of the rtx seqs under loss)."""
    i = bisect_left(sb, seq)
    return i < len(sb) and sb[i] == seq


class CongestionControl:
    """The pluggable congestion-control seam: pure window arithmetic over
    the sender's integer state (cwnd/ssthresh plus the cubic epoch fields
    w_max/epoch_start, which live ON the sender so checkpoint export and
    the determinism fingerprint stay uniform across algorithms).

    Contract: every hook mutates only ``s.cwnd``/``s.ssthresh``/
    ``s.w_max``/``s.epoch_start``, in integer arithmetic with no negative
    floor divisions — the C endpoint twin (native/colcore ``cc_*``
    functions, dispatched on the same ``cc_id``) must reproduce every
    result bit-exactly in int64, so any new algorithm needs BOTH halves
    or the cross-plane byte-identity gates fail."""

    name = "?"
    cc_id = -1

    def on_ack(self, s: "StreamSender", newly: int) -> None:
        """``newly`` bytes newly acknowledged (called for every
        cumulative advance, including during recovery — ack-clocked
        growth, like the pre-seam behavior)."""
        raise NotImplementedError

    def on_loss(self, s: "StreamSender") -> None:
        """Entering fast-retransmit recovery (3rd duplicate ack)."""
        raise NotImplementedError

    def on_rto(self, s: "StreamSender") -> None:
        """Retransmission timeout: collapse to slow start."""
        raise NotImplementedError


class NewReno(CongestionControl):
    """RFC 5681-shaped slow start + AIMD — the extracted default,
    bit-identical to the pre-seam inline arithmetic."""

    name = "newreno"
    cc_id = 0

    def on_ack(self, s, newly):
        if s.cwnd < s.ssthresh:
            s.cwnd += min(newly, s.cwnd)  # slow start (doubles/RTT)
        else:
            s.cwnd += max(1, MSS * newly // s.cwnd)  # AIMD

    def on_loss(self, s):
        s.ssthresh = max(s.inflight // 2, MIN_CWND)
        s.cwnd = max(s.cwnd // 2, MIN_CWND)

    def on_rto(self, s):
        s.ssthresh = max(s.inflight // 2, MIN_CWND)
        s.cwnd = MIN_CWND


class CubicLike(CongestionControl):
    """CUBIC-shaped variant (RFC 8312 reduced to integer arithmetic):
    beta = 0.7 multiplicative decrease, and congestion avoidance grows
    toward the cubic function W(t) = C*(t-K)^3 + w_max with C = 0.4 and
    t measured from the last decrease (``s.epoch_start``). All division
    operands are clamped non-negative and below 2**63 so the C twin's
    truncating int64 division equals Python's floor division."""

    name = "cubic"
    cc_id = 1

    def on_ack(self, s, newly):
        if s.cwnd < s.ssthresh:
            s.cwnd += min(newly, s.cwnd)  # slow start, shared shape
            return
        now = s.ep.host._now
        if s.epoch_start == 0:  # first CA ack with no recorded epoch
            s.epoch_start = now
            s.w_max = s.cwnd
        t_ms = (now - s.epoch_start) // NS_PER_MS
        # K = cbrt(w_max * beta_decrement / C) seconds, in ms; operands
        # clamped so (…)*1e9 stays under 2**63 in the C twin
        wmax_c = min(s.w_max, 1 << 32)
        k_ms = _icbrt((wmax_c * 3 // (4 * MSS)) * 1_000_000_000)
        d = t_ms - k_ms
        if d > 200_000:
            d = 200_000
        elif d < -200_000:
            d = -200_000
        a = -d if d < 0 else d
        # C*(t-K)^3 with C = 0.4*MSS bytes/s^3: cube in ms^3, scaled by
        # 4*MSS/10 over 1e9 — split into two non-negative divisions
        delta = (a * a * a // 1_000_000) * (4 * MSS) // 10_000
        target = s.w_max - delta if d < 0 else s.w_max + delta
        if target < MIN_CWND:
            target = MIN_CWND
        elif target > 1 << 45:
            target = 1 << 45
        nn = min(newly, 1 << 20)
        if s.cwnd < target:
            dd = min(target - s.cwnd, 1 << 40)
            inc = dd * nn // s.cwnd
            s.cwnd = min(s.cwnd + (inc if inc > 1 else 1), target)
        else:
            # at/above the cubic target: slow reno-friendly creep
            inc = MSS * nn // (100 * s.cwnd)
            s.cwnd += inc if inc > 1 else 1

    def on_loss(self, s):
        s.w_max = s.cwnd
        s.epoch_start = s.ep.host._now
        nc = s.cwnd * 7 // 10
        s.ssthresh = s.cwnd = nc if nc > MIN_CWND else MIN_CWND

    def on_rto(self, s):
        s.w_max = s.cwnd
        s.epoch_start = s.ep.host._now
        half = s.inflight // 2
        s.ssthresh = half if half > MIN_CWND else MIN_CWND
        s.cwnd = MIN_CWND


#: config name -> class (config/schema.py validates against these keys)
CONGESTION_CONTROLS = {"newreno": NewReno, "cubic": CubicLike}


class StreamSender:
    """The sending half of one endpoint: segmentation, windows, retransmit."""

    def __init__(self, endpoint: "StreamEndpoint", send_buffer: int,
                 cc: Optional[CongestionControl] = None):
        self.ep = endpoint
        self.chunk = endpoint.host.unit_chunk  # fluid quantum payload size
        self.cc = cc if cc is not None else NewReno()
        self.cwnd = INIT_CWND
        self.ssthresh = 1 << 62
        self.send_buffer = send_buffer
        self.snd_nxt = 0  # next byte offset to segment
        self.snd_una = 0  # oldest unacknowledged byte
        self.adv_wnd = INIT_CWND  # peer's advertised window (from handshake)
        self.sendbuf: deque[tuple[int, Optional[bytes]]] = deque()
        self.buffered = 0  # bytes in sendbuf (not yet segmented)
        self.rtx: deque[tuple[int, int, Optional[bytes]]] = deque()  # (seq, n, payload)
        self.rto_timer: Optional[int] = None
        self.rto_backoff = 1
        self.retries = 0
        self.loss_events = 0
        self.bytes_acked = 0
        self.dup_acks = 0  # consecutive duplicate acks (RFC 5681 counting)
        #: SACK scoreboard: seqs of rtx entries the peer reported holding
        #: (pruned as the cumulative ack passes them), the highest SACKed
        #: byte seen since the last RTO (holes live strictly below it),
        #: and the per-recovery-episode list of already-retransmitted seqs
        #: — "all holes per RTT" means each hole at most once per episode.
        #: Both are SORTED lists (PR 11), not sets: membership stays cheap
        #: at scoreboard scale (entries ⊆ rtx seqs, a handful under real
        #: loss), iteration order is canonical by construction — the
        #: columnar transport export (network/devtransport.py) and the
        #: determinism fingerprint read them without a sort or a detlint
        #: unordered-iteration waiver
        self.sacked: list[int] = []
        self.sack_high = 0
        self.rtx_done: list[int] = []
        self.in_recovery = False
        self.recover = 0  # recovery point: snd_nxt when recovery began
        #: cubic epoch state (CongestionControl contract: on the sender)
        self.w_max = 0
        self.epoch_start = 0

    # -- app side ----------------------------------------------------------
    def queue(self, nbytes: int, payload: Optional[bytes]) -> int:
        room = self.send_buffer - self.buffered
        accept = min(nbytes, max(room, 0))
        if accept <= 0:
            return 0
        self.sendbuf.append((accept, payload[:accept] if payload is not None else None))
        self.buffered += accept
        self.pump()
        return accept

    @property
    def inflight(self) -> int:
        return self.snd_nxt - self.snd_una

    def pump(self) -> None:
        ep = self.ep
        if ep.state not in (ESTABLISHED, CLOSING):
            return  # not yet connected (or closing past data); connect re-pumps
        window = min(self.cwnd, max(self.adv_wnd, MSS))
        while self.buffered > 0 and self.inflight < window:
            usable = window - self.inflight
            # silly-window avoidance (Nagle-shaped): emit only full-size
            # chunks or the final tail of the app buffer; sub-chunk window
            # remainders wait for more acks — except when idle, where
            # sending something is what restarts the ack clock
            if usable < self.chunk and usable < self.buffered and self.inflight > 0:
                break
            budget = min(usable, self.chunk)
            nbytes, payload = self.sendbuf[0]
            if nbytes <= budget:
                self.sendbuf.popleft()
                chunk_p = payload
            else:
                chunk_p = payload[:budget] if payload is not None else None
                rest_p = payload[budget:] if payload is not None else None
                self.sendbuf[0] = (nbytes - budget, rest_p)
                nbytes = budget
            self.buffered -= nbytes
            seq = self.snd_nxt
            self.snd_nxt += nbytes
            self.rtx.append((seq, nbytes, chunk_p))
            self._emit_data(seq, nbytes, chunk_p)
        if self.inflight > 0:
            self._arm_rto()
        elif self.buffered == 0:
            self.ep._on_sender_drained()

    def _emit_data(self, seq: int, nbytes: int, payload: Optional[bytes]) -> None:
        # recovery comes entirely from duplicate acks like real TCP — the
        # sender gets no simulator-side loss information
        self.ep.emit(U.DATA, nbytes=nbytes, payload=payload, seq=seq)

    # -- loss recovery (SACK) ----------------------------------------------
    def _apply_sack(self, payload: bytes) -> None:
        """Fold an arriving ack's SACK blocks (pairs of big-endian u64
        byte offsets) into the scoreboard: mark every rtx segment fully
        covered by a block, and track the highest SACKed byte."""
        sacked = self.sacked
        for off in range(0, len(payload) - 15, 16):
            a = int.from_bytes(payload[off:off + 8], "big")
            b = int.from_bytes(payload[off + 8:off + 16], "big")
            if b > self.sack_high:
                self.sack_high = b
            for seq, n, _p in self.rtx:
                if seq >= b:
                    break  # rtx is seq-ascending
                if seq >= a and seq + n <= b and not _sb_has(sacked, seq):
                    insort(sacked, seq)

    def _retransmit_holes(self, force_head: bool = False) -> int:
        """Retransmit every un-SACKed, not-yet-retransmitted segment
        below the highest SACKed byte — ALL holes in one burst, so a
        multi-unit loss repairs in one RTT. ``force_head`` additionally
        retransmits the oldest segment even without SACK cover (the
        no-SACK-info entry fallback and the NewReno partial-ack rule).
        Returns the number of segments emitted."""
        hi = self.sack_high
        sacked, done = self.sacked, self.rtx_done
        emitted = 0
        for i, (seq, n, p) in enumerate(self.rtx):
            if seq >= hi and not (force_head and i == 0):
                break  # rtx is seq-ascending: nothing past hi is a hole
            if _sb_has(sacked, seq) or _sb_has(done, seq):
                continue
            insort(done, seq)
            self._emit_data(seq, n, p)
            emitted += 1
        return emitted

    def _enter_recovery(self) -> None:
        """The fast-retransmit response (3rd consecutive duplicate ack):
        multiplicative decrease + retransmit of every known hole + RTO
        reset."""
        self.loss_events += 1
        host = self.ep.host
        if host.faults_active:
            host.counters.add("stream_fast_retransmits", 1)
        self.in_recovery = True
        self.recover = self.snd_nxt
        self.rtx_done.clear()
        self.cc.on_loss(self)
        emitted = self._retransmit_holes(force_head=True)
        if emitted > 1 and host.faults_active:
            host.counters.add("stream_sack_retransmits", emitted - 1)
        self._arm_rto(reset=True)

    def _exit_recovery(self) -> None:
        self.in_recovery = False
        self.rtx_done.clear()

    def _arm_rto(self, reset: bool = False) -> None:
        if reset and self.rto_timer is not None:
            self.ep.host.cancel(self.rto_timer)
            self.rto_timer = None
        if self.rto_timer is None:
            self.rto_timer = self.ep.host.schedule_in(
                self.ep.rto_ns * self.rto_backoff, self._on_rto)

    def _cancel_rto(self) -> None:
        if self.rto_timer is not None:
            self.ep.host.cancel(self.rto_timer)
            self.rto_timer = None

    def _on_rto(self) -> None:
        self.rto_timer = None
        if self.inflight == 0 or self.ep.state in (CLOSED, TIME_WAIT):
            return
        if self.adv_wnd > 0:
            # zero-window retransmits are persist probes, not losses: TCP
            # probes a closed peer window indefinitely instead of counting
            # toward the retry limit (the backoff below still applies)
            self.retries += 1
        if self.retries > DATA_RETRIES:
            # terminal ETIMEDOUT: an established connection whose peer is
            # unreachable (crashed host, unhealed partition) dies here,
            # like TCP's retransmission timeout — the application sees
            # connection death instead of a silent stall (faults.py)
            host = self.ep.host
            if host.faults_active:
                host.counters.add("stream_timeouts", 1)
            self.ep._reset(
                "connection timed out (ETIMEDOUT): data retransmission "
                "retries exhausted")
            return
        if self.ep.host.faults_active:
            self.ep.host.counters.add("stream_rto_retransmits", 1)
        # classic RTO response: collapse to slow start, back off, resend the
        # oldest unacked chunk (its ACK, cumulative, repairs everything
        # else). The SACK scoreboard is discarded (RFC 2018 §8 renege
        # safety): after a timeout the receiver's reported state is stale.
        self.sacked.clear()
        self.rtx_done.clear()
        self.sack_high = 0
        self.in_recovery = False
        self.cc.on_rto(self)
        self.rto_backoff = min(self.rto_backoff * 2, 64)
        seq, nbytes, payload = self.rtx[0]
        self._emit_data(seq, nbytes, payload)
        self._arm_rto()

    # -- ack processing ----------------------------------------------------
    def on_ack(self, cum_ack: int, wnd: int,
               sack: Optional[bytes] = None) -> None:
        prev_wnd = self.adv_wnd
        self.adv_wnd = wnd
        if sack is not None:
            self._apply_sack(sack)
        if cum_ack > self.snd_una:
            self.dup_acks = 0
            newly = cum_ack - self.snd_una
            self.snd_una = cum_ack
            self.bytes_acked += newly
            while self.rtx and self.rtx[0][0] + self.rtx[0][1] <= cum_ack:
                self.rtx.popleft()
            if self.sacked:
                del self.sacked[:bisect_left(self.sacked, cum_ack)]
            if self.rtx_done:
                del self.rtx_done[:bisect_left(self.rtx_done, cum_ack)]
            self.rto_backoff = 1
            self.retries = 0
            self._cancel_rto()
            if self.inflight > 0:
                self._arm_rto()
            if self.in_recovery:
                if self.snd_una >= self.recover:
                    self._exit_recovery()
                else:
                    # partial ack: the oldest hole arrived but the burst
                    # is not repaired — retransmit the NEW oldest segment
                    # (NewReno partial-ack rule) plus any holes the
                    # scoreboard newly exposes, each at most once
                    n = self._retransmit_holes(force_head=True)
                    if n and self.ep.host.faults_active:
                        self.ep.host.counters.add(
                            "stream_sack_retransmits", n)
            self.cc.on_ack(self, newly)
            drained = self.ep.on_drain
            if drained is not None and self.buffered < self.send_buffer:
                drained(self.send_buffer - self.buffered)
        elif (cum_ack == self.snd_una
              and wnd == prev_wnd and self.inflight > 0 and self.rtx):
            # duplicate ack (RFC 5681: same cum, same window, data
            # outstanding); the 3rd CONSECUTIVE one enters recovery and
            # retransmits EVERY hole the scoreboard knows about
            self.dup_acks += 1
            if self.dup_acks == 3 and not self.in_recovery:
                self._enter_recovery()
            elif self.in_recovery and sack is not None:
                # later dup acks can expose new holes (higher sack_high)
                n = self._retransmit_holes()
                if n and self.ep.host.faults_active:
                    self.ep.host.counters.add("stream_sack_retransmits", n)
        else:
            self.dup_acks = 0  # anything else breaks the consecutive run
        self.pump()  # pump() fires _on_sender_drained when fully drained


class StreamReceiver:
    """Receiving half: in-order delivery, OOO buffering, cumulative acks."""

    def __init__(self, endpoint: "StreamEndpoint", recv_buffer: int):
        self.ep = endpoint
        self.recv_buffer = recv_buffer
        self.rcv_nxt = 0
        self.ooo: dict[int, tuple[int, Optional[bytes]]] = {}  # seq -> (n, p)
        self.ooo_bytes = 0
        self.bytes_received = 0
        #: optional delegate reporting delivered-but-unread application
        #: bytes (the managed-process bridge wires this to the guest's
        #: rxbuf); plugin apps consume synchronously, so it stays None
        self.app_unread: Optional[Callable[[], int]] = None
        #: the window the peer last heard (via flush_ack / handshake);
        #: drives read-triggered window-update acks
        self.last_wnd = recv_buffer

    def window(self) -> int:
        unread = self.app_unread() if self.app_unread is not None else 0
        return max(self.recv_buffer - self.ooo_bytes - unread, 0)

    def on_data(self, seq: int, n: int, payload: Optional[bytes],
                now: SimTime) -> None:
        if seq + n <= self.rcv_nxt:
            self._dup_ack()  # duplicate (retransmit after lost ACK): re-ack
            return
        if seq > self.rcv_nxt:
            if seq not in self.ooo and n <= self.window():
                self.ooo[seq] = (n, payload)
                self.ooo_bytes += n
            self._dup_ack()  # duplicate ack: rcv_nxt unchanged
            return
        if n > self.window():
            # beyond-window in-order data (a sender probing a closed
            # window): refuse it like TCP drops out-of-window segments —
            # rcv_nxt stays, a COALESCED ack re-advertises the window,
            # and the sender's RTO retries until the app reads. Not a
            # dup ack: counting probe refusals toward fast retransmit
            # would halve cwnd during a stall where nothing was lost.
            self._ack()
            return
        self._deliver(n, payload, now)
        while self.rcv_nxt in self.ooo:
            n2, p2 = self.ooo.pop(self.rcv_nxt)
            self.ooo_bytes -= n2
            self._deliver(n2, p2, now)
        self._ack()

    def on_app_read(self) -> None:
        """The app consumed buffered bytes: if the peer last saw a
        materially closed window, queue a window-update ack (flushed,
        coalesced, at the round barrier)."""
        if (self.last_wnd < (self.recv_buffer >> 2)
                and self.window() > self.last_wnd
                and self.ep.state not in (CLOSED, TIME_WAIT)):
            self._ack()

    def _deliver(self, nbytes: int, payload, now: SimTime) -> None:
        self.rcv_nxt += nbytes
        self.bytes_received += nbytes
        if self.ep.on_data is not None:
            self.ep.on_data(nbytes, payload, now)

    def sack_payload(self) -> Optional[bytes]:
        """The receiver's SACK report: its buffered out-of-order segments
        merged into contiguous [start, end) byte ranges, the lowest
        SACK_MAX_BLOCKS of them, each encoded as two big-endian u64s in
        the ACK's payload field (wire size unchanged — SACK option bytes
        are noise at fluid-quantum granularity). None when nothing is
        buffered, which is every ack of a loss-free connection. The C
        receiver twin (colcore cr_sack_payload) emits identical bytes."""
        ooo = self.ooo
        if not ooo:
            return None
        out = bytearray()
        nblocks = 0
        cs = ce = -1
        for s in sorted(ooo):
            n = ooo[s][0]
            if cs < 0:
                cs, ce = s, s + n
            elif s == ce:
                ce = s + n
            else:
                out += cs.to_bytes(8, "big") + ce.to_bytes(8, "big")
                nblocks += 1
                if nblocks == SACK_MAX_BLOCKS:
                    return bytes(out)
                cs, ce = s, s + n
        out += cs.to_bytes(8, "big") + ce.to_bytes(8, "big")
        return bytes(out)

    def _ack(self) -> None:
        # round-barrier ack coalescing (the fluid analog of delayed acks):
        # every in-round delivery marks the endpoint; the engine flushes ONE
        # cumulative ACK per connection at the barrier. Halves unit volume
        # on bulk transfers with identical reliability (acks are cumulative
        # and the sender's RTO floor far exceeds a round width).
        self.ep.host.mark_ack(self.ep)

    def _dup_ack(self) -> None:
        """Out-of-order / duplicate data: real TCP acks IMMEDIATELY
        (RFC 5681 §4.2 — dup acks must not be delayed, they drive the
        sender's fast-retransmit counter). Two deliberate choices keep
        the counter sound in the fluid model: the dup ack re-advertises
        ``last_wnd`` (the window the peer last heard) rather than the
        recomputed one — buffering the OOO segment shrinks window() by n
        every time, which would make consecutive dup acks all differ and
        defeat the sender's same-window test — and it supersedes any
        coalesced ack queued this round (a same-cum barrier ack would
        inflate the count)."""
        ep = self.ep
        if ep.state in (CLOSED, TIME_WAIT):
            return
        ep.host._ack_eps.pop(ep, None)
        ep.emit(U.ACK, payload=self.sack_payload(), acked=self.rcv_nxt,
                wnd=self.last_wnd)

    def flush_ack(self) -> None:
        self.last_wnd = self.window()
        self.ep.emit(U.ACK, payload=self.sack_payload(),
                     acked=self.rcv_nxt, wnd=self.last_wnd)


# endpoint states
CLOSED, SYN_SENT, ESTABLISHED, CLOSING, FIN_SENT, TIME_WAIT = range(6)


class StreamEndpoint:
    """One host's view of a stream connection (half of the four-tuple).

    Host-local by construction: the only cross-host interaction is emitting
    units into the owning host's egress queue; all recovery is driven by
    this host's own timers and arriving units.
    """

    def __init__(self, host, local_port: int, remote_host: int, remote_port: int,
                 initiator: bool, send_buffer: int = 131072,
                 recv_buffer: int = 174760,
                 cc: Optional[str] = None):
        self.host = host
        self.local_port = local_port
        self.remote_host = remote_host
        self.remote_port = remote_port
        self.initiator = initiator
        self.state = CLOSED
        cc_cls = CONGESTION_CONTROLS[cc] if cc else NewReno
        self.sender = StreamSender(self, send_buffer, cc=cc_cls())
        self.receiver = StreamReceiver(self, recv_buffer)
        self.syn_tries = 0
        self.fin_tries = 0
        self._ctl_timer: Optional[int] = None  # SYN/FIN retransmit timer
        #: optional idle timeout (the app-level keepalive analog): a pure
        #: RECEIVER has no outstanding data, so the RTO ladder can never
        #: detect a dead peer (real TCP has the same blind spot without
        #: keepalive). When armed, the timer rearms on every arrival and
        #: its expiry surfaces ETIMEDOUT. Opt-in per endpoint
        #: (set_idle_timeout); models wire it to an environment knob.
        self.idle_timeout_ns: Optional[SimTime] = None
        self._idle_timer: Optional[int] = None
        self.peer_fin = False  # peer closed while we still had data to send
        # deterministic per-path timeout: 2x RTT, floored and capped
        rtt = (host.engine.latency_between(host.id, remote_host)
               + host.engine.latency_between(remote_host, host.id))
        self.rto_ns: SimTime = min(max(2 * rtt, RTO_MIN_NS), RTO_MAX_NS)
        # app callbacks
        self.on_connected: Optional[Callable[[SimTime], None]] = None
        self.on_data: Optional[Callable[[int, Optional[bytes], SimTime], None]] = None
        self.on_drain: Optional[Callable[[int], None]] = None
        self.on_close: Optional[Callable[[SimTime], None]] = None
        self.on_error: Optional[Callable[[str], None]] = None

    # -- API used by ProcessAPI ------------------------------------------
    def send(self, nbytes: int = 0, payload: Optional[bytes] = None) -> int:
        """Queue bytes for transmission; returns the count accepted (may be
        short when the send buffer is full — see on_drain)."""
        if payload is not None:
            nbytes = len(payload)
        if nbytes <= 0 or self.state in (CLOSING, FIN_SENT, TIME_WAIT):
            return 0
        accepted = self.sender.queue(nbytes, payload)
        self.host.counters.add("stream_bytes_queued", accepted)
        return accepted

    def close(self) -> None:
        if self.state in (CLOSED, CLOSING, FIN_SENT, TIME_WAIT):
            return
        self.state = CLOSING
        self.sender.pump()  # fires _on_sender_drained when nothing remains

    def connect(self) -> None:
        self.state = SYN_SENT
        self._send_syn()

    def set_idle_timeout(self, timeout_ns: SimTime) -> None:
        """Arm (or disarm with None/0) the idle timeout; see the field
        docstring. The C endpoint carries the exact twin
        (colcore CEp_set_idle_timeout — same rearm-per-arrival seq
        consumption, same expiry semantics), so fault configs behave
        identically with the C engine on."""
        self._cancel_idle()
        self.idle_timeout_ns = timeout_ns if timeout_ns else None
        if self.idle_timeout_ns is not None:
            self._idle_timer = self.host.schedule_in(
                self.idle_timeout_ns, self._idle_expired)

    def _cancel_idle(self) -> None:
        if self._idle_timer is not None:
            self.host.cancel(self._idle_timer)
            self._idle_timer = None

    def _rearm_idle(self) -> None:
        if self.idle_timeout_ns is not None:
            self._cancel_idle()
            self._idle_timer = self.host.schedule_in(
                self.idle_timeout_ns, self._idle_expired)

    def _idle_expired(self) -> None:
        self._idle_timer = None
        if self.state in (CLOSED, TIME_WAIT):
            return
        if self.host.faults_active:
            self.host.counters.add("stream_timeouts", 1)
        self._reset("connection timed out (ETIMEDOUT): idle timeout — no "
                    "traffic from peer")

    # -- internals --------------------------------------------------------
    def _send_syn(self) -> None:
        self.syn_tries += 1
        if self.syn_tries > SYN_RETRIES:
            self._reset("connection timed out (ETIMEDOUT): SYN retries "
                        "exhausted")
            return
        self.emit(U.SYN, wnd=self.receiver.window())
        self._ctl_timer = self.host.schedule_in(
            self.rto_ns * min(1 << (self.syn_tries - 1), 64), self._syn_timeout)

    def _syn_timeout(self) -> None:
        if self.state == SYN_SENT:
            self._send_syn()

    def _on_sender_drained(self) -> None:
        """All outbound data sent and acked: finish whichever close is
        pending — the peer's (answer their deferred FIN) or our own."""
        if self.peer_fin and self.state in (ESTABLISHED, CLOSING):
            self.emit(U.FINACK)
            self._enter_time_wait(self.host.now)
        elif self.state == CLOSING:
            self.state = FIN_SENT
            self._send_fin()

    def _send_fin(self) -> None:
        self.fin_tries += 1
        if self.fin_tries > FIN_RETRIES:
            self._drop()  # orphan timeout: give up like TCP would
            return
        self.emit(U.FIN)
        self._ctl_timer = self.host.schedule_in(
            self.rto_ns * min(1 << (self.fin_tries - 1), 64), self._fin_timeout)

    def _fin_timeout(self) -> None:
        if self.state == FIN_SENT:
            self._send_fin()

    def _cancel_ctl(self) -> None:
        if self._ctl_timer is not None:
            self.host.cancel(self._ctl_timer)
            self._ctl_timer = None

    def _reset(self, reason: str) -> None:
        self.host.counters.add("stream_resets", 1)
        err = self.on_error
        self._drop()
        if err is not None:
            err(reason)

    def _drop(self) -> None:
        self._cancel_ctl()
        self.sender._cancel_rto()
        self._cancel_idle()
        self.state = CLOSED
        self.host.drop_endpoint(self)

    def _enter_time_wait(self, now: SimTime) -> None:
        """FINACK sent: linger to re-ack a retransmitted FIN, then vanish."""
        if self.state == TIME_WAIT:
            return
        was_open = self.state in (ESTABLISHED, CLOSING, FIN_SENT)
        self.state = TIME_WAIT
        self._cancel_ctl()
        self.sender._cancel_rto()
        self._cancel_idle()
        self.host.schedule_in(2 * self.rto_ns, self._drop)
        if was_open and self.on_close is not None:
            self.on_close(now)

    def emit(self, kind: int, nbytes: int = 0, payload: Optional[bytes] = None,
             seq: int = 0, acked: int = 0, wnd: int = 0) -> None:
        # control units overload the fields: nbytes carries the cumulative
        # ack, seq carries the advertised window
        self.host.emit_msg(
            kind, self.remote_host, nbytes + HEADER,
            nbytes if kind == U.DATA else acked, payload,
            seq if kind == U.DATA else wnd,
            self.local_port, self.remote_port)

    # -- unit arrivals (dispatched by the host) ---------------------------
    def handle(self, unit: Unit, now: SimTime) -> None:
        self.handle_fields(unit.kind, unit.nbytes, unit.payload, unit.seq,
                           now)

    def handle_fields(self, k: int, nbytes: int, payload: Optional[bytes],
                      seq: int, now: SimTime) -> None:
        """Field-level arrival dispatch shared by the per-unit plane
        (via handle) and the columnar plane's inbox loop. Control units:
        nbytes = cumulative ack, seq = advertised window."""
        if self._idle_timer is not None:
            self._rearm_idle()  # any arrival proves the peer is alive
        if k == U.SYN:
            # (server side) duplicate SYN: the SYNACK was lost — re-ack
            if self.state == ESTABLISHED:
                self.sender.adv_wnd = seq
                self.emit(U.SYNACK, wnd=self.receiver.window())
            return
        if k == U.SYNACK:
            if self.state == SYN_SENT:
                self.state = ESTABLISHED
                self.sender.adv_wnd = seq
                self._cancel_ctl()
                if self.on_connected is not None:
                    self.on_connected(now)
                self.sender.pump()
            return
        if k == U.DATA:
            if self.state in (CLOSED, TIME_WAIT):
                return
            self.host.counters.add("stream_bytes_received", nbytes)
            self.receiver.on_data(seq, nbytes, payload, now)
            return
        if k == U.ACK:
            if self.state in (CLOSED, TIME_WAIT):
                return
            self.sender.on_ack(nbytes, seq, payload)
            return
        if k == U.FIN:
            # the peer's data all precedes its FIN (it fins only once fully
            # acked) — but OUR outbound direction may still be mid-stream
            if self.state == SYN_SENT:
                # peer accepted then closed before our SYNACK arrived view
                self.emit(U.FINACK)
                self._reset("connection closed by peer")
                return
            if (self.state in (ESTABLISHED, CLOSING)
                    and (self.sender.buffered > 0 or self.sender.inflight > 0)):
                # half-close: keep transmitting; FINACK when drained
                # (the peer keeps receiving in FIN_SENT). Its FIN will
                # retransmit until then — each repeat lands here again.
                self.peer_fin = True
                return
            self.emit(U.FINACK)
            if self.state != CLOSED:
                # covers simultaneous close too (FIN while FIN_SENT:
                # treat the peer's FIN as confirmation)
                self._enter_time_wait(now)
            return
        if k == U.FINACK:
            if self.state == FIN_SENT:
                self._cancel_ctl()
                self._drop()
                if self.on_close is not None:
                    self.on_close(now)
            return

    def fingerprint(self) -> tuple:
        """Observable protocol state for the determinism sentinel
        (shadow_tpu/checkpoint.py): the full connection state machine —
        identical across data planes and scheduler policies at a round
        boundary, and the first place a divergence in traffic shows up."""
        s, r = self.sender, self.receiver
        return (self.state, self.initiator, self.syn_tries, self.fin_tries,
                self.peer_fin, s.snd_nxt, s.snd_una, s.cwnd, s.ssthresh,
                s.adv_wnd, s.buffered, s.retries, s.rto_backoff, s.dup_acks,
                s.loss_events, s.bytes_acked, r.rcv_nxt, r.ooo_bytes,
                r.bytes_received, r.last_wnd,
                # PR 9: SACK scoreboard + congestion-control seam state
                # (same order/types in the C twin's CEp_fingerprint)
                s.cc.cc_id, s.w_max, s.epoch_start,
                1 if s.in_recovery else 0, s.recover, s.sack_high,
                # sorted lists since PR 11: canonical by construction
                tuple(s.sacked), tuple(s.rtx_done))


class DatagramSocket:
    """UDP-like socket with fragmentation/reassembly."""

    def __init__(self, host, local_port: int):
        self.host = host
        self.local_port = local_port
        self.on_datagram: Optional[
            Callable[[int, Optional[bytes], tuple, SimTime], None]
        ] = None
        self._next_dgram = 0
        self._partial: dict[tuple, list] = {}  # (src, sport, dgram) -> frags

    def sendto(self, dst_host: int, dst_port: int, nbytes: int = 0,
               payload: Optional[bytes] = None) -> None:
        # nbytes may exceed len(payload): wire size is nbytes, with the real
        # payload bytes riding along (lets workloads model fixed-size
        # messages without materializing padding)
        if payload is not None:
            nbytes = max(nbytes, len(payload))
        dgram = self._next_dgram
        self._next_dgram += 1
        host = self.host
        chunk = host.unit_chunk
        nfrags = max(1, -(-nbytes // chunk))
        host._n_dgrams += 1
        port = self.local_port
        if nfrags == 1:  # the overwhelmingly common case: one row, go
            cp = host.colplane
            if cp is not None and host.pcap is None:
                c = cp._c
                if c is not None:
                    # C engine: packed egress row (round 5)
                    c.emit_row(host.id, U.DGRAM, dst_host, nbytes + HEADER,
                               host._now, port, dst_port, nbytes, dgram,
                               0, 1, payload)
                    return
                # columnar fast path: inline the emit_msg tuple append
                # (this call is the hottest emission site at gossip scale)
                eg = host.egress_rows
                if not eg:
                    cp.emitters.append(host)
                eg.append((U.DGRAM, dst_host, nbytes + HEADER, host._now,
                           port, dst_port, nbytes, dgram, 0, 1, payload))
                host._n_emitted += 1
                return
            host.emit_msg(U.DGRAM, dst_host, nbytes + HEADER, nbytes,
                          payload, dgram, port, dst_port)
            return
        emit = host.emit_msg
        for i in range(nfrags):
            lo = i * chunk
            hi = min(nbytes, lo + chunk)
            emit(U.DGRAM, dst_host, (hi - lo) + HEADER, hi - lo,
                 payload[lo:hi] if payload is not None else None,
                 dgram, port, dst_port, frag_idx=i, nfrags=nfrags)

    def handle(self, unit: Unit, now: SimTime) -> None:
        self.handle_fields(unit.nbytes, unit.payload,
                           (unit.src, unit.src_port), unit.seq,
                           unit.frag_idx, unit.nfrags, now)

    def handle_fields(self, nbytes: int, payload: Optional[bytes],
                      src_addr: tuple, dgram: int, frag_idx: int,
                      nfrags: int, now: SimTime) -> None:
        if nfrags == 1:
            self._deliver(nbytes, payload, src_addr, now)
            return
        key = (src_addr[0], src_addr[1], dgram)
        frags = self._partial.setdefault(key, [None] * nfrags)
        frags[frag_idx] = (nbytes, payload)
        if all(f is not None for f in frags):
            del self._partial[key]
            total = sum(n for n, _ in frags)
            whole = (
                b"".join(p for _, p in frags)
                if all(p is not None for _, p in frags)
                else None
            )
            self._deliver(total, whole, src_addr, now)
        elif len(self._partial) > 4096:  # bound memory: drop oldest partial
            self._partial.pop(next(iter(self._partial)))

    def _deliver(self, nbytes, payload, src_addr, now) -> None:
        self.host._n_dgrams_recv += 1
        if self.on_datagram is not None:
            self.on_datagram(nbytes, payload, src_addr, now)
