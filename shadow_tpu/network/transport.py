"""Transports for plugin workloads: fluid streams (TCP-like) and datagrams.

Re-designs the reference's userspace TCP + UDP socket layer (SURVEY.md §1
layer 9, §2 "TCP stack") as a *fluid* model suited to batched per-round
simulation:

- A stream connection is two half-objects, one per endpoint host, that
  interact ONLY by exchanging units through the network engine. This makes
  every object host-local, so scheduler policies can run hosts on different
  threads with no shared mutable state (SURVEY.md §2 parallelism item 5).
- Congestion control is standard slow-start + AIMD (RFC 5681 shaped) in
  integer bytes: loss halves cwnd, acks grow it. Loss events come from the
  network engine's oracle (the engine knows a unit was dropped and notifies
  the sender one RTT after departure) instead of duplicate-ack machinery —
  a deliberate fluid-model simplification; the phase-4/5 managed-process
  path will carry the full per-packet TCP state machine (SURVEY.md §7
  phase 5).
- Reliability: lost DATA is re-queued at the front of the send buffer
  (go-back-on-loss at unit granularity); byte counts delivered are exact.

Datagram sockets fragment payloads into units and reassemble at the
receiver; losing any fragment loses the datagram (IP semantics).
"""

from __future__ import annotations

from typing import Callable, Optional

from shadow_tpu.core.time import NS_PER_SEC, SimTime
from shadow_tpu.network.fluid import HEADER, MAX_UNIT
from shadow_tpu.network import unit as U
from shadow_tpu.network.unit import Unit

MSS = 1460  # cwnd growth quantum (classic ethernet MSS)
CHUNK = MAX_UNIT - HEADER  # max stream payload bytes per unit
INIT_CWND = 10 * MSS  # RFC 6928
MIN_CWND = 2 * MSS
SYN_RTO_NS = NS_PER_SEC  # handshake retransmit timeout
SYN_RETRIES = 5


class StreamSender:
    """The sending half of one direction of a stream connection.

    Each endpoint host owns a StreamSender for the data it transmits and a
    StreamReceiver for the data it receives. (Both directions of a duplex
    connection get their own sender/receiver pair.)
    """

    def __init__(self, endpoint: "StreamEndpoint"):
        self.ep = endpoint
        self.cwnd = INIT_CWND
        self.ssthresh = 1 << 62
        self.inflight = 0  # payload bytes sent but not acked/lost
        self.sendbuf: list[tuple[int, Optional[bytes]]] = []  # (nbytes, payload)
        self.buffered = 0
        self.next_seq = 0
        self.bytes_acked = 0
        self.loss_events = 0

    def queue(self, nbytes: int, payload: Optional[bytes]) -> None:
        self.sendbuf.append((nbytes, payload))
        self.buffered += nbytes
        self.pump()

    def pump(self) -> None:
        ep = self.ep
        if ep.state not in (ESTABLISHED, CLOSING):
            return  # not yet connected (or fully closed); connect() re-pumps
        while self.buffered > 0 and self.inflight < self.cwnd:
            budget = min(self.cwnd - self.inflight, CHUNK)
            nbytes, payload = self.sendbuf[0]
            if nbytes <= budget:
                self.sendbuf.pop(0)
                chunk_p = payload
            else:
                chunk_p = payload[:budget] if payload is not None else None
                rest_p = payload[budget:] if payload is not None else None
                self.sendbuf[0] = (nbytes - budget, rest_p)
                nbytes = budget
            self.buffered -= nbytes
            self.inflight += nbytes
            seq = self.next_seq
            self.next_seq += nbytes
            ep.emit(
                U.DATA,
                nbytes=nbytes,
                payload=chunk_p,
                seq=seq,
                on_loss=self._make_on_loss(nbytes, chunk_p, seq),
                loss_extra="rtt",
            )
        if self.buffered == 0 and self.inflight == 0:
            self.ep._maybe_fin()

    def _make_on_loss(self, nbytes: int, payload: Optional[bytes], seq: int):
        def on_loss() -> None:
            self.loss_events += 1
            self.ssthresh = max(self.cwnd // 2, MIN_CWND)
            self.cwnd = self.ssthresh
            self.inflight -= nbytes
            # retransmit: back to the front of the send buffer
            self.sendbuf.insert(0, (nbytes, payload))
            self.buffered += nbytes
            self.pump()

        return on_loss

    def on_ack(self, nbytes: int, grow: bool = True) -> None:
        self.inflight -= nbytes
        self.bytes_acked += nbytes
        if grow:
            if self.cwnd < self.ssthresh:
                self.cwnd += min(nbytes, self.cwnd)  # slow start (doubles/RTT)
            else:
                self.cwnd += max(1, MSS * nbytes // self.cwnd)  # AIMD
        self.pump()


class StreamReceiver:
    """Receiving half: counts/collects delivered bytes, acks each unit."""

    def __init__(self, endpoint: "StreamEndpoint"):
        self.ep = endpoint
        self.bytes_received = 0

    def on_data(self, unit: Unit, now: SimTime) -> None:
        self.bytes_received += unit.nbytes
        ep = self.ep
        # ack the unit; if the ACK is lost the sender still frees the window
        # (grow=False) one RTT later — data did arrive, only feedback was lost.
        ack_nbytes = unit.nbytes

        def ack_lost() -> None:
            peer = ep._peer_sender()
            if peer is not None:
                peer.on_ack(ack_nbytes, grow=False)

        ep.emit(U.ACK, acked=ack_nbytes, on_loss=ack_lost, loss_at_peer=True)
        if ep.on_data is not None:
            ep.on_data(unit.nbytes, unit.payload, now)


# endpoint states
CLOSED, LISTEN, SYN_SENT, ESTABLISHED, FIN_WAIT, CLOSING = range(6)


class StreamEndpoint:
    """One host's view of a stream connection (half of the four-tuple).

    Host-local by construction: the only cross-host interaction is emitting
    units into the owning host's egress queue. (The one apparent exception,
    _peer_sender, runs inside a loss-notification event that the engine
    schedules on the peer's own host queue.)
    """

    def __init__(self, host, local_port: int, remote_host: int, remote_port: int,
                 initiator: bool):
        self.host = host
        self.local_port = local_port
        self.remote_host = remote_host
        self.remote_port = remote_port
        self.initiator = initiator
        self.state = CLOSED
        self.sender = StreamSender(self)
        self.receiver = StreamReceiver(self)
        self.syn_tries = 0
        self.syn_timer = None
        self.fin_sent = False
        # app callbacks
        self.on_connected: Optional[Callable[[SimTime], None]] = None
        self.on_data: Optional[Callable[[int, Optional[bytes], SimTime], None]] = None
        self.on_close: Optional[Callable[[SimTime], None]] = None
        self.on_error: Optional[Callable[[str], None]] = None

    # -- API used by ProcessAPI ------------------------------------------
    def send(self, nbytes: int = 0, payload: Optional[bytes] = None) -> None:
        if payload is not None:
            nbytes = len(payload)
        if nbytes <= 0:
            return
        self.host.counters.add("stream_bytes_queued", nbytes)
        self.sender.queue(nbytes, payload)

    def close(self) -> None:
        if self.state in (CLOSED, FIN_WAIT, CLOSING):
            return
        self.state = CLOSING
        self.sender.pump()
        self._maybe_fin()

    # -- internals --------------------------------------------------------
    def _maybe_fin(self) -> None:
        if (
            self.state == CLOSING
            and not self.fin_sent
            and self.sender.buffered == 0
            and self.sender.inflight == 0
        ):
            self.fin_sent = True
            self.emit(U.FIN, on_loss=self._refin)

    def _refin(self) -> None:
        self.fin_sent = False
        self._maybe_fin()

    def connect(self) -> None:
        self.state = SYN_SENT
        self._send_syn()

    def _send_syn(self) -> None:
        self.syn_tries += 1
        if self.syn_tries > SYN_RETRIES:
            self.state = CLOSED
            if self.on_error is not None:
                self.on_error("connection timed out (SYN retries exhausted)")
            return
        self.emit(U.SYN, on_loss=lambda: None)  # rely on the RTO timer
        self.syn_timer = self.host.schedule_in(SYN_RTO_NS, self._syn_timeout)

    def _syn_timeout(self) -> None:
        if self.state == SYN_SENT:
            self._send_syn()

    def emit(self, kind: int, nbytes: int = 0, payload: Optional[bytes] = None,
             seq: int = 0, acked: int = 0, on_loss=None, loss_extra=None,
             loss_at_peer: bool = False) -> None:
        size = nbytes + HEADER
        u = Unit(
            uid=self.host.next_uid(),
            src=self.host.id,
            dst=self.remote_host,
            size=size,
            t_emit=self.host.now,
            kind=kind,
            src_port=self.local_port,
            dst_port=self.remote_port,
            nbytes=nbytes if kind == U.DATA else acked,
            payload=payload,
            seq=seq,
        )
        u.on_loss = on_loss
        if loss_at_peer:
            u.loss_host = self.remote_host
        if loss_extra == "rtt":
            u.loss_extra_ns = self.host.engine.rtt_extra_ns(self.host.id, self.remote_host)
        self.host.emit_unit(u)

    def _peer_sender(self) -> Optional[StreamSender]:
        """Resolve the remote endpoint's sender half. Only ever called from a
        loss-notification event scheduled ON the remote host's queue, so the
        lookup and the returned state are touched on that host's thread."""
        peer_host = self.host.controller.hosts[self.remote_host]
        peer = peer_host.find_endpoint(self.remote_port, self.host.id, self.local_port)
        return peer.sender if peer is not None else None

    # -- unit arrivals (dispatched by the host) ---------------------------
    def handle(self, unit: Unit, now: SimTime) -> None:
        k = unit.kind
        if k == U.SYN:
            # (server side) duplicate SYN: re-ack
            if self.state == ESTABLISHED:
                self.emit(U.SYNACK)
            return
        if k == U.SYNACK:
            if self.state == SYN_SENT:
                self.state = ESTABLISHED
                if self.syn_timer is not None:
                    self.host.cancel(self.syn_timer)
                    self.syn_timer = None
                if self.on_connected is not None:
                    self.on_connected(now)
                self.sender.pump()
            return
        if k == U.DATA:
            self.host.counters.add("stream_bytes_received", unit.nbytes)
            self.receiver.on_data(unit, now)
            return
        if k == U.ACK:
            self.sender.on_ack(unit.nbytes, grow=True)
            return
        if k == U.FIN:
            self.emit(U.FINACK)
            if self.state != CLOSED:
                self.state = CLOSED
                if self.on_close is not None:
                    self.on_close(now)
            self.host.drop_endpoint(self)
            return
        if k == U.FINACK:
            self.state = CLOSED
            self.host.drop_endpoint(self)
            return


class DatagramSocket:
    """UDP-like socket with fragmentation/reassembly."""

    def __init__(self, host, local_port: int):
        self.host = host
        self.local_port = local_port
        self.on_datagram: Optional[
            Callable[[int, Optional[bytes], tuple, SimTime], None]
        ] = None
        self._next_dgram = 0
        self._partial: dict[tuple, list] = {}  # (src, sport, dgram) -> frags

    def sendto(self, dst_host: int, dst_port: int, nbytes: int = 0,
               payload: Optional[bytes] = None) -> None:
        # nbytes may exceed len(payload): wire size is nbytes, with the real
        # payload bytes riding along (lets workloads model fixed-size
        # messages without materializing padding)
        if payload is not None:
            nbytes = max(nbytes, len(payload))
        dgram = self._next_dgram
        self._next_dgram += 1
        nfrags = max(1, -(-nbytes // CHUNK))
        self.host.counters.add("dgrams_sent", 1)
        for i in range(nfrags):
            lo = i * CHUNK
            hi = min(nbytes, lo + CHUNK)
            u = Unit(
                uid=self.host.next_uid(),
                src=self.host.id,
                dst=dst_host,
                size=(hi - lo) + HEADER,
                t_emit=self.host.now,
                kind=U.DGRAM,
                src_port=self.local_port,
                dst_port=dst_port,
                nbytes=hi - lo,
                payload=payload[lo:hi] if payload is not None else None,
                seq=dgram,
                frag_idx=i,
                nfrags=nfrags,
            )
            self.host.emit_unit(u)

    def handle(self, unit: Unit, now: SimTime) -> None:
        src_addr = (unit.src, unit.src_port)
        if unit.nfrags == 1:
            self._deliver(unit.nbytes, unit.payload, src_addr, now)
            return
        key = (unit.src, unit.src_port, unit.seq)
        frags = self._partial.setdefault(key, [None] * unit.nfrags)
        frags[unit.frag_idx] = unit
        if all(f is not None for f in frags):
            del self._partial[key]
            nbytes = sum(f.nbytes for f in frags)
            payload = (
                b"".join(f.payload for f in frags)
                if all(f.payload is not None for f in frags)
                else None
            )
            self._deliver(nbytes, payload, src_addr, now)
        elif len(self._partial) > 4096:  # bound memory: drop oldest partial
            self._partial.pop(next(iter(self._partial)))

    def _deliver(self, nbytes, payload, src_addr, now) -> None:
        self.host.counters.add("dgrams_received", 1)
        if self.on_datagram is not None:
            self.on_datagram(nbytes, payload, src_addr, now)
