"""A small GML (Graph Modelling Language) parser.

The reference loads network topologies from GML files (SURVEY.md §2 "GML
parser", "Network graph + routing"): nodes carry host bandwidth defaults,
edges carry latency and packet loss. This parser supports the subset Shadow
topologies use:

    graph [
      directed 1
      node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
      edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
    ]

Values may be ints, floats, or quoted strings. Nested lists map to dicts;
repeated keys (node/edge) accumulate into lists.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

_TOKEN = re.compile(r'"((?:[^"\\]|\\.)*)"|\[|\]|[^\s\[\]"]+')


@dataclass
class GmlGraph:
    directed: bool = False
    attrs: dict = field(default_factory=dict)
    nodes: list[dict] = field(default_factory=list)
    edges: list[dict] = field(default_factory=list)


def _tokenize(text: str):
    # line-based so '#' comments swallow the rest of their line (quoted
    # strings are single-line in GML)
    for line in text.splitlines():
        for m in _TOKEN.finditer(line):
            if m.group(1) is not None:
                yield ("str", m.group(1))
                continue
            tok = m.group(0)
            if tok == "[":
                yield ("open", tok)
            elif tok == "]":
                yield ("close", tok)
            elif tok.startswith("#"):
                break  # comment: skip rest of line
            else:
                yield ("atom", tok)


def _coerce(kind: str, tok: str):
    if kind == "str":
        return tok
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok


def _parse_list(tokens) -> dict:
    """Parse the body of a [ ... ] list into a dict (repeated keys -> list)."""
    out: dict = {}

    def put(key, val):
        if key in ("node", "edge"):
            out.setdefault(key, []).append(val)
        elif key in out:
            prev = out[key]
            if not isinstance(prev, list):
                out[key] = [prev]
            out[key].append(val)
        else:
            out[key] = val

    while True:
        try:
            kind, tok = next(tokens)
        except StopIteration:
            return out
        if kind == "close":
            return out
        if kind not in ("atom", "str"):
            raise ValueError(f"unexpected token {tok!r} (expected key)")
        key = tok
        try:
            kind2, tok2 = next(tokens)
        except StopIteration:
            raise ValueError(f"GML input truncated after key {key!r}") from None
        if kind2 == "open":
            put(key, _parse_list(tokens))
        else:
            put(key, _coerce(kind2, tok2))


def parse_gml(text: str) -> GmlGraph:
    tokens = _tokenize(text)
    top = _parse_list(tokens)
    if "graph" not in top:
        raise ValueError("GML input has no 'graph [ ... ]' block")
    g = top["graph"]
    if isinstance(g, list):
        g = g[0]
    out = GmlGraph()
    out.directed = bool(g.pop("directed", 0))
    nodes = g.pop("node", [])
    edges = g.pop("edge", [])
    out.nodes = nodes if isinstance(nodes, list) else [nodes]
    out.edges = edges if isinstance(edges, list) else [edges]
    out.attrs = g
    return out


def parse_gml_file(path: str) -> GmlGraph:
    with open(path, "r") as f:
        return parse_gml(f.read())
