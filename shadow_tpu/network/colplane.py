"""The columnar data plane: array-native unit flow for the tpu policies.

This is round 3's answer to VERDICT.md item #1 ("a data path that makes the
TPU matter"). The per-unit plane (network/engine.py) is the faithful
re-implementation of the reference architecture — one Python object per
packet-bundle, one scheduled closure per arrival, exactly like upstream
Shadow's per-packet event flow (SURVEY.md §3.4) — and remains the
``thread_per_core`` / ``thread_per_host`` baseline. The columnar plane keeps
the SAME simulation semantics (bit-identical results, enforced by the
cross-policy determinism tests and bench.py's equality asserts) but
represents traffic as batch-level data end-to-end:

- **Emission** appends one plain tuple per unit to the host's egress-row
  list — no Unit objects, no uid mint, no closure (host/host.py emit_msg).
- **The barrier** resolves the whole round's units at once: departures
  (closed-form buckets), latency gather, and uid/key assignment run as
  numpy vector ops for large batches and as an exact scalar twin for small
  ones (most rounds of a paced workload emit a handful of units; numpy's
  fixed per-op cost would dominate them).
- **Loss draws are coalesced across rounds.** Arrival times are known
  without the flags, so each batch carries a causal deadline (earliest
  possible arrival). numpy-routed batches accumulate until one's deadline
  passes, then ALL accumulated batches resolve in ONE threefry call —
  flags are pure functions of unit identity, so resolving early is
  result-identical. Device-routed batches read back asynchronously.
- **Resolved rows live in a sorted pending store**: each flushed batch
  becomes a (time, key)-sorted row list; every round the engine extracts
  the due prefixes (bisect), buckets them per destination host (TimSort
  merges the few overlapping runs), and each host's event loop merges its
  inbox with its timer heap by (time, band, key) — the same canonical
  order the per-unit plane produces (core/events.py BAND_NET) — charging
  the ingress token bucket per row at dispatch time, in event order.
- **The mesh plane (tpu_mesh) rides the same machinery**: departures are
  host-side closed form (bit-equal to the device math), and everything
  deferrable — per-packet loss draws plus the all_to_all arrival
  exchange and pmin barrier — accumulates per causal window and resolves
  as ONE sharded XLA program at the window's earliest-arrival deadline
  (parallel/mesh.py::_exchange_rounds). Windows below
  ``experimental.tpu_mesh_floor`` units take the numpy twin instead
  (identical flags; the collective's fixed cost loses on tiny windows),
  the same adaptive discipline as the device draw floor.

Equivalence argument (why the two planes cannot diverge): unit identity
(uids), event keys, egress-bucket charge order, ingress charge order, and
the (time, band, key) execution order are all reproduced exactly; loss
flags are the same pure function of unit identity (fluid.loss_flags /
ops/propagate.py); and both planes clamp arrival and notify times to the
emitting barrier's end. tests/test_colplane.py asserts whole-simulation
equality against the per-unit plane on every workload family.

Store row layout (tuples; each host's pending list is kept sorted by the
unique (t, key) prefix):
    (t, key, tgt, kind, peer, aport, bport, nbytes, seq, frag, nfrags,
     size, payload)
tgt/peer = dst/src of the unit.
"""

from __future__ import annotations

import time as _walltime  # detlint: ok(wallclock): phase_wall routing telemetry
from bisect import bisect_left
from collections import deque

import numpy as np

from shadow_tpu.core.time import SimTime, T_NEVER
from shadow_tpu.network.fluid import (
    HARD_MAX_PKTS,
    MTU,
    NetParams,
    TokenBuckets,
    clamped_refill,
    loss_flags,
)
from shadow_tpu.network.devroute import WINDOW_SLOTS, DeviceRoutedPlane
from shadow_tpu.network.graph import INF_I64, NetworkGraph

# egress row field indices (tuples appended by Host.emit_msg)
E_KIND, E_DST, E_SIZE, E_TEMIT, E_SPORT, E_DPORT = 0, 1, 2, 3, 4, 5
E_NBYTES, E_SEQ, E_FRAG, E_NFRAGS, E_PAYLOAD = 6, 7, 8, 9, 10

#: barriers at or below this many units take the exact scalar twin of the
#: vector math (numpy's ~µs fixed cost per op dominates tiny batches)
SMALL_BARRIER = 48


class StoreBatch:
    """One resolved batch: store-row tuples pre-sorted by (t, key),
    consumed as a moving prefix by per-round extraction.  ``cdata`` is an
    optional packed side-car the C engine writes at build time (one
    32-byte record per row) so extraction reads sequential memory instead
    of chasing cold tuple fields; Python paths ignore it."""

    __slots__ = ("rows", "pos", "cdata")

    def __init__(self, rows: list, cdata=None) -> None:
        self.rows = rows
        self.pos = 0
        self.cdata = cdata

    def head_time(self) -> SimTime:
        return self.rows[self.pos][0] if self.pos < len(self.rows) else T_NEVER


class _Outstanding:
    """One barrier's units awaiting loss flags. ``handle`` is a device
    DrawHandle, or None for a lazily-coalesced numpy batch. ``rows`` are
    the egress row tuples (post blackhole filter), ``src`` the per-row
    source host ids."""

    __slots__ = ("rows", "src", "arrival", "keys", "uid_lo", "uid_hi",
                 "npk", "thresh", "forced", "round_end", "deadline",
                 "handle")

    def __init__(self, rows, src, arrival, keys, uid_lo, uid_hi, npk,
                 thresh, forced, round_end, deadline, handle):
        self.rows = rows
        self.src = src  # list[int]
        self.arrival = arrival  # list[int]
        self.keys = keys  # list[int]
        self.uid_lo = uid_lo  # np.uint32 array
        self.uid_hi = uid_hi
        self.npk = npk
        self.thresh = thresh
        self.forced = forced  # list[bool] | None
        self.round_end = round_end
        self.deadline = deadline
        self.handle = handle


class ColumnarPlane(DeviceRoutedPlane):
    """Engine with the NetworkEngine public surface, columnar inside."""

    def __init__(self, graph: NetworkGraph, params: NetParams, hosts,
                 round_ns: SimTime, backend: str = "numpy",
                 tpu_options=None, bootstrap_end: SimTime = 0) -> None:
        self.graph = graph
        self.params = params
        self.hosts = hosts
        self.round_ns = round_ns
        self.backend = backend
        self.buckets = TokenBuckets(params)
        self.bootstrap_end = bootstrap_end
        self.tokens_down = params.cap_down.copy()
        self._last_refill: SimTime = 0
        self._ev_key = 0
        self.outstanding: deque[_Outstanding] = deque()
        self.pending: deque[StoreBatch] = deque()
        self.units_sent = 0
        self.units_dropped = 0
        self.units_blackholed = 0
        self.bytes_sent = 0
        self.fault_filter = None
        #: a faults: config section exists (shadow_tpu/faults.py): hosts
        #: may crash, links may cut; enables per-host blackhole accounting
        self.faults_active = False
        self.emitters: list = []  # hosts with egress rows this round
        self.ack_hosts: list = []  # hosts owing coalesced barrier acks
        self._deferred: set = set()  # hosts with ingress backlog
        #: multi-process sharding (parallel/shards.py): resolved rows for
        #: hosts owned by another shard divert into xout[dst_shard]
        #: (13-field store rows) instead of the local pending store
        self.shard_id = 0
        self.shard_n = 1
        self.xout = None
        #: controller hook: called with a host id when extraction flags it
        #: runnable (keeps the active-host set correct)
        self.activate = None
        self.min_used_latency: SimTime = T_NEVER
        self.qdisc = str(getattr(tpu_options, "interface_qdisc", "fifo")
                         or "fifo")
        #: minimum due-window unit count for the mesh collective; smaller
        #: windows resolve on the numpy twin (identical flags)
        _mf = getattr(tpu_options, "tpu_mesh_floor", None)
        self.mesh_floor = 2048 if _mf is None else int(_mf)
        #: per-phase wall-clock breakdown (VERDICT r2 item #7); merged into
        #: the run summary by the controller. window_* phases attribute the
        #: fused multi-round device windows: host-side array build vs
        #: async dispatch vs realized readback stalls (a pipelined window
        #: shows build+dispatch but ~zero readback).
        self.phase_wall = {"barrier": 0.0, "draw_flush": 0.0,
                           "extract": 0.0, "ingress_deferred": 0.0,
                           "window_build": 0.0, "window_dispatch": 0.0,
                           "window_readback": 0.0, "transport_tick": 0.0}
        for h in hosts:
            h.colplane = self
        self._init_device_routing(backend, tpu_options, params)
        #: C engine (native/colcore/colcore.c): same structures, C hot
        #: loops. Bit-identical to this file's Python paths (enforced by
        #: tests/test_colcore.py + the cross-plane suite); absent or
        #: disabled, everything below runs pure Python.
        self._c = None
        self.attach_colcore(tpu_options)
        #: device-resident columnar transport (network/devtransport.py):
        #: attached when experimental.device_transport is on and the C
        #: engine is not (colcore already owns the scalar fast path —
        #: the column snapshot/adopt ABI remains available either way)
        self.devt = None
        self.attach_devtransport(tpu_options)

    def attach_colcore(self, tpu_options):
        """(Re)build the C engine over the current structures — the
        constructor's hookup, callable again after a checkpoint restore
        (Controller._reattach_runtime). Returns the core or None.

        Cross-plane resume: a checkpoint written on the Python plane
        stores resolved batches as plain StoreBatch row lists; the C
        extractor wants packed CBatches, so convert in place (the deque's
        identity is load-bearing — the core caches it)."""
        self._c = None
        if not (self.backend in ("tpu", "mesh") and self.qdisc == "fifo"
                and getattr(tpu_options, "native_colcore", True)):
            return None
        try:
            from shadow_tpu.native import _colcore
        except ImportError:
            return None
        for i, b in enumerate(self.pending):
            if isinstance(b, StoreBatch):
                cb = _colcore.shell("CBatch")
                cb._restore_state((b.pos, list(b.rows)))
                self.pending[i] = cb
        self._c = _colcore.Core(self)
        if self.shard_n > 1:
            self._bind_shard_core()
        return self._c

    def attach_devtransport(self, tpu_options):
        """(Re)attach the columnar transport engine — constructor hookup
        and the checkpoint-restore twin (Controller._reattach_runtime).
        experimental.device_transport is a volatile wall-clock-policy
        key: engagement cannot change results (every path is
        bit-identical, enforced by tests/test_devtransport.py), so a
        resume may flip it like native_colcore."""
        for h in self.hosts:
            h.devt = None
        self.devt = None
        if not getattr(tpu_options, "device_transport", False):
            return None
        if self._c is not None:
            return None  # colcore IS the fast scalar twin (module doc)
        from shadow_tpu.network.devtransport import DeviceTransport

        self.devt = DeviceTransport(self)
        self.devt.start_device_attach()
        for h in self.hosts:
            h.devt = self.devt
        return self.devt

    def _bind_shard_core(self) -> None:
        """Install the shard filter on the C core: the packed send path
        (SRec buffers drained as wire bytes by take_xout_packed) when
        the build has it, else the legacy per-row tuple divert."""
        if hasattr(self._c, "take_xout_packed"):
            self._c.bind_shard(self.shard_id, self.shard_n, None)
        else:
            if self.xout is None:
                self.xout = [[] for _ in range(self.shard_n)]
            self._c.bind_shard(self.shard_id, self.shard_n, self.xout)

    # state queries (controller) -------------------------------------------
    def pending_head(self) -> SimTime:
        """Earliest resolved-but-undelivered row time in the store."""
        return min((b.head_time() for b in self.pending), default=T_NEVER)

    # round hooks ----------------------------------------------------------
    def start_of_round(self, round_start: SimTime, round_end: SimTime) -> None:
        self.flush_due(round_end)
        dt = round_start - self._last_refill
        self._last_refill = round_start
        if dt > 0:
            if self._c is not None:
                self._c.refill_ingress(dt)
            else:
                p = self.params
                add_down = clamped_refill(p.rate_down, p.cap_down, dt)
                self.tokens_down += np.minimum(
                    add_down, p.cap_down - self.tokens_down)
        if self._deferred:
            t0 = _walltime.perf_counter()
            self._drain_deferred(round_start)
            self.phase_wall["ingress_deferred"] += (
                _walltime.perf_counter() - t0)
        if self.pending:
            t0 = _walltime.perf_counter()
            if self._c is not None:
                self._c.extract(round_end)
            else:
                self._extract(round_end)
            self.phase_wall["extract"] += _walltime.perf_counter() - t0

    def _extract(self, round_end: SimTime) -> None:
        """Hand every store row with t < round_end to its destination
        host's inbox, preserving (t, key) order within each host."""
        slices = []
        for b in self.pending:
            rows, pos = b.rows, b.pos
            if pos >= len(rows) or rows[pos][0] >= round_end:
                continue
            hi = bisect_left(rows, round_end, lo=pos, key=_row_t)
            slices.append(rows[pos:hi])
            b.pos = hi
        while self.pending and self.pending[0].pos >= len(self.pending[0].rows):
            self.pending.popleft()
        if not slices:
            return
        # bucket rows per destination host; each host only needs ITS rows
        # in (t, key) order, so instead of a global k-way merge, dump the
        # (sorted) slices per host and let TimSort merge the k runs — its
        # adaptive path makes this nearly O(rows) on pre-sorted input
        buckets: dict = {}
        for sl in slices:
            for row in sl:
                tg = row[2]
                b = buckets.get(tg)
                if b is None:
                    buckets[tg] = [row]
                else:
                    b.append(row)
        multi = len(slices) > 1
        hosts = self.hosts
        activate = self.activate
        for hid, rows in buckets.items():
            if multi and len(rows) > 1:
                rows.sort(key=_row_tk)
            hosts[hid]._inbox = rows
            activate(hid)

    def _drain_deferred(self, round_start: SimTime) -> None:
        """Retry ingress-deferred rows against the refilled buckets, in
        host-id order, delivering inline at round_start — mirroring the
        per-unit plane's direct deliver() calls before any host event."""
        # copy + clear in place: the set's object identity is load-bearing
        # when the C engine is attached (it caches the set; see
        # native/colcore/colcore.c)
        drain = list(self._deferred)
        self._deferred.clear()
        tokens = self.tokens_down
        boot = round_start < self.bootstrap_end
        for host in sorted(drain, key=lambda h: h.id):
            backlog, host.ingress_deferred_rows = (
                host.ingress_deferred_rows, [])
            toks = int(tokens[host.id])
            for row in backlog:
                if boot or toks >= row[11]:
                    if not boot:
                        toks -= row[11]
                    host._deliver_row(round_start, row[3], row[4], row[5],
                                      row[6], row[7], row[8], row[9],
                                      row[10], row[12])
                else:
                    host.ingress_deferred_rows.append(row)
                    self._deferred.add(host)
            tokens[host.id] = toks

    def end_of_round(self, round_start: SimTime, round_end: SimTime) -> None:
        """The round barrier: resolve all rows emitted this round, then
        advance the fused device-window state machine (dispatch a closed
        window, install ready speculative tables, pull new speculation
        demand). Windows open and close ONLY at round boundaries, so
        checkpoint.py's round-boundary snapshots stay valid."""
        if self.devt is not None:
            # deferred host rounds replay (and their ack cohorts advance
            # as one batched kernel) BEFORE the barrier collects
            # emitters, so replayed emissions join this round's barrier
            # exactly as live-dispatched ones would have
            self.devt.flush_round(round_end)
        self._barrier_round(round_start, round_end)
        self._window_tick(round_end)

    def _barrier_round(self, round_start: SimTime,
                       round_end: SimTime) -> None:
        t0 = _walltime.perf_counter()
        acks = self.ack_hosts
        if acks:
            self.ack_hosts = []
            if len(acks) > 1:
                acks.sort(key=lambda h: h.id)
            if self._c is not None:
                # the whole coalesced-ack flush loop runs in C (the
                # _ack_eps dicts are identity-stable — cleared in place,
                # never rebound — so the C engine caches them)
                self._c.flush_acks(acks)
            else:
                for h in acks:
                    # snapshot + clear IN PLACE: the dict's identity is
                    # load-bearing when the C engine is attached
                    eps = list(h._ack_eps)
                    h._ack_eps.clear()
                    for ep in eps:
                        if ep.state != 0:  # not CLOSED
                            ep.receiver.flush_ack()
        if self._c is not None and self.fault_filter is None:
            # C barrier protocol: tuple = big live batch for the device
            # dispatch machinery; True = kept rows stored inline (tick the
            # floor cooldown, like the vector twin's non-device branch);
            # None = nothing survived (no tick — the twin never ticks on
            # empty rounds)
            r = self._c.barrier(round_start, round_end)
            if isinstance(r, tuple):
                if len(r) == 10:  # mesh hand-off (src/dst arrays appended)
                    self._queue_mesh_batch(r, round_end)
                else:
                    self._dispatch_device_batch(r, round_end)
            elif r and self.device is not None:
                self._floor_cooldown_tick()
            self.phase_wall["barrier"] += _walltime.perf_counter() - t0
            return
        if self._c is not None:
            # fault_filter rounds run the Python barrier below: flush the
            # packed C egress buffers into host.egress_rows first (the
            # C engine keeps emissions packed; round 5)
            self._c.materialize_egress()
        emitters = self.emitters
        if not emitters:
            return
        self.emitters = []
        if len(emitters) > 1:
            emitters.sort(key=lambda h: h.id)
        rows: list = []
        segs: list = []  # (host_id, count, uid_base) per emitter, in order
        rr = self.qdisc == "round_robin"
        uids_l = None
        for h in emitters:
            # copy + clear in place: the egress list's object identity is
            # load-bearing when the C engine is attached (it caches the
            # list; see native/colcore/colcore.c)
            hr = h.egress_rows[:]
            h.egress_rows.clear()
            k = len(hr)
            base = (h.id << 32) | h._uid_counter
            if rr and k > 1:
                # uids follow EMISSION order (the per-unit plane mints
                # them before the qdisc reorders), so carry each row's
                # original index through the reorder
                if uids_l is None:
                    uids_l = []
                    for _hid0, k0, base0 in segs:
                        uids_l.extend(range(base0, base0 + k0))
                hr, orig = _round_robin_rows(hr)
                rows.extend(hr)
                uids_l.extend(base + i for i in orig)
            else:
                rows.extend(hr)
                if uids_l is not None:
                    uids_l.extend(range(base, base + k))
            segs.append((h.id, k, base))
            h._uid_counter += k
        n = len(rows)
        if n == 0:
            return
        if (n <= SMALL_BARRIER and self.mesh_plane is None
                and self.fault_filter is None):
            self._barrier_scalar(rows, segs, round_start, round_end, uids_l)
        else:
            self._barrier_vector(rows, segs, round_start, round_end, uids_l)
        self.phase_wall["barrier"] += _walltime.perf_counter() - t0

    def _mesh_materialize(self) -> None:
        """Resolve EVERY lazily-accumulated mesh barrier in one fused
        collective dispatch (VERDICT r3 item #2): the accumulated window's
        units run through draws + the all_to_all arrival exchange as one
        sharded program (parallel/mesh.py::_exchange_rounds); each
        barrier's handle then reads its own units out of the shared
        exchange tables. Draws are pure functions of unit identity, so
        batch order is immaterial — the lazy-numpy coalescing discipline,
        one program instead of one per barrier."""
        pend = [b for b in self.outstanding
                if isinstance(b.handle, _MeshLazy)]
        if not pend:
            return
        total = sum(len(b.handle.uid) for b in pend)
        if total < self.mesh_floor:
            # small window: the collective's fixed program cost loses to
            # the numpy twin — convert to lazily-coalesced numpy batches
            # (flags identical either way: pure functions of identity)
            for b in pend:
                h = b.handle
                u = h.uid.astype(np.uint64)
                b.uid_lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
                b.uid_hi = (u >> np.uint64(32)).astype(np.uint32)
                b.npk = h.npk.astype(np.uint32)
                b.thresh = h.th.astype(np.uint32)
                b.handle = None
            return

        def cat(field):
            return np.concatenate([getattr(b.handle, field) for b in pend])

        parts = self.mesh_plane.exchange_rounds(
            cat("src"), cat("dst"), cat("arrival"), cat("uid"),
            cat("npk"), cat("th"))
        from shadow_tpu.parallel.mesh import F_FLAGS, F_UID

        tab = np.concatenate(parts) if len(parts) > 1 else parts[0]
        tab = tab[tab[:, F_FLAGS] >= 2]  # valid rows only
        tab = tab[np.argsort(tab[:, F_UID])]  # sorted ONCE, shared
        for b in pend:
            b.handle = _MeshHandle(tab, b.handle.uid)

    # -- scalar barrier (exact twin of the vector math, for tiny rounds) ---
    def _barrier_scalar(self, rows, segs, round_start: SimTime,
                        round_end: SimTime, uids_l=None) -> None:
        p = self.params
        graph_lat = self.graph.latency_ns
        thresh_t = p.drop_thresh
        host_node = p.host_node
        boot = round_start < self.bootstrap_end
        src_all: list = []
        for hid, k, _base in segs:
            src_all.extend([hid] * k)
        if uids_l is not None:
            uids = uids_l
        else:
            uids = []
            for _hid, k, base in segs:
                uids.extend(range(base, base + k))
        if boot:
            depart = [r[E_TEMIT] for r in rows]
        else:
            depart = self.buckets.depart_times_scalar(
                src_all, [r[E_SIZE] for r in rows],
                [r[E_TEMIT] for r in rows], round_start)
        keep_rows: list = []
        src_l: list = []
        arrival_l: list = []
        keys_l: list = []
        uid_keep: list = []
        thresh_l: list = []
        npk_l: list = []
        any_live = False
        mul = self.min_used_latency
        bh = 0
        for i, r in enumerate(rows):
            src = src_all[i]
            sn = host_node[src]
            dn = host_node[r[E_DST]]
            lat = int(graph_lat[sn, dn])
            if lat >= INF_I64:
                bh += 1
                if self.faults_active:
                    self.hosts[src]._n_blackholed += 1
                continue
            if lat < mul:
                mul = lat
            arrival_l.append(depart[i] + lat)
            # the canonical event key IS the uid (placement-independent;
            # see engine.py _schedule_batch) — _ev_key stays a resolved-
            # units counter for the determinism sentinel
            keys_l.append(uids[i])
            uid_keep.append(uids[i])
            th = int(thresh_t[sn, dn])
            thresh_l.append(th)
            if th:
                any_live = True
            q = -(-r[E_SIZE] // MTU)
            npk_l.append(q if 1 <= q <= HARD_MAX_PKTS
                         else (1 if q < 1 else HARD_MAX_PKTS))
            keep_rows.append(r)
            src_l.append(src)
        self._ev_key += len(keys_l)
        self.units_blackholed += bh
        self.min_used_latency = mul
        if not keep_rows:
            return
        if not any_live:
            self._store_resolved(keep_rows, src_l, arrival_l, keys_l,
                                 None, round_end)
            return
        ul = np.array(uid_keep, dtype=np.uint64)
        self.outstanding.append(_Outstanding(
            keep_rows, src_l, arrival_l, keys_l,
            (ul & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            (ul >> np.uint64(32)).astype(np.uint32),
            np.array(npk_l, dtype=np.uint32),
            np.array(thresh_l, dtype=np.uint32),
            None, round_end,
            max(round_end, min(arrival_l)), None))

    # -- vector barrier -----------------------------------------------------
    def _barrier_vector(self, rows, segs, round_start: SimTime,
                        round_end: SimTime, uids_l=None) -> None:
        n = len(rows)
        size = np.fromiter((r[E_SIZE] for r in rows), dtype=np.int64,
                           count=n)
        t_emit = np.fromiter((r[E_TEMIT] for r in rows), dtype=np.int64,
                             count=n)
        dst = np.fromiter((r[E_DST] for r in rows), dtype=np.int32, count=n)
        counts = np.array([s[1] for s in segs], dtype=np.int64)
        src = np.repeat(np.array([s[0] for s in segs], dtype=np.int32),
                        counts)
        if uids_l is not None:  # round_robin carried emission-order uids
            uid = np.array(uids_l, dtype=np.uint64)
        else:
            # per-segment uid ranges without per-segment arange: base minus
            # the segment's start offset, repeated, plus the global position
            starts = np.cumsum(counts) - counts
            bases = np.array([s[2] for s in segs], dtype=np.int64)
            uid = (np.repeat(bases - starts, counts)
                   + np.arange(n, dtype=np.int64)).astype(np.uint64)
        use_mesh = (self.mesh_plane is not None
                    and round_start >= self.bootstrap_end)
        if round_start < self.bootstrap_end:
            depart = t_emit.copy()  # bootstrap: unlimited bandwidth
        else:
            # host-side closed-form departures for EVERY backend — the
            # math is bit-equal on host and device (test_multichip), and
            # computing it where the emissions originate is what lets the
            # mesh plane defer its collective to the causal deadline
            depart = self.buckets.depart_times(src, size, t_emit,
                                               round_start)

        p = self.params
        sn = p.host_node[src]
        dn = p.host_node[dst]
        lat = self.graph.latency_ns[sn, dn]

        reach = lat < INF_I64
        n_bh = n - int(reach.sum())
        keep_rows = rows
        if n_bh:
            self.units_blackholed += n_bh
            if self.faults_active:
                for s in src[~reach].tolist():
                    self.hosts[s]._n_blackholed += 1
            keep = np.flatnonzero(reach)
            kl = keep.tolist()
            keep_rows = [rows[i] for i in kl]
            src, dst, sn, dn = src[keep], dst[keep], sn[keep], dn[keep]
            lat = lat[keep]
            depart = depart[keep]
            size, t_emit, uid = size[keep], t_emit[keep], uid[keep]
            n = len(kl)
            if n == 0:
                return  # buckets already charged for the full batch

        ml = int(lat.min())
        if ml < self.min_used_latency:
            self.min_used_latency = ml
        thresh = p.drop_thresh[sn, dn]
        # canonical keys = uids (placement-independent; engine.py twin)
        keys_l = uid.astype(np.int64).tolist()
        self._ev_key += n

        src_l = src.tolist()
        forced = None
        if self.fault_filter is not None:
            forced = [bool(self.fault_filter(_RowView(r, s, int(u))))
                      for r, s, u in zip(keep_rows, src_l, uid)]
            if not any(forced):
                forced = None

        arrival = depart + lat
        arrival_l = arrival.tolist()

        live = bool((thresh > 0).any())
        if use_mesh:
            if not live and forced is None:
                # nothing can drop: straight to the store (the collective
                # would only confirm all-false flags)
                self._store_resolved(keep_rows, src_l, arrival_l, keys_l,
                                     None, round_end)
                return
            # LAZY collective batch: arrivals are known host-side, draws
            # are pure functions of unit identity, so the whole causal
            # window (every barrier until the earliest arrival comes due)
            # resolves in ONE sharded draws+all_to_all+pmin program at
            # flush (_mesh_materialize) — fused across rounds, not
            # dispatch-bound per barrier (VERDICT r3 item #2).
            npk = np.minimum(np.maximum(1, -(-size // MTU)),
                             HARD_MAX_PKTS).astype(np.int64)
            deadline = max(round_end, int(arrival.min()))
            self.outstanding.append(_Outstanding(
                keep_rows, src_l, arrival_l, keys_l, None, None, None,
                None, forced, round_end, deadline,
                _MeshLazy(src.astype(np.int64), dst.astype(np.int64),
                          arrival, uid.astype(np.int64), npk,
                          thresh.astype(np.int64))))
            return
        if not live and forced is None:
            # nothing can drop: skip draws entirely, straight to the store
            self._store_resolved(keep_rows, src_l, arrival_l, keys_l, None,
                                 round_end)
            return
        uid_lo = (uid & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        uid_hi = (uid >> np.uint64(32)).astype(np.uint32)
        npk = np.minimum(np.maximum(1, -(-size // MTU)),
                         HARD_MAX_PKTS).astype(np.uint32)
        # lazy batch: flags are a pure function of unit identity, so defer
        # to the causal deadline and coalesce across rounds — into ONE
        # numpy call or ONE fused device window, whichever the window
        # state machine (_window_tick / flush_due) routes this window to
        deadline = max(round_end, int(arrival.min()))
        self.outstanding.append(_Outstanding(
            keep_rows, src_l, arrival_l, keys_l, uid_lo, uid_hi, npk,
            thresh, forced, round_end, deadline, None))

    def _queue_mesh_batch(self, r, round_end: SimTime) -> None:
        """C-barrier mesh hand-off: append the lazy collective batch
        exactly as the Python vector path does."""
        (keep_rows, src_l, arrival, keys_l, uid_lo, uid_hi, npk, thresh,
         src_a, dst_a) = r
        uid64 = (uid_lo.astype(np.int64)
                 | (uid_hi.astype(np.int64) << np.int64(32)))
        deadline = max(round_end, int(arrival.min()))
        self.outstanding.append(_Outstanding(
            keep_rows, src_l, arrival.tolist(), keys_l, None, None, None,
            None, None, round_end, deadline,
            _MeshLazy(src_a.astype(np.int64), dst_a.astype(np.int64),
                      arrival, uid64, npk.astype(np.int64),
                      thresh.astype(np.int64))))

    def _dispatch_device_batch(self, r, round_end: SimTime) -> None:
        """A C barrier handed back a big live batch: it joins the open
        device window as a lazy batch (the window state machine owns all
        device dispatch — one fused program per window, two-slot async
        pipeline — instead of the retired one-dispatch-per-barrier loop)."""
        keep_rows, src_l, arrival, keys_l, uid_lo, uid_hi, npk, thresh = r
        deadline = max(round_end, int(arrival.min()))
        self.outstanding.append(_Outstanding(
            keep_rows, src_l, arrival.tolist(), keys_l, uid_lo, uid_hi,
            npk, thresh, None, round_end, deadline, None))

    # -- fused multi-round device windows -----------------------------------
    def _window_tick(self, round_end: SimTime) -> None:
        """Advance the window state machine at this round boundary.

        experimental.device_window_rounds = K:
          K >= 1  close the deferred window every K barriers and dispatch
                  it when it clears the floor (K=1 reproduces the legacy
                  per-round dispatch cadence, through the same machinery);
          auto    dispatch as soon as the open window clears the live
                  break-even estimate (hysteresis in devroute) — smaller
                  windows fall through to the host twin at flush time.

        Routing is pure wall-clock policy: every path yields bit-identical
        flags (tests/test_device_windows.py), only dispatch count moves."""
        dev = self.device
        if dev is None:
            return
        if (self._c is not None and not self._spec_checked
                and self.window_rounds == 0):
            # speculation is an auto-mode feature (documented in
            # MIGRATION.md/README): a fixed K asks for the deterministic
            # deferred-window discipline only
            self._spec_enable()
        if self._spec_on:
            self._spec_tick()
        if not self.outstanding:  # the common C-plane round: all inline
            self._win_open_rounds = 0
            return
        lazy = [b for b in self.outstanding if b.handle is None]
        if not lazy:
            self._win_open_rounds = 0
            return
        self._win_open_rounds += 1
        units = sum(len(b.keys) for b in lazy)
        k = self.window_rounds
        if k > 0:
            if self._win_open_rounds >= k:
                self._note_window_units(units)
                if (units >= self.device_floor
                        and self._win_inflight < WINDOW_SLOTS):
                    self._dispatch_window(lazy, units)
                else:
                    # below floor (or both slots busy): the window stays
                    # lazy and resolves on the host twin at flush
                    self._floor_cooldown_tick()
                    self._win_open_rounds = 0
        elif (self._win_inflight < WINDOW_SLOTS
              and not self._probe_clamped
              and units >= self.window_gate_units(self._win_engaged)):
            self._dispatch_window(lazy, units)

    def _dispatch_window(self, lazy, units: int) -> None:
        """ONE fused device dispatch for the whole window: every lazy
        batch's draw arrays concatenate into one program (chunked only at
        tpu_max_batch); each batch keeps a slice view of the shared handle
        and reads it — for free, once the shared readback landed — at its
        own causal deadline. Readback is deferred exactly as before; only
        the dispatch count changes (one per window, not one per barrier)."""
        t0 = _walltime.perf_counter()
        mb = self.max_batch
        groups: list = []
        cur: list = []
        cur_n = 0
        for b in lazy:
            n = len(b.keys)
            if cur and cur_n + n > mb:
                groups.append((cur, cur_n))
                cur, cur_n = [], 0
            cur.append(b)
            cur_n += n
        groups.append((cur, cur_n))
        t1 = _walltime.perf_counter()
        for batches, n_g in groups:
            self._win_inflight += 1
            if len(batches) == 1 and n_g > mb:
                # one oversized batch: chunk it like the retired per-batch
                # loop did, behind a concatenating handle
                b = batches[0]
                handles = [
                    self.device.dispatch(b.uid_lo[i:i + mb],
                                         b.uid_hi[i:i + mb],
                                         b.npk[i:i + mb],
                                         b.thresh[i:i + mb])
                    for i in range(0, n_g, mb)]
                b.handle = _ConcatHandle(self, handles)
                continue
            if len(batches) == 1:
                b = batches[0]
                lo, hi, npk, th = b.uid_lo, b.uid_hi, b.npk, b.thresh
            else:
                lo = np.concatenate([b.uid_lo for b in batches])
                hi = np.concatenate([b.uid_hi for b in batches])
                npk = np.concatenate([b.npk for b in batches])
                th = np.concatenate([b.thresh for b in batches])
            wh = _WindowHandle(self, self.device.dispatch(lo, hi, npk, th))
            off = 0
            for b in batches:
                n = len(b.keys)
                b.handle = _WindowSlice(wh, off, n)
                off += n
        self._win_open_rounds = 0
        t2 = _walltime.perf_counter()
        self.phase_wall["window_build"] += t1 - t0
        self.phase_wall["window_dispatch"] += t2 - t1
        self._note_window_units(units)
        self._record_window(units, t2 - t0)

    def _window_done(self) -> None:
        """A dispatched window's last deferred readback was consumed: its
        pipeline slot frees for the next window."""
        if self._win_inflight > 0:
            self._win_inflight -= 1

    def _stall_sample(self, dt: float) -> None:
        """A window readback stalled for dt seconds: fold it into the
        break-even EMA (a stalling window costs host wall exactly like
        dispatch does) and the phase attribution."""
        self.phase_wall["window_readback"] += dt
        if dt > 2e-5:
            self._win_cost_ema += 0.25 * dt

    # -- speculative forward windows (C plane) -------------------------------
    def _spec_enable(self) -> None:
        """One-time probe: speculative windows need the C engine's class
        tracker + consult table (spec_demand/spec_install). Older engines
        without the API simply never speculate."""
        self._spec_checked = True
        self._spec_on = (hasattr(self._c, "spec_demand")
                         and self.fault_filter is None)

    def _spec_tick(self) -> None:
        """Drive the speculative pipeline once per round: install every
        speculative wave whose device readback has landed (is_ready —
        never a stall), then, on a coarse cadence so single-host demand
        coalesces into fused waves, pull per-host demand from the C class
        tracker and dispatch it as one program. A wave speculates the
        PREFIX-MIN threefry draw for a contiguous range of FUTURE uids
        under each host's recent npkts classes — threshold-independent
        (dropped == min_draw < thresh), so one row serves every
        destination. The C consult verifies uid range + npkts exactly; a
        wrong guess costs device cycles, never correctness."""
        pend = self._spec_pending
        if pend:
            keep = []
            for wave in pend:
                if wave[0].is_ready():
                    self._install_spec(wave)
                else:
                    keep.append(wave)
            self._spec_pending = keep
        self._spec_round += 1
        if (self._spec_round & 15
                or len(self._spec_pending) >= WINDOW_SLOTS):
            return  # demand keeps queueing C-side between drains
        if (self._spec_round & 255 == 0 and self.dev_windows >= 4
                and self._spec_round >= 1024):
            # live economics (the same telemetry-over-faith rule as the
            # deferred-window break-even): fold the C consult counters and
            # compare realized spend — wave build + dispatch wall plus a
            # compute-contention share for the speculated rows themselves
            # (XLA worker threads take cores the host loop would use) —
            # against realized savings (verified hits x the inline C draw
            # cost, ~0.22us for a full-quantum unit on this class of
            # host). A losing speculation stops demanding new waves;
            # installed windows keep serving their remaining hits for
            # free. On an accelerator-backed device the contention term
            # is ~zero and the clamp never fires.
            hits, draws = self._c.spec_stats()
            self.spec_hits += hits
            self.spec_draws += draws
            spend = self._spec_spend + 2.5e-8 * self._spec_units
            if spend > self.spec_hits * 2.2e-7:
                self._spec_on = False
                self._spec_clamped = True
                return
        # demand coalescing: a wave's fixed dispatch cost wants a sizable
        # host cohort; the coarse age cadence (every 256 rounds) flushes
        # stragglers so every demanding host gets a window within ~one
        # round-trip of simulated time
        min_hosts = 1 if self._spec_round & 255 == 0 else 160
        d = self._c.spec_demand(min_hosts)
        if d is not None:
            self._dispatch_spec(d)

    #: classes cheaper than this many packet draws are not worth a wave
    #: row (the inline threefry twin beats the speculation overhead);
    #: must match SPEC_MIN_NPK in native/colcore/colcore.c
    SPEC_MIN_NPK = 4

    def _dispatch_spec(self, d) -> None:
        """Build and dispatch one speculative wave: for each demanded host
        a contiguous future-uid range min-drawn under up to two npkts
        classes, packed with vectorized range arithmetic. Waves chunk at
        the ONE pinned program shape (DeviceDrawPlane.SPEC_BUCKET), whole
        hosts per chunk (a host's classes must install together), so no
        wave ever compiles a new shape mid-run."""
        hosts, u0, n, npk_a, npk_b = d
        n64 = n.astype(np.int64)
        rows = (n64 * ((npk_a >= self.SPEC_MIN_NPK).astype(np.int64)
                       + (npk_b >= self.SPEC_MIN_NPK).astype(np.int64)))
        bucket = self.device.SPEC_BUCKET
        lo_idx = 0
        idx = np.flatnonzero(rows > 0)
        while lo_idx < idx.size:
            acc, take = 0, []
            while lo_idx < idx.size and \
                    acc + int(rows[idx[lo_idx]]) <= bucket:
                acc += int(rows[idx[lo_idx]])
                take.append(idx[lo_idx])
                lo_idx += 1
            if not take:  # single host larger than the bucket: skip it
                lo_idx += 1
                continue
            g = np.asarray(take)
            self._dispatch_spec_group(
                hosts[g], u0[g], n[g], npk_a[g], npk_b[g])

    def _dispatch_spec_group(self, hosts, u0, n, npk_a, npk_b) -> None:
        t0 = _walltime.perf_counter()
        n64 = n.astype(np.int64)
        parts_lo: list = []
        parts_hi: list = []
        parts_npk: list = []
        off_a = np.full(len(hosts), -1, dtype=np.int64)
        off_b = np.full(len(hosts), -1, dtype=np.int64)
        off = 0
        for npk_c, offs in ((npk_a, off_a), (npk_b, off_b)):
            use = np.flatnonzero(npk_c >= self.SPEC_MIN_NPK)
            if use.size == 0:
                continue
            ns = n64[use]
            total = int(ns.sum())
            starts = np.cumsum(ns) - ns
            uid = (np.repeat(u0[use], ns)
                   + (np.arange(total, dtype=np.int64)
                      - np.repeat(starts, ns)).astype(np.uint64))
            parts_lo.append((uid & np.uint64(0xFFFFFFFF)).astype(np.uint32))
            parts_hi.append((uid >> np.uint64(32)).astype(np.uint32))
            parts_npk.append(
                np.repeat(npk_c[use].astype(np.uint32), ns))
            offs[use] = off + starts
            off += total
        if off == 0:
            return
        t1 = _walltime.perf_counter()
        dh = self.device.dispatch_min(
            np.concatenate(parts_lo), np.concatenate(parts_hi),
            np.concatenate(parts_npk),
            min_bucket=self.device.SPEC_BUCKET)
        self._spec_pending.append(
            (dh, (hosts, u0, n, npk_a, npk_b), off_a, off_b))
        self.dev_windows += 1
        self.dev_window_units += off
        self._spec_units += off
        t2 = _walltime.perf_counter()
        self.phase_wall["window_build"] += t1 - t0
        self.phase_wall["window_dispatch"] += t2 - t1
        # the economics clamp compares speculation's OWN spend against its
        # hits; deferred-window walls must not be billed to it
        self._spec_spend += t2 - t0

    def _install_spec(self, wave) -> None:
        """A speculative wave's min-draws landed: hand them to the C
        consult table in one call (per-host slices by unit offset)."""
        t0 = _walltime.perf_counter()
        dh, d, off_a, off_b = wave
        mins = dh.read()
        hosts, u0, n, npk_a, npk_b = d
        self._c.spec_install(hosts, u0, n, npk_a, npk_b, off_a, off_b,
                             np.ascontiguousarray(mins))
        dt = _walltime.perf_counter() - t0
        self.phase_wall["window_build"] += dt
        self._spec_spend += dt

    # result consumption ----------------------------------------------------
    def flush_due(self, limit: SimTime) -> None:
        """Resolve in-flight batches: every batch whose deadline precedes
        ``limit`` MUST resolve now; while at it, ALL accumulated lazy
        numpy batches resolve in the same single draw call (their flags
        are pure functions of unit identity, so early resolution is
        result-identical — it only coalesces work). Device handles are
        read only when due (an early read would stall on the transfer)."""
        if not self.outstanding:
            return
        if not any(b.deadline < limit for b in self.outstanding):
            return
        t0 = _walltime.perf_counter()
        if self.mesh_plane is not None:
            self._mesh_materialize()
        if self.device is not None:
            # a deadline closes the open window here (even a fixed-K
            # window — causality outranks K): route the WHOLE accumulated
            # window (due and not-yet-due batches — early resolution is
            # result-identical) through ONE fused device dispatch when it
            # clears the gate, with hysteresis in auto mode so a window
            # size hovering at break-even does not flap; smaller windows
            # fall through to the coalesced host twin
            lazy_all = [b for b in self.outstanding if b.handle is None]
            if lazy_all:
                units = sum(len(b.keys) for b in lazy_all)
                self._note_window_units(units)
                # both pipeline slots busy -> the host twin resolves this
                # window (the documented two-slot bound: never queue
                # unbounded device memory behind unread handles)
                slot_free = self._win_inflight < WINDOW_SLOTS
                if self.window_rounds > 0:
                    engage = slot_free and units >= self.device_floor
                else:
                    engage = (slot_free and not self._probe_clamped
                              and units >= self.window_gate_units(
                                  self._win_engaged))
                self._win_engaged = engage
                if engage:
                    self._dispatch_window(lazy_all, units)
                else:
                    self._floor_cooldown_tick()
        take = [b for b in self.outstanding
                if b.handle is None or b.deadline < limit]
        self.outstanding = deque(
            b for b in self.outstanding
            if not (b.handle is None or b.deadline < limit))
        lazy = [b for b in take if b.handle is None]
        if lazy:
            self._win_open_rounds = 0  # flush truncated the open window
        it = None
        if lazy:
            if len(lazy) == 1:
                b = lazy[0]
                lz = [loss_flags(self.params.seed, b.uid_lo, b.uid_hi,
                                 b.npk, b.thresh)]
            else:
                lo = np.concatenate([b.uid_lo for b in lazy])
                hi = np.concatenate([b.uid_hi for b in lazy])
                npk = np.concatenate([b.npk for b in lazy])
                th = np.concatenate([b.thresh for b in lazy])
                flat = loss_flags(self.params.seed, lo, hi, npk, th)
                lz = np.split(
                    flat, np.cumsum([len(b.keys) for b in lazy])[:-1])
            it = iter(lz)
        for b in take:
            if b.handle is None:
                flags = next(it)
                flags_l = flags.tolist() if flags.any() else None
            elif isinstance(b.handle, _MeshHandle):
                arrival_a, mflags = b.handle.read()
                b.arrival = arrival_a.tolist()
                flags_l = mflags.tolist() if mflags.any() else None
            else:
                r0 = _walltime.perf_counter()
                flags = b.handle.read()
                self._record_dev_read(_walltime.perf_counter() - r0,
                                      len(b.keys))
                flags_l = flags.tolist() if flags.any() else None
            if b.forced is not None:
                if flags_l is None:
                    flags_l = b.forced
                else:
                    flags_l = [a or f for a, f in zip(flags_l, b.forced)]
            self._store_resolved(b.rows, b.src, b.arrival, b.keys, flags_l,
                                 b.round_end)
        self._floor_settle()
        self.phase_wall["draw_flush"] += _walltime.perf_counter() - t0

    def flush_all(self) -> None:
        self.flush_due(T_NEVER + 1)
        if (self._spec_on or self._spec_clamped) and self._c is not None:
            # drain the C consult counters (hits served from speculative
            # windows vs inline draws) into the run telemetry — also
            # after an economics clamp, since installed windows keep
            # serving hits post-clamp; in-flight speculative waves are
            # just dropped — they are a cache of a pure function, never
            # simulation state
            hits, draws = self._c.spec_stats()
            self.spec_hits += hits
            self.spec_draws += draws
            self._spec_pending = []
        if self._c is not None:
            self._c.fold_counters()
        if self.mesh_plane is not None:
            # surface the collective's per-window wall attribution in the
            # run summary (mesh_* keys in phase_wall; VERDICT r4 item #7)
            for k, v in self.mesh_plane.phase.items():
                self.phase_wall[f"mesh_{k}"] = (
                    round(v, 4) if isinstance(v, float) else v)

    def _store_resolved(self, rows, src_l, arrival, keys, flags,
                        round_end: SimTime) -> None:
        """Flags known (None = all survive): build one sorted StoreBatch
        of arrival rows for the surviving units. Under multi-process
        sharding, rows for hosts owned by another shard divert into the
        per-shard xout buffers instead (shipped at the round edge)."""
        if self._c is not None:
            self._c.store_resolved(rows, src_l, arrival, keys, flags,
                                   round_end)
            return
        out: list = []
        nbytes_total = 0
        sent = 0
        dropped = 0
        sh_n, sh_id, xout = self.shard_n, self.shard_id, self.xout
        for i, r in enumerate(rows):
            if flags is not None and flags[i]:
                dropped += 1
                continue
            sent += 1
            nbytes_total += r[E_SIZE]
            t = arrival[i]
            if t < round_end:
                t = round_end
            row = (t, keys[i], r[E_DST], r[E_KIND], src_l[i],
                   r[E_SPORT], r[E_DPORT], r[E_NBYTES], r[E_SEQ],
                   r[E_FRAG], r[E_NFRAGS], r[E_SIZE], r[E_PAYLOAD])
            if sh_n > 1 and r[E_DST] % sh_n != sh_id:
                xout[r[E_DST] % sh_n].append(row)
            else:
                out.append(row)
        self.units_sent += sent
        self.units_dropped += dropped
        self.bytes_sent += nbytes_total
        if out:
            out.sort(key=_row_tk)
            self.pending.append(StoreBatch(out))

    # -- multi-process sharding (parallel/shards.py) ------------------------
    def bind_shard(self, shard_id: int, shard_n: int) -> None:
        """Install the shard filter on this plane (and the C core when
        attached): resolved rows for non-owned destinations divert into
        xout[dst_shard] (or the C core's packed buffers) instead of the
        local pending store."""
        self.shard_id = shard_id
        self.shard_n = shard_n
        self.xout = [[] for _ in range(shard_n)]
        if self._c is not None:
            self._bind_shard_core()

    def take_xout(self) -> list:
        """Drain the per-shard cross-shard buffers, each sorted by the
        unique (t, key) prefix. (With the packed C send path bound, rows
        live in the core's buffers instead — take_xout_packed is the
        drain; these Python lists stay empty.)"""
        out, self.xout = self.xout, [[] for _ in range(self.shard_n)]
        if self._c is not None and not hasattr(self._c,
                                              "take_xout_packed"):
            self._c.bind_shard(self.shard_id, self.shard_n, self.xout)
        for rows in out:
            rows.sort(key=_row_tk)
        return out

    def take_xout_packed(self, max_bytes: int):
        """C send-side packer (parallel/shards.py): drain the diverted
        cross-shard rows as ready-to-ship wire-format byte blocks —
        (t, key)-sorted, chunked at ``max_bytes`` — without ever
        materializing per-row Python tuples. Returns None when the C
        core (or a build with the packer) is absent; callers fall back
        to take_xout() + pack_rows."""
        c = self._c
        if c is None or not hasattr(c, "take_xout_packed"):
            return None
        return c.take_xout_packed(int(max_bytes))

    def ingest_remote(self, rows: list) -> None:
        """Arrival rows shipped from another shard (already (t, key)
        sorted): they join the pending store as one more resolved batch —
        extraction merges them with local batches per destination host in
        canonical order, exactly like any other overlapping StoreBatch."""
        if not rows:
            return
        if self._c is not None:
            from shadow_tpu.native import _colcore

            cb = _colcore.shell("CBatch")
            cb._restore_state((0, rows))
            self.pending.append(cb)
        else:
            self.pending.append(StoreBatch(rows))


class _WindowHandle:
    """One fused window dispatch shared by its batches: the device result
    is read once (the only point that can stall — attributed to
    window_readback) and every batch slices it for free at its own causal
    deadline. Frees its pipeline slot when the last slice is consumed."""

    __slots__ = ("plane", "flags", "_dh", "_left")

    def __init__(self, plane, dh, n_slices: int = 0) -> None:
        self.plane = plane
        self.flags = None
        self._dh = dh
        self._left = n_slices

    def read_full(self) -> np.ndarray:
        if self.flags is None:
            t0 = _walltime.perf_counter()
            self.flags = self._dh.read()
            self.plane._stall_sample(_walltime.perf_counter() - t0)
        return self.flags

    def slice_consumed(self) -> None:
        self._left -= 1
        if self._left == 0:
            self.plane._window_done()


class _WindowSlice:
    """One batch's view over its window's shared flags."""

    __slots__ = ("wh", "off", "n")

    def __init__(self, wh: _WindowHandle, off: int, n: int) -> None:
        self.wh = wh
        self.off = off
        self.n = n
        wh._left += 1

    def read(self) -> np.ndarray:
        flags = self.wh.read_full()[self.off:self.off + self.n]
        self.wh.slice_consumed()
        return flags


class _ConcatHandle:
    """An oversized single batch dispatched as several chunks (legacy
    tpu_max_batch split), read back as one flag array."""

    __slots__ = ("plane", "handles")

    def __init__(self, plane, handles) -> None:
        self.plane = plane
        self.handles = handles

    def read(self) -> np.ndarray:
        t0 = _walltime.perf_counter()
        flags = np.concatenate([h.read() for h in self.handles])
        self.plane._stall_sample(_walltime.perf_counter() - t0)
        self.plane._window_done()
        return flags


class _MeshLazy:
    """A barrier's units awaiting the fused collective (draws + arrival
    exchange): post-blackhole arrays, arrivals already resolved host-side.
    Converted to a _MeshHandle over the window's shared exchange tables by
    _mesh_materialize."""

    __slots__ = ("src", "dst", "arrival", "uid", "npk", "th")

    def __init__(self, src, dst, arrival, uid, npk, th):
        self.src = src
        self.dst = dst
        self.arrival = arrival
        self.uid = uid
        self.npk = npk
        self.th = th


class _MeshHandle:
    """A barrier's view over the window's uid-sorted exchange table (built
    once in _mesh_materialize, shared across the window's barriers)."""

    __slots__ = ("tab", "uids")

    def __init__(self, tab, uids):
        self.tab = tab  # (rows, 4) int64, valid rows only, uid-ascending
        self.uids = uids  # (n,) int64, batch order (post blackhole filter)

    def read(self):
        from shadow_tpu.parallel.mesh import F_FLAGS, F_TARR, F_UID

        tab = self.tab
        idx = np.searchsorted(tab[:, F_UID], self.uids)
        if (idx >= len(tab)).any() or (tab[idx, F_UID] != self.uids).any():
            raise RuntimeError(
                "mesh exchange table is missing units — collective "
                "routing bug (capacity truncation?)")
        return tab[idx, F_TARR], (tab[idx, F_FLAGS] & 1).astype(bool)


class _RowView:
    """Unit-shaped view over one egress row (fault_filter compatibility)."""

    __slots__ = ("_r", "src", "uid")

    def __init__(self, row, src, uid):
        self._r = row
        self.src = src
        self.uid = uid

    @property
    def kind(self):
        return self._r[E_KIND]

    @property
    def t_emit(self):
        return self._r[E_TEMIT]

    @property
    def frag_idx(self):
        return self._r[E_FRAG]

    @property
    def nfrags(self):
        return self._r[E_NFRAGS]

    @property
    def dst(self):
        return self._r[E_DST]

    @property
    def size(self):
        return self._r[E_SIZE]

    @property
    def src_port(self):
        return self._r[E_SPORT]

    @property
    def dst_port(self):
        return self._r[E_DPORT]

    @property
    def nbytes(self):
        return self._r[E_NBYTES]

    @property
    def seq(self):
        return self._r[E_SEQ]

    @property
    def payload(self):
        return self._r[E_PAYLOAD]


def _row_t(row):
    return row[0]


def _row_tk(row):
    return row[0], row[1]


def _round_robin_rows(rows):
    """interface_qdisc: round_robin over egress ROW tuples — same fairness
    rule as the per-unit plane's _round_robin (emission-time causality
    primary; same-instant ties interleave flows by per-flow rank).
    Returns (reordered rows, their original emission indices) so uid
    assignment can follow emission order like the per-unit plane."""
    rank: dict = {}
    order: dict = {}
    keyed = []
    for i, r in enumerate(rows):
        f = r[E_SPORT]
        rk = rank.get(f, 0)
        rank[f] = rk + 1
        keyed.append((r[E_TEMIT], rk, order.setdefault(f, len(order)), i, r))
    keyed.sort(key=lambda t: t[:4])
    return [t[4] for t in keyed], [t[3] for t in keyed]
