"""Network model: topology graph, routing, packets, token-bucket routers.

Mirrors the reference's ``src/main/network`` + ``src/main/routing`` layers
(SURVEY.md §1 layers 7-8). The hot paths (token buckets, latency lookup,
loss sampling) have twin implementations: a numpy reference
(shadow_tpu/network/fluid.py) and JAX device kernels (shadow_tpu/ops/*),
which must agree bit-for-bit (SURVEY.md §7 phase 2 exit criteria).
"""
