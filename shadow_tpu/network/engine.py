"""The network engine: per-round batched data plane orchestration.

This replaces the reference's per-packet Router/Relay push model (SURVEY.md
§3.4) with a batched design: hosts emit units into host-local egress lists
during a round; at the round barrier the engine assembles one flat batch,
runs the depart kernel (numpy or TPU backend — same integer semantics), and
scatters results back as arrival events on destination hosts' queues. The
conservative-PDES invariant (every latency >= round width) guarantees all
arrivals land in future rounds, so this single synchronization point per
round is the only cross-host communication in the simulator — exactly the
structure that maps onto an ICI mesh in the tpu_batch policy
(shadow_tpu/parallel/).

Batches are split into chunks of at most ``chunk_units`` units AND 2**30
wire bytes; chunk boundaries are computed by this engine, identically for
every backend, so int32 cumulative sums on the device are exact and
bit-equality with the numpy backend survives chunking. (Head-of-line
blocking is per-chunk: a source whose queue is split across chunks re-bases
its cumulative drain against the tokens remaining after the earlier chunk —
the same sequential semantics on both backends.)

Ingress (down-link) token buckets are enforced at arrival time: an arrival
event that finds insufficient ingress tokens parks the unit in the host's
deferred queue, which the engine re-drains after each round's refill. This
logic is shared by all backends, preserving cross-backend bit-equality.

Units whose route is unreachable (APSP latency >= INF) are "blackholed":
counted, then silently discarded — matching IP semantics for no-route.
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core.time import SimTime
from shadow_tpu.network.fluid import CPUDataPlane, NetParams, clamped_refill
from shadow_tpu.network.graph import INF_I32, NetworkGraph
from shadow_tpu.network.unit import Unit

CHUNK_BYTES_CAP = 1 << 30


class NetworkEngine:
    def __init__(self, graph: NetworkGraph, params: NetParams, hosts,
                 round_ns: SimTime, backend: str = "numpy",
                 tpu_options=None) -> None:
        self.graph = graph
        self.params = params
        self.hosts = hosts
        self.round_ns = round_ns
        self.backend = backend
        self.chunk_units = int(getattr(tpu_options, "tpu_max_batch", 65536) or 65536)
        self.tokens_down = params.cap_down.copy()
        self._last_refill: SimTime = 0
        self.pending: list[list[Unit]] = [[] for _ in hosts]
        self.n_pending = 0
        self.units_sent = 0
        self.units_dropped = 0
        self.units_blackholed = 0
        self.bytes_sent = 0
        self._up_refill_dt = 0  # accumulated elapsed ns awaiting up-link refill
        if backend == "tpu":
            from shadow_tpu.ops.propagate import DeviceDataPlane

            self.plane = DeviceDataPlane(params, round_ns, tpu_options)
        else:
            self.plane = CPUDataPlane(params, round_ns)

    # latency helpers ------------------------------------------------------
    def latency_between(self, src_host: int, dst_host: int) -> SimTime:
        p = self.params
        return int(self.graph.latency_ns[p.host_node[src_host], p.host_node[dst_host]])

    def rtt_extra_ns(self, src_host: int, dst_host: int) -> SimTime:
        """Extra delay beyond one-way latency for loss notifications: the
        return-path latency (so the sender learns of a loss one RTT after
        departure, like a fast-retransmit signal)."""
        return self.latency_between(dst_host, src_host)

    def has_pending(self) -> bool:
        return self.n_pending > 0 or any(h.ingress_deferred for h in self.hosts)

    # round hooks ----------------------------------------------------------
    def start_of_round(self, round_start: SimTime) -> None:
        """Refill both token buckets for the elapsed window and re-drain any
        ingress-deferred units at the new round's start time."""
        dt = round_start - self._last_refill
        self._last_refill = round_start
        if dt > 0:
            p = self.params
            # up-link refill is deferred to the round's first depart chunk
            # (saves a device dispatch; tokens can only saturate while idle,
            # and both backends defer identically)
            self._up_refill_dt += dt
            add_down = clamped_refill(p.rate_down, p.cap_down, dt)
            self.tokens_down += np.minimum(add_down, p.cap_down - self.tokens_down)
        for host in self.hosts:
            if host.ingress_deferred:
                backlog, host.ingress_deferred = host.ingress_deferred, []
                for u in backlog:
                    self.ingress_arrival(u, round_start)

    def ingress_arrival(self, u: Unit, now: SimTime) -> None:
        """Down-link token bucket at the destination (runs on the dst host's
        thread via its arrival event, or single-threaded from round start)."""
        if self.tokens_down[u.dst] >= u.size:
            self.tokens_down[u.dst] -= u.size
            self.hosts[u.dst].deliver(u, now)
        else:
            self.hosts[u.dst].ingress_deferred.append(u)

    def end_of_round(self, round_start: SimTime, round_end: SimTime) -> None:
        """The round barrier: batch all pending egress and run the kernel."""
        for h in self.hosts:
            if h.egress:
                self.pending[h.id].extend(h.egress)
                self.n_pending += len(h.egress)
                h.egress = []
        if self.n_pending == 0:
            return

        units: list[Unit] = []
        for lst in self.pending:
            units.extend(lst)
        new_pending: list[list[Unit]] = [[] for _ in self.hosts]
        n_left = 0

        # chunk boundaries: identical for every backend (see module doc)
        i = 0
        n = len(units)
        while i < n:
            j = i
            nbytes = 0
            while j < n and j - i < self.chunk_units:
                nbytes += units[j].size
                if nbytes > CHUNK_BYTES_CAP and j > i:
                    break
                j += 1
            n_left += self._run_chunk(units[i:j], round_start, round_end, new_pending)
            i = j

        self.pending = new_pending
        self.n_pending = n_left

    def _run_chunk(self, units: list[Unit], round_start: SimTime,
                   round_end: SimTime, new_pending: list[list[Unit]]) -> int:
        n = len(units)
        src = np.fromiter((u.src for u in units), dtype=np.int32, count=n)
        dst = np.fromiter((u.dst for u in units), dtype=np.int32, count=n)
        size = np.fromiter((u.size for u in units), dtype=np.int32, count=n)
        dep_off = np.fromiter(
            (max(u.t_emit - round_start, 0) for u in units), dtype=np.int32, count=n
        )
        npkts = np.fromiter((u.npkts for u in units), dtype=np.int32, count=n)
        uid = np.fromiter((u.uid for u in units), dtype=np.uint64, count=n)
        uid_lo = (uid & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        uid_hi = (uid >> np.uint64(32)).astype(np.uint32)

        refill_dt, self._up_refill_dt = self._up_refill_dt, 0
        sent, dropped, arrival_off = self.plane.depart_chunk(
            src, dst, size, dep_off, npkts, uid_lo, uid_hi, self.chunk_units,
            refill_dt=refill_dt,
        )

        n_left = 0
        inf = int(INF_I32)
        for i, u in enumerate(units):
            if not sent[i]:
                new_pending[u.src].append(u)
                n_left += 1
            elif arrival_off[i] >= inf:
                # no route (also reads as 100% loss): discard silently, like
                # IP with no route — must precede the drop check
                self.units_blackholed += 1
            elif dropped[i]:
                self.units_dropped += 1
                if u.on_loss is not None:
                    t_notify = max(u.t_emit, round_start) + self.latency_between(
                        u.src, u.dst) + u.loss_extra_ns
                    who = u.loss_host if u.loss_host is not None else u.src
                    self.hosts[who].schedule(max(t_notify, round_end), u.on_loss)
            else:
                self.units_sent += 1
                self.bytes_sent += u.size
                # clamp keeps causality when experimental.runahead widens the
                # round beyond the graph's min latency
                t_arr = max(round_start + int(arrival_off[i]), round_end)
                self.hosts[u.dst].schedule(t_arr, _make_arrival(self, u, t_arr))
        return n_left


def _make_arrival(engine: NetworkEngine, u: Unit, t_arr: SimTime):
    def arrive() -> None:
        engine.ingress_arrival(u, t_arr)

    return arrive
