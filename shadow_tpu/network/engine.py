"""The network engine: per-round batched data plane orchestration.

This replaces the reference's per-packet Router/Relay push model (SURVEY.md
§3.4) with a batched design: hosts emit units into host-local egress lists
during a round; at the round barrier the engine assembles one flat batch and
resolves EVERY unit in closed form — departure time from the fluid token
buckets (shadow_tpu/network/fluid.py::TokenBuckets, O(1)/unit), arrival time
from the APSP latency gather, and loss from counter-based draws. There is no
retry queue: a unit that must wait for tokens gets its exact future departure
time immediately, so backlog costs nothing per round (round 1 re-dispatched
the whole backlog every round — VERDICT.md weak #1's ~105 ms × rounds).

Loss draws are the one heavy computation (20-round threefry × MAX_PKTS per
unit). They route either to the numpy twin (fluid.loss_flags) or to the
device kernel (ops/propagate.py) — bit-identical by construction — based on
batch size vs a calibrated floor. Device batches are read back
*asynchronously with a causal deadline*: the flags are not needed until the
earliest time any unit of the batch can arrive, which is
computable host-side; until then the readback streams in the background and
subsequent rounds proceed. Event ordering is canonicalized with per-unit
keys assigned at the emission barrier (core/events.py BAND_NET), so the
inline and deferred paths produce byte-identical simulations.

Ingress (down-link) token buckets are enforced at arrival time: an arrival
that finds insufficient ingress tokens parks the unit in the host's deferred
queue, which the engine re-drains after each round's refill.

Units whose route is unreachable (APSP latency == INF) are "blackholed":
counted, then silently discarded — matching IP semantics for no-route.
"""

from __future__ import annotations

import time as _walltime  # detlint: ok(wallclock): phase_wall + device break-even routing
from collections import deque
from dataclasses import dataclass
from functools import partial

import numpy as np

from shadow_tpu.core.events import BAND_NET
from shadow_tpu.core.time import SimTime, T_NEVER
from shadow_tpu.network.fluid import (
    NetParams,
    TokenBuckets,
    clamped_refill,
    loss_flags,
)
from shadow_tpu.network.devroute import DeviceRoutedPlane
from shadow_tpu.network.graph import INF_I64, NetworkGraph
from shadow_tpu.network.unit import Unit


@dataclass
class _Outstanding:
    """One dispatched draw batch awaiting its causal deadline."""

    units: list  # list[Unit], batch order
    arrival: np.ndarray  # (N,) int64 — depart + latency
    keys: np.ndarray  # (N,) int64 canonical event keys
    round_end: SimTime  # barrier that emitted the batch
    deadline: SimTime  # earliest event time any unit can produce
    handle: object  # DrawHandle


class NetworkEngine(DeviceRoutedPlane):
    def __init__(self, graph: NetworkGraph, params: NetParams, hosts,
                 round_ns: SimTime, backend: str = "numpy",
                 tpu_options=None, bootstrap_end: SimTime = 0) -> None:
        self.graph = graph
        self.params = params
        self.hosts = hosts
        self.round_ns = round_ns
        self.backend = backend
        self.buckets = TokenBuckets(params)
        #: before this sim time, bandwidth limits are suspended (reference:
        #: general.bootstrap_end_time — lets large deployments bootstrap
        #: without token-bucket congestion; loss still applies)
        self.bootstrap_end = bootstrap_end
        self.tokens_down = params.cap_down.copy()
        self._last_refill: SimTime = 0
        self._ev_key = 0  # canonical per-unit event key counter
        self.outstanding: deque[_Outstanding] = deque()
        self.units_sent = 0
        self.units_dropped = 0
        self.units_blackholed = 0
        self.bytes_sent = 0
        #: targeted fault injection (tests, experiments): units for which
        #: this predicate returns True are force-dropped in the network —
        #: silently; recovery must come from the endpoints' own machinery
        #: (dup acks, RTO timers — SURVEY.md §5.3).
        self.fault_filter = None
        #: a faults: config section exists (shadow_tpu/faults.py): hosts
        #: may crash, links may cut; enables per-host blackhole accounting
        self.faults_active = False
        self.phase_wall: dict = {}  # per-phase timing lives in colplane

        self._deferred: set = set()  # hosts with ingress backlog
        #: multi-process sharding (parallel/shards.py): when bound, rows
        #: resolved here whose destination lives on another shard divert
        #: into xout[dst_shard] (13-field store rows) instead of the local
        #: heaps; bind_shard/take_xout/ingest_remote are the whole surface
        self.shard_id = 0
        self.shard_n = 1
        self.xout = None  # list[list[row]] per destination shard
        #: dynamic runahead (reference: experimental.use_dynamic_runahead):
        #: the smallest latency any resolved unit has actually used. Rounds
        #: may widen to this instead of the graph-wide minimum; a new flow
        #: over a shorter edge gets its first arrivals clamped to one
        #: barrier (the documented fidelity trade), then shrinks the window
        self.min_used_latency: SimTime = T_NEVER
        self.qdisc = str(getattr(tpu_options, "interface_qdisc", "fifo")
                         or "fifo")
        # device attach/calibration + adaptive routing floor (shared with
        # the columnar plane: network/devroute.py)
        self._init_device_routing(backend, tpu_options, params)

    def pending_head(self) -> SimTime:
        """Resolved-but-undelivered arrivals: always T_NEVER here — this
        plane pushes arrivals straight into host heaps (the columnar
        plane's store is where this is a real quantity)."""
        return T_NEVER

    # round hooks ----------------------------------------------------------
    def start_of_round(self, round_start: SimTime, round_end: SimTime) -> None:
        """Flush due draw results, refill the ingress buckets for the elapsed
        window, and re-drain any ingress-deferred units."""
        self.flush_due(round_end)
        dt = round_start - self._last_refill
        self._last_refill = round_start
        if dt > 0:
            p = self.params
            add_down = clamped_refill(p.rate_down, p.cap_down, dt)
            self.tokens_down += np.minimum(add_down, p.cap_down - self.tokens_down)
        if self._deferred:
            drain, self._deferred = self._deferred, set()
            for host in sorted(drain, key=lambda h: h.id):
                backlog, host.ingress_deferred = host.ingress_deferred, []
                for u in backlog:
                    self.ingress_arrival(u, round_start)

    def ingress_arrival(self, u: Unit, now: SimTime) -> None:
        """Down-link token bucket at the destination (runs on the dst host's
        thread via its arrival event, or single-threaded from round start)."""
        h = self.hosts[u.dst]
        if h.down:
            # crashed host (faults.py): dead NIC — no charge, no delivery
            h._n_teardown += 1
            return
        if now < self.bootstrap_end:
            self.hosts[u.dst].deliver(u, now)
            return
        if self.tokens_down[u.dst] >= u.size:
            self.tokens_down[u.dst] -= u.size
            self.hosts[u.dst].deliver(u, now)
        else:
            h = self.hosts[u.dst]
            h.ingress_deferred.append(u)
            self._deferred.add(h)

    def end_of_round(self, round_start: SimTime, round_end: SimTime) -> None:
        """The round barrier: resolve all units emitted this round."""
        units: list[Unit] = []
        for h in self.hosts:  # host-id order == src-sorted FIFO, no sort
            if h._ack_eps:
                # flush coalesced acks (transport.StreamReceiver._ack);
                # snapshot + clear in place — the dict's identity is
                # load-bearing for the C engine's cached reference
                eps = list(h._ack_eps)
                h._ack_eps.clear()
                for ep in eps:
                    if ep.state != 0:  # not CLOSED
                        ep.receiver.flush_ack()
            if h.egress:
                if self.qdisc == "round_robin" and len(h.egress) > 1:
                    h.egress = _round_robin(h.egress)
                units.extend(h.egress)
                h.egress = []
        n = len(units)
        if n == 0:
            return

        src = np.fromiter((u.src for u in units), dtype=np.int32, count=n)
        size = np.fromiter((u.size for u in units), dtype=np.int32, count=n)
        t_emit = np.fromiter((u.t_emit for u in units), dtype=np.int64, count=n)
        if round_start < self.bootstrap_end:
            depart = t_emit.copy()  # bootstrap: unlimited bandwidth
        else:
            depart = self.buckets.depart_times(src, size, t_emit, round_start)

        dst = np.fromiter((u.dst for u in units), dtype=np.int32, count=n)
        sn = self.params.host_node[src]
        dn = self.params.host_node[dst]
        lat = self.graph.latency_ns[sn, dn]

        reach = lat < INF_I64
        n_bh = n - int(reach.sum())
        if n_bh:
            self.units_blackholed += n_bh
            if self.faults_active:
                # per-host accounting (fault experiments): which sources
                # lost traffic to cut links / no-route
                for s in src[~reach].tolist():
                    self.hosts[s]._n_blackholed += 1
            units = [u for u, ok in zip(units, reach) if ok]
            if not units:
                return
            src, dst, sn, dn = src[reach], dst[reach], sn[reach], dn[reach]
            depart, lat = depart[reach], lat[reach]
            n = len(units)

        arrival = depart + lat
        if n:
            ml = int(lat.min())
            if ml < self.min_used_latency:
                self.min_used_latency = ml
        thresh = self.params.drop_thresh[sn, dn]
        # canonical event keys are the unit uids ((src << 32) | per-src
        # seq): a pure function of unit identity, so same-time arrival
        # ordering at a destination is independent of WHERE the unit was
        # resolved — the property that makes multi-process sharding
        # (parallel/shards.py) byte-identical at any shard count. _ev_key
        # stays a resolved-units counter (the determinism sentinel hashes
        # it; per-shard counts sum to the single-process value).
        keys = np.fromiter((u.uid for u in units), dtype=np.int64, count=n)
        self._ev_key += n

        forced = None
        if self.fault_filter is not None:
            forced = np.fromiter((self.fault_filter(u) for u in units),
                                 dtype=bool, count=n)
            if not forced.any():
                forced = None

        use_device = (
            self.device is not None
            and n >= self.device_floor
            and bool((thresh > 0).any())
        )
        if not use_device:
            self._floor_cooldown_tick()
            flags = loss_flags(self.params.seed, *_uid_arrays(units, n), thresh)
            if forced is not None:
                flags = flags | forced
            self._schedule_batch(units, arrival, flags, keys, round_end)
            return
        for i in range(0, n, self.max_batch):
            j = min(n, i + self.max_batch)
            lo, hi, npk = _uid_arrays(units[i:j], j - i)
            handle = self.device.dispatch(lo, hi, npk, thresh[i:j])
            if forced is not None:
                handle = _ForcedHandle(handle, forced[i:j])
            deadline = max(round_end, int(arrival[i:j].min()))
            self.outstanding.append(_Outstanding(
                units[i:j], arrival[i:j], keys[i:j],
                round_end, deadline, handle,
            ))

    # result consumption ----------------------------------------------------
    def flush_due(self, limit: SimTime) -> None:
        """Materialize every in-flight batch whose deadline precedes
        ``limit`` (the end of the round about to run). Batches flush in
        emission order; canonical keys make the order immaterial anyway."""
        if not self.outstanding:
            return
        due = [b for b in self.outstanding if b.deadline < limit]
        if not due:
            return
        self.outstanding = deque(b for b in self.outstanding if b.deadline >= limit)
        for b in due:
            t0 = _walltime.perf_counter()
            flags = b.handle.read()
            self._record_dev_read(_walltime.perf_counter() - t0,
                                  len(b.units))
            self._schedule_batch(b.units, b.arrival,
                                 flags, b.keys, b.round_end)
        self._floor_settle()

    def flush_all(self) -> None:
        self.flush_due(T_NEVER + 1)

    def _schedule_batch(self, units, arrival, dropped, keys,
                        round_end: SimTime) -> None:
        # bulk numpy->Python conversions (tolist is C-speed; per-element
        # int() boxing dominated this loop at 10k-host scale). The clamps
        # keep causality when experimental.runahead widens the round
        # beyond the graph's min latency.
        t_arrs = np.maximum(arrival, round_end).tolist()
        key_l = keys.tolist()
        drop_l = dropped.tolist()
        hosts = self.hosts
        ingress = self.ingress_arrival
        sent = 0
        nbytes = 0
        dropped_ct = 0
        sh_n, sh_id, xout = self.shard_n, self.shard_id, self.xout
        for i, u in enumerate(units):
            if drop_l[i]:
                dropped_ct += 1
            else:
                sent += 1
                nbytes += u.size
                t_arr = t_arrs[i]
                if sh_n > 1 and u.dst % sh_n != sh_id:
                    # cross-shard arrival: the sender resolved everything
                    # (departure, loss, arrival time, canonical key); the
                    # owning shard charges ingress + delivers in event
                    # order — the 13-field columnar store row is the wire
                    # format (parallel/shards.py packs/ships it)
                    xout[u.dst % sh_n].append(
                        (t_arr, key_l[i], u.dst, u.kind, u.src, u.src_port,
                         u.dst_port, u.nbytes, u.seq, u.frag_idx, u.nfrags,
                         u.size, u.payload))
                    continue
                hosts[u.dst].equeue.push(
                    t_arr, partial(ingress, u, t_arr),
                    band=BAND_NET, key=key_l[i])
        self.units_sent += sent
        self.units_dropped += dropped_ct
        self.bytes_sent += nbytes

    # -- multi-process sharding (parallel/shards.py) ------------------------
    def bind_shard(self, shard_id: int, shard_n: int) -> None:
        """Install the shard filter: this engine resolves only its owned
        hosts' emissions and diverts rows for other shards into xout."""
        self.shard_id = shard_id
        self.shard_n = shard_n
        self.xout = [[] for _ in range(shard_n)]

    def take_xout(self) -> list:
        """Drain the per-shard cross-shard row buffers, each sorted by the
        unique (t, key) prefix (the receiving shard's merge order)."""
        out, self.xout = self.xout, [[] for _ in range(self.shard_n)]
        for rows in out:
            rows.sort(key=lambda r: (r[0], r[1]))
        return out

    def ingest_remote(self, rows: list) -> None:
        """Arrival rows shipped from another shard (sorted by (t, key)):
        rebuild the per-unit plane's arrival events. The uid IS the key
        (canonical-key scheme), so the reconstructed Unit draws nothing
        and orders exactly as the local plane would have ordered it."""
        hosts = self.hosts
        ingress = self.ingress_arrival
        for (t, key, tgt, kind, peer, aport, bport, nbytes, seq, frag,
             nfrags, size, payload) in rows:
            u = Unit(uid=key, src=peer, dst=tgt, size=size, t_emit=0,
                     kind=kind, src_port=aport, dst_port=bport,
                     nbytes=nbytes, payload=payload, seq=seq,
                     frag_idx=frag, nfrags=nfrags)
            hosts[tgt].equeue.push(t, partial(ingress, u, t),
                                   band=BAND_NET, key=key)


def _round_robin(egress):
    """interface_qdisc: round_robin — fair interleave across this host's
    flows (src_port). Emission-time causality is primary (a unit emitted
    later can never charge the link bucket before an earlier one — the
    fluid serialization is FIFO in t_emit); fairness applies where it
    actually binds: among units emitted at the same instant, flows take
    turns (per-flow rank breaks the tie) instead of one flow's burst
    monopolizing the link. O(n log n), deterministic."""
    rank: dict = {}
    order: dict = {}
    keyed = []
    for i, u in enumerate(egress):
        f = u.src_port
        r = rank.get(f, 0)
        rank[f] = r + 1
        keyed.append((u.t_emit, r, order.setdefault(f, len(order)), i, u))
    keyed.sort(key=lambda t: t[:4])
    return [t[4] for t in keyed]


class _ForcedHandle:
    """Wraps a DrawHandle, OR-ing in fault-injected drops at read time."""

    __slots__ = ("_inner", "_forced")

    def __init__(self, inner, forced):
        self._inner = inner
        self._forced = forced

    def read(self):
        return self._inner.read() | self._forced


def _uid_arrays(units, n):
    uid = np.fromiter((u.uid for u in units), dtype=np.uint64, count=n)
    lo = (uid & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (uid >> np.uint64(32)).astype(np.uint32)
    npk = np.fromiter((u.npkts for u in units), dtype=np.uint32, count=n)
    return lo, hi, npk
