"""Device-resident columnar transport: endpoint ticks as a batched
tensor program (ROADMAP open item 2, PR 11).

The per-endpoint hot path — cumulative-ack application and the
congestion-control window arithmetic behind it — is scalar code in both
existing planes: Python closures on the columnar plane, C in colcore.
Either way a 100k-endpoint round costs 100k scalar callbacks, so
throughput scales with cores, not with the accelerator.  This module
makes the tick itself columnar: whole cohorts of endpoints advance per
round through ONE batched integer kernel (ops/transport_kernels.py — the
third twin surface, audited by tools/twincheck), with the scalar twin
serving every odd path.

How byte-identity is preserved (the load-bearing argument):

- **Deferral, not reordering.**  A host whose round inbox looks
  ack-dominated has its ENTIRE round deferred: ``Host.run_events``
  hands the inbox to ``DeviceTransport.intercept`` untouched, and the
  whole round replays at the barrier (``flush_round``) through the exact
  inbox<->timer-heap merge discipline of ``run_events`` — same
  (time, band, key) order, same clock updates, same token charges, same
  event counts.  Host rounds are independent within a round (the
  conservative-PDES invariant), so WHEN within the round a host's events
  execute cannot be observed; the replayed emissions join the same
  barrier they always joined.
- **Guess, verify, fall back** (the PR 3 speculative-window discipline):
  at flush, clean-looking cumulative acks are gathered into
  struct-of-arrays columns and advanced by one batched kernel; at
  replay, each row re-verifies its gathered input snapshot against the
  live endpoint (state ESTABLISHED, snd_una/cwnd/ssthresh/cubic-epoch
  unchanged, scoreboards empty, not in recovery, clock as predicted).
  Any mismatch — a second ack to the same endpoint this round, a
  connection that closed under a merged timer, a SACK-bearing ack —
  takes the scalar twin for that row.  A wrong guess costs kernel
  cycles, never correctness.
- **Emission-bearing side effects stay scalar, in order.**  rtx pruning,
  RTO cancel/rearm (timer seqs mint in replay order — identical to the
  scalar twin's, since the whole round replays), on_drain callbacks and
  the post-ack pump all run per row during replay with the host clock at
  that row's dispatch time.

Engagement is pure wall-clock policy behind
``experimental.device_transport`` (default off) with the devroute
break-even economics: an EMA of batched cost per ack vs a periodically
probed scalar cost per ack, engage/release hysteresis at the same
0.8x/1.25x bands, so on a box where the scalar twin wins the feature is
a measured no-op.  Cohorts above ``_DEVICE_FLOOR`` route the kernel to a
jax.jit twin at pinned bucket shapes (bit-identical int64 ops); smaller
cohorts take the numpy twin.

With the C engine attached, colcore IS the fast scalar twin and owns the
host loop, so this module does not intercept; the column
snapshot/adopt ABI (``Core.transport_columns`` /
``Core.adopt_transport_columns``, colcore ABI 4) exposes the same
struct-of-arrays view of C endpoint state for the cross-surface identity
gates and window-edge writeback.
"""

from __future__ import annotations

import time as _walltime  # detlint: ok(wallclock): engagement economics + phase_wall
from typing import Optional

import numpy as np

from shadow_tpu.network import unit as U
from shadow_tpu.network.transport import ESTABLISHED, StreamEndpoint
from shadow_tpu.ops import transport_kernels as TK

# store-row field indices (colplane.py row layout)
_R_T, _R_KEY, _R_TGT, _R_KIND = 0, 1, 2, 3
_R_PEER, _R_APORT, _R_BPORT, _R_NBYTES, _R_SEQ = 4, 5, 6, 7, 8
_R_FRAG, _R_NFRAGS, _R_SIZE, _R_PAYLOAD = 9, 10, 11, 12

#: minimum clean-looking ack rows in a host round to defer it (smaller
#: rounds cannot amortize even the gather loop)
_MIN_STAGE = 2
#: minimum cohort size to route the kernel to the device twin (jax);
#: below it the numpy twin wins on fixed dispatch cost
_DEVICE_FLOOR = 4096
#: EMA weight + engage/release hysteresis (devroute's constants, applied
#: to the transport tick)
_EMA_ALPHA = 0.25
_ENGAGE = 0.8
_RELEASE = 1.25

#: the canonical per-endpoint column set (struct-of-arrays, int64): what
#: export_columns/Core.transport_columns snapshot and what the
#: determinism gates compare across the three surfaces.  sacked_n /
#: rtx_done_n are the bounded-scoreboard lengths (the scoreboards are
#: sorted lists since PR 11, so the column view is canonical by
#: construction — no set-iteration waiver needed).
COLUMNS = (
    "state", "cwnd", "ssthresh", "snd_nxt", "snd_una", "adv_wnd",
    "buffered", "bytes_acked", "rto_backoff", "retries", "dup_acks",
    "loss_events", "cc_id", "in_recovery", "recover", "sack_high",
    "w_max", "epoch_start", "sacked_n", "rtx_done_n",
    "rcv_nxt", "ooo_bytes", "bytes_received", "last_wnd",
)
#: endpoint identity columns (snapshot/adopt join keys)
KEY_COLUMNS = ("hid", "local_port", "remote_host", "remote_port")
#: the columns adopt_transport_columns may write back: pure window/CC
#: arithmetic state — never sequence/buffer state, whose invariants are
#: owned by the scalar machinery (rtx ring consistency etc.)
ADOPT_COLUMNS = ("cwnd", "ssthresh", "w_max", "epoch_start",
                 "rto_backoff", "retries", "dup_acks")


def export_columns(hosts) -> dict:
    """Snapshot every Python stream endpoint's transport state as SoA
    int64 columns, hosts in id order, connections in sorted-key order —
    the Python-plane twin of colcore's ``Core.transport_columns`` (the
    cross-plane tests assert the two produce identical arrays for twin
    runs).  Caveat shared with the C twin: on a colcore run, pcap
    hosts' endpoints stay Python objects and the C snapshot omits them
    — compare snapshots on pcap-free configs only."""
    eps = []
    for h in hosts:
        conns = h._conns
        for key in sorted(conns):
            ep = conns[key]
            if isinstance(ep, StreamEndpoint):
                eps.append((h.id, key, ep))
    n = len(eps)
    out = {name: np.empty(n, dtype=np.int64)
           for name in KEY_COLUMNS + COLUMNS}
    for i, (hid, key, ep) in enumerate(eps):
        s, r = ep.sender, ep.receiver
        row = (hid, key[0], key[1], key[2],
               ep.state, s.cwnd, s.ssthresh, s.snd_nxt, s.snd_una,
               s.adv_wnd, s.buffered, s.bytes_acked, s.rto_backoff,
               s.retries, s.dup_acks, s.loss_events, s.cc.cc_id,
               1 if s.in_recovery else 0, s.recover, s.sack_high,
               s.w_max, s.epoch_start, len(s.sacked), len(s.rtx_done),
               r.rcv_nxt, r.ooo_bytes, r.bytes_received, r.last_wnd)
        for name, v in zip(KEY_COLUMNS + COLUMNS, row):
            out[name][i] = v
    return out


def adopt_columns(hosts, cols: dict) -> int:
    """Write the ADOPT_COLUMNS subset of a column snapshot back into the
    Python endpoints (window-edge writeback twin of
    ``Core.adopt_transport_columns``).  Joins on the identity columns;
    raises if any row no longer matches a live endpoint — BEFORE
    writing anything (refusal is atomic: a half-adopted cohort would be
    a state no snapshot ever described).  Returns the endpoint count
    written."""
    by_hid: dict = {h.id: h for h in hosts}
    n = len(cols["hid"]) if "hid" in cols else 0
    for name in KEY_COLUMNS + ADOPT_COLUMNS:  # atomicity needs lengths
        if name not in cols or len(cols[name]) != n:
            raise ValueError(f"adopt_columns: column {name!r} missing or "
                             f"not length {n}")
    eps = []
    for i in range(n):
        h = by_hid.get(int(cols["hid"][i]))
        ep = h._conns.get((int(cols["local_port"][i]),
                           int(cols["remote_host"][i]),
                           int(cols["remote_port"][i]))) if h else None
        if not isinstance(ep, StreamEndpoint):
            raise ValueError(
                f"adopt_columns: row {i} names no live Python endpoint")
        eps.append(ep)
    for i, ep in enumerate(eps):
        s = ep.sender
        s.cwnd = int(cols["cwnd"][i])
        s.ssthresh = int(cols["ssthresh"][i])
        s.w_max = int(cols["w_max"][i])
        s.epoch_start = int(cols["epoch_start"][i])
        s.rto_backoff = int(cols["rto_backoff"][i])
        s.retries = int(cols["retries"][i])
        s.dup_acks = int(cols["dup_acks"][i])
    return n


class _Ent:
    """One gathered kernel entry: the input snapshot (for replay-time
    verification) plus the kernel outputs (filled after dispatch)."""

    __slots__ = ("ep", "s", "key", "cum", "wnd", "now", "cc_id",
                 "snd_una", "cwnd", "ssthresh", "w_max", "epoch_start",
                 "o_cwnd", "o_wmax", "o_eps")

    def __init__(self, ep, key, cum, wnd, now):
        self.ep = ep
        s = ep.sender
        self.s = s
        self.key = key
        self.cum = cum
        self.wnd = wnd
        self.now = now
        self.cc_id = s.cc.cc_id
        self.snd_una = s.snd_una
        self.cwnd = s.cwnd
        self.ssthresh = s.ssthresh
        self.w_max = s.w_max
        self.epoch_start = s.epoch_start


class DeviceTransport:
    """The columnar transport engine for one ColumnarPlane (attached
    only when ``experimental.device_transport`` is on and the C engine
    is not — colcore already owns the scalar fast path; see the module
    docstring)."""

    def __init__(self, plane) -> None:
        self.plane = plane
        self.staged: list = []  # (host, rows, end) deferred this round
        self.executed = 0  # replayed event count, drained per round
        # telemetry / economics (wall-clock policy, never sim state)
        self.cohorts = 0  # columnar flushes served
        self.acks_batched = 0  # rows advanced by the kernel
        self.misguesses = 0  # gathered rows that failed replay verify
        self.scalar_probes = 0  # probe flushes run on the scalar twin
        self.device_cohorts = 0  # cohorts served by the jax kernel twin
        self.rounds_deferred = 0
        self._flushes = 0
        self._eligible = 0
        self._engaged = True
        self._batch_ema = 0.0
        self._scalar_ema = 0.0
        self._warm = False  # first columnar flush is attach noise
        self._devk = None  # DeviceAckKernel, published by the bg attach
        self._bg = None

    # -- background device attach (the devroute discipline) -----------------
    def start_device_attach(self) -> None:
        import threading

        self._bg = threading.Thread(target=self._bg_attach, daemon=True)
        self._bg.start()

    def _bg_attach(self) -> None:
        self._devk = TK.DeviceAckKernel.attach()  # None when unusable

    def close(self) -> None:
        t = self._bg
        if t is not None and t.is_alive():
            t.join()

    # -- staging (called from Host.run_events) ------------------------------
    def intercept(self, host, rows, end) -> bool:
        """Decide whether to defer this host's round to the barrier.
        Deferral is always result-identical (the whole round replays in
        canonical order); the scan is a pure profitability guess."""
        if host.pcap is not None:
            return False  # capture order is owned by the live dispatch
        if not self._engaged:
            # released by the economics: skip even the profitability
            # scan, re-probing the columnar path on a coarse cadence so
            # a changed traffic shape can re-engage
            self._eligible += 1
            if self._eligible & 127:
                return False
        n = 0
        for r in rows:
            if r[_R_KIND] == U.ACK and r[_R_PAYLOAD] is None:
                n += 1
        if n < _MIN_STAGE:
            return False
        self.staged.append((host, rows, end, n))
        self.rounds_deferred += 1
        return True

    def take_executed(self) -> int:
        n, self.executed = self.executed, 0
        return n

    # -- the barrier flush ---------------------------------------------------
    def flush_round(self, round_end) -> None:
        staged = self.staged
        if not staged:
            return
        self.staged = []
        self._flushes += 1
        t0 = _walltime.perf_counter()
        probe = (self._flushes & 15) == 0 and self._warm
        # both EMAs divide the whole-flush wall by the SAME denominator —
        # the clean-looking ack rows the intercept scan already counted
        # (carried in the staged tuple) — so the break-even comparison
        # is apples to apples even when gather rejects part of the
        # population (dup acks, repeat endpoints)
        nacks = sum(s[3] for s in staged)
        if probe:
            # scalar probe: the same deferred replay, every row through
            # the scalar twin, timed — the live denominator of the
            # break-even comparison (bit-identical by construction)
            self.scalar_probes += 1
            for host, rows, end, _n in staged:
                self.executed += self._replay(host, rows, end, None)
            dt = _walltime.perf_counter() - t0
            if nacks:
                self._scalar_ema = _ema(self._scalar_ema, dt / nacks)
        else:
            fast_maps, cols = self._gather(staged)
            n = len(cols[0]) if cols is not None else 0
            if n:
                outs = self._kernel(cols, n)
                off = 0
                for fm in fast_maps:
                    if fm:
                        for ent in fm.values():
                            ent.o_cwnd = int(outs[2][off])
                            ent.o_wmax = int(outs[3][off])
                            ent.o_eps = int(outs[4][off])
                            off += 1
                self.cohorts += 1
                self.acks_batched += n
            for (host, rows, end, _n), fm in zip(staged, fast_maps):
                self.executed += self._replay(host, rows, end,
                                              fm or None)
            dt = _walltime.perf_counter() - t0
            if not self._warm:
                self._warm = True  # kernel warmup flush: not signal
            elif nacks:
                self._batch_ema = _ema(self._batch_ema, dt / nacks)
        self._decide()
        self.plane.phase_wall["transport_tick"] += (
            _walltime.perf_counter() - t0)

    def _decide(self) -> None:
        """Engage/release with the devroute hysteresis bands: both paths
        are bit-identical, so this is pure wall-clock routing policy."""
        b, s = self._batch_ema, self._scalar_ema
        if b <= 0.0 or s <= 0.0:
            return
        if self._engaged and b > _RELEASE * s:
            self._engaged = False
        elif not self._engaged and b < _ENGAGE * s:
            self._engaged = True

    # -- gather: rows -> columns --------------------------------------------
    def _gather(self, staged):
        """Classify each deferred host's ack rows and build the cohort
        columns.  Classification is a guess — replay verifies row by
        row; here we only need the gathered inputs to be the live
        pre-round state (true: deferred hosts ran nothing yet)."""
        fast_maps = []
        ents = []
        for host, rows, _end, _n in staged:
            fm = {}
            conns = host._conns
            seen = {}
            now0 = host._now
            for i, r in enumerate(rows):
                if r[_R_KIND] != U.ACK or r[_R_PAYLOAD] is not None:
                    continue
                ep = conns.get((r[_R_BPORT], r[_R_PEER], r[_R_APORT]))
                if type(ep) is not StreamEndpoint or ep in seen:
                    continue
                s = ep.sender
                cum = r[_R_NBYTES]
                if not self._stageable(ep, s, cum):
                    continue
                seen[ep] = None
                t = r[_R_T]
                ent = _Ent(ep, (r[_R_BPORT], r[_R_PEER], r[_R_APORT]),
                           cum, r[_R_SEQ], t if t > now0 else now0)
                fm[i] = ent
                ents.append(ent)
            fast_maps.append(fm)
        if not ents:
            return fast_maps, None
        n = len(ents)
        cols = tuple(np.empty(n, dtype=np.int64) for _ in range(9))
        (cc_id, cwnd, ssthresh, w_max, eps, snd_una, bytes_acked, cum,
         now) = cols
        for j, e in enumerate(ents):
            cc_id[j] = e.cc_id
            cwnd[j] = e.cwnd
            ssthresh[j] = e.ssthresh
            w_max[j] = e.w_max
            eps[j] = e.epoch_start
            snd_una[j] = e.snd_una
            bytes_acked[j] = e.s.bytes_acked
            cum[j] = e.cum
            now[j] = e.now
        return fast_maps, cols

    @staticmethod
    def _stageable(ep, s, cum) -> bool:
        """The clean-advance GUESS (replay verifies it row by row; the
        wrong-kernel-guess test forces this to lie and asserts results
        are still byte-identical — the PR 3 discipline)."""
        return (ep.state == ESTABLISHED and not s.in_recovery
                and not s.sacked and not s.rtx_done and cum > s.snd_una)

    def _kernel(self, cols, n: int):
        """ONE batched dispatch for the whole cohort: the jax twin at
        pinned bucket shapes above the device floor, the numpy twin
        below — bit-identical integer programs either way."""
        devk = self._devk
        if devk is not None and n >= _DEVICE_FLOOR:
            self.device_cohorts += 1
            return devk.run(*cols[:8], now=cols[8])
        (cc_id, cwnd, ssthresh, w_max, eps, snd_una, bytes_acked, cum,
         now) = cols
        return TK.ack_advance(cc_id, cwnd, ssthresh, w_max, eps,
                              snd_una, bytes_acked, cum, now)

    # -- replay: the deferred round, in canonical order ----------------------
    def _replay(self, host, rows, end, fast: Optional[dict]) -> int:
        """Execute the deferred round exactly as Host.run_events would
        have: the inbox<->timer-heap merge in (time, band, key) order,
        each row either kernel-applied (verified) or dispatched through
        the scalar twin."""
        eq = host.equeue
        heap = eq._heap
        head = eq.head
        pop = eq.pop_until
        n = 0
        pos, ln = 0, len(rows)
        dispatch = host.dispatch_row
        # fast path (run_events' twin): no heap events at all — straight
        # row drain, re-checking only the emptiness bit per row
        while pos < ln and not heap:
            ent = fast.get(pos) if fast is not None else None
            if ent is not None:
                self._fast_row(host, rows[pos], ent)
            else:
                dispatch(rows[pos])
            pos += 1
            n += 1
        while True:
            h0 = head()
            hv = h0 is not None and h0[0] < end
            if pos < ln:
                row = rows[pos]
                ti = row[0]
                if (not hv or ti < h0[0]
                        or (ti == h0[0]
                            and (0, row[1]) < (h0[1], h0[2]))):
                    ent = fast.get(pos) if fast is not None else None
                    if ent is not None:
                        self._fast_row(host, row, ent)
                    else:
                        dispatch(row)
                    pos += 1
                    n += 1
                    continue
            if hv:
                host._now, task = pop(end)
                task()
                n += 1
                continue
            break
        host._n_events += n
        return n

    def _fast_row(self, host, row, ent) -> None:
        """dispatch_row's clock/NIC accounting, then the verified
        kernel writeback — or the scalar twin when verification fails
        (the wrong-guess path: cycles, never correctness)."""
        t = row[_R_T]
        if t > host._now:
            host._now = t
        if host.down:
            host._n_teardown += 1
            return
        eng = self.plane
        if t >= eng.bootstrap_end:
            tokens = eng.tokens_down
            if tokens[host.id] >= row[_R_SIZE]:
                tokens[host.id] -= row[_R_SIZE]
            else:
                host.ingress_deferred_rows.append(row)
                eng._deferred.add(host)
                return
        ep, s = ent.ep, ent.s
        if (host._conns.get(ent.key) is not ep
                or ep.state != ESTABLISHED
                or ent.cum <= s.snd_una
                or s.snd_una != ent.snd_una or s.cwnd != ent.cwnd
                or s.ssthresh != ent.ssthresh or s.w_max != ent.w_max
                or s.epoch_start != ent.epoch_start
                or s.in_recovery or s.sacked or s.rtx_done
                or (ent.cc_id == TK.CC_CUBIC and ent.now != host._now)):
            self.misguesses += 1
            host._deliver_row(t, row[_R_KIND], row[_R_PEER],
                              row[_R_APORT], row[_R_BPORT],
                              row[_R_NBYTES], row[_R_SEQ], row[_R_FRAG],
                              row[_R_NFRAGS], row[_R_PAYLOAD])
            return
        host._n_delivered += 1
        # handle_fields(ACK) for the verified clean advance, kernel
        # results written back in the scalar twin's exact order
        if ep._idle_timer is not None:
            ep._rearm_idle()
        cum = ent.cum
        s.adv_wnd = ent.wnd
        s.dup_acks = 0
        s.snd_una = cum
        s.bytes_acked += cum - ent.snd_una
        rtx = s.rtx
        while rtx and rtx[0][0] + rtx[0][1] <= cum:
            rtx.popleft()
        s.rto_backoff = 1
        s.retries = 0
        s._cancel_rto()
        if s.snd_nxt > cum:
            s._arm_rto()
        s.cwnd = ent.o_cwnd
        s.w_max = ent.o_wmax
        s.epoch_start = ent.o_eps
        drained = ep.on_drain
        if drained is not None and s.buffered < s.send_buffer:
            drained(s.send_buffer - s.buffered)
        s.pump()

    # -- telemetry -----------------------------------------------------------
    def summary(self) -> dict:
        """Wall-clock routing telemetry (volatile, never sim state)."""
        return {
            "cohorts": self.cohorts,
            "acks_batched": self.acks_batched,
            "misguesses": self.misguesses,
            "rounds_deferred": self.rounds_deferred,
            "scalar_probes": self.scalar_probes,
            "device_cohorts": self.device_cohorts,
            "engaged": self._engaged,
            "batch_per_ack_us": round(self._batch_ema * 1e6, 3),
            "scalar_per_ack_us": round(self._scalar_ema * 1e6, 3),
        }


def _ema(cur: float, sample: float) -> float:
    return sample if cur == 0.0 else cur + _EMA_ALPHA * (sample - cur)
