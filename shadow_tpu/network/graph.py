"""Network topology graph, routing, and IP assignment.

Mirrors the reference's network-graph layer (SURVEY.md §1 layer 8, §2
"Network graph + routing"): load a GML topology (nodes carry default host
bandwidths; edges carry latency + packet loss), assign hosts to graph nodes,
assign IPs, and answer ``latency(src_node, dst_node)`` / ``reliability(src,
dst)`` queries from an all-pairs-shortest-path (APSP) table.

Memory note (SURVEY.md §7): hosts map to G graph nodes (G is small — a few
thousand even for full-Tor topologies), so we store dense (G, G) latency and
reliability matrices plus an O(H) host->node index vector. Nothing is ever
(H, H).

APSP algorithm: min-plus matrix "squaring" repeated ceil(log2(G)) times,
with the path reliability (product of per-edge (1 - loss)) carried along the
argmin decomposition. The same algorithm runs in numpy (here, canonical) and
as a JAX kernel (shadow_tpu/ops/apsp.py) so the two backends agree; ties are
broken identically (first minimal k) in both.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from shadow_tpu.core.time import NS_PER_MS, SimTime, parse_time
from shadow_tpu.network.gml import GmlGraph, parse_gml, parse_gml_file
from shadow_tpu.utils.units import parse_bandwidth

#: Sentinel for "unreachable" in int64 latency matrices. Chosen so that
#: INF + INF still fits in int64 (min-plus sums saturate back to INF).
INF_I64 = np.int64(1) << np.int64(61)
#: Device kernels use int32 ns with this saturating infinity (~1.07 s).
#: Chosen so INF + INF still fits in int32 (min-plus sums saturate back).
INF_I32 = (np.int32(1) << np.int32(30)) - np.int32(1)


@dataclass
class NodeDefaults:
    bandwidth_up: Optional[int] = None  # bytes/sec
    bandwidth_down: Optional[int] = None  # bytes/sec


@dataclass
class NetworkGraph:
    """Loaded topology + routing tables.

    latency_ns: (G, G) int64, INF_I64 where unreachable, 0 on the diagonal
                unless the graph provides an explicit self-edge.
    reliability: (G, G) float32 in [0, 1]; product of (1 - loss) along the
                chosen shortest-latency path.
    """

    n_nodes: int
    latency_ns: np.ndarray
    reliability: np.ndarray
    node_defaults: list[NodeDefaults]
    node_id_map: dict[int, int] = field(default_factory=dict)  # gml id -> index

    @property
    def min_latency_ns(self) -> SimTime:
        """The conservative-PDES lookahead bound: the smallest finite
        nonzero latency anywhere in the table (including self-edges, which
        bound same-node host pairs)."""
        finite = self.latency_ns[self.latency_ns < INF_I64]
        finite = finite[finite > 0]
        if finite.size == 0:
            return NS_PER_MS  # degenerate graph: fall back to 1 ms rounds
        return int(finite.min())

    def latency(self, src_node: int, dst_node: int) -> SimTime:
        return int(self.latency_ns[src_node, dst_node])

    def reliability_of(self, src_node: int, dst_node: int) -> float:
        return float(self.reliability[src_node, dst_node])

    def reachable(self, src_node: int, dst_node: int) -> bool:
        return self.latency_ns[src_node, dst_node] < INF_I64


def _apsp_minplus(lat: np.ndarray, rel: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Min-plus repeated squaring; carries reliability along argmin paths.

    lat: (G, G) int64 with INF_I64 sentinels, 0 diagonal.
    rel: (G, G) float32, 1.0 diagonal.
    Ties on latency pick the first (lowest) intermediate k — matching
    jnp.argmin semantics so the device kernel reproduces this exactly.
    """
    g = lat.shape[0]
    steps = max(1, int(np.ceil(np.log2(max(g, 2)))))
    for _ in range(steps):
        # cand[i, j, k] = lat[i, k] + lat[k, j]; block over i to bound memory.
        new_lat = np.empty_like(lat)
        new_rel = np.empty_like(rel)
        block = max(1, min(g, int(4e7 // max(g * g, 1)) or 1))
        for i0 in range(0, g, block):
            i1 = min(g, i0 + block)
            # cand[i, k, j] = lat[i, k] + lat[k, j]
            cand = lat[i0:i1, :, None] + lat[None, :, :]
            cand = np.minimum(cand, INF_I64)  # saturate (2*INF fits int64)
            k_star = np.argmin(cand, axis=1)  # (b, G=j), first minimum
            new_lat[i0:i1] = np.take_along_axis(cand, k_star[:, None, :], axis=1)[:, 0, :]
            # gather reliability along the chosen decomposition only (no G^3
            # float product): rel[i, k*] * rel[k*, j]
            rel_ik = np.take_along_axis(rel[i0:i1], k_star, axis=1)
            rel_kj = rel[k_star, np.arange(g)[None, :]]
            new_rel[i0:i1] = rel_ik * rel_kj
        lat, rel = new_lat, new_rel
    return lat, rel


def _parse_loss(v) -> float:
    if v is None:
        return 0.0
    f = float(v)
    if not (0.0 <= f <= 1.0):
        raise ValueError(f"packet_loss must be in [0,1], got {f}")
    return f


def from_gml(gml: GmlGraph) -> NetworkGraph:
    nodes = gml.nodes
    edges = gml.edges
    g = len(nodes)
    if g == 0:
        raise ValueError("topology has no nodes")

    node_id_map: dict[int, int] = {}
    defaults: list[NodeDefaults] = []
    for idx, n in enumerate(nodes):
        nid = n.get("id", idx)
        if nid in node_id_map:
            raise ValueError(f"duplicate GML node id {nid}")
        node_id_map[nid] = idx
        d = NodeDefaults()
        if "host_bandwidth_up" in n:
            d.bandwidth_up = parse_bandwidth(n["host_bandwidth_up"])
        if "host_bandwidth_down" in n:
            d.bandwidth_down = parse_bandwidth(n["host_bandwidth_down"])
        defaults.append(d)

    lat = np.full((g, g), INF_I64, dtype=np.int64)
    rel = np.zeros((g, g), dtype=np.float32)
    np.fill_diagonal(lat, 0)
    np.fill_diagonal(rel, 1.0)

    for e in edges:
        try:
            s = node_id_map[e["source"]]
            t = node_id_map[e["target"]]
        except KeyError as exc:
            raise ValueError(f"edge references unknown node: {e}") from exc
        l_ns = parse_time(e.get("latency", "1 ms"))
        if l_ns <= 0:
            raise ValueError(f"edge latency must be > 0: {e}")
        loss = _parse_loss(e.get("packet_loss"))
        pairs = [(s, t)] if gml.directed else [(s, t), (t, s)]
        for a, b in pairs:
            if a == b:
                # self-edge: latency between two hosts on the same node
                if l_ns < lat[a, b] or lat[a, b] == 0:
                    lat[a, b] = l_ns
                    rel[a, b] = 1.0 - loss
            elif l_ns < lat[a, b]:
                lat[a, b] = l_ns
                rel[a, b] = 1.0 - loss

    # Hosts on the same node with no explicit self-edge: use the smallest
    # adjacent edge latency as a stand-in (diagonal must be > 0 for the
    # conservative lookahead to be sound for same-node pairs).
    for i in range(g):
        if lat[i, i] == 0:
            row = np.concatenate([lat[i, :i], lat[i, i + 1:]])
            finite = row[row < INF_I64]
            lat[i, i] = int(finite.min()) if finite.size else NS_PER_MS
            rel[i, i] = 1.0

    # APSP must not relax through the (host-pair) diagonal: set diag to 0 for
    # the solve (identity of min-plus), then restore self-latencies after.
    self_lat = lat.diagonal().copy()
    self_rel = rel.diagonal().copy()
    np.fill_diagonal(lat, 0)
    np.fill_diagonal(rel, 1.0)
    lat, rel = _apsp_minplus(lat, rel)
    np.fill_diagonal(lat, self_lat)
    np.fill_diagonal(rel, self_rel)

    return NetworkGraph(
        n_nodes=g,
        latency_ns=lat,
        reliability=rel,
        node_defaults=defaults,
        node_id_map=node_id_map,
    )


def one_gbit_switch(latency_ns: SimTime = NS_PER_MS) -> NetworkGraph:
    """The reference's built-in single-switch shorthand topology
    (SURVEY.md §5.6: '1 Gbit switch')."""
    bw = parse_bandwidth("1 Gbit")
    lat = np.full((1, 1), latency_ns, dtype=np.int64)
    rel = np.ones((1, 1), dtype=np.float32)
    return NetworkGraph(
        n_nodes=1,
        latency_ns=lat,
        reliability=rel,
        node_defaults=[NodeDefaults(bandwidth_up=bw, bandwidth_down=bw)],
        node_id_map={0: 0},
    )


def load_graph(spec: dict) -> NetworkGraph:
    """Load from a config ``network.graph`` section: type gml|1_gbit_switch,
    with ``file:`` path or ``inline:`` text for gml."""
    gtype = str(spec.get("type", "gml")).replace(" ", "_").lower()
    if gtype in ("1_gbit_switch", "1gbit_switch", "switch"):
        return one_gbit_switch()
    if gtype == "gml":
        if "file" in spec:
            path = spec["file"]
            if isinstance(path, dict):  # shadow's {path: ..., compression: ...}
                path = path["path"]
            return from_gml(parse_gml_file(path))
        if "inline" in spec:
            return from_gml(parse_gml(spec["inline"]))
        raise ValueError("network.graph of type gml needs 'file' or 'inline'")
    raise ValueError(f"unknown network.graph.type: {spec.get('type')!r}")
