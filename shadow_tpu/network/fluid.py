"""The network data plane: closed-form fluid token buckets + loss sampling.

This is the re-design of the reference's Router/Relay token-bucket hot path
(SURVEY.md §2 "Router + Relay", §3.4) as a *batched tensor program*. Round 1
iterated the buckets round-by-round (refill, drain what fits, retry the rest
next round), which forced one device dispatch per round per backlog — the
exact failure mode SURVEY.md §7 "Hard parts" #2 warned about. Round 2
replaces iteration with a closed form:

**While a source is backlogged its bucket never idles at capacity, so the
available-token curve is linear in time.** The departure time of the unit at
cumulative FIFO byte offset Q is therefore

    t_dep = max(t_emit, t_base + ceil((Q - T) * 1e9 / rate))

with (t_base, T) the bucket's accounting base — pure integer math, O(1) per
unit, evaluated once at the unit's emission barrier. No retries, no per-round
device sync, and the result is independent of the round width W (the
conservative-PDES window only gates *when* cross-host effects are applied,
never the computed times).

Semantics owned by this module (both the numpy and device paths consume
them; there is exactly ONE implementation of the bucket math, host-side):
- Buckets accrue tokens continuously at ``rate`` bytes/sec (integer ns math,
  floored once over the whole interval — no per-round floor truncation).
- Saturation (clamp at capacity) is evaluated lazily at emission barriers:
  if a bucket would exceed capacity at barrier time t_now, its base is reset
  to (t_now, cap). While backlogged a bucket can't saturate, so this is
  exact whenever it matters; for an idle bucket it quantizes the saturation
  instant to the barrier that next touches the source (documented choice).
- Loss is sampled per MTU-sized packet within a unit with counter-based
  threefry draws keyed on (seed, uid, packet index) — a pure function of
  unit identity, so numpy and TPU produce identical drops in any order
  (SURVEY.md §7 "Determinism across backends").

All quantities are integers (bytes, ns). The only floats anywhere are the
float64 loss-threshold precompute at startup (quantize_loss).

Unit sizes are bounded by the configured quantum (experimental.unit_mtus,
default MAX_UNIT): streams are chunked
by the transport (shadow_tpu/network/transport.py), datagrams are fragmented
by the socket layer. Loss probability scales with unit size exactly the same
way on both backends with pure integer compares.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from shadow_tpu.core.time import NS_PER_SEC, SimTime
from shadow_tpu.ops.prng import threefry2x32, quantize_loss

MTU = 1500  # bytes on the wire per packet
HEADER = 40  # modeled header overhead per unit and per ack
MAX_UNIT = 10 * MTU  # DEFAULT max wire bytes per transmission unit
MAX_PKTS = 10  # = MAX_UNIT / MTU, loss draws per unit (default quantum)
#: experimental.unit_mtus can widen the fluid quantum up to this bound;
#: the per-packet counter packing (PKT_SHIFT) reserves 6 bits, and uid
#: packing ((hid << 32) | ctr: host id sits in uid_hi, the 32-bit
#: per-host counter in uid_lo) then caps host ids at 2**26 (enforced in
#: NetParams.build — the bound that admits the 1M-host topologies;
#: a host would need 2**32 lifetime emissions to overflow its counter)
HARD_MAX_PKTS = 64
PKT_SHIFT = 26  # packet-lane index position inside the threefry counter
MIN_CAP = 16384  # token bucket capacity floor: one default MAX_UNIT + room
#: per-host rate ceiling (bytes/sec) keeping rate * 1e9 within uint64
#: (the closed-form math runs its two sub-second products in uint64)
MAX_RATE = 16_000_000_000  # 128 Gbit/s


@dataclass
class NetParams:
    """Static per-simulation network parameters."""

    host_node: np.ndarray  # (H,) int32: host -> graph node index
    rate_up: np.ndarray  # (H,) int64 bytes/sec
    rate_down: np.ndarray  # (H,) int64 bytes/sec
    cap_up: np.ndarray  # (H,) int64 bucket capacity, < 2**31
    cap_down: np.ndarray  # (H,) int64
    latency_ns: np.ndarray  # (G, G) int64
    drop_thresh: np.ndarray  # (G, G) uint32 q24 drop probability
    seed: int

    @classmethod
    def build(
        cls,
        host_node: np.ndarray,
        rate_up: np.ndarray,
        rate_down: np.ndarray,
        latency_ns: np.ndarray,
        reliability: np.ndarray,
        seed: int,
        round_ns: SimTime,
        max_unit: int = MAX_UNIT,
    ) -> "NetParams":
        rate_up = np.asarray(rate_up, dtype=np.int64)
        rate_down = np.asarray(rate_down, dtype=np.int64)
        if (rate_up <= 0).any() or (rate_down <= 0).any():
            raise ValueError("host bandwidths must be > 0")
        if len(host_node) >= (1 << PKT_SHIFT):
            # uid packing: uid_hi IS the host id, the packet lane
            # occupies uid_hi bits PKT_SHIFT.. — they must not overlap
            raise ValueError(
                f"host count exceeds 2**{PKT_SHIFT} (uid packing bound)")
        if (rate_up > MAX_RATE).any() or (rate_down > MAX_RATE).any():
            raise ValueError(
                f"host bandwidth exceeds {MAX_RATE} B/s "
                f"(= {MAX_RATE * 8 / 1e9:.0f} Gbit/s), the integer-exact "
                "ceiling of the closed-form bucket math"
            )
        # capacity floor: at least one full unit (+ header) must fit, or a
        # max-size unit could never clear the bucket
        floor = max(MIN_CAP, max_unit + HEADER)
        cap_up = np.maximum(rate_up * round_ns // NS_PER_SEC, floor)
        cap_down = np.maximum(rate_down * round_ns // NS_PER_SEC, floor)
        limit = (np.int64(1) << np.int64(31)) - 1
        # capacities stay int32-safe so offsets fit device-side arrays
        cap_up = np.minimum(cap_up, limit - 1)
        cap_down = np.minimum(cap_down, limit - 1)
        return cls(
            host_node=np.asarray(host_node, dtype=np.int32),
            rate_up=rate_up,
            rate_down=rate_down,
            cap_up=cap_up,
            cap_down=cap_down,
            latency_ns=np.asarray(latency_ns, dtype=np.int64),
            drop_thresh=quantize_loss(reliability),
            seed=int(seed),
        )


def bytes_over(rate: np.ndarray, dt_ns) -> np.ndarray:
    """Exact ``rate * dt // 1e9`` without overflow (dt may be hours): split
    dt into whole seconds + remainder ns; the remainder product runs in
    uint64 (< 2**64 given rate <= MAX_RATE and r < 1e9)."""
    dt_ns = np.asarray(dt_ns, dtype=np.int64)
    q, r = dt_ns // NS_PER_SEC, dt_ns % NS_PER_SEC
    frac = (rate.astype(np.uint64) * r.astype(np.uint64) // np.uint64(NS_PER_SEC))
    return rate * q + frac.astype(np.int64)


def clamped_refill(rate: np.ndarray, cap: np.ndarray, dt_ns: int) -> np.ndarray:
    """Token refill for an elapsed window of dt_ns, pre-clamped to capacity
    (down-link ingress buckets, which stay round-quantized host-side)."""
    return np.minimum(bytes_over(rate, dt_ns), cap).astype(np.int64)


class TokenBuckets:
    """Per-source closed-form egress buckets — THE bucket implementation.

    State per source: (t_base ns, T tokens at t_base, debt bytes committed
    since t_base). Available tokens at barrier time t:
    ``T + bytes_over(rate, t - t_base) - debt``. All int64, exact.
    """

    def __init__(self, params: NetParams) -> None:
        h = params.rate_up.shape[0]
        self.params = params
        self.t_base = np.zeros(h, dtype=np.int64)
        self.tokens = params.cap_up.copy()  # T at t_base
        self.debt = np.zeros(h, dtype=np.int64)

    def available(self, t_now: SimTime) -> np.ndarray:
        p = self.params
        return self.tokens + bytes_over(p.rate_up, t_now - self.t_base) - self.debt

    def levels(self, t_now: SimTime) -> np.ndarray:
        """Capped available-at-now — THE canonical plane-independent
        bucket observable (the vector path rebases every source each
        barrier while the scalar twin rebases lazily, an outcome-identical
        representation difference; capping removes it). Shared by the
        determinism sentinel (checkpoint.state_digest) and the telemetry
        samplers (telemetry/collector.py)."""
        return np.minimum(self.available(t_now), self.params.cap_up)

    def rebase(self, t_now: SimTime) -> None:
        """Clamp saturated buckets to capacity at t_now (lazy, exact for any
        source that still has committed departures pending — see module doc)."""
        p = self.params
        avail = self.available(t_now)
        sat = avail > p.cap_up
        if sat.any():
            self.t_base[sat] = t_now
            self.tokens[sat] = p.cap_up[sat]
            self.debt[sat] = 0

    def depart_times(self, src: np.ndarray, size: np.ndarray,
                     t_emit: np.ndarray, t_now: SimTime) -> np.ndarray:
        """Departure time for each unit of a (src-sorted, per-source FIFO)
        batch emitted by barrier time t_now. Commits the batch (updates debt).

        Returns (N,) int64 ns. Vectorized closed form; see module docstring.
        """
        self.rebase(t_now)
        n = src.shape[0]
        if n == 0:
            return np.zeros(0, dtype=np.int64)
        p = self.params
        size64 = size.astype(np.int64)
        csum = np.cumsum(size64)
        seg_first = np.ones(n, dtype=bool)
        seg_first[1:] = src[1:] != src[:-1]
        seg_base = np.where(seg_first, csum - size64, 0)
        seg_base = np.maximum.accumulate(seg_base)
        cum_in_seg = csum - seg_base

        need = self.debt[src] + cum_in_seg - self.tokens[src]  # X = Q - T
        rate = p.rate_up[src]
        q, r = need // rate, need % rate  # floor semantics fine: need>0 below
        # ceil(r * 1e9 / rate) with the product in uint64 (r < rate <= MAX_RATE)
        frac = (r.astype(np.uint64) * np.uint64(NS_PER_SEC)
                + rate.astype(np.uint64) - np.uint64(1)) // rate.astype(np.uint64)
        t_off = q * NS_PER_SEC + frac.astype(np.int64)
        t_ready = np.where(need > 0, self.t_base[src] + t_off, np.int64(0))
        t_dep = np.maximum(t_emit.astype(np.int64), t_ready)

        # commit: debt += per-source batch totals (exact integer segment sums)
        starts = np.flatnonzero(seg_first)
        self.debt[src[starts]] += np.add.reduceat(size64, starts)
        return t_dep

    def depart_times_scalar(self, src_l, size_l, t_emit_l,
                            t_now: SimTime) -> list:
        """Exact scalar twin of depart_times for tiny batches (numpy's
        fixed per-op cost dominates them). Python ints are arbitrary
        precision, so the arithmetic matches the vector path bit-for-bit;
        rebase runs lazily on the touched sources only — outcome-identical
        (an untouched saturated bucket clamps to capacity at whichever
        barrier next reads it, with the same resulting state)."""
        p = self.params
        t_base, tokens, debt = self.t_base, self.tokens, self.debt
        rate_up, cap_up = p.rate_up, p.cap_up
        for s in set(src_l):
            rate = int(rate_up[s])
            dt = t_now - int(t_base[s])
            q, r = divmod(dt, NS_PER_SEC)
            avail = (int(tokens[s]) + rate * q + rate * r // NS_PER_SEC
                     - int(debt[s]))
            if avail > int(cap_up[s]):
                t_base[s] = t_now
                tokens[s] = cap_up[s]
                debt[s] = 0
        out = []
        cum: dict = {}
        for i, s in enumerate(src_l):
            qsum = cum.get(s, 0) + size_l[i]
            cum[s] = qsum
            need = int(debt[s]) + qsum - int(tokens[s])
            if need > 0:
                rate = int(rate_up[s])
                q, r = divmod(need, rate)
                t_ready = (int(t_base[s]) + q * NS_PER_SEC
                           + (r * NS_PER_SEC + rate - 1) // rate)
            else:
                t_ready = 0
            te = t_emit_l[i]
            out.append(te if te > t_ready else t_ready)
        for s, qsum in cum.items():
            debt[s] += qsum
        return out


def loss_flags(seed: int, uid_lo: np.ndarray, uid_hi: np.ndarray,
               npkts: np.ndarray, thresh: np.ndarray) -> np.ndarray:
    """numpy twin of the device draw kernel (shadow_tpu/ops/propagate.py):
    per-packet threefry draws; a unit is dropped iff any of its first npkts
    draws is below its threshold. Bit-identical to the device by
    construction (same integer arithmetic, tests/test_bitmatch.py)."""
    n = uid_lo.shape[0]
    out = np.zeros(n, dtype=bool)
    live = thresh > 0  # threshold 0 can never hit; skip the draw work
    if not live.any():
        return out
    lo, hi = uid_lo[live].astype(np.uint32), uid_hi[live].astype(np.uint32)
    npk, th = npkts[live], thresh[live]
    k = int(npk.max())
    pkt = np.arange(k, dtype=np.uint32)[None, :]
    c0 = np.broadcast_to(lo[:, None], (lo.shape[0], k))
    c1 = hi[:, None] | (pkt << np.uint32(PKT_SHIFT))
    k0 = np.uint32(seed & 0xFFFFFFFF)
    k1 = np.uint32((seed >> 32) & 0xFFFFFFFF)
    draws, _ = threefry2x32(k0, k1, c0, c1, xp=np)
    draws = (draws >> np.uint32(8)).astype(np.uint32)
    hit = (draws < th.astype(np.uint32)[:, None]) & (pkt < npk.astype(np.uint32)[:, None])
    out[live] = hit.any(axis=1)
    return out
