"""The per-round network data plane: numpy reference implementation.

This is the re-design of the reference's Router/Relay token-bucket hot path
(SURVEY.md §2 "Router + Relay", §3.4) as a *batched tensor program*: per
round, every pending transmission unit from every host is processed in one
vectorized step — token-bucket drain (FIFO with head-of-line blocking per
source), shortest-path latency lookup, and counter-based loss sampling.

The exact same integer math runs as JAX kernels on TPU
(shadow_tpu/ops/propagate.py); tests/test_bitmatch.py asserts bit-equality.

Key invariants (conservative PDES, SURVEY.md §2 parallelism item 4):
- every edge latency >= round width W, so every computed arrival time lands
  at or after the next round boundary — cross-host effects never need
  rollback.
- all quantities are integers (bytes, ns); the only floats anywhere are the
  float64 loss-threshold precompute at startup (quantize_loss).

Unit sizes are bounded by MAX_UNIT (a handful of MTUs): streams are chunked
by the transport (shadow_tpu/network/transport.py), datagrams are fragmented
by the socket layer. Loss is sampled per MTU-sized packet *within* a unit
(up to MAX_PKTS draws, any hit drops the unit) so that loss probability
scales with unit size exactly the same way on both backends with pure
integer compares.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from shadow_tpu.core.time import SimTime
from shadow_tpu.ops.prng import draw_24bit, quantize_loss

MTU = 1500  # bytes on the wire per packet
HEADER = 40  # modeled header overhead per unit and per ack
MAX_UNIT = 10 * MTU  # max wire bytes per transmission unit
MAX_PKTS = 10  # = MAX_UNIT / MTU, loss draws per unit
MIN_CAP = 16384  # token bucket capacity floor: one MAX_UNIT + headroom


@dataclass
class NetParams:
    """Static per-simulation network parameters (CPU-resident canonical copy;
    the device backend keeps int32 replicas)."""

    host_node: np.ndarray  # (H,) int32: host -> graph node index
    rate_up: np.ndarray  # (H,) int64 bytes/sec
    rate_down: np.ndarray  # (H,) int64 bytes/sec
    cap_up: np.ndarray  # (H,) int64 bucket capacity, < 2**31
    cap_down: np.ndarray  # (H,) int64
    latency_ns: np.ndarray  # (G, G) int64
    drop_thresh: np.ndarray  # (G, G) uint32 q24 drop probability
    seed: int

    @classmethod
    def build(
        cls,
        host_node: np.ndarray,
        rate_up: np.ndarray,
        rate_down: np.ndarray,
        latency_ns: np.ndarray,
        reliability: np.ndarray,
        seed: int,
        round_ns: SimTime,
    ) -> "NetParams":
        rate_up = np.asarray(rate_up, dtype=np.int64)
        rate_down = np.asarray(rate_down, dtype=np.int64)
        cap_up = np.maximum(rate_up * round_ns // 1_000_000_000, MIN_CAP)
        cap_down = np.maximum(rate_down * round_ns // 1_000_000_000, MIN_CAP)
        limit = (np.int64(1) << np.int64(31)) - 1
        if (cap_up >= limit).any() or (cap_down >= limit).any():
            # device tokens are int32; clamp (only hit for absurd rate*W)
            cap_up = np.minimum(cap_up, limit - 1)
            cap_down = np.minimum(cap_down, limit - 1)
        return cls(
            host_node=np.asarray(host_node, dtype=np.int32),
            rate_up=rate_up,
            rate_down=rate_down,
            cap_up=cap_up,
            cap_down=cap_down,
            latency_ns=np.asarray(latency_ns, dtype=np.int64),
            drop_thresh=quantize_loss(reliability),
            seed=int(seed),
        )


def clamped_refill(rate: np.ndarray, cap: np.ndarray, dt_ns: int) -> np.ndarray:
    """Token refill for an elapsed window of dt_ns, pre-clamped to capacity
    (so it fits int32 and the device can apply it overflow-free as
    ``tokens += min(add, cap - tokens)``, which equals
    ``min(tokens + true_add, cap)`` exactly)."""
    add = rate * np.int64(dt_ns) // np.int64(1_000_000_000)
    return np.minimum(add, cap).astype(np.int64)


@dataclass
class DepartResult:
    sent: np.ndarray  # (N,) bool — left the source this round
    dropped: np.ndarray  # (N,) bool — sent but lost in the network
    arrival_ns: np.ndarray  # (N,) int64 — valid where sent & ~dropped
    tokens_after: np.ndarray  # (H,) int64


def depart_round(
    params: NetParams,
    tokens_up: np.ndarray,
    src: np.ndarray,
    dst: np.ndarray,
    size: np.ndarray,
    t_emit: np.ndarray,
    npkts: np.ndarray,
    uid_lo: np.ndarray,
    uid_hi: np.ndarray,
    round_start: SimTime,
) -> DepartResult:
    """One round of the egress hot path (numpy reference).

    Arrays must be ordered by (src ascending, per-source FIFO order); the
    caller (NetworkEngine) guarantees this. All arrays length N.

    Semantics, matched exactly by the JAX kernel:
    1. per-source FIFO token drain: unit i departs iff the cumulative wire
       bytes of its source's queue up to and including i fit in tokens_up.
    2. departure time = max(t_emit, round_start); arrival = departure +
       APSP latency between the endpoints' graph nodes.
    3. loss: for each MTU packet p < npkts, draw threefry(seed, uid, p);
       the unit is dropped iff any draw < drop_thresh[src_node, dst_node].
    """
    n = src.shape[0]
    tokens_after = tokens_up.copy()
    if n == 0:
        empty = np.zeros(0, dtype=bool)
        return DepartResult(empty, empty.copy(), np.zeros(0, dtype=np.int64), tokens_after)

    size64 = size.astype(np.int64)
    csum = np.cumsum(size64)
    # cumulative bytes before each source segment (src-sorted input)
    seg_first = np.ones(n, dtype=bool)
    seg_first[1:] = src[1:] != src[:-1]
    base = np.where(seg_first, csum - size64, 0)
    base = np.maximum.accumulate(base)
    cum_in_seg = csum - base
    sent = cum_in_seg <= tokens_up[src]

    sent_bytes = np.zeros_like(tokens_after)
    np.add.at(sent_bytes, src[sent], size64[sent])
    tokens_after -= sent_bytes

    src_node = params.host_node[src]
    dst_node = params.host_node[dst]
    lat = params.latency_ns[src_node, dst_node]
    thresh = params.drop_thresh[src_node, dst_node]

    # per-packet loss draws: counter = (uid_lo, uid_hi | pkt << 28)
    pkt = np.arange(MAX_PKTS, dtype=np.uint32)[None, :]
    c0 = np.broadcast_to(uid_lo.astype(np.uint32)[:, None], (n, MAX_PKTS))
    c1 = uid_hi.astype(np.uint32)[:, None] | (pkt << np.uint32(28))
    draws = draw_24bit(params.seed, c0, c1)
    hit = (draws < thresh[:, None]) & (pkt < npkts.astype(np.uint32)[:, None])
    dropped = sent & hit.any(axis=1)

    depart_t = np.maximum(t_emit, np.int64(round_start))
    arrival = depart_t + lat
    return DepartResult(sent, dropped, arrival, tokens_after)


class CPUDataPlane:
    """numpy twin of shadow_tpu/ops/propagate.py::DeviceDataPlane — the same
    chunked interface, so the engine treats both backends identically and
    results match bit-for-bit."""

    name = "numpy"

    def __init__(self, params: NetParams, round_ns: int = 0) -> None:
        self.params = params
        self.round_ns = int(round_ns)
        self.tokens = params.cap_up.copy()  # int64 (values int32-safe)

    def tokens_host(self) -> np.ndarray:
        return self.tokens

    def _refill(self, dt_ns: int) -> None:
        p = self.params
        add = clamped_refill(p.rate_up, p.cap_up, dt_ns)
        self.tokens += np.minimum(add, p.cap_up - self.tokens)

    def depart_chunk(self, src, dst, size, dep_off, npkts, uid_lo, uid_hi,
                     chunk_cap: int, refill_dt: int = 0):
        if refill_dt:
            self._refill(refill_dt)
        res = depart_round(
            self.params, self.tokens, src, dst, size,
            dep_off.astype(np.int64), npkts, uid_lo, uid_hi, round_start=0,
        )
        self.tokens = res.tokens_after
        return res.sent, res.dropped, res.arrival_ns
