"""Transmission units — the simulator's wire-level quantum.

The reference models individual packets (SURVEY.md §2 "Packet"); we batch at
a slightly coarser quantum called a *unit*: up to experimental.unit_mtus
MTU-sized packets
that travel together (loss is still sampled per MTU packet inside the unit,
see shadow_tpu/network/fluid.py). Streams are chunked into units by the
transport; datagrams are fragmented into units by the socket layer. This
keeps per-round batches small enough for Python assembly while the math
stays per-packet-faithful.

uid layout: (host_id << 32) | per-host counter — globally unique and
assignable without cross-thread/cross-process coordination, so unit
creation is deterministic under every scheduler policy AND every
sim_shards partition (the uid doubles as the canonical BAND_NET event
key). The 32-bit counter keeps host ids inside uid_hi below the
threefry packet lane (fluid.PKT_SHIFT), admitting 2**26 hosts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from shadow_tpu.core.time import SimTime
from shadow_tpu.network.fluid import HARD_MAX_PKTS, HEADER, MTU

# unit kinds
SYN, SYNACK, DATA, ACK, FIN, FINACK, DGRAM = range(7)
KIND_NAMES = ("SYN", "SYNACK", "DATA", "ACK", "FIN", "FINACK", "DGRAM")


@dataclass(slots=True)
class Unit:
    uid: int
    src: int  # source host id
    dst: int  # destination host id
    size: int  # wire bytes (payload + HEADER)
    t_emit: SimTime
    kind: int
    src_port: int
    dst_port: int
    nbytes: int = 0  # application payload byte count
    payload: Optional[bytes] = None
    seq: int = 0  # stream byte offset / datagram id
    frag_idx: int = 0
    nfrags: int = 1

    @property
    def npkts(self) -> int:
        return min(max(1, -(-self.size // MTU)), HARD_MAX_PKTS)


def wire_size(payload_bytes: int) -> int:
    return payload_bytes + HEADER
