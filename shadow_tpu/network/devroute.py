"""Shared device-routing machinery for the two data planes.

Both the per-unit plane (network/engine.py) and the columnar plane
(network/colplane.py) route loss-draw batches either to the numpy twin
(fluid.loss_flags) or to the accelerator kernel (ops/propagate.py) — the
paths are bit-identical, so routing is pure wall-clock policy. This base
class carries everything that policy needs and that is identical across
the planes:

- background device attach + floor calibration (the first JAX touch on a
  tunneled chip costs seconds; simulations start on the numpy twin and
  switch over when the device publishes),
- the adaptive floor: realized readback stalls are compared against what
  the numpy twin would have cost; the floor backs off ×4 when the device
  is clearly losing and decays back toward the calibrated floor when it
  stops (a starved floor also decays on a round-count cooldown),
- interpreter-teardown safety (close() joins the init thread: a daemon
  thread mid-JAX-call at exit aborts the process when XLA backend
  destruction races the in-flight computation),
- the latency/deferred/outstanding accessors the controller polls.
"""

from __future__ import annotations

from shadow_tpu.core.time import SimTime, T_NEVER


class DeviceRoutedPlane:
    """Mixin state + helpers; subclasses populate graph/params/_deferred/
    outstanding and call _init_device_routing() from __init__."""

    def _init_device_routing(self, backend: str, tpu_options,
                             params) -> None:
        self.max_batch = int(
            getattr(tpu_options, "tpu_max_batch", 65536) or 65536)
        self.max_pkts = int(getattr(tpu_options, "unit_mtus", 10) or 10)
        self.device = None
        self.device_floor = float("inf")
        self._dev_stall = 0.0
        self._dev_reads = 0
        self._dev_units = 0
        self._dev_warm = False  # first read (compile/attach) is excluded
        self._floor_cooldown = 0  # rounds until a starved floor decays
        self._np_per_unit = 4e-6  # refined by calibration when available
        self._floor0 = float("inf")  # calibrated floor: decay lower bound
        self.mesh_plane = None
        if backend == "mesh":
            # scheduler_policy: tpu_mesh — the WHOLE per-round network
            # program (closed-form bucket departures, latency gather, loss
            # draws, all_to_all arrival exchange, pmin barrier, psum
            # counters) runs as ONE sharded XLA program per round, hosts
            # sharded over the local device mesh. Bit-identical to the
            # host planes (tests/test_multichip.py), so policy choice
            # cannot change results.
            from shadow_tpu.parallel.mesh import MeshDataPlane
            import jax

            n_shards = int(getattr(tpu_options, "tpu_mesh_shards", 0) or 0)
            n = n_shards or len(jax.devices())
            # per-shard slot width: every scan step pads to (N, C), so C
            # tracks realistic per-barrier chunk sizes, not max_batch —
            # bulk barriers just span more fused steps. Chunk boundaries
            # cannot change results (sequential chunks at one t_now equal
            # one batched call).
            ups = max(256, min(2048, 4096 // n))
            self.mesh_plane = MeshDataPlane(
                params, n_shards=n, units_per_shard=ups,
                max_pkts=self.max_pkts)
        elif backend == "tpu":
            n_shards = int(getattr(tpu_options, "tpu_mesh_shards", 0) or 0)
            floor = int(getattr(tpu_options, "tpu_device_floor", 0) or 0)
            if floor < 0:
                # device draws disabled: the numpy twin serves every batch.
                # This is the published ablation row (BENCH device_off) —
                # results are bit-identical by construction, only wall
                # time moves, so the knob isolates the device's
                # contribution to any config's headline rate.
                pass
            elif floor > 0:
                from shadow_tpu.ops.propagate import DeviceDrawPlane

                self.device = DeviceDrawPlane(params.seed, self.max_batch,
                                              n_shards=n_shards,
                                              max_pkts=self.max_pkts)
                self.device_floor = floor
            else:
                # auto mode: device attach, kernel compile, and floor
                # calibration run on a background thread; batches route to
                # the numpy twin until the plane publishes. Because both
                # paths are bit-identical and event order is
                # canonicalized, WHEN the device comes online cannot
                # affect results — only wall time.
                import threading

                self._bg_thread = threading.Thread(
                    target=self._bg_init_device,
                    args=(params.seed, n_shards), daemon=True)
                self._bg_thread.start()

    def _bg_init_device(self, seed: int, n_shards: int) -> None:
        try:
            from shadow_tpu.ops.propagate import DeviceDrawPlane

            plane = DeviceDrawPlane(seed, self.max_batch, n_shards=n_shards,
                                    max_pkts=self.max_pkts)
            dev_s, np_per_unit = plane.calibrate()
            if np_per_unit > 0:
                self._np_per_unit = np_per_unit
                self.device_floor = max(512, min(
                    int(dev_s / np_per_unit), self.max_batch))
                self._floor0 = self.device_floor
            self.device = plane  # publish last (reads are GIL-atomic)
        except Exception:
            pass  # no usable device: the numpy twin serves everything

    def close(self) -> None:
        """Join the background device-init thread (if any)."""
        t = getattr(self, "_bg_thread", None)
        if t is not None and t.is_alive():
            t.join()

    # -- checkpoint/restore (shadow_tpu/checkpoint.py) ----------------------
    def __getstate__(self):
        """Drop the runtime-only device plumbing from snapshots: the JAX
        device plane, the mesh plane, the init thread, and the C engine
        are all re-creatable (and result-transparent — routing is pure
        wall-clock policy, enforced by test_bitmatch / test_multichip /
        test_colcore)."""
        d = self.__dict__.copy()
        for k in ("device", "mesh_plane", "_bg_thread", "_c"):
            d.pop(k, None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.device = None
        self.mesh_plane = None
        self._c = None

    def reattach_device(self, tpu_options) -> None:
        """Restore-time twin of __init__'s device hookup: re-runs attach,
        calibration, and floor state from scratch. Calibration state is
        not carried across a resume — the adaptive floor cannot change
        results, only wall time."""
        self._init_device_routing(self.backend, tpu_options, self.params)

    # -- adaptive floor -----------------------------------------------------
    def _floor_cooldown_tick(self) -> None:
        """Called on barriers that did NOT use the device: a backed-off
        floor must be able to recover even when it now starves the device
        entirely (no reads -> no stall windows)."""
        if self.device_floor > self._floor0 and self._floor_cooldown > 0:
            self._floor_cooldown -= 1
            if self._floor_cooldown == 0:
                self.device_floor = max(self._floor0, self.device_floor // 4)
                self._floor_cooldown = 512
                self._dev_stall = 0.0
                self._dev_reads = 0
                self._dev_units = 0

    def _record_dev_read(self, dt: float, n_units: int) -> None:
        if not self._dev_warm:
            self._dev_warm = True  # compile/attach stall: not signal
        else:
            self._dev_stall += dt
            self._dev_reads += 1
            self._dev_units += n_units

    def _floor_settle(self) -> None:
        """Every 8 realized device reads, compare stalls against what the
        numpy twin would have cost for the same units: back off only when
        the device is clearly LOSING, decay back toward the calibrated
        floor when it stops (results are identical either way)."""
        if self._dev_reads < 8:
            return
        np_cost = self._np_per_unit * self._dev_units
        if self._dev_stall > 4 * np_cost + 0.02:
            self.device_floor = min(self.device_floor * 4, 1 << 30)
            self._floor_cooldown = 512
        elif (self._dev_stall < np_cost and
              self.device_floor > self._floor0):
            self.device_floor = max(self._floor0, self.device_floor // 4)
        self._dev_stall = 0.0
        self._dev_reads = 0
        self._dev_units = 0

    # -- accessors shared by the controller --------------------------------
    def latency_between(self, src_host: int, dst_host: int) -> SimTime:
        p = self.params
        return int(self.graph.latency_ns[p.host_node[src_host],
                                         p.host_node[dst_host]])

    def rtt_extra_ns(self, src_host: int, dst_host: int) -> SimTime:
        """Extra delay beyond one-way latency for loss notifications: the
        return-path latency (so the sender learns of a loss one RTT after
        departure, like a fast-retransmit signal)."""
        return self.latency_between(dst_host, src_host)

    def has_immediate_work(self) -> bool:
        """True if the next round must run even with empty event queues
        (deferred ingress backlog waiting on token refill)."""
        return bool(self._deferred)

    def earliest_outstanding(self) -> SimTime:
        """Earliest event time any in-flight draw batch can produce."""
        return min((b.deadline for b in self.outstanding), default=T_NEVER)
