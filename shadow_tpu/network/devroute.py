"""Shared device-routing machinery for the two data planes.

Both the per-unit plane (network/engine.py) and the columnar plane
(network/colplane.py) route loss-draw batches either to the numpy twin
(fluid.loss_flags) or to the accelerator kernel (ops/propagate.py) — the
paths are bit-identical, so routing is pure wall-clock policy. This base
class carries everything that policy needs and that is identical across
the planes:

- background device attach + floor calibration (the first JAX touch on a
  tunneled chip costs seconds; simulations start on the numpy twin and
  switch over when the device publishes),
- the adaptive floor: realized readback stalls are compared against what
  the numpy twin would have cost; the floor backs off ×4 when the device
  is clearly losing and decays back toward the calibrated floor when it
  stops (a starved floor also decays on a round-count cooldown),
- fused multi-round device windows: draw batches accumulate lazily across
  rounds and dispatch as ONE device program per causal window instead of
  one per round, with a two-slot in-flight pipeline (deferred readbacks
  overlap subsequent host rounds) and a live break-even estimate from
  window telemetry deciding when the device is worth engaging at all,
- interpreter-teardown safety (close() joins the init thread: a daemon
  thread mid-JAX-call at exit aborts the process when XLA backend
  destruction races the in-flight computation),
- the latency/deferred/outstanding accessors the controller polls.
"""

from __future__ import annotations

from shadow_tpu.core.time import SimTime, T_NEVER

#: deferred windows in flight at once (double-buffered handles): window
#: N's device execution overlaps the build of window N+1; a third window
#: waits (stays lazy) rather than queueing unbounded device memory
WINDOW_SLOTS = 2
#: EMA weight for the per-window fixed-cost estimate (dispatch + stall)
_BE_ALPHA = 0.25
#: hysteresis around the break-even unit count: engage above 1.25x,
#: release below 0.8x — so a window size hovering at break-even does not
#: flap the routing decision every window
_BE_ENGAGE = 1.25
_BE_RELEASE = 0.8


class DeviceRoutedPlane:
    """Mixin state + helpers; subclasses populate graph/params/_deferred/
    outstanding and call _init_device_routing() from __init__."""

    def _init_device_routing(self, backend: str, tpu_options,
                             params) -> None:
        self.max_batch = int(
            getattr(tpu_options, "tpu_max_batch", 65536) or 65536)
        self.max_pkts = int(getattr(tpu_options, "unit_mtus", 10) or 10)
        self.device = None
        self.device_floor = float("inf")
        self._dev_stall = 0.0
        self._dev_reads = 0
        self._dev_units = 0
        self._dev_warm = False  # first read (compile/attach) is excluded
        self._floor_cooldown = 0  # rounds until a starved floor decays
        self._np_per_unit = 4e-6  # refined by calibration when available
        self._floor0 = float("inf")  # calibrated floor: decay lower bound
        self._floor_forced = False  # explicit tpu_device_floor > 0: the
        #                             operator owns routing; break-even
        #                             estimation and probe clamping yield
        #: fused-window state (experimental.device_window_rounds; 0 = auto)
        self.window_rounds = int(
            getattr(tpu_options, "device_window_rounds", 0) or 0)
        self._win_open_rounds = 0  # barriers since the window opened
        self._win_inflight = 0  # dispatched windows not yet fully read
        self._win_engaged = False  # hysteresis state of the flush gate
        self._win_cost_ema = 0.0  # seconds of host wall per window
        self.dev_windows = 0  # fused windows dispatched to the device
        self.dev_window_units = 0  # units those windows carried
        self.spec_hits = 0  # C-plane speculative-window consults served
        self.spec_draws = 0  # C-plane inline draws (speculation missed)
        self._max_window_units = 0  # biggest window this run has seen
        self._probe_clamped = False  # satellite: probing suppressed
        #: speculative forward windows (C plane only; colplane drives)
        self._spec_on = False
        self._spec_checked = False
        self._spec_clamped = False  # live economics turned speculation off
        self._spec_pending = []
        self._spec_round = 0
        self._spec_spend = 0.0  # wall seconds speculation itself cost
        self._spec_units = 0  # rows speculation itself dispatched
        self.mesh_plane = None
        if backend == "mesh":
            # scheduler_policy: tpu_mesh — the WHOLE per-round network
            # program (closed-form bucket departures, latency gather, loss
            # draws, all_to_all arrival exchange, pmin barrier, psum
            # counters) runs as ONE sharded XLA program per round, hosts
            # sharded over the local device mesh. Bit-identical to the
            # host planes (tests/test_multichip.py), so policy choice
            # cannot change results.
            from shadow_tpu.parallel.mesh import MeshDataPlane
            import jax

            n_shards = int(getattr(tpu_options, "tpu_mesh_shards", 0) or 0)
            n = n_shards or len(jax.devices())
            # per-shard slot width: every scan step pads to (N, C), so C
            # tracks realistic per-barrier chunk sizes, not max_batch —
            # bulk barriers just span more fused steps. Chunk boundaries
            # cannot change results (sequential chunks at one t_now equal
            # one batched call).
            ups = max(256, min(2048, 4096 // n))
            self.mesh_plane = MeshDataPlane(
                params, n_shards=n, units_per_shard=ups,
                max_pkts=self.max_pkts)
        elif backend == "tpu":
            n_shards = int(getattr(tpu_options, "tpu_mesh_shards", 0) or 0)
            floor = int(getattr(tpu_options, "tpu_device_floor", 0) or 0)
            if floor < 0:
                # device draws disabled: the numpy twin serves every batch.
                # This is the published ablation row (BENCH device_off) —
                # results are bit-identical by construction, only wall
                # time moves, so the knob isolates the device's
                # contribution to any config's headline rate.
                pass
            elif floor > 0:
                from shadow_tpu.ops.propagate import DeviceDrawPlane

                self.device = DeviceDrawPlane(params.seed, self.max_batch,
                                              n_shards=n_shards,
                                              max_pkts=self.max_pkts)
                self.device_floor = floor
                self._floor_forced = True
            else:
                # fleet mode (shadow_tpu/fleet.py): a sweep member routes
                # its draw windows to the fleet parent's ONE shared
                # attach instead of attaching its own — the proxy quacks
                # like the device plane and its results are bit-identical
                # to the local twins, so this is pure wall-clock policy.
                # Connection happens on the background thread (the
                # parent's attach may still be warming); the member runs
                # the numpy twin until the proxy publishes — exactly the
                # background-attach discipline below.
                import os as _os

                svc = _os.environ.get("SHADOW_TPU_DRAW_SERVICE")
                if svc:
                    import threading

                    self._svc_abort = False
                    self._bg_thread = threading.Thread(
                        target=self._bg_connect_service,
                        args=(svc, params.seed, n_shards), daemon=True)
                    self._bg_thread.start()
                    return
                # auto mode: device attach, kernel compile, and floor
                # calibration run on a background thread — except when a
                # previous run of this process already attached this
                # parameter tuple, in which case the cached plane (and its
                # calibration) publishes SYNCHRONOUSLY so the device is
                # live from round 0. Probe the cache via sys.modules so a
                # cold process does NOT pay the multi-second jax import on
                # the main thread just to find an empty cache. Because
                # both paths are bit-identical and event order is
                # canonicalized, WHEN the device comes online cannot
                # affect results — only wall time.
                import sys

                mod = sys.modules.get("shadow_tpu.ops.propagate")
                key = (int(params.seed), self.max_batch, int(n_shards),
                       self.max_pkts)
                hit = (mod.DeviceDrawPlane._cache.get(key)
                       if mod is not None else None)
                if hit is not None:
                    self._publish_device(*hit)
                else:
                    import threading

                    self._bg_thread = threading.Thread(
                        target=self._bg_init_device,
                        args=(params.seed, n_shards), daemon=True)
                    self._bg_thread.start()

    def _publish_device(self, plane, dev_s: float,
                        np_per_unit: float) -> None:
        if np_per_unit > 0:
            self._np_per_unit = np_per_unit
            self.device_floor = max(512, min(
                int(dev_s / np_per_unit), self.max_batch))
            self._floor0 = self.device_floor
        self.device = plane  # publish last (reads are GIL-atomic)

    def _bg_init_device(self, seed: int, n_shards: int) -> None:
        try:
            from shadow_tpu.ops.propagate import DeviceDrawPlane

            self._publish_device(*DeviceDrawPlane.attach_cached(
                seed, self.max_batch, n_shards, self.max_pkts))
        except Exception:
            pass  # no usable device: the numpy twin serves everything

    def _bg_connect_service(self, address: str, seed: int,
                            n_shards: int) -> None:
        """Fleet member: connect to the parent's shared draw service and
        publish the proxy as this run's device plane. An unreachable
        service degrades to the normal local attach path (which itself
        degrades to the numpy twin) — never an error, never a result
        change."""
        try:
            from shadow_tpu.fleet import FleetDrawClient

            proxy = FleetDrawClient.connect(
                address, seed, self.max_batch, self.max_pkts,
                abort=lambda: self._svc_abort)
        except Exception:
            if getattr(self, "_svc_abort", False):
                return  # run already over; nothing to publish
            self._bg_init_device(seed, n_shards)
            return
        self._publish_device(proxy, proxy.dev_s, proxy.np_per_unit)

    def close(self) -> None:
        """Join the background device-init thread (if any) and release a
        fleet draw-service proxy connection. A connect still waiting on
        the service (short member run, slow parent attach) is aborted
        rather than waited out."""
        self._svc_abort = True
        t = getattr(self, "_bg_thread", None)
        if t is not None and t.is_alive():
            t.join()
        devt = getattr(self, "devt", None)
        if devt is not None:
            devt.close()  # join the transport-kernel attach thread too
        d = getattr(self, "device", None)
        if d is not None and hasattr(d, "close_client"):
            d.close_client()

    # -- checkpoint/restore (shadow_tpu/checkpoint.py) ----------------------
    def __getstate__(self):
        """Drop the runtime-only device plumbing from snapshots: the JAX
        device plane, the mesh plane, the init thread, and the C engine
        are all re-creatable (and result-transparent — routing is pure
        wall-clock policy, enforced by test_bitmatch / test_multichip /
        test_colcore)."""
        d = self.__dict__.copy()
        for k in ("device", "mesh_plane", "_bg_thread", "_c",
                  "_spec_pending", "devt"):
            d.pop(k, None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.device = None
        self.mesh_plane = None
        self._c = None
        self._spec_pending = []
        self._spec_on = False
        self._spec_checked = False
        self._spec_clamped = False
        self.devt = None  # reattached by Controller._reattach_runtime

    def reattach_device(self, tpu_options) -> None:
        """Restore-time twin of __init__'s device hookup: re-runs attach,
        calibration, and floor state from scratch. Calibration state is
        not carried across a resume — the adaptive floor cannot change
        results, only wall time."""
        self._init_device_routing(self.backend, tpu_options, self.params)

    # -- adaptive floor + window break-even ---------------------------------
    def break_even_units(self) -> int:
        """Units at which one fused window dispatch beats the host twin,
        from live telemetry: the EMA'd per-window host cost (dispatch wall
        + readback stall) divided by the calibrated per-unit host cost.
        Before the first window lands, fall back to the calibrated floor
        (same quantity measured at attach time)."""
        if self._win_cost_ema > 0.0 and self._np_per_unit > 0.0:
            return max(256, int(self._win_cost_ema / self._np_per_unit))
        return int(self._floor0) if self._floor0 != float("inf") else 4096

    def window_gate_units(self, engaged: bool) -> float:
        """The unit count a deferred window must reach to route to the
        device. An explicitly forced tpu_device_floor IS the gate (the
        operator owns routing — tests and A/B runs rely on it); otherwise
        the live break-even estimate applies with hysteresis: 1.25x to
        engage, and a currently-engaged window releases only below 0.8x,
        so a size hovering at break-even does not flap the decision."""
        if self._floor_forced:
            return self.device_floor
        return max(self.device_floor,
                   (_BE_RELEASE if engaged else _BE_ENGAGE)
                   * self.break_even_units())

    def _record_window(self, n_units: int, host_cost: float) -> None:
        """One fused window landed: fold its realized host-side cost
        (dispatch wall + any readback stall) into the break-even EMA and
        the run counters."""
        self.dev_windows += 1
        self.dev_window_units += n_units
        if not self._dev_warm:
            self._dev_warm = True  # compile/attach window: not signal
            return
        if self._win_cost_ema == 0.0:
            self._win_cost_ema = host_cost
        else:
            self._win_cost_ema += _BE_ALPHA * (host_cost - self._win_cost_ema)

    def _note_window_units(self, n_units: int) -> None:
        """Track the biggest causal window this config has produced and
        clamp device probing when the config provably cannot reach
        break-even (round-5 Weak #5 satellite): if even the largest window
        is under 25% of break-even, re-probing the device on a cadence
        only burns dispatches — stop until the traffic shape changes."""
        if self._floor_forced:
            if n_units > self._max_window_units:
                self._max_window_units = n_units
            return
        if n_units > self._max_window_units:
            self._max_window_units = n_units
            if self._probe_clamped and \
                    n_units >= 0.25 * self.break_even_units():
                self._probe_clamped = False
        elif (not self._probe_clamped
              and self._max_window_units > 0
              and self._dev_warm
              and self._max_window_units < 0.25 * self.break_even_units()):
            self._probe_clamped = True

    def _floor_cooldown_tick(self) -> None:
        """Called on barriers that did NOT use the device: a backed-off
        floor must be able to recover even when it now starves the device
        entirely (no reads -> no stall windows). When probing is clamped
        (the config's windows cannot reach break-even) the decay pauses:
        recovering the floor would only re-probe a device that provably
        loses at this config's window sizes."""
        if self._probe_clamped:
            return
        if self.device_floor > self._floor0 and self._floor_cooldown > 0:
            self._floor_cooldown -= 1
            if self._floor_cooldown == 0:
                self.device_floor = max(self._floor0, self.device_floor // 4)
                self._floor_cooldown = 512
                self._dev_stall = 0.0
                self._dev_reads = 0
                self._dev_units = 0

    def _record_dev_read(self, dt: float, n_units: int) -> None:
        if not self._dev_warm:
            self._dev_warm = True  # compile/attach stall: not signal
        else:
            self._dev_stall += dt
            self._dev_reads += 1
            self._dev_units += n_units

    def _floor_settle(self) -> None:
        """Every 8 realized device reads, compare stalls against what the
        numpy twin would have cost for the same units: back off only when
        the device is clearly LOSING, decay back toward the live
        break-even estimate (never below the calibrated floor) when it
        stops (results are identical either way)."""
        if self._dev_reads < 8:
            return
        np_cost = self._np_per_unit * self._dev_units
        lo = max(self._floor0, float(self.break_even_units()))
        if self._dev_stall > 4 * np_cost + 0.02:
            self.device_floor = min(self.device_floor * 4, 1 << 30)
            self._floor_cooldown = 512
        elif self._dev_stall < np_cost and self.device_floor > lo:
            self.device_floor = max(lo, self.device_floor // 4)
        self._dev_stall = 0.0
        self._dev_reads = 0
        self._dev_units = 0

    def device_summary(self) -> dict:
        """Window/speculation telemetry for the run summary (wall-clock
        routing state — volatile across runs, never simulation state)."""
        return {
            "windows_dispatched": self.dev_windows,
            "window_units": self.dev_window_units,
            "spec_hits": self.spec_hits,
            "spec_draws": self.spec_draws,
            "break_even_units": self.break_even_units(),
            "max_window_units": self._max_window_units,
            "probe_clamped": self._probe_clamped,
            "spec_clamped": self._spec_clamped,
            "window_rounds": self.window_rounds or "auto",
        }

    def heartbeat_note(self) -> str:
        """One heartbeat-line fragment describing the routing decision."""
        if self.device is None and self.mesh_plane is None:
            return "dev=off"
        state = "clamped" if self._probe_clamped else (
            "engaged" if self.dev_windows else "probing")
        if self._spec_clamped:
            state += "+spec_clamped"
        elif self.spec_hits:
            state += "+spec"
        return (f"dev={state} windows={self.dev_windows} "
                f"be={self.break_even_units()} "
                f"maxwin={self._max_window_units}")

    # -- telemetry (shadow_tpu/telemetry/) ----------------------------------
    def telemetry_sample(self, t_now: SimTime) -> dict:
        """Engine-side half of one telemetry sample: run-global counters
        plus the per-host NIC token-bucket levels, all plane-independent
        (capped egress availability via fluid.TokenBuckets.levels; the
        round-quantized ingress tokens are shared state — the C engine
        mutates the same numpy array). Caller flushes in-flight draws
        first so every plane sits at the same resolution frontier."""
        return {
            "units_sent": self.units_sent,
            "units_dropped": self.units_dropped,
            "units_blackholed": self.units_blackholed,
            "bytes_sent": self.bytes_sent,
            "bucket_up": self.buckets.levels(t_now).tolist(),
            "tokens_down": self.tokens_down.tolist(),
        }

    # -- accessors shared by the controller --------------------------------
    def latency_between(self, src_host: int, dst_host: int) -> SimTime:
        p = self.params
        return int(self.graph.latency_ns[p.host_node[src_host],
                                         p.host_node[dst_host]])

    def has_immediate_work(self) -> bool:
        """True if the next round must run even with empty event queues
        (deferred ingress backlog waiting on token refill)."""
        return bool(self._deferred)

    def earliest_outstanding(self) -> SimTime:
        """Earliest event time any in-flight draw batch can produce."""
        return min((b.deadline for b in self.outstanding), default=T_NEVER)
