"""Bitcoin-like inv/getdata/tx gossip flood (BASELINE.md config 4).

Each node picks k peers (deterministically from its host RNG), originates
transactions on a timer, and floods them: announce (INV, 64B) -> request
(GETDATA, 64B) -> payload (TX, ~400B), all over datagrams. Stresses event
fan-out: one tx triggers O(k) messages per hop across the network.
"""

from __future__ import annotations

from shadow_tpu.core.time import NS_PER_SEC

INV, GETDATA, TX = b"I", b"G", b"T"
TX_SIZE = 400


class GossipNode:
    """args: [port, n_hosts, k_peers, txs_to_originate, interval_sec]

    Peers are chosen as deterministic random host ids != self. Host names
    must be resolvable as ``node{i}`` (use quantity expansion with a host
    template named ``node``).

    environment GOSSIP_REANNOUNCE_SEC=S (default 0 = off): an originator
    re-announces its own transactions every S seconds — the minimal
    churn-survival behavior (a flood cut off by a partition or a crashed
    first hop restarts after the network heals; peers that already hold
    the tx answer nothing, so a healthy network sees only the INVs).
    """

    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 7000
        self.n_hosts = int(args[1]) if len(args) > 1 else 10
        self.k = int(args[2]) if len(args) > 2 else 4
        self.originate = int(args[3]) if len(args) > 3 else 1
        self.interval = float(args[4]) if len(args) > 4 else 1.0
        self.reannounce = float(env.get("GOSSIP_REANNOUNCE_SEC", 0))
        self.seen: set[bytes] = set()
        self.own: list[bytes] = []  # txids this node originated
        self.received_tx = 0
        self.originated = 0
        self._c = None  # C gossip state (set in start when available)
        #: telemetry (shadow_tpu/telemetry/): pending GETDATA send times
        #: by txid — a flow record per INV->GETDATA->TX fetch closes when
        #: the TX lands. None when telemetry is off (zero per-message
        #: work; the C gossip twin then keeps the hot half).
        self._pending: dict = None

    def start(self):
        self.sock = self.api.udp_socket(self.port)
        self.sock.on_datagram = self._on_msg
        rng = self.api.rng
        me = self.api.host_id
        peers = set()
        while len(peers) < min(self.k, self.n_hosts - 1):
            p = int(rng.integers(0, self.n_hosts))
            if p != me:
                peers.add(p)
        self.peers = sorted(peers)
        # delegate the hot half (message handling, announce fan-out, the
        # seen set) to the C engine when the plane runs one — identical
        # logic, identical emissions (tests/test_colcore.py asserts the
        # whole output tree matches the pure-Python run)
        self._c = None
        host = getattr(self.api, "_host", None)
        cp = getattr(host, "colplane", None)
        core = getattr(cp, "_c", None)
        tel = getattr(host, "telemetry", None)
        if tel is not None:
            # telemetry: fetch timing lives in the model, so message
            # handling stays in Python — bit-identical to the C twin
            # (test_colcore asserts the whole output tree matches), only
            # wall time moves; the fetch records need the GETDATA instant
            self._pending = {}
        elif core is not None and host.pcap is None:
            self._c = core.gossip_register(host.id, self.port, self.peers)
        if self.originate > 0:
            delay = int((0.25 + 0.5 * float(rng.random())) * self.interval * NS_PER_SEC)
            self.api.after(delay, self._originate)
            if self.reannounce > 0:
                self.api.after(int(self.reannounce * NS_PER_SEC),
                               self._reannounce)

    def _originate(self):
        self.originated += 1
        txid = f"{self.api.host_id}:{self.originated}".encode()
        self.own.append(txid)
        if self._c is not None:
            self._c.originate(txid)
        else:
            self.seen.add(txid)
            self._announce(txid)
        if self.originated < self.originate:
            self.api.after(int(self.interval * NS_PER_SEC), self._originate)

    def _reannounce(self):
        for txid in self.own:
            self._announce(txid)
        self.api.after(int(self.reannounce * NS_PER_SEC), self._reannounce)

    def _announce(self, txid: bytes, exclude: int = -1):
        for p in self.peers:
            if p != exclude:
                self.sock.sendto(p, self.port, payload=INV + txid, nbytes=64)

    def _on_msg(self, nbytes, payload, src_addr, now):
        if self._c is not None:
            # Python-delivered paths (deferred-ingress drains, fragmented
            # datagrams) re-enter the C state so seen/counters stay single
            self._c.on_msg(payload, src_addr[0], now)
            return
        if payload is None:
            return
        kind, txid = payload[:1], payload[1:]
        src_host, src_port = src_addr
        if kind == INV:
            if txid not in self.seen:
                pend = self._pending
                if pend is not None and txid not in pend:
                    # first GETDATA for this txid opens the fetch flow
                    if len(pend) > 4096:  # bound memory like _partial
                        pend.pop(next(iter(pend)))
                    pend[txid] = now
                self.sock.sendto(src_host, self.port, payload=GETDATA + txid, nbytes=64)
        elif kind == GETDATA:
            self.sock.sendto(src_host, self.port, payload=TX + txid, nbytes=TX_SIZE)
        elif kind == TX:
            if txid not in self.seen:
                self.seen.add(txid)
                self.received_tx += 1
                pend = self._pending
                if pend is not None:
                    t_open = pend.pop(txid, None)
                    if t_open is not None:
                        # datagram fetch: the TX is the first (and last)
                        # payload byte, so TTFB == completion latency
                        self.api._host.record_flow(
                            "gossip_fetch", src_host, t_open, now,
                            nbytes, "ok")
                self._announce(txid, exclude=src_host)

    def stop(self):
        received, known = self.received_tx, len(self.seen)
        if self._c is not None:
            received, known = self._c.stats()
        self.api.log(
            f"gossip done: originated={self.originated} received={received} "
            f"known={known}"
        )
