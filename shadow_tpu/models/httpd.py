"""Minimal HTTP/1.0 server plugin — a destination for real HTTP clients
(e.g. a CPython guest using urllib) running inside the simulation.

args: [port, body_bytes]
"""

from __future__ import annotations


class HttpServer:
    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 80
        self.body = int(args[1]) if len(args) > 1 else 100_000
        self.served = 0

    def start(self):
        self.api.listen(self.port, self._on_accept)

    def _on_accept(self, conn, now):
        req = {"buf": b""}

        def push(room=0):
            if req.get("left", 0) > 0:
                req["left"] -= conn.send(req["left"])

        def on_data(nbytes, payload, t):
            if "left" in req:
                return  # request already answered
            req["buf"] += payload or b""
            if b"\r\n\r\n" not in req["buf"]:
                return
            self.served += 1
            head = (f"HTTP/1.0 200 OK\r\nContent-Length: {self.body}\r\n"
                    f"Content-Type: application/octet-stream\r\n\r\n")
            conn.send(payload=head.encode())
            req["left"] = self.body
            push()

        conn.on_data = on_data
        conn.on_drain = push

    def stop(self):
        pass
