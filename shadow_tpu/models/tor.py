"""Tor-shaped onion-routing workload (BASELINE.md config #3).

Models the traffic *shape* of the reference's headline use case — the Tor
network simulated by Shadow — without the cryptography: clients build
3-hop circuits (guard, middle, exit chosen deterministically from the
per-host RNG), telescope them with CREATE/EXTEND cells, then stream data
through the circuit from a destination server via the exit. Every hop is a
separate simulated TCP stream; relays maintain a circuit table and forward
cells/bytes hop by hop, so the model exercises multi-hop stream relaying,
connection fan-in at relays, and cascaded congestion control — the load
profile of BASELINE config #3 (tornettools-shaped topologies).

Wire protocol (framed over the byte stream; send boundaries may split but
never merge, and byte counts are exact):
  control cell: 12 real bytes [type:1][circ:2][len:2][pad:7] + len real
                payload bytes (e.g. the EXTEND target's name)
  data:         a DATA header cell followed by `len` counted bytes
                (synthetic payload — only byte counts matter)

Cell types: CREATE, CREATED, EXTEND, EXTENDED, BEGIN, CONNECTED, DATA, END.
"""

from __future__ import annotations

from shadow_tpu.utils.units import parse_size

HDR = 12
CREATE, CREATED, EXTEND, EXTENDED, BEGIN, CONNECTED, DATA, END = range(8)


def cell(ctype: int, circ: int, payload: bytes = b"") -> bytes:
    return (bytes([ctype]) + circ.to_bytes(2, "big")
            + len(payload).to_bytes(2, "big") + b"\0" * 7 + payload)


def data_header(circ: int, body_len: int) -> bytes:
    """A DATA cell header announcing `body_len` counted bytes to follow."""
    return (bytes([DATA]) + circ.to_bytes(2, "big")
            + body_len.to_bytes(2, "big") + b"\0" * 7)


class FrameReader:
    """Reassembles the framed protocol from (nbytes, payload|None) chunks.

    Control bytes arrive as real payload; DATA bodies arrive as counted
    synthetic bytes. on_cell(type, circ, payload); on_body(circ, nbytes).
    """

    def __init__(self, on_cell, on_body, on_data_hdr=None):
        self.buf = b""
        self.body_left = 0
        self.body_circ = 0
        self.on_cell = on_cell
        self.on_body = on_body
        self.on_data_hdr = on_data_hdr  # (circ, body_len); relays forward it

    def feed(self, nbytes: int, payload) -> None:
        if self.body_left > 0 and payload is None:
            take = min(nbytes, self.body_left)
            self.body_left -= take
            self.on_body(self.body_circ, take)
            if nbytes > take:  # next body's bytes can't precede its header
                raise ValueError("framing error: stray counted bytes")
            return
        if payload is None:
            raise ValueError("framing error: counted bytes outside DATA body")
        self.buf += payload
        while len(self.buf) >= HDR:
            ctype = self.buf[0]
            circ = int.from_bytes(self.buf[1:3], "big")
            ln = int.from_bytes(self.buf[3:5], "big")
            if ctype == DATA:
                self.buf = self.buf[HDR:]
                self.body_left = ln
                self.body_circ = circ
                if self.on_data_hdr is not None:
                    self.on_data_hdr(circ, ln)
                return  # counted body follows in subsequent chunks
            if len(self.buf) < HDR + ln:
                return
            payload_bytes = self.buf[HDR: HDR + ln]
            self.buf = self.buf[HDR + ln:]
            self.on_cell(ctype, circ, payload_bytes)


class _Conn:
    """One framed connection (either direction) owned by a relay/client.

    Writes go through a pending queue pumped by on_drain: send() accepts
    only what the bounded socket send buffer can hold, and a partially
    written frame header would desync the peer's FrameReader."""

    __slots__ = ("ep", "reader", "pending")

    def __init__(self, ep, on_cell, on_body, on_data_hdr=None):
        self.ep = ep
        self.reader = FrameReader(on_cell, on_body, on_data_hdr)
        self.pending = []  # ('p', bytes, offset) | ('n', count)
        ep.on_data = lambda n, p, now: self.reader.feed(n, p)
        ep.on_drain = lambda room: self._pump()

    def write(self, payload: bytes) -> None:
        self.pending.append(["p", payload, 0])
        self._pump()

    def write_counted(self, nbytes: int) -> None:
        self.pending.append(["n", nbytes])
        self._pump()

    def _pump(self) -> None:
        while self.pending:
            head = self.pending[0]
            if head[0] == "p":
                sent = self.ep.send(payload=head[1][head[2]:])
                head[2] += sent
                done = head[2] >= len(head[1])
            else:
                sent = self.ep.send(nbytes=head[1])
                head[1] -= sent
                done = head[1] <= 0
            if done:
                self.pending.pop(0)
            if sent == 0 and not done:
                return  # buffer full; on_drain resumes

    def close_when_drained(self) -> None:
        if not self.pending:
            self.ep.close()
        else:
            prev = self.ep.on_drain

            def pump_then_close(room):
                prev(room)
                if not self.pending:
                    self.ep.close()

            self.ep.on_drain = pump_then_close


class TorRelay:
    """args: [or_port]"""

    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 9001
        # circuit table: (conn id, circ) -> (peer conn, peer circ) both ways
        self.table = {}
        self.conns = {}
        self._next_conn = 0
        self._next_circ = 1
        self.cells_relayed = 0
        self.bytes_relayed = 0
        self._c = None  # C relay data path (plain relays on the C engine)

    def _c_engine(self):
        """The engine gate shared by relay and exit starts: the C relay
        data path engages when the C engine runs this host and no pcap
        capture needs the Python dispatch."""
        host = getattr(self.api, "_host", None)
        core = getattr(getattr(host, "colplane", None), "_c", None)
        if core is not None and host.pcap is None:
            return core
        return None

    def start(self):
        # relays delegate the hot path (frame parsing, circuit
        # forwarding, pending-write pumping) to the C engine; the control
        # plane (EXTEND connects, teardown observation) stays here.
        # TorExit runs the same C relay in exit mode (BEGIN cells reach
        # its _on_ctrl; the reframe loop is a C ExitStream).
        core = self._c_engine()
        if type(self) is TorRelay and core is not None:
            self._c = core.relay_new(self.api._host.id, self._on_ctrl)
            self.api.listen(self.port, self._on_accept_c)
            return
        self.api.listen(self.port, self._on_accept)

    # -- C data-path control plane -----------------------------------------
    def _on_accept_c(self, ep, now):
        self._c.add_conn(ep)

    def _on_ctrl(self, cid, ctype, circ, payload):
        # only EXTEND-at-circuit-head reaches Python: open the next-hop
        # connection and splice a fresh segment into the C table
        target, port = payload.decode().rsplit(":", 1)
        ep = self.api.connect(target, int(port))
        ncid = self._c.add_conn(ep)
        ncirc = self._c.splice(cid, circ, ncid)
        ep.on_connected = lambda now: self._c.write_cell(
            ncid, CREATE, ncirc)
        ep.connect()

    def _new_conn(self, ep):
        cid = self._next_conn
        self._next_conn += 1
        conn = _Conn(ep,
                     lambda t, c, p: self._on_cell(cid, t, c, p),
                     lambda c, n: self._on_body(cid, c, n),
                     lambda c, ln: self._on_data_hdr(cid, c, ln))
        self.conns[cid] = conn
        # circuit teardown cascades along the connection chain: when one
        # side closes, close every spliced peer connection too
        ep.on_close = lambda now: self._on_conn_close(cid)
        return cid, conn

    def _on_conn_close(self, cid):
        self.conns.pop(cid, None)
        peers = [v for k, v in self.table.items() if k[0] == cid]
        self.table = {k: v for k, v in self.table.items()
                      if k[0] != cid and v[0] != cid}
        for ncid, _ in peers:
            pc = self.conns.get(ncid)
            if pc is not None:
                pc.close_when_drained()

    def _on_accept(self, ep, now):
        self._new_conn(ep)

    def _on_cell(self, cid, ctype, circ, payload):
        api = self.api
        key = (cid, circ)
        if ctype == CREATE:
            self.conns[cid].write(cell(CREATED, circ))
            return
        if ctype == EXTEND and key not in self.table:
            # this relay is the circuit's current endpoint: open a
            # connection to the named next relay and splice a new segment
            # (an EXTEND for a further hop falls through to forwarding)
            target, port = payload.decode().rsplit(":", 1)
            ep = api.connect(target, int(port))
            ncid, nconn = self._new_conn(ep)
            ncirc = self._next_circ
            self._next_circ += 1
            self.table[key] = (ncid, ncirc)
            self.table[(ncid, ncirc)] = key

            def on_connected(now):
                nconn.write(cell(CREATE, ncirc))

            ep.on_connected = on_connected
            ep.connect()
            return
        if ctype == CREATED:
            back = self.table.get((cid, circ))
            if back is not None:
                self.conns[back[0]].write(cell(EXTENDED, back[1]))
            return
        # everything else forwards along the circuit unchanged
        nxt = self.table.get(key)
        if nxt is None:
            return
        self.cells_relayed += 1
        self.conns[nxt[0]].write(cell(ctype, nxt[1], payload))

    def _on_data_hdr(self, cid, circ, body_len):
        nxt = self.table.get((cid, circ))
        if nxt is not None:
            self.conns[nxt[0]].write(data_header(nxt[1], body_len))

    def _on_body(self, cid, circ, nbytes):
        nxt = self.table.get((cid, circ))
        if nxt is None:
            return
        self.bytes_relayed += nbytes
        self.conns[nxt[0]].write_counted(nbytes)

    def stop(self):
        cells, nbytes = self.cells_relayed, self.bytes_relayed
        if self._c is not None:
            cells, nbytes = self._c.stats()
        self.api.log(f"relay done: cells={cells} bytes={nbytes}")


class TorExit(TorRelay):
    """An exit relay: terminates BEGIN cells by fetching from the
    destination (a tgen-format server) and streaming DATA back.

    On the C engine the whole data path is native (round 5): forwarding
    rides the C relay like plain relays, and the server->client reframe
    loop runs as a C ExitStream — only the BEGIN/EXTEND control cells
    (one each per circuit) reach Python.

    args: [or_port]
    """

    def start(self):
        core = self._c_engine()
        if core is not None:
            self._c = core.relay_new(self.api._host.id, self._on_ctrl,
                                     True)
            self.api.listen(self.port, self._on_accept_c)
            return
        self.api.listen(self.port, self._on_accept)

    def _on_ctrl(self, cid, ctype, circ, payload):
        if ctype != BEGIN:
            super()._on_ctrl(cid, ctype, circ, payload)
            return
        # exit termination: connect to the destination, announce
        # CONNECTED, and hand the reframe loop to the C stream
        dest, port, want = payload.decode().split(":")
        api = self.api
        ep = api.connect(dest, int(port))
        want_n = int(want)

        def on_connected(now):
            ep.send(payload=str(want_n).encode().rjust(8))
            self._c.write_cell(cid, CONNECTED, circ)

        ep.on_connected = on_connected
        self._c.exit_stream(ep, cid, circ, want_n)
        ep.connect()

    def _on_cell(self, cid, ctype, circ, payload):
        if ctype != BEGIN or (cid, circ) in self.table:
            # mid-circuit relays forward BEGIN; only the endpoint exits
            super()._on_cell(cid, ctype, circ, payload)
            return
        dest, port, want = payload.decode().split(":")
        api = self.api
        ep = api.connect(dest, int(port))
        got = {"n": 0}
        want_n = int(want)

        def on_connected(now):
            ep.send(payload=str(want_n).encode().rjust(8))
            self.conns[cid].write(cell(CONNECTED, circ))

        def on_data(nbytes, p, now):
            got["n"] += nbytes
            # re-frame the fetched bytes as circuit DATA toward the client
            out = self.conns[cid]
            out.write(data_header(circ, nbytes))
            out.write_counted(nbytes)
            if got["n"] >= want_n:
                ep.close()
                out.write(cell(END, circ))

        ep.on_connected = on_connected
        ep.on_data = on_data
        ep.connect()


class TorClient:
    """args: [n_relays, relay_port, server, server_port, size, circuits,
              n_exits?]

    Relay hosts must be named relay0..relayN-1; when ``n_exits`` is given,
    relay0..relay{n_exits-1} are the exit-capable population (the
    generator places TorExit processes there) and the circuit's LAST hop
    is drawn from it — a plain TorRelay cannot terminate a BEGIN. Without
    it, every relay is assumed exit-capable (the pre-round-4 behavior).
    The client telescopes guard->middle->exit, BEGINs a fetch of `size`
    bytes from `server`, and records completion.
    """

    def __init__(self, api, args, env):
        self.api = api
        self.n_relays = int(args[0])
        self.relay_port = int(args[1])
        self.server = args[2]
        self.server_port = int(args[3])
        self.size = parse_size(args[4]) if len(args) > 4 else 100_000
        self.n_circuits = int(args[5]) if len(args) > 5 else 1
        self.n_exits = int(args[6]) if len(args) > 6 else self.n_relays
        self.completed = 0
        self.failed = 0
        self.attempted = 0
        self.completion_times = []  # ns, fetch end-to-end (incl. build)
        self.build_times = []  # ns, telescoping (CREATE..last EXTENDED)

    def start(self):
        for _ in range(self.n_circuits):
            self._build_circuit()

    def _pick_hops(self):
        # exit drawn FIRST (from the exit-capable population), then
        # guard/middle from the full relay range excluding it — the
        # other order can spin forever when every exit is already a
        # guard/middle (e.g. n_exits=1)
        rng = self.api.rng
        exit_r = int(rng.integers(0, self.n_exits))
        hops = [exit_r]
        while len(hops) < 3:
            r = int(rng.integers(0, self.n_relays))
            if r not in hops:
                hops.append(r)
        return [f"relay{hops[1]}", f"relay{hops[2]}", f"relay{exit_r}"]

    def _build_circuit(self):
        api = self.api
        hops = self._pick_hops()
        self.attempted += 1
        t0 = api.now
        circ = 1
        # stage: hops established so far (guard = 1); bd: telescoping-done
        # instant — the tor fetch's TTFB analog for the telemetry flow
        # record (observable identically under the Python closures and the
        # C tor sink: both fire on_ctrl for every control cell)
        state = {"stage": 0, "bd": None}
        tel = getattr(getattr(api, "_host", None), "telemetry", None)

        ep = api.connect(hops[0], self.relay_port)

        def advance():
            if state["stage"] < 3:
                nxt = hops[state["stage"]]
                conn.write(cell(
                    EXTEND, circ, f"{nxt}:{self.relay_port}".encode()))
            else:
                conn.write(cell(
                    BEGIN, circ,
                    f"{self.server}:{self.server_port}:{self.size}".encode()))

        def finish_fetch(got):
            elapsed = api.now - t0
            if got >= self.size:
                self.completed += 1
                self.completion_times.append(elapsed)
                api.log(f"circuit-complete hops={hops} bytes={got} "
                        f"elapsed_ms={elapsed // 1_000_000}")
            else:
                self.failed += 1
            if tel is not None:
                api._host.record_flow(
                    "tor_fetch", self.server, t0, state["bd"], got,
                    "ok" if got >= self.size else "error",
                    retx=int(ep.sender.loss_events))
            ep.close()
            self._finish()

        def on_ctrl(ctype, got):
            if ctype in (CREATED, EXTENDED):
                state["stage"] += 1
                if state["stage"] == 3:  # telescoping done; BEGIN follows
                    self.build_times.append(api.now - t0)
                    state["bd"] = api.now
                advance()
            elif ctype == END:
                finish_fetch(got)

        host = getattr(api, "_host", None)
        core = getattr(getattr(host, "colplane", None), "_c", None)
        make_sink = getattr(core, "tor_client_sink", None)
        if make_sink is not None and host.pcap is None:
            # C-engine endpoint: frame parsing, DATA-body byte counting,
            # AND the circuit-build control plane run in native/colcore
            # (TorSink). The sink holds the three pre-built advance
            # frames and answers each CREATED/EXTENDED natively through
            # its own pending-write queue; Python sees exactly two
            # events per circuit — the stage-3 EXTENDED (record the
            # build time) and END (finish the fetch). Exact twin of the
            # closures below (same cells, same order, same instants).
            frames = (
                cell(EXTEND, circ, f"{hops[1]}:{self.relay_port}".encode()),
                cell(EXTEND, circ, f"{hops[2]}:{self.relay_port}".encode()),
                cell(BEGIN, circ,
                     f"{self.server}:{self.server_port}:{self.size}"
                     .encode()),
            )

            def on_ctrl_c(ctype, c, payload, got):
                if ctype == END:
                    finish_fetch(got)
                else:  # the stage-3 EXTENDED: telescoping done
                    self.build_times.append(api.now - t0)
                    state["bd"] = api.now

            conn = make_sink(ep, on_ctrl_c, frames)
        else:
            got = {"n": 0}

            def on_cell(ctype, c, payload):
                on_ctrl(ctype, got["n"])

            def on_body(c, nbytes):
                got["n"] += nbytes

            conn = _Conn(ep, on_cell, on_body)

        def on_connected(now):
            conn.write(cell(CREATE, circ))

        def on_error(msg):
            self.failed += 1
            api.log(f"circuit-failed hops={hops}: {msg}")
            if tel is not None:
                api._host.record_flow(
                    "tor_fetch", self.server, t0, state["bd"], 0,
                    "timeout" if "ETIMEDOUT" in msg else "error",
                    retx=int(ep.sender.loss_events))
            self._finish()

        ep.on_connected = on_connected
        ep.on_error = on_error
        ep.connect()

    def _finish(self):
        if self.completed + self.failed >= self.n_circuits:
            self.api.log(
                f"tor client done: {self.completed}/{self.n_circuits} ok")
            self.api.exit(0 if self.failed == 0 else 1)

    def stop(self):
        pass
