"""DNS resolution chains: authoritative servers, a caching recursive
resolver, and the stub client the web workload depends on.

The modern-web family (ROADMAP open item 4) needs name resolution as a
first-class simulated dependency — page fetch latency in production is
DNS + connect + transfer, and a resolver cache turns the first cost from
a multi-hop chain into a local hit. The model is deliberately small but
production-shaped:

- ``DnsAuth`` — an authoritative server: answers every query for a name
  the simulation can resolve with the target's host id (the simulated
  A record). One UDP round trip.
- ``DnsResolver`` — a caching RECURSIVE resolver: a client query either
  hits the TTL cache (answered immediately) or walks the configured
  upstream chain (root -> TLD -> authoritative, one UDP round trip per
  hop — the referral chain without zone-file bookkeeping), caches the
  final answer for ``DNS_TTL_SEC``, and answers every waiter that piled
  up behind the miss. Lost datagrams (lossy links, partitions) are
  repaired by a per-hop retry timer; exhausted retries answer SERVFAIL.
- ``DnsStub`` — the client half (not a process): owns an ephemeral UDP
  socket, matches answers to queries by qid, retries on timeout, and
  emits one ``dns.resolve`` flow record per lookup (ok / servfail /
  timeout) through the telemetry subsystem.

Wire format (payload bytes; sizes counted at ~72B per message):
query ``b"Q" + qid(4, BE) + name``; answer ``b"R" + qid + ascii host
id``; SERVFAIL ``b"N" + qid``.

Determinism: all timing is simulated, qids are per-socket counters, and
the cache is keyed on exact names — byte-identical across scheduler
policies and the Python/C transport twins (DNS rides datagrams, which
have no C fast path to diverge from).
"""

from __future__ import annotations

from shadow_tpu.core.time import NS_PER_SEC

QUERY, ANSWER, SERVFAIL = b"Q", b"R", b"N"
DNS_MSG_BYTES = 72  # modeled wire size of every DNS message


def _pack(kind: bytes, qid: int, body: bytes = b"") -> bytes:
    return kind + qid.to_bytes(4, "big") + body


def _unpack(payload: bytes):
    return payload[:1], int.from_bytes(payload[1:5], "big"), payload[5:]


class DnsAuth:
    """Authoritative server. args: [port]"""

    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 53
        self.answered = 0

    def start(self):
        self.sock = self.api.udp_socket(self.port)
        self.sock.on_datagram = self._on_msg

    def _on_msg(self, nbytes, payload, src_addr, now):
        if payload is None or payload[:1] != QUERY:
            return
        _k, qid, name = _unpack(payload)
        try:
            hid = self.api.resolve(name.decode())
        except (KeyError, ValueError):
            self.sock.sendto(src_addr[0], src_addr[1],
                             payload=_pack(SERVFAIL, qid),
                             nbytes=DNS_MSG_BYTES)
            return
        self.answered += 1
        self.sock.sendto(src_addr[0], src_addr[1],
                         payload=_pack(ANSWER, qid, str(hid).encode()),
                         nbytes=DNS_MSG_BYTES)

    def stop(self):
        pass


class DnsResolver:
    """Caching recursive resolver. args: [port, upstream, upstream, ...]

    The upstream list is the referral chain (e.g. root, tld, auth): a
    cache miss queries each in order, one round trip per hop; the LAST
    hop's answer is authoritative and enters the cache.

    environment:
      DNS_TTL_SEC    (default 60): cache lifetime of an answer
      DNS_RETRY_SEC  (default 1):  per-hop retransmit timer
      DNS_TRIES      (default 4):  per-hop attempts before SERVFAIL
    """

    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 53
        self.upstreams = args[1:]
        self.ttl_ns = int(float(env.get("DNS_TTL_SEC", 60)) * NS_PER_SEC)
        self.retry_ns = int(float(env.get("DNS_RETRY_SEC", 1)) * NS_PER_SEC)
        self.tries = int(env.get("DNS_TRIES", 4))
        self.cache: dict[bytes, tuple[bytes, int]] = {}  # name -> (ans, exp)
        self.hits = 0
        self.misses = 0
        #: in-flight recursions by name: {"waiters": [(src, qid)...], ...}
        self._pending: dict[bytes, dict] = {}
        self._next_qid = 0

    def start(self):
        self.sock = self.api.udp_socket(self.port)
        self.sock.on_datagram = self._on_msg
        self.up_ids = [self.api.resolve(u) for u in self.upstreams]

    def _on_msg(self, nbytes, payload, src_addr, now):
        if payload is None:
            return
        kind, qid, body = _unpack(payload)
        if kind == QUERY:
            self._client_query(body, src_addr, qid, now)
        elif kind in (ANSWER, SERVFAIL):
            self._upstream_reply(kind, qid, body, now)

    def _client_query(self, name, src_addr, qid, now):
        ent = self.cache.get(name)
        if ent is not None and now < ent[1]:
            self.hits += 1
            self.sock.sendto(src_addr[0], src_addr[1],
                             payload=_pack(ANSWER, qid, ent[0]),
                             nbytes=DNS_MSG_BYTES)
            return
        self.misses += 1
        pend = self._pending.get(name)
        if pend is not None:  # recursion already running: pile on
            pend["waiters"].append((src_addr, qid))
            return
        self._pending[name] = {"waiters": [(src_addr, qid)], "hop": 0,
                               "attempt": 0, "qid": -1, "timer": None}
        self._query_hop(name, now)

    def _query_hop(self, name, now):
        pend = self._pending[name]
        qid = self._next_qid
        self._next_qid += 1
        pend["qid"] = qid
        pend["attempt"] += 1
        up = self.up_ids[pend["hop"]]
        # upstreams listen on the same port by convention
        self.sock.sendto(up, self.port,
                         payload=_pack(QUERY, qid, name),
                         nbytes=DNS_MSG_BYTES)
        pend["timer"] = self.api.after(
            self.retry_ns, lambda: self._hop_timeout(name))

    def _hop_timeout(self, name):
        pend = self._pending.get(name)
        if pend is None:
            return
        pend["timer"] = None
        if pend["attempt"] >= self.tries:
            self._finish(name, None)
            return
        self._query_hop(name, self.api.now)

    def _upstream_reply(self, kind, qid, body, now):
        # match the reply to its recursion by qid (names are unique keys;
        # a stale reply after a retry re-query simply misses)
        for name, pend in self._pending.items():
            if pend["qid"] == qid:
                break
        else:
            return
        if pend["timer"] is not None:
            self.api.cancel(pend["timer"])
            pend["timer"] = None
        if kind == SERVFAIL:
            self._finish(name, None)
            return
        pend["hop"] += 1
        pend["attempt"] = 0
        if pend["hop"] >= len(self.up_ids):
            # the final hop's answer is authoritative
            self.cache[name] = (body, now + self.ttl_ns)
            self._finish(name, body)
        else:
            self._query_hop(name, now)

    def _finish(self, name, answer):
        pend = self._pending.pop(name)
        for (src_addr, qid) in pend["waiters"]:
            if answer is None:
                self.sock.sendto(src_addr[0], src_addr[1],
                                 payload=_pack(SERVFAIL, qid),
                                 nbytes=DNS_MSG_BYTES)
            else:
                self.sock.sendto(src_addr[0], src_addr[1],
                                 payload=_pack(ANSWER, qid, answer),
                                 nbytes=DNS_MSG_BYTES)

    def stop(self):
        self.api.log(f"dns resolver done: hits={self.hits} "
                     f"misses={self.misses} cached={len(self.cache)}")


class DnsStub:
    """The client half of a lookup (owned by a workload model, not a
    process): per-lookup timeout/retry and one ``dns.resolve`` flow
    record per lookup. ``cb(hid_or_none)`` fires exactly once."""

    def __init__(self, api, resolver: str, port: int,
                 retry_ns: int, tries: int):
        self.api = api
        self.resolver = resolver
        self.resolver_id = api.resolve(resolver)
        self.port = port
        self.retry_ns = retry_ns
        self.tries = tries
        self.sock = api.udp_socket()  # ephemeral
        self.sock.on_datagram = self._on_msg
        self._next_qid = 0
        self._out: dict[int, dict] = {}  # qid -> lookup state

    def lookup(self, name: str, cb) -> None:
        qid = self._next_qid
        self._next_qid += 1
        self._out[qid] = {"name": name.encode(), "cb": cb, "attempt": 0,
                          "t_open": self.api.now, "timer": None}
        self._send(qid)

    def _send(self, qid):
        st = self._out[qid]
        st["attempt"] += 1
        self.sock.sendto(self.resolver_id, self.port,
                         payload=_pack(QUERY, qid, st["name"]),
                         nbytes=DNS_MSG_BYTES)
        st["timer"] = self.api.after(self.retry_ns,
                                     lambda: self._timeout(qid))

    def _timeout(self, qid):
        st = self._out.get(qid)
        if st is None:
            return
        st["timer"] = None
        if st["attempt"] >= self.tries:
            del self._out[qid]
            self._record(st, None, "timeout")
            st["cb"](None)
            return
        self._send(qid)

    def _on_msg(self, nbytes, payload, src_addr, now):
        if payload is None:
            return
        kind, qid, body = _unpack(payload)
        st = self._out.pop(qid, None)
        if st is None:
            return  # stale duplicate (a retry already won)
        if st["timer"] is not None:
            self.api.cancel(st["timer"])
        if kind == ANSWER:
            self._record(st, now, "ok")
            st["cb"](int(body.decode()))
        else:
            self._record(st, now, "servfail")
            st["cb"](None)

    def _record(self, st, t_answer, status):
        self.api._host.record_flow(
            "dns.resolve", self.resolver, st["t_open"], t_answer,
            DNS_MSG_BYTES, status, retx=st["attempt"] - 1)
