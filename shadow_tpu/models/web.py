"""HTTP/CDN tier: clients -> edge caches -> origin, with hierarchical
page fan-out and a cache hit-ratio knob.

The modern-web workload family's request/response backbone (ROADMAP open
item 4): production traffic is not bulk fetches but page hierarchies —
resolve a name, fetch a main object, fan out subresource fetches, think,
repeat — served through an edge tier whose cache hit ratio decides how
much traffic reaches the origin. Three models:

- ``WebOrigin`` — the origin server: parses newline-framed
  ``GET <obj> <nbytes>`` requests off a stream connection and pushes
  ``nbytes`` counted bytes per request (tgen-style: no payload
  materialization, so big configs stay in memory).
- ``WebEdge`` — an edge cache: terminates client connections, serves
  cache HITS locally and proxies MISSES to the origin (store-and-forward
  over a fresh origin connection, one ``web.origin`` flow record per
  miss). The hit set is a deterministic hash knob — ``crc32(obj) % 100 <
  hit_pct`` — so a config dials the origin offload directly and every
  plane/policy computes the identical hit set.
- ``WebClient`` — the page loop: DNS-resolve the edge (models/dns.py
  DnsStub — one ``dns.resolve`` flow per lookup), fetch the page's main
  object, then fan out N subresource fetches in parallel, think
  (seeded-exponential), next page. One ``web.fetch`` flow record per
  object; ETIMEDOUT fetches retry up to WEB_RETRIES then count failed.

Request wire format (real payload bytes): ``GET <obj> <nbytes>\\n``.
Responses are counted bytes. Everything else is deterministic: object
ids derive from (host, page, index), edge choice and think times from
the per-host counter-based RNG, hits from crc32 — byte-identical across
scheduler policies and the Python/C transport twins (the transfer path
is exactly the machinery tgen already proves).
"""

from __future__ import annotations

import zlib

from shadow_tpu.core.time import NS_PER_SEC


def parse_requests(buf: dict, payload) -> list[tuple[bytes, int]]:
    """Accumulate stream payload into ``buf["b"]`` and split off every
    complete ``GET <obj> <nbytes>\\n`` request. Malformed lines parse as
    (obj, 0) and are ignored by servers."""
    if payload is None:
        return []
    buf["b"] += payload
    out = []
    while b"\n" in buf["b"]:
        line, buf["b"] = buf["b"].split(b"\n", 1)
        parts = line.split()
        if len(parts) == 3 and parts[0] == b"GET":
            try:
                out.append((parts[1], int(parts[2])))
            except ValueError:
                pass
    return out


def request_line(obj: bytes, nbytes: int) -> bytes:
    return b"GET %s %d\n" % (obj, nbytes)


def fetch_counted(api, tel, target_id, port, obj, want, *, flow_kind,
                  peer, retries, idle_ns, x=None, on_ok, on_fail):
    """Fetch ``want`` counted bytes of ``obj`` from ``(target_id,
    port)`` over a fresh stream connection — the one fetch closure the
    whole family shares (WebClient objects, WebEdge origin proxying,
    AbrClient segments). Connect, send the request line, count response
    bytes, and resolve EXACTLY once: ``on_ok(conn, got_n, t_open, ttfb,
    now, retx)`` at completion (success flow recording stays with the
    caller — the groups differ in fields), or ``on_fail(msg)`` once
    ETIMEDOUT retries are exhausted, the peer closes short, or any other
    error lands. Failure flows are recorded here — exactly ONE per
    object, at retry exhaustion (status timeout/error, ``tel`` gating).
    ``retx`` — on both paths — folds the final attempt's transport
    retransmits plus the prior timed-out attempts. An armed idle
    timeout turns a silent established
    peer into ETIMEDOUT; late teardown noise after a completed fetch is
    ignored."""
    def attempt_fetch(attempt):
        t_open = api.now
        conn = api._host.connect(target_id, port)
        got = {"n": 0}
        first = {"t": None}

        def on_connected(now):
            conn.send(payload=request_line(obj, want))

        def on_data(nbytes, payload, now):
            if first["t"] is None:
                first["t"] = now
            got["n"] += nbytes
            if got["n"] >= want:
                on_ok(conn, got["n"], t_open, first["t"], now,
                      int(conn.sender.loss_events) + attempt)

        def on_error(msg):
            if got["n"] >= want:
                return  # late teardown noise after a completed fetch
            if "ETIMEDOUT" in msg and attempt < retries:
                attempt_fetch(attempt + 1)
                return
            # one failure record per OBJECT, at retry exhaustion — the
            # DnsStub discipline; intermediate timed-out attempts are
            # visible through retx on the final record
            if tel is not None:
                api._host.record_flow(
                    flow_kind, peer, t_open, first["t"], got["n"],
                    "timeout" if "ETIMEDOUT" in msg else "error",
                    retx=int(conn.sender.loss_events) + attempt, x=x)
            on_fail(msg)

        def on_close(now):
            if got["n"] < want:
                on_error("connection closed by peer (short response)")

        conn.on_connected = on_connected
        conn.on_data = on_data
        conn.on_error = on_error
        conn.on_close = on_close
        if idle_ns:
            conn.set_idle_timeout(idle_ns)
        conn.connect()

    attempt_fetch(0)


class WebOrigin:
    """Origin server. args: [port]"""

    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 80
        self.served = 0

    def start(self):
        self.api.listen(self.port, self._on_accept)

    def _on_accept(self, conn, now):
        buf = {"b": b""}
        pending = {"n": 0}

        def push(room=0):
            if pending["n"] > 0:
                pending["n"] -= conn.send(pending["n"])

        def on_data(nbytes, payload, t):
            for _obj, want in parse_requests(buf, payload):
                if want > 0:
                    self.served += 1
                    pending["n"] += want
            push()

        conn.on_data = on_data
        conn.on_drain = push

    def stop(self):
        pass


def is_cache_hit(obj: bytes, hit_pct: int) -> bool:
    """The hit-ratio knob: deterministic per-object hash — the same
    ~hit_pct% of the object population hits on every plane/policy/run."""
    return zlib.crc32(obj) % 100 < hit_pct


class WebEdge:
    """Edge cache. args: [port, origin_name, origin_port, hit_pct]

    environment:
      WEB_EDGE_RETRIES (default 1): origin-fetch retries on ETIMEDOUT
                       before the client connection is closed (the
                       client's own on_close/retry then owns recovery)
      WEB_EDGE_IDLE_TIMEOUT_SEC (default 30): idle timeout on origin
                       connections, so an origin that goes silent
                       mid-response (crash, long partition) surfaces as
                       ETIMEDOUT instead of a stuck proxy fetch
    """

    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 80
        self.origin = args[1] if len(args) > 1 else "origin0"
        self.origin_port = int(args[2]) if len(args) > 2 else 80
        self.hit_pct = int(args[3]) if len(args) > 3 else 80
        self.retries = int(env.get("WEB_EDGE_RETRIES", 1))
        self.idle_ns = int(
            float(env.get("WEB_EDGE_IDLE_TIMEOUT_SEC", 30)) * NS_PER_SEC)
        self.hits = 0
        self.misses = 0
        host = getattr(api, "_host", None)
        self._tel = getattr(host, "telemetry", None)

    def start(self):
        self.origin_id = self.api.resolve(self.origin)
        self.api.listen(self.port, self._on_accept)

    def _on_accept(self, conn, now):
        buf = {"b": b""}
        #: per-request FIFO of {want, ready}: the counted-byte protocol
        #: has no framing, so response bytes must leave in REQUEST order
        #: — a hit pipelined behind a pending miss waits for it
        queue = []
        pending = {"n": 0}
        dead = {"v": False}

        def push(room=0):
            if dead["v"]:
                return
            while queue and queue[0]["ready"]:
                pending["n"] += queue.pop(0)["want"]
            if pending["n"] > 0:
                pending["n"] -= conn.send(pending["n"])

        def on_data(nbytes, payload, t):
            for obj, want in parse_requests(buf, payload):
                if want <= 0:
                    continue
                entry = {"want": want, "ready": False}
                queue.append(entry)
                if is_cache_hit(obj, self.hit_pct):
                    self.hits += 1
                    entry["ready"] = True
                else:
                    self.misses += 1
                    self._fetch_origin(conn, obj, entry, push)
            push()

        def on_dead(*_a):
            # the client connection is gone (reset after DATA_RETRIES
            # during a partition, or fully closed): drop the response
            # backlog so a late origin-miss completion can't queue bytes
            # and re-arm RTO cycles on the dead sender
            dead["v"] = True
            queue.clear()
            pending["n"] = 0

        conn.on_data = on_data
        conn.on_drain = push
        conn.on_close = on_dead
        conn.on_error = on_dead

    def _fetch_origin(self, conn, obj, entry, push):
        """Proxy a miss: fetch ``entry["want"]`` counted bytes from the
        origin (store-and-forward), then mark the response entry ready —
        push() releases it in request order. A terminal origin failure
        closes the client connection — the client's on_close sees a
        short response and owns recovery — so a dead origin can never
        strand the client's page loop."""
        def on_ok(oc, got_n, t_open, ttfb, now, retx):
            if self._tel is not None:
                self.api._host.record_flow(
                    "web.origin", self.origin, t_open, ttfb, got_n,
                    "ok", retx=retx)
            oc.close()
            entry["ready"] = True
            push()

        fetch_counted(self.api, self._tel, self.origin_id,
                      self.origin_port, obj, entry["want"],
                      flow_kind="web.origin", peer=self.origin,
                      retries=self.retries, idle_ns=self.idle_ns,
                      on_ok=on_ok, on_fail=lambda msg: conn.close())

    def stop(self):
        self.api.log(f"edge done: hits={self.hits} misses={self.misses}")


class WebClient:
    """Page-fetch loop.
    args: [pages, fanout, main_bytes, sub_bytes, port, resolver, edge...]

    Each page: DNS-resolve a seeded-random edge from the list, fetch the
    main object, then ``fanout`` subresources in parallel, think, next.

    environment:
      WEB_THINK_SEC   (default 1.0): mean think time between pages
                      (seeded uniform on [0, 2*mean); 0 disables)
      WEB_RETRIES     (default 0): per-object ETIMEDOUT reconnects
      WEB_IDLE_TIMEOUT_SEC (default 30): per-fetch idle timeout — a
                      silent edge (crashed, partitioned past SYN
                      retries) fails the fetch with ETIMEDOUT instead
                      of stranding the page loop forever
      WEB_DNS_PORT    (default 53), DNS_RETRY_SEC (default 1),
      DNS_TRIES       (default 4): stub resolver knobs (models/dns.py)
    """

    def __init__(self, api, args, env):
        from shadow_tpu.utils.units import parse_size

        self.api = api
        self.pages = int(args[0]) if args else 1
        self.fanout = int(args[1]) if len(args) > 1 else 4
        self.main_bytes = parse_size(args[2]) if len(args) > 2 else 100_000
        self.sub_bytes = parse_size(args[3]) if len(args) > 3 else 30_000
        self.port = int(args[4]) if len(args) > 4 else 80
        self.resolver = args[5] if len(args) > 5 else "resolver0"
        self.edges = args[6:]
        self.think_ns = int(
            float(env.get("WEB_THINK_SEC", 1.0)) * NS_PER_SEC)
        self.retries = int(env.get("WEB_RETRIES", 0))
        self.idle_ns = int(
            float(env.get("WEB_IDLE_TIMEOUT_SEC", 30)) * NS_PER_SEC)
        self.dns_port = int(env.get("WEB_DNS_PORT", 53))
        self.dns_retry_ns = int(
            float(env.get("DNS_RETRY_SEC", 1)) * NS_PER_SEC)
        self.dns_tries = int(env.get("DNS_TRIES", 4))
        self.pages_done = 0
        self.objects_ok = 0
        self.objects_failed = 0
        self.dns_failed = 0
        self.page_times = []
        host = getattr(api, "_host", None)
        self._tel = getattr(host, "telemetry", None)

    def start(self):
        from shadow_tpu.models.dns import DnsStub

        if not self.edges:
            self.api.log("web client: no edges configured")
            self.api.exit(1)
            return
        self.stub = DnsStub(self.api, self.resolver, self.dns_port,
                            self.dns_retry_ns, self.dns_tries)
        self._page(0)

    # -- page machinery ----------------------------------------------------
    def _page(self, p):
        rng = self.api.rng
        edge = self.edges[int(rng.integers(0, len(self.edges)))]
        t_page = self.api.now

        def resolved(hid):
            if hid is None:
                self.dns_failed += 1
                self._page_done(p, t_page, failed=True)
                return
            self._fetch_page(p, hid, edge, t_page)

        self.stub.lookup(edge, resolved)

    def _fetch_page(self, p, edge_id, edge_name, t_page):
        me = self.api.host_id
        main_obj = b"h%d.p%d.m" % (me, p)
        state = {"left": 1 + self.fanout, "failed": 0}

        def one_done(ok):
            if not ok:
                state["failed"] += 1
            state["left"] -= 1
            if state["left"] == 0:
                self._page_done(p, t_page, failed=state["failed"] > 0)

        def main_done(ok):
            if not ok:
                # the page skeleton failed: subresources never start
                state["left"] = 1
                one_done(False)
                return
            one_done(True)
            for k in range(self.fanout):
                self._fetch(b"h%d.p%d.s%d" % (me, p, k), self.sub_bytes,
                            edge_id, edge_name, one_done)

        self._fetch(main_obj, self.main_bytes, edge_id, edge_name,
                    main_done)

    def _fetch(self, obj, want, edge_id, edge_name, done):
        def on_ok(conn, got_n, t_open, ttfb, now, retx):
            self.objects_ok += 1
            if self._tel is not None:
                self.api._host.record_flow(
                    "web.fetch", edge_name, t_open, ttfb, got_n, "ok",
                    retx=retx)
            conn.close()
            done(True)

        def on_fail(msg):
            self.objects_failed += 1
            done(False)

        fetch_counted(self.api, self._tel, edge_id, self.port, obj, want,
                      flow_kind="web.fetch", peer=edge_name,
                      retries=self.retries, idle_ns=self.idle_ns,
                      on_ok=on_ok, on_fail=on_fail)

    def _page_done(self, p, t_page, failed):
        self.pages_done += 1
        if not failed:
            self.page_times.append(self.api.now - t_page)
        if self.pages_done >= self.pages:
            self.api.log(
                f"web client done: pages={self.pages_done} "
                f"objects_ok={self.objects_ok} "
                f"objects_failed={self.objects_failed} "
                f"dns_failed={self.dns_failed}")
            self.api.exit(0 if self.objects_failed == 0
                          and self.dns_failed == 0 else 1)
            return
        delay = 1
        if self.think_ns > 0:
            delay = 1 + int(float(self.api.rng.random()) * 2 * self.think_ns)
        self.api.after(delay, lambda: self._page(p + 1))

    def stop(self):
        pass
