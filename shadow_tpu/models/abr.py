"""Video ABR sessions: rate-switching segment fetches against the fluid
NIC buckets, with playback-buffer stall accounting.

The third member of the modern-web family (ROADMAP open item 4): a video
client fetches fixed-duration segments over the stream transport,
estimates throughput from each download, and walks a bitrate ladder —
the classic throughput-based ABR loop (buffer-capped, EWMA estimator,
safety factor). What makes it a SIMULATION workload rather than a toy:
segment downloads ride the same fluid token buckets, congestion control,
and SACK recovery as every other stream, so a `link_degrade` window
produces exactly the rate downshifts and rebuffering stalls a real
player would show — and the telemetry subsystem prices them:

- one ``abr.segment`` flow record per segment (bytes, TTFB, latency,
  retransmits) carrying the segment's selected bitrate in the record's
  ``x`` field — the summary and metrics_report reduce it to the mean
  selected rate;
- one ``abr.stall`` flow record per rebuffering event, whose latency IS
  the stall duration (stall-seconds and stall-duration percentiles come
  free from the generic flow machinery);
- counters ``abr_segments`` / ``abr_stall_ns`` / ``abr_rate_sum_bps``
  fold into the run summary (the quality/stall roll-up).

Determinism: the throughput estimator and ladder walk are integer
arithmetic over simulated timestamps; the playback model is event-driven
(advanced at segment completions) — byte-identical across scheduler
policies and the Python/C transport twins.
"""

from __future__ import annotations

from shadow_tpu.core.time import NS_PER_SEC
from shadow_tpu.models.web import WebOrigin, fetch_counted


class AbrServer(WebOrigin):
    """Segment server: the origin protocol, serving GET <seg> <nbytes>.
    args: [port]"""


class AbrClient:
    """One video session.
    args: [server, port, n_segments, seg_ms, rate_bps, rate_bps, ...]

    ``rates`` is the bitrate ladder in bits/sec, ascending. Segment i's
    size is selected_rate * seg_ms / 8000 bytes.

    environment:
      ABR_STARTUP_SEGS   (default 2): buffered segments before playback
      ABR_BUFFER_CAP_SEC (default 12): max buffered content; downloads
                         pause while above the cap
      ABR_SAFETY_PCT     (default 80): pick the highest ladder rate <=
                         estimate * safety / 100
      ABR_RETRIES        (default 1): per-segment ETIMEDOUT reconnects
      ABR_IDLE_TIMEOUT_SEC (default 30): per-segment idle timeout — a
                         server gone silent mid-segment fails the fetch
                         with ETIMEDOUT instead of stranding the session
    """

    def __init__(self, api, args, env):
        self.api = api
        self.server = args[0] if args else "video0"
        self.port = int(args[1]) if len(args) > 1 else 80
        self.n_segments = int(args[2]) if len(args) > 2 else 10
        self.seg_ns = (int(args[3]) if len(args) > 3 else 2000) * 1_000_000
        self.rates = [int(r) for r in args[4:]] or [
            400_000, 1_000_000, 2_500_000, 5_000_000]
        self.startup_segs = int(env.get("ABR_STARTUP_SEGS", 2))
        self.buffer_cap_ns = int(
            float(env.get("ABR_BUFFER_CAP_SEC", 12)) * NS_PER_SEC)
        self.safety_pct = int(env.get("ABR_SAFETY_PCT", 80))
        self.retries = int(env.get("ABR_RETRIES", 1))
        self.idle_ns = int(
            float(env.get("ABR_IDLE_TIMEOUT_SEC", 30)) * NS_PER_SEC)
        # session state
        self.seg = 0
        self.rate = self.rates[0]  # start at the ladder floor
        self.est_bps = 0  # EWMA throughput estimate (bits/sec)
        self.buffer_ns = 0
        self.playing = False
        self.last_t = 0  # playback-accounting cursor
        self.stall_ns = 0
        self.stalls = 0
        self.rate_sum = 0
        self.downshifts = 0
        self.failed = 0
        host = getattr(api, "_host", None)
        self._tel = getattr(host, "telemetry", None)

    def start(self):
        self.server_id = self.api.resolve(self.server)
        self.last_t = self.api.now
        self._next_segment()

    # -- playback accounting ----------------------------------------------
    def _advance(self, now):
        """Drain the playback buffer over [last_t, now); any shortfall is
        a rebuffering stall (recorded as an ``abr.stall`` flow whose
        latency is the stall duration)."""
        if self.playing:
            elapsed = now - self.last_t
            if elapsed > self.buffer_ns:
                stall = elapsed - self.buffer_ns
                self.stall_ns += stall
                self.stalls += 1
                self.buffer_ns = 0
                if self._tel is not None:
                    self.api._host.record_flow(
                        "abr.stall", self.server, now - stall, None, 0,
                        "ok")
            else:
                self.buffer_ns -= elapsed
        self.last_t = now

    # -- download loop -----------------------------------------------------
    def _next_segment(self):
        if self.seg >= self.n_segments:
            self._finish()
            return
        want = self.rate * (self.seg_ns // 1_000_000) // 8000  # bytes
        if want <= 0:
            want = 1
        self._fetch_segment(self.seg, want, self.rate)

    def _fetch_segment(self, i, want, rate):
        def on_ok(conn, got_n, t_open, ttfb, now, retx):
            conn.close()
            self._segment_done(i, want, rate, t_open, ttfb, retx, now)

        def on_fail(msg):
            self.failed += 1
            self.seg += 1
            self._next_segment()  # skip the segment (a real player would)

        fetch_counted(self.api, self._tel, self.server_id, self.port,
                      b"seg%d" % i, want, flow_kind="abr.segment",
                      peer=self.server, retries=self.retries,
                      idle_ns=self.idle_ns, x=rate,
                      on_ok=on_ok, on_fail=on_fail)

    def _segment_done(self, i, nbytes, rate, t_open, ttfb, retx, now):
        if self._tel is not None:
            self.api._host.record_flow(
                "abr.segment", self.server, t_open, ttfb, nbytes, "ok",
                retx=retx, x=rate)
        self.rate_sum += rate
        self._advance(now)
        self.buffer_ns += self.seg_ns
        self.seg += 1
        if not self.playing and self.seg >= self.startup_segs:
            self.playing = True
            self.last_t = now  # startup latency is not a stall
        # throughput sample -> EWMA -> ladder walk
        elapsed = now - t_open
        if elapsed > 0:
            sample = nbytes * 8 * NS_PER_SEC // elapsed  # bits/sec
            self.est_bps = (sample if self.est_bps == 0
                            else (self.est_bps * 7 + sample) // 8)
        budget = self.est_bps * self.safety_pct // 100
        new_rate = self.rates[0]
        for r in self.rates:
            if r <= budget:
                new_rate = r
        if new_rate < self.rate:
            self.downshifts += 1
        self.rate = new_rate
        # buffer cap: hold the next request until playback drains room
        if self.playing and self.buffer_ns > self.buffer_cap_ns:
            self.api.after(self.buffer_ns - self.buffer_cap_ns,
                           self._next_segment)
        else:
            self._next_segment()

    def _finish(self):
        # drain the remaining buffer through playback before judging
        self._advance(self.api.now)
        n = self.seg - self.failed
        host = self.api._host
        host.counters.add("abr_segments", n)
        if self.stall_ns:
            host.counters.add("abr_stall_ns", self.stall_ns)
        if self.rate_sum:
            host.counters.add("abr_rate_sum_bps", self.rate_sum)
        mean_rate = self.rate_sum // n if n else 0
        self.api.log(
            f"abr session done: segments={n}/{self.n_segments} "
            f"mean_rate_bps={mean_rate} stalls={self.stalls} "
            f"stall_ms={self.stall_ns // 1_000_000} "
            f"downshifts={self.downshifts} failed={self.failed}")
        self.api.exit(0 if self.failed == 0 else 1)

    def stop(self):
        pass
