"""Workload applications ("model families").

The reference's workloads are external real programs run as managed
processes — tgen (traffic generator), tor, bitcoind (SURVEY.md §1 bottom
note). Phase-1 ships plugin re-implementations of the workload *behaviors*
the benchmark configs need (BASELINE.md configs 1, 2, 4):

- echo:   minimal UDP request/response pair (smoke tests)
- tgen:   stream transfer client/server in tgen's shape (connect, request
          N bytes, stream back, record completion)
- gossip: bitcoin-like inv/getdata/tx flood over datagrams

Real tgen/tor binaries become runnable in phase 4 via the native shim.
"""
