"""Minimal UDP echo pair — the simulator's smoke-test workload."""

from __future__ import annotations

from shadow_tpu.core.time import NS_PER_SEC


class EchoServer:
    """args: [port]"""

    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 9000

    def start(self):
        sock = self.api.udp_socket(self.port)
        sock.on_datagram = self._on_dgram
        self.sock = sock
        self.api.log(f"echo server listening on {self.port}")

    def _on_dgram(self, nbytes, payload, src_addr, now):
        src_host, src_port = src_addr
        self.sock.sendto(src_host, src_port, nbytes=nbytes, payload=payload)

    def stop(self):
        pass


class EchoClient:
    """args: [server, port, count?, payload?]"""

    def __init__(self, api, args, env):
        self.api = api
        self.server = args[0]
        self.port = int(args[1]) if len(args) > 1 else 9000
        self.count = int(args[2]) if len(args) > 2 else 3
        self.payload = (args[3].encode() if len(args) > 3 else b"ping")
        self.sent = 0
        self.received = 0
        self.rtts = []
        self._t_sent = {}

    def start(self):
        self.sock = self.api.udp_socket()
        self.sock.on_datagram = self._on_reply
        self._send_next()

    def _send_next(self):
        if self.sent >= self.count:
            return
        self.sent += 1
        self._t_sent[self.sent] = self.api.now
        server_id = self.api.resolve(self.server)
        self.sock.sendto(server_id, self.port, payload=self.payload)
        self.api.after(NS_PER_SEC, self._send_next)

    def _on_reply(self, nbytes, payload, src_addr, now):
        self.received += 1
        t0 = self._t_sent.get(self.received)
        if t0 is not None:
            rtt = now - t0
            self.rtts.append(rtt)
            self.api.log(f"echo reply {self.received}/{self.count} rtt={rtt}ns")
        if self.received >= self.count:
            self.api.exit(0)

    def stop(self):
        pass
