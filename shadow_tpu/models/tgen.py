"""tgen-shaped stream transfer workload.

Models the behavior of the reference's canonical workload (the tgen traffic
generator run as a managed process; SURVEY.md §1, BASELINE.md configs 1-2):
clients connect to servers, request a transfer of N bytes, the server
streams the bytes back, and the client records the completion. Repeats
``count`` times per peer, either round-robin or to every peer (all-to-all).

Request wire format (8 bytes of real payload): the requested size encoded
as decimal ASCII. Everything else is synthetic byte counts (no payload
materialization), which is what lets 100k-host configs fit in memory.
"""

from __future__ import annotations

from shadow_tpu.core.time import NS_PER_MS, NS_PER_SEC


class TGenServer:
    """args: [port]"""

    def __init__(self, api, args, env):
        self.api = api
        self.port = int(args[0]) if args else 8080
        self.transfers = 0

    def start(self):
        self.api.listen(self.port, self._on_accept)
        self.api.log(f"tgen server listening on {self.port}")

    def _on_accept(self, conn, now):
        serve = getattr(conn, "tgen_serve", None)
        if serve is not None:
            # C-engine endpoint: request parsing and counted-byte pushing
            # run in native/colcore (exact twin of the closures below,
            # which remain the Python-plane path); only the once-per-
            # transfer request notification comes back here
            def on_request(want):
                self.transfers += 1

            serve(on_request)
            return
        pending = {"n": 0}

        def push(room=0):
            # send() may accept only part (bounded send buffer); the rest
            # streams out through on_drain as acks free space
            if pending["n"] > 0:
                pending["n"] -= conn.send(pending["n"])

        def on_data(nbytes, payload, t):
            if payload is not None:
                try:
                    want = int(payload.decode().strip())
                except ValueError:
                    want = 0
                if want > 0:
                    self.transfers += 1
                    pending["n"] += want
                    push()

        conn.on_data = on_data
        conn.on_drain = push

    def stop(self):
        pass


class TGenClient:
    """args: [size, count, mode, port, peer, peer, ...]

    size:  bytes per transfer ("1 MB" style units ok)
    count: transfers per peer
    mode:  "serial" (one at a time round-robin) | "parallel" (all at once)

    environment TGEN_RETRIES=N (default 0): a transfer that dies with
    ETIMEDOUT (crashed peer, unhealed partition — the transport's terminal
    RTO path) reconnects up to N times before counting as failed, so churn
    configs run to completion. Non-timeout errors never retry.

    environment TGEN_IDLE_TIMEOUT_SEC=S (default 0 = off): arm the
    transport idle timeout on each connection — a client that is purely
    RECEIVING has no outstanding data, so only this detects a peer that
    crashed mid-response (identical on the Python and C endpoints).
    """

    def __init__(self, api, args, env):
        from shadow_tpu.utils.units import parse_size

        self.api = api
        self.size = parse_size(args[0]) if args else 1_000_000
        self.count = int(args[1]) if len(args) > 1 else 1
        self.mode = args[2] if len(args) > 2 else "serial"
        self.port = int(args[3]) if len(args) > 3 else 8080
        self.peers = args[4:]
        self.retries = int(env.get("TGEN_RETRIES", 0))
        self.idle_timeout_ns = int(
            float(env.get("TGEN_IDLE_TIMEOUT_SEC", 0)) * NS_PER_SEC)
        self.retried = 0
        self.completed = 0
        self.failed = 0
        self.total = self.count * len(self.peers)
        self.completion_times = []
        #: telemetry (shadow_tpu/telemetry/): one flow record per fetch
        #: attempt at close. Checked ONCE here so the off path adds no
        #: per-chunk work (the on_data closures below stay untouched).
        host = getattr(api, "_host", None)
        self._tel = getattr(host, "telemetry", None)

    def start(self):
        if not self.peers:
            self.api.log("tgen client: no peers configured")
            self.api.exit(1)
            return
        if self.mode == "parallel":
            for peer in self.peers:
                for _ in range(self.count):
                    self._start_transfer(peer)
        else:
            self._serial_queue = [
                peer for _ in range(self.count) for peer in self.peers
            ]
            self._start_transfer(self._serial_queue.pop(0))

    def _start_transfer(self, peer, attempt=0):
        t_start = self.api.now
        conn = self.api.connect(peer, self.port)
        tel = self._tel
        first = {"t": None} if tel is not None else None

        def on_connected(now):
            conn.send(payload=str(self.size).encode().rjust(8))

        def _ttfb():
            """Absolute sim time of the first response byte: the Python
            closure's capture, or the C twin's tgen_t_first (recorded at
            the same delivery instant — colcore.c cr_deliver)."""
            if first is not None and first["t"] is not None:
                return first["t"]
            t = getattr(conn, "tgen_t_first", -1)
            return t if isinstance(t, int) and t >= 0 else None

        def finish(now, got):
            elapsed = now - t_start
            self.completion_times.append(elapsed)
            self.completed += 1
            self.api.log(
                f"transfer-complete peer={peer} bytes={got} "
                f"elapsed_ms={elapsed // NS_PER_MS}"
            )
            if tel is not None:
                self.api._host.record_flow(
                    "tgen_fetch", peer, t_start, _ttfb(), got, "ok",
                    retx=int(conn.sender.loss_events))
            conn.close()
            self._next()

        def on_error(msg):
            if tel is not None:
                self.api._host.record_flow(
                    "tgen_fetch", peer, t_start, _ttfb(),
                    int(conn.receiver.bytes_received),
                    "timeout" if "ETIMEDOUT" in msg else "error",
                    retx=int(conn.sender.loss_events))
            if "ETIMEDOUT" in msg and attempt < self.retries:
                self.retried += 1
                self.api.log(
                    f"transfer-retry peer={peer} attempt={attempt + 1}: {msg}")
                self._start_transfer(peer, attempt + 1)
                return
            self.failed += 1
            self.api.log(f"transfer-failed peer={peer}: {msg}")
            self._next()

        tgen_client = getattr(conn, "tgen_client", None)
        if tgen_client is not None:
            # C-engine endpoint: received-byte counting runs in
            # native/colcore; finish fires once per transfer with the
            # same (now, got) the Python closure would compute
            tgen_client(self.size, finish)
        elif tel is not None:
            got = {"n": 0}

            def on_data(nbytes, payload, now):
                if first["t"] is None:
                    first["t"] = now
                got["n"] += nbytes
                if got["n"] >= self.size:
                    finish(now, got["n"])

            conn.on_data = on_data
        else:
            got = {"n": 0}

            def on_data(nbytes, payload, now):
                got["n"] += nbytes
                if got["n"] >= self.size:
                    finish(now, got["n"])

            conn.on_data = on_data
        conn.on_connected = on_connected
        conn.on_error = on_error
        if self.idle_timeout_ns:
            conn.set_idle_timeout(self.idle_timeout_ns)
        conn.connect()

    def _next(self):
        if self.completed + self.failed >= self.total:
            self.api.log(
                f"tgen client done: {self.completed}/{self.total} ok, "
                f"{self.failed} failed"
            )
            self.api.exit(0 if self.failed == 0 else 1)
            return
        if self.mode != "parallel" and self._serial_queue:
            self._start_transfer(self._serial_queue.pop(0))

    def stop(self):
        pass
