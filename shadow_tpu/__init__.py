"""shadow_tpu — a TPU-native discrete-event network simulator.

A ground-up re-design of the capabilities of beastsam/shadow (a fork of the
Shadow discrete-event network simulator, see SURVEY.md) for TPU hardware:

- CPU side owns control flow: config, hosts, event queues, (managed) processes,
  syscall emulation, and the conservative round-based scheduler.
- TPU side owns the per-round network data plane: token-bucket bandwidth
  enforcement, (graph-node x graph-node) latency/loss lookup, packet-loss
  sampling with counter-based RNG, and all-pairs shortest-path routing — all as
  batched JAX kernels behind the ``scheduler_policy: tpu_batch`` config knob
  (SURVEY.md §7, BASELINE.json north_star).

Provenance note: the reference mount /root/reference was empty in every session
so far; component citations refer to SURVEY.md sections (which reconstruct the
upstream shadow/shadow architecture) rather than reference file:line.
"""

__version__ = "0.1.0"

from shadow_tpu.core.time import SimTime, EmulatedTime  # noqa: F401

__all__ = ["SimTime", "EmulatedTime", "__version__"]
