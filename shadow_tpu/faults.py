"""Deterministic fault injection: link/host churn, partitions, degradation.

Shadow's headline use case is protocol behavior under ADVERSE networks, so
adversity is a first-class simulated input here, not a test-only hook: a
``faults:`` config section declares a timeline of events —

- ``link_down`` / ``link_up``: cut (restore) every path between two sets of
  graph nodes. A single edge is the 1x1 case; two sets form a bipartite
  partition. Cut pairs take INF latency in the APSP table, so in-flight
  emissions route through the engines' existing blackhole path.
- ``link_degrade``: multiply path latency, add loss probability, and/or
  scale the NIC bandwidth of hosts attached to the targeted nodes. The
  modified latencies and drop thresholds flow into the per-unit plane, the
  columnar plane, and the device draw kernel identically (both gather from
  ``graph.latency_ns`` / ``params.drop_thresh`` at the emission barrier),
  so cross-plane and numpy/device bit-identity is preserved.
- ``host_down`` / ``host_up``: crash (reboot) hosts. A crash tears down the
  host's sockets and parked ingress units and cancels its application
  timers; queued network arrivals stay queued and are discarded at
  delivery (so event counts match the columnar plane, whose resolved
  arrivals live outside the heap). Surviving peers discover the failure
  through their own RTO exponential backoff, terminating in ``ETIMEDOUT``.
  A reboot respawns the host's processes as fresh instances.
- ``churn``: seeded random up/down cycling (exponential
  ``mean_uptime``/``mean_downtime`` draws from the counter-based fault RNG
  in core/rng.py), materialized into explicit host_down/host_up actions at
  startup — reproducible and independent of scheduler policy.

Timing model: the controller applies due actions at round starts, i.e. an
action at time t takes effect at the first round boundary >= t (the same
quantization the conservative-PDES barrier already imposes on cross-host
effects). The round grid is identical across scheduler policies, so fault
application instants are policy-independent; the skip-ahead path treats the
next pending action as a wake-up so idle simulations cannot jump over a
transition. Latency factors are >= 1 by validation, so the conservative
lookahead (round width <= min BASE latency) stays sound under degradation.

Faults run on every data plane, including the C engine: the injector
rewrites the effective latency/loss/rate matrices and bucket arrays IN
PLACE, and native/colcore holds raw pointers into those same numpy
buffers, so a transition is visible to the C barrier at exactly the same
instant as the Python ones. Host crash/reboot additionally drives the C
core's explicit teardown hooks (Core.host_crash/host_boot) through
Host.crash/reboot. Determinism across policies AND across the C/Python
twins under churn is asserted by tests/test_faults.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from shadow_tpu.config.schema import ConfigOptions
from shadow_tpu.core.rng import fault_rng
from shadow_tpu.core.time import SimTime, T_NEVER, format_time
from shadow_tpu.network.fluid import bytes_over, clamped_refill
from shadow_tpu.network.graph import INF_I64
from shadow_tpu.ops.prng import quantize_loss


@dataclass(eq=False)
class FaultAction:
    """One materialized timeline entry (config events + expanded churn).

    eq=False: actions are compared by IDENTITY — degrade_end removes its
    ``ref`` from the active list with list.remove, and a generated __eq__
    over the numpy node-set fields would raise (ambiguous array truth)
    whenever two same-time degrade windows coexist."""

    t: SimTime
    kind: str  # link_down | link_up | link_degrade | degrade_end |
    #          host_down | host_up
    src: Optional[np.ndarray] = None  # node indices (graph index space)
    dst: Optional[np.ndarray] = None
    host_ids: list = field(default_factory=list)
    latency_factor: float = 1.0
    loss_add: float = 0.0
    bandwidth_scale: float = 1.0
    ref: Optional["FaultAction"] = None  # degrade_end -> its link_degrade


def _resolve_nodes(gml_ids, graph, all_but=None) -> np.ndarray:
    if gml_ids:
        out = []
        for nid in gml_ids:
            if nid not in graph.node_id_map:
                raise ValueError(f"faults: node id {nid} not in graph")
            out.append(graph.node_id_map[nid])
        return np.array(sorted(set(out)), dtype=np.intp)
    # empty dst set = "everything except the src side"
    rest = sorted(set(range(graph.n_nodes)) - set(all_but.tolist()))
    if not rest:
        raise ValueError("faults: dst_nodes empty and src_nodes covers "
                         "the whole graph")
    return np.array(rest, dtype=np.intp)


def _resolve_hosts(patterns, by_name) -> list:
    """Host-name patterns -> sorted host ids; a trailing ``*`` globs over
    quantity-expanded templates (``n3_*`` matches ``n3_0..n3_K``)."""
    ids = set()
    for pat in patterns:
        if pat.endswith("*"):
            pre = pat[:-1]
            matched = [hid for name, hid in by_name.items()
                       if name.startswith(pre)]
            if not matched:
                raise ValueError(f"faults: host pattern {pat!r} matches "
                                 f"no hosts")
            ids.update(matched)
        else:
            if pat not in by_name:
                raise ValueError(f"faults: unknown host {pat!r}")
            ids.add(by_name[pat])
    return sorted(ids)


def build_timeline(cfg: ConfigOptions, graph, by_name: dict,
                   stop: SimTime) -> list[FaultAction]:
    """Materialize config events + churn draws into one sorted action list.

    Pure function of (config, graph, seed): no simulation state involved,
    so the timeline is identical under every policy and data plane.
    """
    actions: list[FaultAction] = []
    # cfg.faults is None when the injector exists only for runtime live
    # commands (live.ensure_fault_injector): empty config timeline.
    events = cfg.faults.events if cfg.faults is not None else []
    churn = cfg.faults.churn if cfg.faults is not None else []
    for ev in events:
        a = FaultAction(t=ev.time, kind=ev.kind,
                        latency_factor=ev.latency_factor,
                        loss_add=ev.loss_add,
                        bandwidth_scale=ev.bandwidth_scale)
        if ev.kind in ("link_down", "link_up", "link_degrade"):
            a.src = _resolve_nodes(ev.src_nodes, graph)
            a.dst = _resolve_nodes(ev.dst_nodes, graph, all_but=a.src)
        else:
            a.host_ids = _resolve_hosts(ev.hosts, by_name)
        actions.append(a)
        if ev.duration is not None:
            end_kind = {"link_down": "link_up", "host_down": "host_up",
                        "link_degrade": "degrade_end"}[ev.kind]
            actions.append(FaultAction(
                t=ev.time + ev.duration, kind=end_kind, src=a.src,
                dst=a.dst, host_ids=a.host_ids, ref=a))
    for ch in churn:
        for hid in _resolve_hosts(ch.hosts, by_name):
            rng = fault_rng(cfg.general.seed, hid)
            t = ch.start_time
            up = True
            while True:
                mean = ch.mean_uptime if up else ch.mean_downtime
                # inverse-CDF exponential from one uniform draw: fully
                # specified arithmetic (Generator.exponential's ziggurat
                # would also be deterministic, but this is auditable)
                u = float(rng.random())
                t += max(int(-mean * np.log1p(-u)), 1)
                if t >= stop:
                    break
                actions.append(FaultAction(
                    t=t, kind="host_down" if up else "host_up",
                    host_ids=[hid]))
                up = not up
    actions.sort(key=lambda a: a.t)  # stable: same-t keeps build order
    return actions


class FaultInjector:
    """Runtime state: applies due timeline actions at round starts.

    Link state is recomputed from scratch on every link transition (base
    matrices + active degrades in timeline order + cut overlay) rather
    than patched incrementally — G is small, transitions are rare, and
    recomputation makes overlapping windows and exact restoration trivial.
    The effective matrices are written IN PLACE into ``graph.latency_ns``
    and ``params.drop_thresh`` (the same objects every plane gathers from
    at its barrier), so a transition is visible to all planes atomically
    at the next barrier.
    """

    def __init__(self, controller) -> None:
        self.ctl = controller
        self.engine = controller.engine
        self.graph = controller.graph
        self.params = controller.engine.params
        cfg = controller.cfg
        stop = cfg.general.stop_time
        self.actions = build_timeline(cfg, self.graph, controller._by_name,
                                      stop)
        # host lifecycle events cover both process models: pyapp plugins
        # and managed executables share the kill/spawn crash contract
        # (ManagedProcess.kill SIGKILLs + reaps the real guest at the
        # boundary; Host.reboot respawns a fresh instance)
        self.idx = 0
        self.applied = 0
        #: telemetry hook (telemetry/collector.py::record_fault): called
        #: once per applied action with (now, rounds, action) so fault
        #: windows are annotated in the metrics stream. Application order
        #: is deterministic, so the annotations are too.
        self.on_apply = None
        g = self.graph.n_nodes
        self._base_lat = self.graph.latency_ns.copy()
        self._base_rel = self.graph.reliability.copy()
        self._base_rate_up = self.params.rate_up.copy()
        self._base_rate_down = self.params.rate_down.copy()
        self._cut = np.zeros((g, g), dtype=np.int32)
        self._degrades: list[FaultAction] = []

    def next_time(self) -> SimTime:
        """Time of the next unapplied action (a skip-ahead wake-up)."""
        return self.actions[self.idx].t if self.idx < len(self.actions) \
            else T_NEVER

    def insert_runtime(self, acts: list[FaultAction]) -> None:
        """Insert live-command actions (live.materialize_command) into the
        unapplied tail, keeping it t-sorted.  A runtime action lands AFTER
        existing actions with the same t (command application is ordered
        after the config timeline at a shared boundary), and never before
        ``idx`` — an action due now is picked up by the ``apply_due`` call
        at this same boundary."""
        for a in acts:
            i = len(self.actions)
            while i > self.idx and self.actions[i - 1].t > a.t:
                i -= 1
            self.actions.insert(i, a)

    def apply_due(self, now: SimTime) -> None:
        """Apply every action with t <= now. Called by the controller at
        round start, before any host event of the round executes."""
        if self.idx >= len(self.actions) or self.actions[self.idx].t > now:
            return
        link_dirty = False
        log = self.ctl.log
        while self.idx < len(self.actions) and self.actions[self.idx].t <= now:
            a = self.actions[self.idx]
            self.idx += 1
            self.applied += 1
            if a.kind == "link_down":
                self._cut[np.ix_(a.src, a.dst)] += 1
                self._cut[np.ix_(a.dst, a.src)] += 1
                link_dirty = True
            elif a.kind == "link_up":
                self._cut[np.ix_(a.src, a.dst)] -= 1
                self._cut[np.ix_(a.dst, a.src)] -= 1
                np.maximum(self._cut, 0, out=self._cut)
                link_dirty = True
            elif a.kind == "link_degrade":
                self._degrades.append(a)
                link_dirty = True
            elif a.kind == "degrade_end":
                self._degrades.remove(a.ref)
                link_dirty = True
            elif a.kind == "host_down":
                # multi-process sharding: the fault timeline broadcasts to
                # every shard (identical actions, identical cursor), but
                # host lifecycle mutations touch only OWNED hosts — a
                # non-owned Host object here is pure topology, and its
                # down flag is never read on this shard (arrivals for it
                # divert to the owning shard before delivery)
                for hid in a.host_ids:
                    if not self.ctl.owns(hid):
                        continue
                    h = self.ctl.hosts[hid]
                    if not h.down:
                        h.crash(now)
            elif a.kind == "host_up":
                for hid in a.host_ids:
                    if not self.ctl.owns(hid):
                        continue
                    h = self.ctl.hosts[hid]
                    if h.down:
                        h.reboot(now)
            log.debug(f"fault at {format_time(now)}: {a.kind} "
                      f"(scheduled {format_time(a.t)})")
            if self.on_apply is not None:
                self.on_apply(now, self.ctl.rounds, a)
        if link_dirty:
            self._recompute(now)

    # -- effective link state ----------------------------------------------
    def _recompute(self, now: SimTime) -> None:
        g = self.graph.n_nodes
        lat = self._base_lat.astype(np.float64)
        rel = self._base_rel.astype(np.float64)
        for a in self._degrades:
            mask = np.zeros((g, g), dtype=bool)
            mask[np.ix_(a.src, a.dst)] = True
            mask[np.ix_(a.dst, a.src)] = True
            if a.latency_factor != 1.0:
                lat[mask] = np.floor(lat[mask] * a.latency_factor)
            if a.loss_add != 0.0:
                rel[mask] = rel[mask] - a.loss_add
        rel = np.clip(rel, 0.0, 1.0)
        lat_i = np.minimum(lat, float(INF_I64)).astype(np.int64)
        cut = self._cut > 0
        lat_i[cut] = INF_I64
        rel[cut] = 0.0
        self.graph.latency_ns[...] = lat_i
        self.params.drop_thresh[...] = quantize_loss(rel.astype(np.float32))
        self._apply_rates(now)

    def _apply_rates(self, now: SimTime) -> None:
        host_node = self.params.host_node
        scale = np.ones(host_node.shape[0], dtype=np.float64)
        for a in self._degrades:
            if a.bandwidth_scale != 1.0:
                nodes = np.union1d(a.src, a.dst)
                scale[np.isin(host_node, nodes)] *= a.bandwidth_scale
        new_up = np.maximum(
            (self._base_rate_up * scale).astype(np.int64), 1)
        new_down = np.maximum(
            (self._base_rate_down * scale).astype(np.int64), 1)
        p = self.params
        if (np.array_equal(new_up, p.rate_up)
                and np.array_equal(new_down, p.rate_down)):
            return
        eng = self.engine
        # settle the round-quantized ingress buckets for the elapsed window
        # at the OLD rates, so the change takes effect exactly at `now`
        dt = now - eng._last_refill
        if dt > 0:
            add = clamped_refill(p.rate_down, p.cap_down, dt)
            eng.tokens_down += np.minimum(add, p.cap_down - eng.tokens_down)
            eng._last_refill = now
        # settle the closed-form egress buckets: available(now) under the
        # old rate becomes the new accounting base — exact continuity, and
        # departures computed after this barrier use the new rate
        b = eng.buckets
        changed = (new_up != p.rate_up)
        if changed.any():
            avail = (b.tokens + bytes_over(p.rate_up, now - b.t_base)
                     - b.debt)
            b.tokens[changed] = np.minimum(avail, p.cap_up)[changed]
            b.t_base[changed] = now
            b.debt[changed] = 0
        p.rate_up[...] = new_up
        p.rate_down[...] = new_down
