"""Command-line entry point.

Reference analog: the ``shadow [options] config.yaml`` binary (SURVEY.md §1
layer 1). Common options get first-class flags; every config option is
reachable via ``--set dotted.path=value`` (the CLI-overrides-YAML contract
of SURVEY.md §5.6).

Usage:
    python -m shadow_tpu [flags] config.yaml
"""

from __future__ import annotations

import argparse
import json
import sys

from shadow_tpu.config.schema import SCHEDULER_POLICIES


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow_tpu",
        description="TPU-native discrete-event network simulator",
    )
    p.add_argument("config", help="simulation YAML config file")
    p.add_argument("--stop-time", help="override general.stop_time")
    p.add_argument("--seed", type=int, help="override general.seed")
    p.add_argument("--parallelism", type=int, help="override general.parallelism")
    p.add_argument("--log-level", help="override general.log_level")
    p.add_argument("--data-directory", help="override general.data_directory")
    p.add_argument(
        "--scheduler-policy",
        choices=list(SCHEDULER_POLICIES),
        help="override experimental.scheduler_policy",
    )
    p.add_argument(
        "--shards", type=int, metavar="N",
        help="partition the host set across N worker processes "
        "(general.sim_shards): static id-modulo placement, conservative "
        "cross-shard windows, byte-identical results at any shard count",
    )
    p.add_argument(
        "--checkpoint-every", metavar="SIMTIME",
        help="write a full-state checkpoint every SIMTIME of simulated "
        "time (general.checkpoint_every); resumed runs are byte-identical "
        "to uninterrupted ones",
    )
    p.add_argument(
        "--checkpoint-dir",
        help="checkpoint directory (general.checkpoint_dir; default "
        "<data-directory>/checkpoints)",
    )
    p.add_argument(
        "--resume-from", metavar="CKPT",
        help="resume from a checkpoint file written by --checkpoint-every "
        "(the config must match the original run)",
    )
    p.add_argument(
        "--sample-every", metavar="SIMTIME",
        help="enable the telemetry subsystem and snapshot per-host/per-NIC "
        "state every SIMTIME of simulated time (telemetry.sample_every); "
        "metrics.jsonl + flows.jsonl land in the metrics directory and are "
        "byte-identical across scheduler policies and data planes",
    )
    p.add_argument(
        "--metrics-dir",
        help="enable telemetry and write metrics.jsonl/flows.jsonl here "
        "(telemetry.metrics_dir; default <data-directory>)",
    )
    p.add_argument(
        "--state-digest-every", type=int, metavar="N",
        help="determinism sentinel: emit a canonical state digest every N "
        "rounds to <data-directory>/state_digests.jsonl "
        "(general.state_digest_every); diff two streams with "
        "tools/bisect_divergence.py",
    )
    p.add_argument(
        "--live-endpoint", metavar="PATH",
        help="bind an AF_UNIX live-operations endpoint "
        "(general.live_endpoint): stream heartbeats/metrics/flow "
        "snapshots and accept runtime fault commands, applied at the "
        "next round boundary and logged to commands.jsonl; 'auto' = "
        "<data-directory>/live.sock",
    )
    p.add_argument(
        "--replay-commands", metavar="FILE",
        help="replay a recorded commands.jsonl (general.replay_commands): "
        "re-applies each command at its original round boundary, "
        "reproducing an interactively driven run byte-identically",
    )
    p.add_argument(
        "--supervise", action="store_true",
        help="run under the self-healing supervisor (general.supervise): "
        "liveness watchdogs name dead/wedged workers, the run auto-resumes "
        "from the newest complete checkpoint with a bounded restart budget, "
        "and an unrecoverable crash writes crash_report.json; tune with "
        "--set general.supervise.max_restarts / .backoff",
    )
    p.add_argument(
        "--set",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="override any config option by dotted path "
        "(e.g. --set experimental.runahead=5ms); repeatable",
    )
    p.add_argument(
        "--show-config", action="store_true",
        help="print the resolved configuration and exit",
    )
    p.add_argument(
        "--json-summary", action="store_true",
        help="print the end-of-run summary as one JSON line on stdout",
    )
    p.add_argument("--quiet", action="store_true", help="no log mirroring to stderr")
    return p


def overrides_from_args(args: argparse.Namespace) -> dict:
    ov: dict = {}
    flag_map = {
        "stop_time": "general.stop_time",
        "seed": "general.seed",
        "parallelism": "general.parallelism",
        "log_level": "general.log_level",
        "data_directory": "general.data_directory",
        "scheduler_policy": "experimental.scheduler_policy",
        "shards": "general.sim_shards",
        "checkpoint_every": "general.checkpoint_every",
        "checkpoint_dir": "general.checkpoint_dir",
        "state_digest_every": "general.state_digest_every",
        "sample_every": "telemetry.sample_every",
        "metrics_dir": "telemetry.metrics_dir",
        "live_endpoint": "general.live_endpoint",
        "replay_commands": "general.replay_commands",
    }
    for attr, key in flag_map.items():
        val = getattr(args, attr)
        if val is not None:
            ov[key] = val
    if args.supervise:
        # boolean flag: schema normalizes True -> defaults
        ov["general.supervise"] = True
    for item in args.set:
        if "=" not in item:
            print(f"shadow_tpu: --set expects KEY=VALUE, got {item!r}",
                  file=sys.stderr)
            raise SystemExit(2)
        k, v = item.split("=", 1)
        import yaml as _yaml

        ov[k] = _yaml.safe_load(v)
    return ov


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "fork":
        # first-class fork verb: `python -m shadow_tpu fork cfg.yaml
        # --from CKPT --branches branches.yaml` — checkpoint-forked
        # what-if trees (shadow_tpu/forks.py; also reachable as
        # `python -m shadow_tpu.fleet sweep --fork-from`)
        from shadow_tpu.forks import main as _fork_main

        return _fork_main(argv[1:])
    args = build_parser().parse_args(argv)
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    try:
        cfg = load_config(args.config, overrides_from_args(args))
    except FileNotFoundError:
        print(f"shadow_tpu: config file not found: {args.config}", file=sys.stderr)
        return 2
    except ValueError as exc:
        print(f"shadow_tpu: {exc}", file=sys.stderr)
        return 2
    if args.show_config:
        import dataclasses

        def enc(o):
            if dataclasses.is_dataclass(o):
                return dataclasses.asdict(o)
            return str(o)

        print(json.dumps(
            {
                "general": dataclasses.asdict(cfg.general),
                "network": cfg.network,
                "experimental": dataclasses.asdict(cfg.experimental),
                "hosts": [dataclasses.asdict(h) for h in cfg.hosts],
                **({"faults": dataclasses.asdict(cfg.faults)}
                   if cfg.faults is not None else {}),
                **({"telemetry": dataclasses.asdict(cfg.telemetry)}
                   if cfg.telemetry is not None else {}),
            },
            indent=2, default=enc,
        ))
        return 0

    if cfg.general.supervise is not None:
        # self-healing path (shadow_tpu/supervise.py): wraps the sharded or
        # single-process run in restart-on-failure with checkpoint resume;
        # the recovered result is byte-identical to an uninterrupted run
        from shadow_tpu.checkpoint import CheckpointError
        from shadow_tpu.supervise import SupervisorGaveUp, run_supervised

        try:
            result = run_supervised(cfg, mirror_log=not args.quiet,
                                    resume_from=args.resume_from or None)
        except FileNotFoundError as exc:
            print(f"shadow_tpu: checkpoint not found: {exc}", file=sys.stderr)
            return 2
        except (ValueError, CheckpointError) as exc:
            print(f"shadow_tpu: {exc}", file=sys.stderr)
            return 2
        except SupervisorGaveUp as exc:
            # restart budget exhausted or unrecoverable failure class; the
            # structured post-mortem is in <data_dir>/crash_report.json
            print(f"shadow_tpu: {exc}", file=sys.stderr)
            return 1
    elif cfg.general.sim_shards > 1:
        # multi-process host partitioning (shadow_tpu/parallel/shards.py):
        # the parent coordinator replaces the single-process controller;
        # results are byte-identical at any shard count
        from shadow_tpu.checkpoint import CheckpointError
        from shadow_tpu.parallel.shards import run_sharded

        try:
            result = run_sharded(cfg, mirror_log=not args.quiet,
                                 resume_from=args.resume_from or None)
        except FileNotFoundError as exc:
            print(f"shadow_tpu: checkpoint not found: {exc}",
                  file=sys.stderr)
            return 2
        except (ValueError, CheckpointError) as exc:
            print(f"shadow_tpu: {exc}", file=sys.stderr)
            return 2
    elif args.resume_from:
        from shadow_tpu.checkpoint import CheckpointError, load_checkpoint

        try:
            controller, resume_at = load_checkpoint(
                args.resume_from, cfg, mirror_log=not args.quiet)
        except FileNotFoundError:
            print(f"shadow_tpu: checkpoint not found: {args.resume_from}",
                  file=sys.stderr)
            return 2
        except CheckpointError as exc:
            print(f"shadow_tpu: {exc}", file=sys.stderr)
            return 2
        result = controller.run(resume_at=resume_at)
    else:
        try:
            controller = Controller(cfg, mirror_log=not args.quiet)
        except ValueError as exc:
            # build-time refusals (checkpoint-unsupported configs, unknown
            # fault targets, missing executables) keep the clean one-line
            # error contract instead of a traceback
            print(f"shadow_tpu: {exc}", file=sys.stderr)
            return 2
        result = controller.run()
    if args.json_summary:
        print(json.dumps(result))
    if result.get("exit_reason") == "interrupted":
        # conventional signal exit status; the JSON summary above is still
        # a valid (partial) artifact
        import signal as _signal

        sig = result.get("interrupt_signal", "SIGINT")
        return 128 + int(getattr(_signal.Signals, sig, _signal.SIGINT))
    return 1 if result["process_errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
