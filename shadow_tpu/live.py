"""Live operations plane: streaming telemetry, runtime fault commands,
and time-travel debugging (ROADMAP item 5).

Three capabilities over one AF_UNIX endpoint (``general.live_endpoint``):

* **Streaming** — a running sim (or sharded run, or fleet sweep)
  broadcasts newline-framed JSON records: heartbeats, raw
  ``metrics.jsonl``/``flows.jsonl`` lines as they are written, flow-group
  percentile snapshots, applied commands, and per-shard/per-seed status.
  ``tools/metrics_report.py --follow`` renders them live.  Supervised
  runs (``--supervise``, shadow_tpu/supervise.py) additionally publish
  ``{"type": "supervisor", "event": "restart", ...}`` records naming the
  failure, the restart attempt, and the checkpoint being resumed from;
  fleet sweeps publish ``seed_retry`` alongside ``seed_failed``.

* **Runtime fault commands** — clients send the ``faults:`` timeline
  verbs (``link_down``/``link_up``/``link_degrade``/``host_down``/
  ``host_up``) plus ``pause``/``resume``/``checkpoint_now``/``stop`` as
  JSON objects on the same socket.  Commands are validated through the
  config-grade parser, quantized to the NEXT round boundary (the same
  discipline as the config fault timeline), applied there, and appended
  to ``commands.jsonl`` in the run directory.  An interactively driven
  run replays byte-identically from config + command log via
  ``general.replay_commands`` / ``--replay-commands``.

* **Time travel** — ``python -m shadow_tpu.live jump RUN_DIR --round R
  --config CFG`` restores the nearest single-process checkpoint strictly
  below round R, re-executes to R (determinism makes replay exact),
  recomputes the state digest, compares it against the recorded
  ``state_digests.jsonl`` entry, dumps host state, and optionally opens
  a REPL at that boundary.  ``--from-bisect`` consumes the JSON emitted
  by ``tools/bisect_divergence.py --json`` so "first divergent round"
  becomes "a shell AT that round".

Determinism contract: the endpoint itself is a pure wall-clock plane
(the PR 8 DrawServer discipline — accept immediately, serve on niced
sibling threads, never block the sim thread; a slow or absent client
drops records, never stalls rounds).  The only way a client affects
simulation state is through the command path, which is quantized to
round boundaries and logged with sim timestamps — wall time never
leaks into simulation results.
"""

from __future__ import annotations

import collections
import json
import os
import socket
import sys
import threading
import time as _walltime  # detlint: ok(wallclock): the live plane is pure wall-clock transport; commands only act at round boundaries via the logged sim timestamp
from pathlib import Path

#: Canonical command-log artifact in the run directory.
COMMANDS_FILE = "commands.jsonl"
#: Socket filename for ``general.live_endpoint: auto``.
SOCKET_NAME = "live.sock"
#: Framed-record protocol version (bumped on incompatible changes).
PROTOCOL_VERSION = 1
#: Control verbs (no fault payload; never materialize FaultActions).
CONTROL_KINDS = ("pause", "resume", "checkpoint_now", "stop")
#: All keys a command object may carry. ``_parse_fault_event`` silently
#: ignores unknown keys, so the whitelist check lives here: a typo'd
#: parameter must be refused, not dropped.
_COMMAND_KEYS = frozenset((
    "cmd", "src_nodes", "dst_nodes", "hosts",
    "latency_factor", "loss_add", "bandwidth_scale", "duration",
))
#: Per-client outbound bound. A reader this far behind loses the OLDEST
#: records (drop-oldest keeps the stream current and the sim unblocked).
MAX_QUEUE = 4096
#: AF_UNIX sun_path limit (about 108 bytes on Linux); refuse early with
#: a named error instead of a cryptic bind() failure.
_MAX_SOCKET_PATH = 100


def _dumps(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def resolve_endpoint(value, data_dir) -> str:
    """``auto`` means ``<data_dir>/live.sock``; anything else is a path."""
    if str(value) == "auto":
        return str(Path(data_dir) / SOCKET_NAME)
    return str(value)


def default_endpoint(path) -> str:
    """CLI convenience: a run directory means its ``live.sock``."""
    p = Path(path)
    if p.is_dir():
        return str(p / SOCKET_NAME)
    return str(p)


def command_log_path(data_dir) -> Path:
    return Path(data_dir) / COMMANDS_FILE


# ---------------------------------------------------------------------------
# Command validation + materialization
# ---------------------------------------------------------------------------

def normalize_command(payload) -> dict:
    """Validate one wire command and return its canonical dict.

    Fault verbs go through ``_parse_fault_event`` — the exact validator
    the config ``faults:`` timeline uses — so a live command can never
    express a fault the config language could not. The result is plain
    dict/list/str/int/float (JSON- and marshal-safe: it rides both the
    command log and the shard marker protocol).
    """
    from shadow_tpu.config.schema import FAULT_KINDS, _parse_fault_event

    if not isinstance(payload, dict):
        raise ValueError(
            f"command must be a JSON object, got {type(payload).__name__}")
    kind = payload.get("cmd")
    if kind in CONTROL_KINDS:
        extra = sorted(set(payload) - {"cmd"})
        if extra:
            raise ValueError(f"command {kind!r} takes no parameters "
                             f"(got {extra})")
        return {"cmd": kind}
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"unknown command {kind!r} (want one of "
            f"{sorted(FAULT_KINDS) + list(CONTROL_KINDS)})")
    unknown = sorted(set(payload) - _COMMAND_KEYS)
    if unknown:
        raise ValueError(f"command {kind!r}: unknown keys {unknown}")
    e = {k: v for k, v in payload.items() if k != "cmd"}
    e["kind"] = kind
    e["time"] = 0  # commands take effect at the next round boundary
    ev = _parse_fault_event(e)
    out = {"cmd": kind}
    if ev.src_nodes:
        out["src_nodes"] = list(ev.src_nodes)
    if ev.dst_nodes:
        out["dst_nodes"] = list(ev.dst_nodes)
    if ev.hosts:
        out["hosts"] = list(ev.hosts)
    if kind == "link_degrade":
        out["latency_factor"] = float(ev.latency_factor)
        out["loss_add"] = float(ev.loss_add)
        out["bandwidth_scale"] = float(ev.bandwidth_scale)
    if ev.duration is not None:
        # canonical duration is an explicit-unit string: parse_time reads
        # a bare int as SECONDS (the config convention), so a ns integer
        # would not survive the log-load re-validation round trip
        out["duration"] = f"{int(ev.duration)} ns"
    return out


def materialize_command(controller, norm, t):
    """Turn a normalized fault command into ``FaultAction``s at sim time
    ``t`` (a round boundary) — the runtime mirror of
    ``faults.build_timeline``'s per-event block, including the paired
    end-action when ``duration`` is given."""
    from shadow_tpu.faults import FaultAction, _resolve_hosts, _resolve_nodes

    kind = norm["cmd"]
    a = FaultAction(
        t=t, kind=kind,
        latency_factor=float(norm.get("latency_factor", 1.0)),
        loss_add=float(norm.get("loss_add", 0.0)),
        bandwidth_scale=float(norm.get("bandwidth_scale", 1.0)))
    if kind in ("link_down", "link_up", "link_degrade"):
        a.src = _resolve_nodes(norm.get("src_nodes") or [], controller.graph)
        a.dst = _resolve_nodes(norm.get("dst_nodes") or [], controller.graph,
                               all_but=a.src)
    else:
        # host lifecycle works for BOTH process models: pyapp plugins and
        # managed executables expose the same kill/spawn crash contract
        # (ManagedProcess.kill SIGKILLs + reaps the real guest; reboot
        # respawns a fresh instance, deterministically at the boundary)
        a.host_ids = _resolve_hosts(norm.get("hosts") or [],
                                    controller._by_name)
    acts = [a]
    dur = norm.get("duration")
    if dur is not None:
        from shadow_tpu.core.time import parse_time

        end_kind = {"link_down": "link_up", "host_down": "host_up",
                    "link_degrade": "degrade_end"}[kind]
        acts.append(FaultAction(t=t + parse_time(dur), kind=end_kind,
                                src=a.src, dst=a.dst, host_ids=a.host_ids,
                                ref=a))
    return acts


def ensure_fault_injector(controller):
    """Lazily create the injector at the boundary where the FIRST
    runtime fault command lands.  A commandless live run keeps
    ``faults_active`` off and stays byte-identical to a detached run;
    flipping it here is deterministic because the live leg and its
    replay flip it at the same sim boundary (the counters it gates are
    plane-mirrored via ``Core_set_faults_active``)."""
    if controller.faults is not None:
        return controller.faults
    from shadow_tpu.faults import FaultInjector

    controller.engine.faults_active = True
    for h in controller.hosts:
        h.faults_active = True
    core = getattr(controller, "_c_core", None)
    if core is not None:
        core.set_faults_active(True)
    controller.faults = FaultInjector(controller)
    if (controller.telemetry is not None
            and getattr(controller, "shard_id", 0) == 0):
        controller.faults.on_apply = controller.telemetry.record_fault
    return controller.faults


def apply_command(controller, norm, now):
    """Apply one normalized fault command at the boundary ``now``."""
    faults = ensure_fault_injector(controller)
    faults.insert_runtime(materialize_command(controller, norm, now))
    return faults


# ---------------------------------------------------------------------------
# Command log
# ---------------------------------------------------------------------------

def format_command_record(norm, seq, rnd, t, wall_only=False) -> str:
    """One canonical ``commands.jsonl`` line.  ``wall_only`` marks
    records (pause/resume) that never touch sim state — replay skips
    them, so a paused-and-resumed run and its replay write byte-equal
    fault/control entries."""
    rec = {"cmd": norm, "round": int(rnd), "seq": int(seq), "t": int(t)}
    if wall_only:
        rec["wall_only"] = True
    return _dumps(rec)


def append_command_lines(data_dir, lines) -> None:
    if not lines:
        return
    p = command_log_path(data_dir)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "a") as f:
        f.write("\n".join(lines) + "\n")


def load_command_log(path):
    """Parse + re-validate a ``commands.jsonl``.  File order is
    application order; ``t`` (the boundary each command applied at)
    must be non-decreasing."""
    p = Path(path)
    recs = []
    with open(p) as f:
        for i, raw in enumerate(f):
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError as exc:
                raise ValueError(f"{p}:{i + 1}: bad command record ({exc})")
            for k in ("cmd", "round", "seq", "t"):
                if k not in rec:
                    raise ValueError(
                        f"{p}:{i + 1}: command record missing {k!r}")
            rec["cmd"] = normalize_command(rec["cmd"])
            recs.append(rec)
    for a, b in zip(recs, recs[1:]):
        if b["t"] < a["t"]:
            raise ValueError(
                f"{p}: command log goes backwards in sim time "
                f"(seq {a['seq']} at t={a['t']} then seq {b['seq']} "
                f"at t={b['t']})")
    return recs


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class _LiveClient:
    """One accepted connection: a reader thread (commands in) and a
    writer thread (records out) around a bounded drop-oldest queue."""

    def __init__(self, server, sock):
        self.server = server
        self.sock = sock
        self._cond = threading.Condition()
        self._queue = collections.deque()
        self._dropped = 0
        self._dead = False
        threading.Thread(target=self._write_loop, daemon=True,
                         name="shadow-live-write").start()
        threading.Thread(target=self._read_loop, daemon=True,
                         name="shadow-live-read").start()

    def enqueue(self, line) -> None:
        with self._cond:
            if self._dead:
                return
            if len(self._queue) >= MAX_QUEUE:
                self._queue.popleft()
                self._dropped += 1
            self._queue.append(line)
            self._cond.notify()

    def flush(self, deadline) -> None:
        while _walltime.monotonic() < deadline:
            with self._cond:
                if not self._queue or self._dead:
                    return
            _walltime.sleep(0.01)

    def _write_loop(self) -> None:
        try:
            while True:
                with self._cond:
                    while not self._queue and not self._dead:
                        self._cond.wait(timeout=1.0)
                    if self._dead and not self._queue:
                        return
                    batch = list(self._queue)
                    self._queue.clear()
                self.sock.sendall(("\n".join(batch) + "\n").encode())
        except OSError:
            pass
        finally:
            self.close()

    def _read_loop(self) -> None:
        buf = b""
        try:
            while True:
                chunk = self.sock.recv(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line.strip():
                        self._handle(line)
        except OSError:
            pass
        finally:
            self.close()

    def _handle(self, line) -> None:
        try:
            norm = normalize_command(json.loads(line))
            refused = self.server._refuse(norm)
            if refused:
                raise ValueError(refused)
        except ValueError as exc:
            self.enqueue(_dumps({"type": "error", "error": str(exc)}))
            return
        n = self.server._submit(norm)
        self.enqueue(_dumps({"type": "ack", "cmd": norm, "n": n}))

    def close(self) -> None:
        with self._cond:
            if self._dead:
                return
            self._dead = True
            self._cond.notify_all()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.server._drop(self)


class LiveServer:
    """AF_UNIX live endpoint.  The sim thread only ever calls
    :meth:`publish` / :meth:`publish_stream` (non-blocking broadcast)
    and :meth:`poll_commands` (drain validated commands); all socket
    work runs on niced daemon threads.

    ``refuse(norm) -> str | None`` lets the owner veto commands its
    topology cannot honor (sharded runs refuse pause/resume; fleet
    sweep endpoints are status-only).
    """

    def __init__(self, address, log=None, refuse=None):
        self.address = str(address)
        if len(self.address.encode()) > _MAX_SOCKET_PATH:
            raise ValueError(
                f"live endpoint path exceeds the AF_UNIX limit "
                f"(~{_MAX_SOCKET_PATH} bytes): {self.address!r}")
        self._refuse_hook = refuse
        self._clients = []
        self._clients_lock = threading.Lock()
        self._cmd_cond = threading.Condition()
        self._commands = collections.deque()
        self._submitted = 0
        self._closing = False
        path = Path(self.address)
        if path.parent != Path("."):
            path.parent.mkdir(parents=True, exist_ok=True)
        if path.exists():
            path.unlink()  # stale socket from a previous run
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.address)
        self._listener.listen(8)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="shadow-live-accept").start()
        if log is not None:
            log.info(f"live endpoint listening on {self.address}")

    def _accept_loop(self) -> None:
        try:
            # stay out of the sim thread's way (the DrawServer discipline)
            os.setpriority(os.PRIO_PROCESS, threading.get_native_id(), 5)
        except (AttributeError, OSError):
            pass
        while not self._closing:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            client = _LiveClient(self, sock)
            with self._clients_lock:
                self._clients.append(client)
            client.enqueue(_dumps({"type": "hello", "v": PROTOCOL_VERSION,
                                   "pid": os.getpid()}))

    # -- sim-thread API ---------------------------------------------------

    def publish(self, rec) -> None:
        """Broadcast one record to all connected clients; never blocks."""
        with self._clients_lock:
            clients = list(self._clients)
        if not clients:
            return
        line = _dumps(rec)
        for c in clients:
            c.enqueue(line)

    def publish_stream(self, name, lines) -> None:
        """Broadcast raw artifact lines (metrics.jsonl / flows.jsonl) as
        they are written, wrapped so followers can tee them verbatim."""
        with self._clients_lock:
            clients = list(self._clients)
        if not clients:
            return
        out = [_dumps({"type": "stream", "stream": name, "line": ln})
               for ln in lines]
        for c in clients:
            for line in out:
                c.enqueue(line)

    def poll_commands(self, timeout=0.0):
        """Drain all validated commands received so far (optionally
        waiting up to ``timeout`` wall seconds for the first one)."""
        with self._cmd_cond:
            if timeout and not self._commands:
                self._cmd_cond.wait(timeout)
            out = list(self._commands)
            self._commands.clear()
        return out

    # -- client-thread internals ------------------------------------------

    def _refuse(self, norm):
        if self._refuse_hook is not None:
            return self._refuse_hook(norm)
        return None

    def _submit(self, norm) -> int:
        with self._cmd_cond:
            self._commands.append(norm)
            self._submitted += 1
            n = self._submitted
            self._cmd_cond.notify_all()
        return n

    def _drop(self, client) -> None:
        with self._clients_lock:
            try:
                self._clients.remove(client)
            except ValueError:
                pass

    def close(self) -> None:
        self._closing = True
        deadline = _walltime.monotonic() + 1.0
        with self._clients_lock:
            clients = list(self._clients)
        for c in clients:
            c.flush(deadline)
            c.close()
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            Path(self.address).unlink()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Client helpers
# ---------------------------------------------------------------------------

def connect(address, timeout=10.0):
    """Connect to a live endpoint, retrying while the run binds it."""
    deadline = _walltime.monotonic() + timeout
    while True:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.connect(str(address))
            return s
        except OSError:
            s.close()
            if _walltime.monotonic() >= deadline:
                raise
            _walltime.sleep(0.02)


def stream_records(address, timeout=10.0):
    """Yield parsed records from a live endpoint until it closes."""
    s = connect(address, timeout)
    buf = b""
    try:
        while True:
            chunk = s.recv(65536)
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if line.strip():
                    yield json.loads(line)
    finally:
        s.close()


def send_command(address, payload, timeout=10.0):
    """Send one command and wait for its ``ack``/``error`` record
    (broadcast records interleave on the same socket and are skipped)."""
    s = connect(address, timeout)
    try:
        s.settimeout(timeout)
        s.sendall((json.dumps(payload) + "\n").encode())
        buf = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                raise OSError("live endpoint closed before acking")
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                rec = json.loads(line)
                if rec.get("type") in ("ack", "error"):
                    return rec
    finally:
        s.close()


# ---------------------------------------------------------------------------
# Time-travel debugging
# ---------------------------------------------------------------------------

def _jump_overrides(run_dir, inspect_dir) -> dict:
    """Volatile-key overrides for an inspection run: write nothing into
    the original run dir, bind no endpoint, force single-process, and
    replay the recorded command log if one exists."""
    over = {
        "general.data_directory": str(inspect_dir),
        "general.checkpoint_every": None,
        "general.checkpoint_dir": None,
        "general.state_digest_every": 0,
        "general.progress": False,
        "general.heartbeat_interval": None,
        "general.live_endpoint": None,
        "general.sim_shards": 1,
    }
    cl = command_log_path(run_dir)
    if cl.is_file():
        over["general.replay_commands"] = str(cl)
    return over


def _find_checkpoint(run_dir, target_round):
    """Newest single-process checkpoint strictly below ``target_round``
    (strict so the jump always re-executes >= 1 round and the digest is
    computed with the true round_end, matching the recorded stream).
    Sharded checkpoint sets are skipped — the jump re-executes from
    round 0 at shards=1 instead, which is byte-identical."""
    from shadow_tpu import checkpoint as _ckpt

    best = None
    ckpt_dir = Path(run_dir) / "checkpoints"
    if not ckpt_dir.is_dir():
        return None
    for p in sorted(ckpt_dir.glob("ckpt_t*.ckpt")):
        if ".shard" in p.name:
            continue
        try:
            h = _ckpt.read_header(str(p))
        except Exception:
            continue
        if int(h.get("sim_shards", 1) or 1) != 1:
            continue
        r = int(h.get("rounds", 0))
        if r < target_round and (best is None or r > best[0]):
            best = (r, p)
    return best


def _digest_record(run_dir, rnd):
    p = Path(run_dir) / "state_digests.jsonl"
    if not p.is_file():
        return None
    with open(p) as f:
        for raw in f:
            raw = raw.strip()
            if raw:
                rec = json.loads(raw)
                if rec.get("round") == rnd:
                    return rec
    return None


def jump(run_dir, target_round, config_path, repl=False, inspect_dir=None,
         show_hosts=None, out=print) -> int:
    """Restore the nearest checkpoint < ``target_round``, re-execute to
    it, verify the recomputed state digest against the recorded one, and
    dump (or REPL over) host state at that boundary.  Returns 0 on
    digest match (or when no digest was recorded), 1 on mismatch."""
    from shadow_tpu import checkpoint as _ckpt
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.telemetry.collector import host_columns

    run_dir = Path(run_dir)
    target_round = int(target_round)
    if target_round < 1:
        raise ValueError("--round must be >= 1")
    if inspect_dir is None:
        inspect_dir = run_dir / f"jump_r{target_round}"
    cfg = load_config(str(config_path), _jump_overrides(run_dir, inspect_dir))
    # a dotted override cannot REMOVE a section: silence telemetry on the
    # object (result-transparent — streams are volatile planes)
    cfg.telemetry = None

    best = _find_checkpoint(run_dir, target_round)
    if best is not None:
        ckpt_round, path = best
        ctl, resume_at = _ckpt.load_checkpoint(str(path), cfg,
                                               mirror_log=False)
        out(f"jump: restored {path.name} (round {ckpt_round}); "
            f"re-executing {target_round - ckpt_round} round(s)")
    else:
        ctl, resume_at = Controller(cfg, mirror_log=False), None
        out(f"jump: no single-process checkpoint below round "
            f"{target_round}; re-executing from round 0")

    state = {}

    def _at_round(controller, round_end):
        g, hosts = _ckpt.state_digest(controller, round_end)
        state.update(digest=g, hosts=hosts, t=round_end,
                     round=controller.rounds)
        rec = _digest_record(run_dir, controller.rounds)
        state["recorded"] = rec
        out(f"jump: at round {controller.rounds} (t={round_end} ns)")
        out(f"  state digest: {g}")
        if rec is None:
            out(f"  no recorded digest for round {controller.rounds} "
                f"in {run_dir / 'state_digests.jsonl'}")
        elif rec.get("digest") == g:
            out(f"  recorded digest: {rec['digest']}  [MATCH]")
        else:
            out(f"  recorded digest: {rec.get('digest')}  [MISMATCH]")
        names = list(show_hosts) if show_hosts else \
            sorted(h.name for h in controller.hosts)[:8]
        cols = host_columns(controller.hosts)
        by_name = {h.name: i for i, h in enumerate(controller.hosts)}
        for name in names:
            i = by_name.get(name)
            if i is None:
                out(f"  host {name!r}: not in this simulation")
                continue
            row = " ".join(f"{k}={v[i]}" for k, v in sorted(cols.items()))
            out(f"  host {name}: digest={hosts[name]} {row}")
        if repl:
            import code
            ns = {"controller": controller, "ctl": controller,
                  "hosts": controller.hosts, "by_name": by_name,
                  "digest": g, "host_digests": hosts, "columns": cols,
                  "round": controller.rounds, "t": round_end}
            code.interact(
                banner=(f"shadow_tpu live jump: round {controller.rounds} "
                        f"(t={round_end} ns). Locals: "
                        f"{', '.join(sorted(ns))}. Ctrl-D resumes exit."),
                local=ns)

    ctl.stop_after_round = target_round
    ctl.on_stop_round = _at_round
    ctl.run(resume_at=resume_at)
    if "digest" not in state:
        raise ValueError(
            f"simulation ended at round {ctl.rounds}, before the "
            f"requested round {target_round}")
    rec = state["recorded"]
    if rec is not None and rec.get("digest") != state["digest"]:
        return 1
    return 0


# ---------------------------------------------------------------------------
# CLI: python -m shadow_tpu.live {jump,send,tail}
# ---------------------------------------------------------------------------

def _read_bisect(src):
    raw = sys.stdin.read() if src == "-" else Path(src).read_text()
    rec = None
    for line in raw.splitlines():
        line = line.strip()
        if line.startswith("{"):
            rec = json.loads(line)
    if rec is None:
        raise ValueError(f"no JSON record found in bisect output {src!r}")
    return rec


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m shadow_tpu.live",
        description="Live-operations client + time-travel debugger")
    sub = ap.add_subparsers(dest="op", required=True)

    j = sub.add_parser("jump", help="restore nearest checkpoint and "
                                    "re-execute to a round")
    j.add_argument("run_dir", help="original run's data directory")
    j.add_argument("--round", type=int, dest="round_", default=None,
                   help="target round (or use --from-bisect)")
    j.add_argument("--config", required=True,
                   help="the config the run was started from")
    j.add_argument("--from-bisect", default=None,
                   help="bisect_divergence --json output file, or - "
                        "for stdin")
    j.add_argument("--repl", action="store_true",
                   help="open an interactive shell at the target round")
    j.add_argument("--inspect-dir", default=None,
                   help="scratch data dir for the inspection run "
                        "(default: RUN_DIR/jump_rR)")
    j.add_argument("--hosts", default=None,
                   help="comma-separated host names to dump "
                        "(default: from bisect, else first 8)")

    s = sub.add_parser("send", help="send one command, print the ack")
    s.add_argument("endpoint", help="socket path or run directory")
    s.add_argument("command", help='JSON object, e.g. '
                                   '\'{"cmd":"link_down","src_nodes":["3"]}\'')

    t = sub.add_parser("tail", help="stream records to stdout")
    t.add_argument("endpoint", help="socket path or run directory")
    t.add_argument("--max", type=int, default=0,
                   help="exit after N records (0 = until the run ends)")

    args = ap.parse_args(argv)
    if args.op == "jump":
        target, hosts = args.round_, None
        if args.from_bisect is not None:
            rec = _read_bisect(args.from_bisect)
            if rec.get("kind") == "identical":
                print("bisect found no divergence; nothing to jump to")
                return 0
            target = rec.get("round") if target is None else target
            hosts = rec.get("hosts") or None
        if target is None:
            ap.error("jump needs --round or --from-bisect")
        if args.hosts:
            hosts = [h for h in args.hosts.split(",") if h]
        return jump(args.run_dir, target, args.config, repl=args.repl,
                    inspect_dir=args.inspect_dir, show_hosts=hosts)
    if args.op == "send":
        rec = send_command(default_endpoint(args.endpoint),
                           json.loads(args.command))
        print(_dumps(rec))
        return 0 if rec.get("type") == "ack" else 1
    if args.op == "tail":
        n = 0
        for rec in stream_records(default_endpoint(args.endpoint)):
            print(_dumps(rec), flush=True)
            n += 1
            if args.max and n >= args.max:
                break
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
