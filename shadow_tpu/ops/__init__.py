"""Device kernels for the per-round network data plane.

These are the TPU-native replacements for the reference's Router/Relay token
bucket and routing-lookup hot path (SURVEY.md §3.4, BASELINE.json
north_star). Every kernel has a numpy twin used by the CPU scheduler
policies; the two must agree bit-for-bit (tested in tests/test_bitmatch.py).
"""
