"""Central JAX runtime configuration for shadow_tpu kernels.

Import-and-call before any kernel dispatch. Enables the persistent
compilation cache so the (20-40 s on TPU) first-compile cost of each padded
batch shape is paid once per machine, not once per process — a simulation
binary is a short-lived CLI, unlike a training job.
"""

from __future__ import annotations

import os

_done = False


def configure() -> None:
    global _done
    if _done:
        return
    _done = True
    import jax

    # SHADOW_FORCE_CPU_DEVICES=N: run on an N-virtual-device CPU platform
    # (the pod stand-in for mesh benchmarks/tests — SURVEY.md §4). Env
    # vars like JAX_PLATFORMS are read at jax import, which sitecustomize
    # may have pinned already; config updates work until backend init.
    force_cpu = os.environ.get("SHADOW_FORCE_CPU_DEVICES")
    if force_cpu:
        try:
            jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_num_cpu_devices", int(force_cpu))
        except (RuntimeError, AttributeError):
            # RuntimeError: backends already initialized (run on what
            # exists). AttributeError: this jax predates
            # jax_num_cpu_devices — the XLA_FLAGS path covers it.
            pass

    cache = os.environ.get(
        "SHADOW_TPU_JAX_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "shadow_tpu", "jax"),
    )
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        # cache even fast compiles: a simulation CLI pays per-process
        # compile cost on every invocation, and the window kernels
        # compile in ~0.1-0.3 s each — below the old 0.5 s threshold, so
        # they were rebuilt every process
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:  # cache is an optimization; never fail the sim for it
        pass
