"""Batched integer kernels for the device-resident columnar transport.

The third twin surface (PR 11): `network/transport.py` is the scalar
Python oracle, `native/colcore/colcore.c` the scalar C twin, and this
module the COLUMNAR twin — the same endpoint arithmetic expressed over
struct-of-arrays int64 columns, parameterized over the array namespace
(numpy or jax.numpy, the ops/prng.py discipline) so the numpy path and
the accelerator path execute the exact same integer operations.

Twin discipline: the transport constants and the per-congestion-control
integer literals below are DELIBERATE duplicates of the scalar twins —
like colcore.c, a kernel cannot import its constants from the module it
must agree with and still be audited for drift.  tools/twincheck
cross-checks all three surfaces (`kernel-const-drift:*` /
`kernel-cc-drift:*` findings); editing a literal in any one twin without
the other two fails CI by name.

Bit-exactness argument (why numpy/jax int64 equals scalar Python int):
every scalar operand is clamped below 2**63 by the transport's documented
clamps (cwnd <= 2**45, newly <= 2**20 in cubic, |d| <= 2e5, w_max capped
at 2**32 before the cube root), no division has a negative dividend in
the scalar twins, and numpy/jax floor division on int64 equals Python's
`//` wherever both are defined.  The cube root is a fixed-iteration
binary search (identical decisions to transport._icbrt's while-loop —
once lo == hi the invariant lo**3 <= x makes further iterations no-ops).
"""

from __future__ import annotations

import numpy as np

# -- shared transport constants (audited against transport.py + colcore.c
# by tools/twincheck; see the twin-discipline note above) -----------------
NS_PER_MS = 1_000_000
MSS = 1460
INIT_CWND = 10 * MSS
MIN_CWND = 2 * MSS

#: CongestionControl.cc_id dispatch values (transport.py registry twin)
CC_NEWRENO = 0
CC_CUBIC = 1

#: fixed iteration count for the vectorized cube root: ceil(log2(2**20))
#: + 1 covers transport._icbrt's full [0, 2**20] search interval
_ICBRT_ITERS = 21


def icbrt(x, xp=np):
    """Vectorized floor integer cube root — the exact batch twin of
    transport._icbrt (same binary search over [0, 2**20], fixed-trip).
    ``x`` is a non-negative int64 array with values < 2**60."""
    lo = xp.zeros_like(x)
    hi = xp.full_like(x, 1 << 20)
    one = xp.asarray(1, dtype=x.dtype)
    for _ in range(_ICBRT_ITERS):
        mid = (lo + hi + one) >> one
        ok = mid * mid * mid <= x
        lo = xp.where(ok, mid, lo)
        hi = xp.where(ok, hi, mid - one)
    return lo


def cc_on_ack(cc_id, cwnd, ssthresh, w_max, epoch_start, newly, now,
              xp=np):
    """Batched CongestionControl.on_ack over a cohort of endpoints: the
    columnar twin of NewReno.on_ack and CubicLike.on_ack dispatched on
    the ``cc_id`` column.  All inputs are int64 arrays of one cohort
    length; returns (cwnd', w_max', epoch_start').  ssthresh is read-only
    here (neither algorithm moves it on an ack) and passed for the
    slow-start test.

    Every arithmetic step below mirrors one line of the scalar twins —
    keep them in lockstep (twincheck audits the literal sets, the
    identity tests the results)."""
    ss = cwnd < ssthresh
    # slow start, shared shape: cwnd += min(newly, cwnd)
    cwnd_ss = cwnd + xp.minimum(newly, cwnd)

    # NewReno congestion avoidance: cwnd += max(1, MSS * newly // cwnd)
    cwnd_nr = cwnd + xp.maximum(
        xp.asarray(1, dtype=cwnd.dtype), MSS * newly // cwnd)

    # CubicLike congestion avoidance (first CA ack with no recorded
    # epoch adopts (now, cwnd) as the epoch — vectorized via where)
    es0 = epoch_start == 0
    eps = xp.where(es0, now, epoch_start)
    wmax = xp.where(es0, cwnd, w_max)
    t_ms = (now - eps) // NS_PER_MS
    wmax_c = xp.minimum(wmax, 1 << 32)
    k_ms = icbrt((wmax_c * 3 // (4 * MSS)) * 1_000_000_000, xp)
    d = xp.clip(t_ms - k_ms, -200_000, 200_000)
    a = xp.where(d < 0, -d, d)
    delta = (a * a * a // 1_000_000) * (4 * MSS) // 10_000
    target = xp.clip(xp.where(d < 0, wmax - delta, wmax + delta),
                     MIN_CWND, 1 << 45)
    nn = xp.minimum(newly, 1 << 20)
    one = xp.asarray(1, dtype=cwnd.dtype)
    inc = xp.minimum(target - cwnd, 1 << 40) * nn // cwnd
    below = xp.minimum(cwnd + xp.maximum(inc, one), target)
    creep = cwnd + xp.maximum(MSS * nn // (100 * cwnd), one)
    cwnd_cu = xp.where(cwnd < target, below, creep)

    cubic = cc_id == CC_CUBIC
    cwnd_out = xp.where(ss, cwnd_ss, xp.where(cubic, cwnd_cu, cwnd_nr))
    # cubic epoch adoption happens only on a cubic CA ack
    adopt = cubic & ~ss
    return (cwnd_out,
            xp.where(adopt, wmax, w_max),
            xp.where(adopt, eps, epoch_start))


def ack_advance(cc_id, cwnd, ssthresh, w_max, epoch_start, snd_una,
                bytes_acked, cum_ack, now, xp=np):
    """One clean cumulative-ack advance for a cohort: the batched twin of
    StreamSender.on_ack's strict-advance arithmetic (scoreboards empty,
    not in recovery — the verifier in network/devtransport.py guarantees
    the preconditions row by row; rows that fail take the scalar twin).

    Returns (snd_una', bytes_acked', cwnd', w_max', epoch_start').
    dup_acks/rto_backoff/retries reset to (0, 1, 0) on every advance —
    constants, applied by the caller during writeback."""
    newly = cum_ack - snd_una
    cwnd2, w_max2, eps2 = cc_on_ack(
        cc_id, cwnd, ssthresh, w_max, epoch_start, newly, now, xp=xp)
    return cum_ack, bytes_acked + newly, cwnd2, w_max2, eps2


def rto_min_scan(deadline, xp=np):
    """Vectorized RTO expiry scan: (earliest deadline, its column index)
    over a cohort's armed-RTO deadline column (T_NEVER-filled when
    unarmed).  One min-reduce instead of a heap walk — the device surface
    for timer-wheel-free expiry checks at cohort scale."""
    i = int(xp.argmin(deadline))
    return int(deadline[i]), i


# -- device dispatch ---------------------------------------------------------

#: cohort sizes pad up to the next bucket so every device round reuses
#: one of a handful of compiled program shapes (the devroute pinned-shape
#: discipline: no mid-run compiles)
_BUCKETS = (256, 1024, 4096, 16384, 65536)


class DeviceAckKernel:
    """jax.jit'd ack_advance at pinned bucket shapes.  Results are
    bit-identical to the numpy twin (same integer ops, x64 enabled), so
    routing between them is pure wall-clock policy — the devroute
    argument, applied to transport arithmetic.

    attach() returns None when jax/x64 is unavailable; callers fall back
    to the numpy twin (never an error, never a result change)."""

    def __init__(self, jax, jnp) -> None:
        self._jax = jax
        self._jnp = jnp
        self._fns: dict = {}

    @classmethod
    def attach(cls):
        try:
            from shadow_tpu.ops.jaxcfg import configure

            configure()
            import jax
            import jax.numpy as jnp

            jax.config.update("jax_enable_x64", True)
            k = cls(jax, jnp)
            k.run(*[np.zeros(2, dtype=np.int64) for _ in range(8)])
            return k
        except Exception:
            return None  # no usable device path: numpy serves everything

    def _fn(self, n: int):
        fn = self._fns.get(n)
        if fn is None:
            jnp = self._jnp
            fn = self._jax.jit(
                lambda *cols: ack_advance(*cols, xp=jnp))
            self._fns[n] = fn
        return fn

    def run(self, cc_id, cwnd, ssthresh, w_max, epoch_start, snd_una,
            bytes_acked, cum_ack, now=None):
        """Pad the cohort to a pinned bucket, dispatch, slice the
        readback.  Cohorts above the largest bucket CHUNK at it (rows
        are independent, so chunk boundaries cannot change results)
        instead of compiling a fresh shape mid-run — the devroute
        no-mid-run-compiles discipline.  ``now`` defaults allowed only
        in the warmup call."""
        if now is None:
            now = np.zeros_like(cc_id)
        n = len(cc_id)
        cols = (cc_id, cwnd, ssthresh, w_max, epoch_start, snd_una,
                bytes_acked, cum_ack, now)
        top = _BUCKETS[-1]
        if n > top:
            parts = [self.run(*(c[i:i + top] for c in cols[:8]),
                              now=cols[8][i:i + top])
                     for i in range(0, n, top)]
            return tuple(np.concatenate(ps) for ps in zip(*parts))
        b = next(b for b in _BUCKETS if b >= n)
        if b != n:
            pad = b - n
            # padding rows are inert NewReno slow-start no-ops (newly=0)
            fill = (0, MIN_CWND, 1 << 62, 0, 0, 0, 0, 0, 0)
            cols = tuple(
                np.concatenate([c, np.full(pad, f, dtype=np.int64)])
                for c, f in zip(cols, fill))
        out = self._fn(b)(*cols)
        return tuple(np.asarray(o[:n]) for o in out)
