"""TPU kernel for the per-round egress hot path.

This is the device twin of shadow_tpu/network/fluid.py::depart_round — the
re-design of the reference's Router/Relay token bucket + routing lookup
(SURVEY.md §3.4, BASELINE.json north_star) as one fused XLA program:

    per-source FIFO cumulative token drain -> APSP latency gather ->
    per-packet threefry loss draws -> arrival offsets

Design notes (TPU-first):
- Static shapes: unit batches are padded to power-of-two buckets (bounded
  set of compiled shapes); a boolean mask marks real entries. The engine
  chunks batches so per-chunk byte totals stay below 2**31, making int32
  cumulative sums exact (both backends chunk identically, so bit-equality
  survives chunking).
- int32 everywhere on device: times are offsets from the round start (the
  engine re-bases), token capacities are validated < 2**31 at build, and
  finite latencies are validated < 2**30 (INF_I32) for device use; >= INF
  arrival offsets are blackholed by the engine on every backend. No int64
  emulation on the device path.
- Token refill is overflow-safe without int64: the host pre-clamps the add
  to the capacity, the device applies tokens += min(add, cap - tokens),
  which equals min(tokens + true_add, cap) exactly.
- Loss draws are threefry2x32 — identical integer arithmetic to the numpy
  twin (shadow_tpu/ops/prng.py), keyed on (seed, uid, packet index), so
  drops are a pure function of unit identity on every backend.
- Tokens live on the device between rounds (donated buffers); per round the
  only host->device traffic is the unit batch + refill vector, the only
  device->host traffic is the three result arrays.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from shadow_tpu.network.fluid import MAX_PKTS, NetParams
from shadow_tpu.network.graph import INF_I32
from shadow_tpu.ops.prng import threefry2x32

#: padded-bucket floor; buckets are powers of two between MIN and the
#: engine's chunk cap, so at most ~log2(cap) shapes ever compile
MIN_BUCKET = 256


def _bucket(n: int, cap: int) -> int:
    """Next power-of-two >= n (n never exceeds the engine's chunk cap)."""
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


@functools.partial(jax.jit, static_argnames=("seed",), donate_argnums=(0,))
def _depart_kernel(tokens, add, cap, ints, uids, lat32, thresh, host_node, seed):
    """One padded chunk, with the round's token refill fused in.

    tokens: (H,) int32, donated. add: (H,) int32 refill (zeros after the
    first chunk of a round). ints: (5, P) int32 rows [src, dst, size,
    dep_off, npkts]; uids: (2, P) uint32 rows [uid_lo, uid_hi]. Padded
    entries carry src == H (sentinel segment) and size 0."""
    nhosts = tokens.shape[0]
    src, dst, size, dep_off, npkts = ints
    uid_lo, uid_hi = uids
    valid = src < nhosts

    tokens = tokens + jnp.minimum(add, cap - tokens)  # overflow-safe refill

    # per-source FIFO cumulative drain (src-sorted; padding sorts last)
    size_m = jnp.where(valid, size, 0)
    csum = jnp.cumsum(size_m, dtype=jnp.int32)
    prev_src = jnp.concatenate([jnp.full((1,), -1, jnp.int32), src[:-1]])
    seg_first = src != prev_src
    base = jnp.where(seg_first, csum - size_m, 0)
    base = jax.lax.cummax(base)
    cum_in_seg = csum - base
    sent = (cum_in_seg <= tokens[jnp.minimum(src, nhosts - 1)]) & valid

    drained = jax.ops.segment_sum(
        jnp.where(sent, size_m, 0), src, num_segments=nhosts + 1,
        indices_are_sorted=True,
    )[:nhosts]
    tokens = tokens - drained.astype(jnp.int32)

    sn = host_node[jnp.minimum(src, host_node.shape[0] - 1)]
    dn = host_node[dst]
    lat = lat32[sn, dn]
    th = thresh[sn, dn]

    pkt = jnp.arange(MAX_PKTS, dtype=jnp.uint32)[None, :]
    c0 = jnp.broadcast_to(uid_lo[:, None], (uid_lo.shape[0], MAX_PKTS))
    c1 = uid_hi[:, None] | (pkt << jnp.uint32(28))
    k0 = jnp.uint32(seed & 0xFFFFFFFF)
    k1 = jnp.uint32((seed >> 32) & 0xFFFFFFFF)
    draws, _ = threefry2x32(k0, k1, c0, c1, xp=jnp)
    draws = (draws >> jnp.uint32(8)).astype(jnp.uint32)
    hit = (draws < th[:, None]) & (pkt < npkts.astype(jnp.uint32)[:, None])
    dropped = sent & jnp.any(hit, axis=1)

    arrival_off = dep_off + lat
    return tokens, sent, dropped, arrival_off


class DeviceDataPlane:
    """Device-resident egress data plane (up-link tokens live on the TPU).

    Interface contract shared with the numpy twin
    (shadow_tpu/network/fluid.py::CPUDataPlane): the engine accumulates
    refill time and hands it to the first depart of a round; both twins
    compute the refill vector with the same clamped_refill() integer math.
    """

    name = "tpu"

    def __init__(self, params: NetParams, round_ns: int, options=None,
                 device=None) -> None:
        from shadow_tpu.network.fluid import clamped_refill

        self.params = params
        lat = params.latency_ns
        finite = lat[lat < np.int64(INF_I32)]
        if finite.size and finite.max() >= np.int64(INF_I32):
            raise ValueError(
                "graph has finite path latencies >= ~1.07s; the int32 device "
                "data plane cannot represent them — use a CPU scheduler policy"
            )
        self.round_ns = int(round_ns)
        self.lat32 = jnp.asarray(np.minimum(lat, np.int64(INF_I32)).astype(np.int32))
        self.thresh = jnp.asarray(params.drop_thresh)
        self.host_node = jnp.asarray(params.host_node)
        self.cap32 = jnp.asarray(params.cap_up.astype(np.int32))
        self.tokens = jnp.asarray(params.cap_up.astype(np.int32))
        self.seed = int(params.seed)
        # cached refill vectors: the standard round width (the common case)
        # and zeros (later chunks) never leave the device
        self._std_add = jnp.asarray(
            clamped_refill(params.rate_up, params.cap_up, self.round_ns
                           ).astype(np.int32))
        self._zero_add = jnp.zeros_like(self._std_add)
        self._clamped_refill = clamped_refill

    def tokens_host(self) -> np.ndarray:
        return np.asarray(self.tokens).astype(np.int64)

    def _add_for(self, refill_dt: int):
        if refill_dt == 0:
            return self._zero_add
        if refill_dt == self.round_ns:
            return self._std_add
        p = self.params
        return jnp.asarray(
            self._clamped_refill(p.rate_up, p.cap_up, refill_dt).astype(np.int32))

    def depart_chunk(self, src, dst, size, dep_off, npkts, uid_lo, uid_hi,
                     chunk_cap: int, refill_dt: int = 0):
        """Run one (unpadded, src-sorted) chunk; refill_dt is the elapsed ns
        to refill for before draining (first chunk of a round only).
        Returns numpy (sent, dropped, arrival_off[int64])."""
        n = src.shape[0]
        p = _bucket(n, chunk_cap)
        pad = p - n
        nhosts = int(self.cap32.shape[0])

        ints = np.empty((5, p), dtype=np.int32)
        for row, (a, fill) in enumerate(
            ((src, nhosts), (dst, 0), (size, 0), (dep_off, 0), (npkts, 0))
        ):
            ints[row, :n] = a
            ints[row, n:] = fill
        uids = np.zeros((2, p), dtype=np.uint32)
        uids[0, :n] = uid_lo
        uids[1, :n] = uid_hi

        tokens, sent, dropped, arrival_off = _depart_kernel(
            self.tokens,
            self._add_for(refill_dt),
            self.cap32,
            jnp.asarray(ints),
            jnp.asarray(uids),
            self.lat32,
            self.thresh,
            self.host_node,
            seed=self.seed,
        )
        self.tokens = tokens
        sent, dropped, arrival_off = jax.device_get((sent, dropped, arrival_off))
        return sent[:n], dropped[:n], arrival_off[:n].astype(np.int64)
