"""TPU kernel for the data-plane hot math: batched per-packet loss draws.

Round-2 redesign. The bucket/departure math moved to a closed form with ONE
host-side implementation (shadow_tpu/network/fluid.py::TokenBuckets) — it is
O(1) integer work per unit and needs no twin. What remains hot is the loss
sampling: 20-round threefry2x32 × MAX_PKTS counters per unit (hundreds of
integer ops each), a pure function of (seed, uid, npkts, threshold) with no
state and no feedback — exactly the shape the TPU's vector unit wants.

Design notes (TPU-first):
- Stateless kernel: the host gathers each unit's q24 drop threshold from the
  (G,G) table and ships ONE packed (4, P) uint32 array; the kernel returns
  one (P,) bool. No device-resident state, no donation, no coherence with
  the host bucket state — which also makes the kernel trivially shardable
  over a mesh (shadow_tpu/parallel/) and lets small batches route to the
  bit-identical numpy twin (fluid.loss_flags) with no semantic difference.
- Static shapes: batches pad to power-of-two buckets between MIN_BUCKET and
  the configured cap, so at most ~log2(cap) shapes ever compile.
- Deferred readback: results are copied device->host asynchronously
  (copy_to_host_async) and only *consumed* when the simulation clock reaches
  the batch's causal deadline — the earliest time any unit's arrival or
  loss notification can fire, which the engine computes host-side without
  the flags. On links where the device->host path has high latency (e.g. a
  tunneled chip) the readback overlaps subsequent rounds instead of
  stalling each one; this is what fixes round 1's ~100 ms-per-round sync
  (VERDICT.md weak #1).
- calibrate() measures the real dispatch+readback latency and the numpy
  twin's per-unit cost once at startup, giving the engine an evidence-based
  floor for routing batches. Because both paths produce identical flags and
  event ordering is canonicalized (core/events.py BAND_NET), the floor can
  NOT affect simulation results — calibration is determinism-safe.
"""

from __future__ import annotations

import functools
import time as _walltime

import numpy as np

import jax
import jax.numpy as jnp

from shadow_tpu.network.fluid import MAX_PKTS, PKT_SHIFT, loss_flags

#: padded-bucket floor; buckets are powers of two between MIN and the cap
MIN_BUCKET = 256


def _bucket(n: int, cap: int) -> int:
    """Next power-of-two >= n (n never exceeds the engine's chunk cap)."""
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


@functools.partial(jax.jit, static_argnames=("seed", "width"))
def _min_draw_kernel(packed, seed, width=MAX_PKTS):
    """packed: (3, P) uint32 rows [uid_lo, uid_hi, npkts]; returns (P,)
    uint32: the MINIMUM 24-bit draw over each unit's first npkts packet
    lanes (0xFFFFFFFF for npkts == 0, which no threshold can undercut).
    This is the threshold-independent sufficient statistic behind the
    speculative forward windows: ``dropped == (min_draw < thresh)`` for
    ANY thresh, so one speculated row serves every destination a host
    later picks — same integer math as _draw_kernel/fluid.loss_flags."""
    from shadow_tpu.ops.prng import threefry2x32

    uid_lo, uid_hi, npkts = packed
    p = uid_lo.shape[0]
    pkt = jnp.arange(width, dtype=jnp.uint32)[None, :]
    c0 = jnp.broadcast_to(uid_lo[:, None], (p, width))
    c1 = uid_hi[:, None] | (pkt << jnp.uint32(PKT_SHIFT))
    k0 = jnp.uint32(seed & 0xFFFFFFFF)
    k1 = jnp.uint32((seed >> 32) & 0xFFFFFFFF)
    draws, _ = threefry2x32(k0, k1, c0, c1, xp=jnp)
    draws = (draws >> jnp.uint32(8)).astype(jnp.uint32)
    sentinel = jnp.uint32(0xFFFFFFFF)
    return jnp.min(jnp.where(pkt < npkts[:, None], draws, sentinel), axis=1)


@functools.partial(jax.jit, static_argnames=("seed", "width"))
def _draw_kernel(packed, seed, width=MAX_PKTS):
    """packed: (4, P) uint32 rows [uid_lo, uid_hi, npkts, thresh]; returns
    (P,) bool dropped flags. Mirrors fluid.loss_flags exactly: a unit drops
    iff any of its first npkts threefry draws is below its q24 threshold.
    (Padded entries carry thresh == 0, which can never hit.)"""
    from shadow_tpu.ops.prng import threefry2x32

    uid_lo, uid_hi, npkts, thresh = packed
    p = uid_lo.shape[0]
    pkt = jnp.arange(width, dtype=jnp.uint32)[None, :]
    c0 = jnp.broadcast_to(uid_lo[:, None], (p, width))
    c1 = uid_hi[:, None] | (pkt << jnp.uint32(PKT_SHIFT))
    k0 = jnp.uint32(seed & 0xFFFFFFFF)
    k1 = jnp.uint32((seed >> 32) & 0xFFFFFFFF)
    draws, _ = threefry2x32(k0, k1, c0, c1, xp=jnp)
    draws = (draws >> jnp.uint32(8)).astype(jnp.uint32)
    hit = (draws < thresh[:, None]) & (pkt < npkts[:, None])
    # bit-pack the flags: the device->host readback is the scarce resource
    # (see module doc), so ship 1 bit per unit, not 1 byte
    return jnp.packbits(jnp.any(hit, axis=1), bitorder="little")


class DrawHandle:
    """An in-flight device draw: read() yields the (n,) bool flags."""

    __slots__ = ("_arr", "_n")

    def __init__(self, arr, n: int) -> None:
        self._arr = arr
        self._n = n

    def read(self) -> np.ndarray:
        packed = np.asarray(self._arr)
        return np.unpackbits(packed, bitorder="little")[: self._n].astype(bool)

    def is_ready(self) -> bool:
        """True when the device result has landed host-side — read() will
        not stall. Backends without the poll hint report ready (read()
        then blocks, which is the pre-window behavior)."""
        poll = getattr(self._arr, "is_ready", None)
        return True if poll is None else bool(poll())


class MinDrawHandle:
    """An in-flight speculative min-draw batch: read() yields (n,) uint32
    prefix-min draws (see _min_draw_kernel)."""

    __slots__ = ("_arr", "_n")

    def __init__(self, arr, n: int) -> None:
        self._arr = arr
        self._n = n

    def read(self) -> np.ndarray:
        return np.asarray(self._arr)[: self._n]

    def is_ready(self) -> bool:
        poll = getattr(self._arr, "is_ready", None)
        return True if poll is None else bool(poll())


class DeviceDrawPlane:
    """Dispatches loss-draw batches to the accelerator.

    The numpy twin is fluid.loss_flags; tests/test_bitmatch.py asserts the
    two produce identical flags for identical inputs.
    """

    name = "tpu"

    def __init__(self, seed: int, max_batch: int = 65536,
                 n_shards: int = 0, max_pkts: int = MAX_PKTS) -> None:
        """n_shards > 1 shards each batch over that many local devices
        (experimental.tpu_mesh_shards; 0 = all local devices). The kernel
        is elementwise along the unit axis, so XLA partitions it with no
        communication — data-parallel draws across the mesh."""
        from shadow_tpu.ops.jaxcfg import configure

        configure()
        self.seed = int(seed)
        self.max_batch = int(max_batch)
        self.max_pkts = int(max_pkts)  # kernel packet-lane width
        self._sharding = None
        devs = jax.devices()
        n = n_shards if n_shards > 0 else len(devs)
        n = min(n, len(devs))
        if n > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            mesh = Mesh(np.array(devs[:n]), ("d",))
            self._sharding = NamedSharding(mesh, PartitionSpec(None, "d"))
            self._n_shards = n

    def dispatch(self, uid_lo: np.ndarray, uid_hi: np.ndarray,
                 npkts: np.ndarray, thresh: np.ndarray) -> DrawHandle:
        """Launch one batch (any length <= max_batch) and start the async
        device->host copy; returns a handle to read when due."""
        n = uid_lo.shape[0]
        p = _bucket(n, self.max_batch)
        if self._sharding is not None:
            q = 8 * self._n_shards  # packbits + even split across shards
            p = -(-max(p, q) // q) * q
        packed = np.zeros((4, p), dtype=np.uint32)
        packed[0, :n] = uid_lo
        packed[1, :n] = uid_hi
        packed[2, :n] = npkts
        packed[3, :n] = thresh
        dev_in = (jax.device_put(packed, self._sharding)
                  if self._sharding is not None else jnp.asarray(packed))
        out = _draw_kernel(dev_in, seed=self.seed, width=self.max_pkts)
        try:
            out.copy_to_host_async()
        except AttributeError:  # some backends lack the hint; read() suffices
            pass
        return DrawHandle(out, n)

    #: every speculative wave pads to this one bucket so exactly ONE
    #: min-draw program shape ever compiles (warmed at attach_cached);
    #: callers chunk bigger waves at this size
    SPEC_BUCKET = 16384

    def dispatch_min(self, uid_lo: np.ndarray, uid_hi: np.ndarray,
                     npkts: np.ndarray,
                     min_bucket: int = 0) -> MinDrawHandle:
        """Launch one speculative min-draw batch (threshold-independent;
        see _min_draw_kernel) with the async device->host copy started.
        ``min_bucket`` pins the padded shape (shape stability = no
        mid-run compiles; padded rows carry npkts 0 and can never hit)."""
        n = uid_lo.shape[0]
        p = max(_bucket(n, self.max_batch), min_bucket)
        if self._sharding is not None:
            q = 8 * self._n_shards
            p = -(-max(p, q) // q) * q
        packed = np.zeros((3, p), dtype=np.uint32)
        packed[0, :n] = uid_lo
        packed[1, :n] = uid_hi
        packed[2, :n] = npkts
        dev_in = (jax.device_put(packed, self._sharding)
                  if self._sharding is not None else jnp.asarray(packed))
        out = _min_draw_kernel(dev_in, seed=self.seed, width=self.max_pkts)
        try:
            out.copy_to_host_async()
        except AttributeError:
            pass
        return MinDrawHandle(out, n)

    _cache: dict = {}  # (seed, max_batch, n_shards, max_pkts) -> entry

    @classmethod
    def attach_cached(cls, seed: int, max_batch: int, n_shards: int,
                      max_pkts: int):
        """Process-wide attach cache: (plane, dev_s, np_per_unit) for this
        parameter tuple, building + calibrating on first use. A simulation
        binary runs many short Controllers (benchmarks, tests, resumed
        checkpoints); paying the attach + compile + calibrate cost once
        per process instead of once per run is what lets the device come
        online BEFORE the round loop ends on fast configs — round 5's
        device_x < 1.0 was largely a device that published after the loop
        finished. Pure wall-clock policy: the plane is stateless, so
        sharing it across runs cannot change results."""
        key = (int(seed), int(max_batch), int(n_shards), int(max_pkts))
        hit = cls._cache.get(key)
        if hit is None:
            plane = cls(seed, max_batch, n_shards=n_shards,
                        max_pkts=max_pkts)
            dev_s, np_per_unit = plane.calibrate()
            # warm EVERY program shape this plane can ever dispatch
            # (VERDICT r5 item #7): calibrate() compiles only its probe
            # bucket, so the remaining power-of-two buckets (and the
            # speculative min-draw shape) used to compile lazily INSIDE
            # the first run's measured round loop — the warm-up leak that
            # made the first tpu rep ~2.1x slow in interleaved raws.
            # ~log2(max_batch) shapes, on the attach thread, amortized by
            # the persistent compile cache across processes.
            plane.warm_shapes()
            if len(cls._cache) >= 4:  # a handful of configs per process
                cls._cache.pop(next(iter(cls._cache)))
            hit = cls._cache[key] = (plane, dev_s, np_per_unit)
        return hit

    def warm_shapes(self) -> None:
        """Compile every padded bucket shape of the draw kernel plus the
        pinned speculative min-draw shape, so no dispatch ever compiles
        inside a simulation round loop (static shapes bound the set to
        ~log2(max_batch) programs — the module-doc design point). Pure
        wall-clock work: flags are never read for results here."""
        b = MIN_BUCKET
        while True:
            z = np.zeros(b, dtype=np.uint32)
            self.dispatch(z, z, z, z).read()
            if b >= self.max_batch:
                break
            b <<= 1
        k = self.SPEC_BUCKET
        z = np.zeros(k, dtype=np.uint32)
        self.dispatch_min(z, z, z, min_bucket=k).read()

    def calibrate(self, n_probe: int = 4096) -> tuple[float, float]:
        """Measure (device seconds per dispatch+readback at n_probe, numpy
        seconds per unit). Used by the engine to set the routing floor; has
        no effect on simulation results (both paths are bit-identical)."""
        rng = np.random.default_rng(0)
        lo = rng.integers(0, 1 << 32, n_probe, dtype=np.uint64).astype(np.uint32)
        hi = rng.integers(0, 1 << 32, n_probe, dtype=np.uint64).astype(np.uint32)
        npk = np.full(n_probe, self.max_pkts, np.uint32)
        th = np.full(n_probe, 1 << 10, np.uint32)
        self.dispatch(lo, hi, npk, th).read()  # compile + warm
        t0 = _walltime.perf_counter()
        self.dispatch(lo, hi, npk, th).read()
        dev_s = _walltime.perf_counter() - t0
        t0 = _walltime.perf_counter()
        loss_flags(self.seed, lo, hi, npk, th)
        np_per_unit = (_walltime.perf_counter() - t0) / n_probe
        return dev_s, np_per_unit
