"""TPU kernel for the data-plane hot math: batched per-packet loss draws.

Round-2 redesign. The bucket/departure math moved to a closed form with ONE
host-side implementation (shadow_tpu/network/fluid.py::TokenBuckets) — it is
O(1) integer work per unit and needs no twin. What remains hot is the loss
sampling: 20-round threefry2x32 × MAX_PKTS counters per unit (hundreds of
integer ops each), a pure function of (seed, uid, npkts, threshold) with no
state and no feedback — exactly the shape the TPU's vector unit wants.

Design notes (TPU-first):
- Stateless kernel: the host gathers each unit's q24 drop threshold from the
  (G,G) table and ships ONE packed (4, P) uint32 array; the kernel returns
  one (P,) bool. No device-resident state, no donation, no coherence with
  the host bucket state — which also makes the kernel trivially shardable
  over a mesh (shadow_tpu/parallel/) and lets small batches route to the
  bit-identical numpy twin (fluid.loss_flags) with no semantic difference.
- Static shapes: batches pad to power-of-two buckets between MIN_BUCKET and
  the configured cap, so at most ~log2(cap) shapes ever compile.
- Deferred readback: results are copied device->host asynchronously
  (copy_to_host_async) and only *consumed* when the simulation clock reaches
  the batch's causal deadline — the earliest time any unit's arrival or
  loss notification can fire, which the engine computes host-side without
  the flags. On links where the device->host path has high latency (e.g. a
  tunneled chip) the readback overlaps subsequent rounds instead of
  stalling each one; this is what fixes round 1's ~100 ms-per-round sync
  (VERDICT.md weak #1).
- calibrate() measures the real dispatch+readback latency and the numpy
  twin's per-unit cost once at startup, giving the engine an evidence-based
  floor for routing batches. Because both paths produce identical flags and
  event ordering is canonicalized (core/events.py BAND_NET), the floor can
  NOT affect simulation results — calibration is determinism-safe.
"""

from __future__ import annotations

import functools
import time as _walltime  # detlint: ok(wallclock): device attach/calibration wall measures

import numpy as np

import jax
import jax.numpy as jnp

from shadow_tpu.network.fluid import MAX_PKTS, PKT_SHIFT, loss_flags

#: padded-bucket floor; buckets are powers of two between MIN and the cap
MIN_BUCKET = 256


def _bucket(n: int, cap: int) -> int:
    """Next power-of-two >= n (n never exceeds the engine's chunk cap)."""
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


@functools.partial(jax.jit, static_argnames=("width",))
def _min_draw_kernel(packed, seed_key, width=MAX_PKTS):
    """packed: (3, P) uint32 rows [uid_lo, uid_hi, npkts]; returns (P,)
    uint32: the MINIMUM 24-bit draw over each unit's first npkts packet
    lanes (0xFFFFFFFF for npkts == 0, which no threshold can undercut).
    This is the threshold-independent sufficient statistic behind the
    speculative forward windows: ``dropped == (min_draw < thresh)`` for
    ANY thresh, so one speculated row serves every destination a host
    later picks — same integer math as _draw_kernel/fluid.loss_flags.
    ``seed_key`` is the (2,) uint32 threefry key, passed as DATA (not a
    static arg) so ONE compiled program per shape serves EVERY seed —
    fleet mode (shadow_tpu/fleet.py) packs M seeded simulations behind
    one shared device plane, and a baked-in seed would recompile every
    bucket shape per member."""
    from shadow_tpu.ops.prng import threefry2x32

    uid_lo, uid_hi, npkts = packed
    p = uid_lo.shape[0]
    pkt = jnp.arange(width, dtype=jnp.uint32)[None, :]
    c0 = jnp.broadcast_to(uid_lo[:, None], (p, width))
    c1 = uid_hi[:, None] | (pkt << jnp.uint32(PKT_SHIFT))
    draws, _ = threefry2x32(seed_key[0], seed_key[1], c0, c1, xp=jnp)
    draws = (draws >> jnp.uint32(8)).astype(jnp.uint32)
    sentinel = jnp.uint32(0xFFFFFFFF)
    return jnp.min(jnp.where(pkt < npkts[:, None], draws, sentinel), axis=1)


@functools.partial(jax.jit, static_argnames=("width",))
def _draw_kernel(packed, seed_key, width=MAX_PKTS):
    """packed: (4, P) uint32 rows [uid_lo, uid_hi, npkts, thresh]; returns
    (P,) bool dropped flags. Mirrors fluid.loss_flags exactly: a unit drops
    iff any of its first npkts threefry draws is below its q24 threshold.
    (Padded entries carry thresh == 0, which can never hit.) ``seed_key``
    is traced data like in _min_draw_kernel: one program per shape, any
    seed."""
    from shadow_tpu.ops.prng import threefry2x32

    uid_lo, uid_hi, npkts, thresh = packed
    p = uid_lo.shape[0]
    pkt = jnp.arange(width, dtype=jnp.uint32)[None, :]
    c0 = jnp.broadcast_to(uid_lo[:, None], (p, width))
    c1 = uid_hi[:, None] | (pkt << jnp.uint32(PKT_SHIFT))
    draws, _ = threefry2x32(seed_key[0], seed_key[1], c0, c1, xp=jnp)
    draws = (draws >> jnp.uint32(8)).astype(jnp.uint32)
    hit = (draws < thresh[:, None]) & (pkt < npkts[:, None])
    # bit-pack the flags: the device->host readback is the scarce resource
    # (see module doc), so ship 1 bit per unit, not 1 byte
    return jnp.packbits(jnp.any(hit, axis=1), bitorder="little")


class DrawHandle:
    """An in-flight device draw: read() yields the (n,) bool flags."""

    __slots__ = ("_arr", "_n")

    def __init__(self, arr, n: int) -> None:
        self._arr = arr
        self._n = n

    def read(self) -> np.ndarray:
        packed = np.asarray(self._arr)
        return np.unpackbits(packed, bitorder="little")[: self._n].astype(bool)

    def is_ready(self) -> bool:
        """True when the device result has landed host-side — read() will
        not stall. Backends without the poll hint report ready (read()
        then blocks, which is the pre-window behavior)."""
        poll = getattr(self._arr, "is_ready", None)
        return True if poll is None else bool(poll())


class MinDrawHandle:
    """An in-flight speculative min-draw batch: read() yields (n,) uint32
    prefix-min draws (see _min_draw_kernel)."""

    __slots__ = ("_arr", "_n")

    def __init__(self, arr, n: int) -> None:
        self._arr = arr
        self._n = n

    def read(self) -> np.ndarray:
        return np.asarray(self._arr)[: self._n]

    def is_ready(self) -> bool:
        poll = getattr(self._arr, "is_ready", None)
        return True if poll is None else bool(poll())


class DeviceDrawPlane:
    """Dispatches loss-draw batches to the accelerator.

    The numpy twin is fluid.loss_flags; tests/test_bitmatch.py asserts the
    two produce identical flags for identical inputs.
    """

    name = "tpu"

    def __init__(self, seed: int, max_batch: int = 65536,
                 n_shards: int = 0, max_pkts: int = MAX_PKTS) -> None:
        """n_shards > 1 shards each batch over that many local devices
        (experimental.tpu_mesh_shards; 0 = all local devices). The kernel
        is elementwise along the unit axis, so XLA partitions it with no
        communication — data-parallel draws across the mesh."""
        from shadow_tpu.ops.jaxcfg import configure

        configure()
        self.seed = int(seed)
        self.max_batch = int(max_batch)
        self.max_pkts = int(max_pkts)  # kernel packet-lane width
        self._sharding = None
        devs = jax.devices()
        n = n_shards if n_shards > 0 else len(devs)
        n = min(n, len(devs))
        if n > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            mesh = Mesh(np.array(devs[:n]), ("d",))
            self._sharding = NamedSharding(mesh, PartitionSpec(None, "d"))
            self._n_shards = n

    def _seed_key(self, seed) -> np.ndarray:
        """(2,) uint32 threefry key for ``seed`` (None = the plane's own).
        Shipped to the kernels as data, so serving another simulation's
        seed (fleet mode) reuses the already-compiled programs."""
        s = self.seed if seed is None else int(seed)
        return np.array([s & 0xFFFFFFFF, (s >> 32) & 0xFFFFFFFF],
                        dtype=np.uint32)

    def dispatch(self, uid_lo: np.ndarray, uid_hi: np.ndarray,
                 npkts: np.ndarray, thresh: np.ndarray,
                 seed: int = None) -> DrawHandle:
        """Launch one batch (any length <= max_batch) and start the async
        device->host copy; returns a handle to read when due. ``seed``
        overrides the plane's seed for this batch (the fleet draw server
        serves many member sims' windows from one attach)."""
        n = uid_lo.shape[0]
        p = _bucket(n, self.max_batch)
        if self._sharding is not None:
            q = 8 * self._n_shards  # packbits + even split across shards
            p = -(-max(p, q) // q) * q
        packed = np.zeros((4, p), dtype=np.uint32)
        packed[0, :n] = uid_lo
        packed[1, :n] = uid_hi
        packed[2, :n] = npkts
        packed[3, :n] = thresh
        dev_in = (jax.device_put(packed, self._sharding)
                  if self._sharding is not None else jnp.asarray(packed))
        out = _draw_kernel(dev_in, self._seed_key(seed), width=self.max_pkts)
        try:
            out.copy_to_host_async()
        except AttributeError:  # some backends lack the hint; read() suffices
            pass
        return DrawHandle(out, n)

    #: every speculative wave pads to this one bucket so exactly ONE
    #: min-draw program shape ever compiles (warmed at attach_cached);
    #: callers chunk bigger waves at this size
    SPEC_BUCKET = 16384

    def dispatch_min(self, uid_lo: np.ndarray, uid_hi: np.ndarray,
                     npkts: np.ndarray, min_bucket: int = 0,
                     seed: int = None) -> MinDrawHandle:
        """Launch one speculative min-draw batch (threshold-independent;
        see _min_draw_kernel) with the async device->host copy started.
        ``min_bucket`` pins the padded shape (shape stability = no
        mid-run compiles; padded rows carry npkts 0 and can never hit);
        ``seed`` overrides the plane's seed (fleet draw server)."""
        n = uid_lo.shape[0]
        p = max(_bucket(n, self.max_batch), min_bucket)
        if self._sharding is not None:
            q = 8 * self._n_shards
            p = -(-max(p, q) // q) * q
        packed = np.zeros((3, p), dtype=np.uint32)
        packed[0, :n] = uid_lo
        packed[1, :n] = uid_hi
        packed[2, :n] = npkts
        dev_in = (jax.device_put(packed, self._sharding)
                  if self._sharding is not None else jnp.asarray(packed))
        out = _min_draw_kernel(dev_in, self._seed_key(seed),
                               width=self.max_pkts)
        try:
            out.copy_to_host_async()
        except AttributeError:
            pass
        return MinDrawHandle(out, n)

    _cache: dict = {}  # (seed, max_batch, n_shards, max_pkts) -> entry

    @classmethod
    def attach_cached(cls, seed: int, max_batch: int, n_shards: int,
                      max_pkts: int, should_abort=None):
        """Process-wide attach cache: (plane, dev_s, np_per_unit) for this
        parameter tuple, building + calibrating on first use. A simulation
        binary runs many short Controllers (benchmarks, tests, resumed
        checkpoints); paying the attach + compile + calibrate cost once
        per process instead of once per run is what lets the device come
        online BEFORE the round loop ends on fast configs — round 5's
        device_x < 1.0 was largely a device that published after the loop
        finished. Pure wall-clock policy: the plane is stateless, so
        sharing it across runs cannot change results.

        ``should_abort`` (callable -> bool) is polled between the attach
        phases; when it fires, the partial attach is discarded (nothing
        cached) and None is returned. This bounds how long a teardown
        must wait on an in-flight attach to a single XLA compile."""
        key = (int(seed), int(max_batch), int(n_shards), int(max_pkts))
        hit = cls._cache.get(key)
        if hit is None:
            if should_abort is not None and should_abort():
                return None
            plane = cls(seed, max_batch, n_shards=n_shards,
                        max_pkts=max_pkts)
            if should_abort is not None and should_abort():
                return None
            dev_s, np_per_unit = plane.calibrate()
            # warm EVERY program shape this plane can ever dispatch
            # (VERDICT r5 item #7): calibrate() compiles only its probe
            # bucket, so the remaining power-of-two buckets (and the
            # speculative min-draw shape) used to compile lazily INSIDE
            # the first run's measured round loop — the warm-up leak that
            # made the first tpu rep ~2.1x slow in interleaved raws.
            # ~log2(max_batch) shapes, on the attach thread, amortized by
            # the persistent compile cache across processes.
            plane.warm_shapes(should_abort=should_abort)
            if should_abort is not None and should_abort():
                return None  # partially warmed: do not cache
            if len(cls._cache) >= 4:  # a handful of configs per process
                cls._cache.pop(next(iter(cls._cache)))
            hit = cls._cache[key] = (plane, dev_s, np_per_unit)
        return hit

    def warm_shapes(self, should_abort=None) -> None:
        """Compile every padded bucket shape of the draw kernel plus the
        pinned speculative min-draw shape, so no dispatch ever compiles
        inside a simulation round loop (static shapes bound the set to
        ~log2(max_batch) programs — the module-doc design point). Pure
        wall-clock work: flags are never read for results here.
        ``should_abort`` is polled between shapes (attach teardown)."""
        b = MIN_BUCKET
        while True:
            if should_abort is not None and should_abort():
                return
            z = np.zeros(b, dtype=np.uint32)
            self.dispatch(z, z, z, z).read()
            if b >= self.max_batch:
                break
            b <<= 1
        if should_abort is not None and should_abort():
            return
        k = self.SPEC_BUCKET
        z = np.zeros(k, dtype=np.uint32)
        self.dispatch_min(z, z, z, min_bucket=k).read()

    def calibrate(self, n_probe: int = 4096) -> tuple[float, float]:
        """Measure (device seconds per dispatch+readback at n_probe, numpy
        seconds per unit). Used by the engine to set the routing floor; has
        no effect on simulation results (both paths are bit-identical)."""
        rng = np.random.default_rng(0)
        lo = rng.integers(0, 1 << 32, n_probe, dtype=np.uint64).astype(np.uint32)
        hi = rng.integers(0, 1 << 32, n_probe, dtype=np.uint64).astype(np.uint32)
        npk = np.full(n_probe, self.max_pkts, np.uint32)
        th = np.full(n_probe, 1 << 10, np.uint32)
        self.dispatch(lo, hi, npk, th).read()  # compile + warm
        t0 = _walltime.perf_counter()
        self.dispatch(lo, hi, npk, th).read()
        dev_s = _walltime.perf_counter() - t0
        t0 = _walltime.perf_counter()
        loss_flags(self.seed, lo, hi, npk, th)
        np_per_unit = (_walltime.perf_counter() - t0) / n_probe
        return dev_s, np_per_unit


#: authkey for the fleet draw-service socket (local AF_UNIX only; the
#: socket path lives in a mode-0700 directory — the key is a protocol
#: sanity check, not the access control)
DRAW_SERVICE_AUTHKEY = b"shadow-tpu-draw-service-v1"


class DrawServer:
    """The fleet parent's shared device plane: ONE process-group attach
    (DeviceDrawPlane.attach_cached — compile, calibrate, warm_shapes paid
    once) serving every member simulation's draw windows over an AF_UNIX
    socket (shadow_tpu/fleet.py owns the member-side proxy). Because the
    kernels take the threefry key as data, M members with M different
    seeds share the same compiled programs — the batch-amortized regime
    the 118 ms-round-trip device needs, without M redundant attaches.

    Protocol (multiprocessing.connection, one serving thread per member):
      member -> ("hello", seed)
      server -> ("ok", dev_s, np_per_unit, SPEC_BUCKET, max_batch)
      member -> ("draw", rid, seed, lo, hi, npk, th)
               | ("min", rid, seed, lo, hi, npk, min_bucket) | ("bye",)
      server -> (rid, result_array)   # FIFO per member; member demuxes

    Routing is pure wall-clock policy (both paths are bit-identical), so
    a dead or slow server can never change results — the member proxy
    falls back to the in-process numpy twin on any transport error."""

    def __init__(self, seed: int, max_batch: int = 65536,
                 n_shards: int = 0, max_pkts: int = MAX_PKTS,
                 address: str = None) -> None:
        import os
        import tempfile
        import threading
        from multiprocessing.connection import Listener

        if address is None:
            d = tempfile.mkdtemp(prefix="stpu_draw_")
            os.chmod(d, 0o700)
            address = os.path.join(d, "sock")
        self.address = address
        # the listener accepts IMMEDIATELY while the (multi-second)
        # attach runs on a sibling thread: members connect and complete
        # the socket handshake at once, then their hello waits (with an
        # abortable poll on their side) for the plane to publish — so no
        # member ever blocks uninterruptibly on a server that is still
        # compiling (members run the numpy twin meanwhile, exactly like
        # the background-attach path of a standalone run)
        self._listener = Listener(address, family="AF_UNIX",
                                  backlog=64, authkey=DRAW_SERVICE_AUTHKEY)
        self._attach_args = (int(seed), int(max_batch), int(n_shards),
                             int(max_pkts))
        self.plane = None
        self.dev_s = 0.0
        self.np_per_unit = 0.0
        self.attach_wall = 0.0
        self._closing = False
        self._ready = threading.Event()
        self.served_batches = 0
        self.served_units = 0
        self._attach_thread = threading.Thread(
            target=self._attach, name="draw-server-attach", daemon=True)
        self._attach_thread.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="draw-server-accept",
            daemon=True)
        self._accept_thread.start()

    def _attach(self) -> None:
        import os
        import threading

        try:
            # mildly deprioritize the attach/compile against the fleet's
            # pinned member processes: the shared plane is background
            # amortization. Mild (nice 5), NOT SCHED_IDLE: the XLA host
            # threads created during attach inherit this priority and
            # later serve live member readbacks — starving them turns
            # member window flushes into stalls (measured: SCHED_IDLE
            # here made the whole sweep slower).
            os.setpriority(os.PRIO_PROCESS, threading.get_native_id(), 5)
        except (AttributeError, OSError, PermissionError):
            pass
        t0 = _walltime.perf_counter()
        try:
            hit = DeviceDrawPlane.attach_cached(
                *self._attach_args, should_abort=lambda: self._closing)
        except Exception:
            hit = None  # no usable device
        if hit is None:
            # no usable device, or close() raced the attach: close the
            # listener so member proxies get a clean connection error and
            # fall back to local routing
            self._closing = True
            try:
                self._listener.close()
            except OSError:
                pass
            return
        self.plane, self.dev_s, self.np_per_unit = hit
        self.attach_wall = _walltime.perf_counter() - t0
        self._ready.set()

    def _accept_loop(self) -> None:
        import os
        import threading

        try:
            # the accept/serve path answers live member requests: keep it
            # at normal priority (threads spawned here inherit it), while
            # the attach thread — and the XLA pool it creates — idles
            os.sched_setscheduler(0, os.SCHED_OTHER, os.sched_param(0))
        except (AttributeError, OSError, PermissionError):
            pass
        while not self._closing:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                break  # listener closed
            except Exception:
                continue  # failed handshake from one member; keep serving
            threading.Thread(target=self._serve, args=(conn,),
                             name="draw-server-member",
                             daemon=True).start()

    def _serve(self, conn) -> None:
        """Serve one member connection: dispatch requests on the shared
        plane immediately (the device queues programs; concurrent member
        threads interleave naturally under the GIL), answer in FIFO
        order. The blocking read at the bottom only happens when no new
        request is waiting in the pipe — the member that sent it is
        either already blocked on exactly this response or still running
        its rounds, so serving the oldest handle first is always
        progress."""
        from collections import deque

        pending: deque = deque()
        try:
            msg = conn.recv()
            if not (isinstance(msg, tuple) and msg and msg[0] == "hello"):
                return
            # the hello reply waits for the attach (member side polls
            # with its own abort, so a member tearing down mid-attach
            # just disconnects)
            while not self._ready.wait(0.25):
                if self._closing:
                    return
            plane = self.plane
            conn.send(("ok", self.dev_s, self.np_per_unit,
                       plane.SPEC_BUCKET, plane.max_batch))
            while not self._closing:
                while pending and pending[0][1].is_ready():
                    rid, h = pending.popleft()
                    conn.send((rid, h.read()))
                if conn.poll(0.001 if pending else 0.25):
                    msg = conn.recv()
                    op = msg[0]
                    if op == "bye":
                        break
                    rid, seed, lo, hi, npk, arg = msg[1:7]
                    if op == "draw":
                        h = plane.dispatch(lo, hi, npk, arg, seed=seed)
                    else:  # "min"
                        h = plane.dispatch_min(lo, hi, npk,
                                               min_bucket=arg, seed=seed)
                    pending.append((rid, h))
                    self.served_batches += 1
                    self.served_units += len(lo)
                elif pending:
                    rid, h = pending.popleft()
                    conn.send((rid, h.read()))
        except (EOFError, OSError, BrokenPipeError):
            pass  # member exited; its fallback twin is bit-identical
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        import os
        import shutil

        self._closing = True
        # closing the listener does NOT wake a thread blocked in
        # accept(): poke it with a throwaway connection so the accept
        # loop observes _closing and exits promptly
        try:
            from multiprocessing.connection import Client

            Client(self.address, family="AF_UNIX",
                   authkey=DRAW_SERVICE_AUTHKEY).close()
        except Exception:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2)
        # join the attach thread before returning: a daemon thread left
        # inside an XLA compile at interpreter exit dies by C++
        # std::terminate (the fleet-smoke SIGABRT). attach_cached polls
        # _closing between phases, so the residual wait is bounded by a
        # single compile; the timeout is a backstop for a wedged backend.
        self._attach_thread.join(timeout=120)
        shutil.rmtree(os.path.dirname(self.address), ignore_errors=True)
