"""Counter-based RNG: Threefry-2x32 (20 rounds).

Packet-loss sampling must be a pure function of (seed, unit id) so that the
numpy and TPU network backends — and any sharding layout — produce identical
drops (SURVEY.md §7 "Determinism across backends"). Python/numpy RNG state
would make results depend on execution order; a counter-based generator keyed
on stable unit ids does not.

This is the Threefry-2x32-20 function of Salmon et al., "Parallel Random
Numbers: As Easy as 1, 2, 3" (SC'11) — the same generator family JAX uses —
implemented once, parameterized over the array namespace (numpy or
jax.numpy) so both backends execute the exact same integer arithmetic.

Loss decisions avoid floats entirely: a unit is dropped iff
``draw_24bit < floor(loss * 2**24)`` with the threshold precomputed host-side
(see quantize_loss); integer compares are bit-identical everywhere.
"""

from __future__ import annotations

import contextlib

import numpy as np

_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)
_PARITY = 0x1BD11BDA


def threefry2x32(k0, k1, c0, c1, xp=np):
    """Threefry-2x32, 20 rounds. All args uint32 arrays (or scalars); returns
    (x0, x1) uint32. ``xp`` is numpy or jax.numpy."""
    u32 = xp.uint32

    def as_u32(v):
        return xp.asarray(v, dtype=xp.uint32)

    def cast(v):
        # uint32-op-uint32 already yields uint32: skip the copying astype
        # (same bits either way; this is the numpy hot path's biggest cost)
        return v if getattr(v, "dtype", None) == np.uint32 \
            else v.astype(xp.uint32)

    k0, k1, c0, c1 = as_u32(k0), as_u32(k1), as_u32(c0), as_u32(c1)
    ks = (k0, k1, xp.bitwise_xor(xp.bitwise_xor(k0, k1), u32(_PARITY)))

    def rotl(x, r):
        return cast(xp.bitwise_or(
            (x << u32(r)) & u32(0xFFFFFFFF), x >> u32(32 - r)
        ))

    # uint32 wraparound is intended; numpy warns on scalar overflow only.
    ctx = np.errstate(over="ignore") if xp is np else contextlib.nullcontext()
    with ctx:
        x0 = cast(c0 + ks[0])
        x1 = cast(c1 + ks[1])
        for group in range(5):
            rots = _ROT_A if group % 2 == 0 else _ROT_B
            for r in rots:
                x0 = cast(x0 + x1)
                x1 = rotl(x1, r)
                x1 = xp.bitwise_xor(x0, x1)
            j = group + 1
            x0 = cast(x0 + ks[j % 3])
            x1 = cast(x1 + ks[(j + 1) % 3] + u32(j))
    return x0, x1


def draw_24bit(seed: int, uid_lo, uid_hi, xp=np):
    """A 24-bit uniform integer per unit, keyed on (seed, uid). uid is the
    globally unique 64-bit unit id split into two uint32 halves."""
    k0 = np.uint32(seed & 0xFFFFFFFF)
    k1 = np.uint32((seed >> 32) & 0xFFFFFFFF)
    x0, _ = threefry2x32(k0, k1, uid_lo, uid_hi, xp=xp)
    return (x0 >> xp.uint32(8)).astype(xp.uint32)  # top 24 bits


def quantize_loss(reliability: np.ndarray) -> np.ndarray:
    """Precompute integer drop thresholds from a float32 reliability matrix:
    drop iff draw_24bit < threshold, threshold = round((1-rel) * 2**24).

    Computed once, host-side, in float64 for exactness; the per-unit compare
    is pure integer on both backends."""
    loss = 1.0 - reliability.astype(np.float64)
    thresh = np.rint(loss * float(1 << 24)).astype(np.int64)
    return np.clip(thresh, 0, 1 << 24).astype(np.uint32)
