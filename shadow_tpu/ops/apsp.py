"""All-pairs shortest path on the device: blocked min-plus repeated squaring.

The reference computes shortest-path latency/loss tables CPU-side with
Dijkstra over petgraph (SURVEY.md §2 "Network graph + routing"). For TPU we
re-cast APSP as ceil(log2(G)) min-plus matrix squarings — dense (G, G)
work that XLA tiles well — carrying path reliability along the argmin
decomposition exactly like the numpy canonical implementation
(shadow_tpu/network/graph.py::_apsp_minplus), with the same first-minimum
tie-breaking. For reachable pairs the two implementations agree exactly
(int32 saturation only ever affects candidates that lose the argmin; see
tests/test_apsp_device.py).

Memory: the (B, K, J) candidate tensor is blocked over rows via lax.map so
peak usage stays ~B * G^2 * 8 bytes regardless of G.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from shadow_tpu.network.graph import INF_I32, INF_I64


@functools.partial(jax.jit, static_argnames=("steps", "block"))
def _apsp_kernel(lat, rel, steps: int, block: int):
    g = lat.shape[0]
    nb = g // block
    j_idx = jnp.arange(g, dtype=jnp.int32)[None, :]

    def one_squaring(carry, _):
        lat, rel = carry

        def do_block(blk):
            lat_b, rel_b = blk  # (B, G)
            cand = lat_b[:, :, None] + lat[None, :, :]  # (B, K, J)
            cand = jnp.minimum(cand, INF_I32)
            k_star = jnp.argmin(cand, axis=1).astype(jnp.int32)  # first min
            new_lat = jnp.take_along_axis(cand, k_star[:, None, :], axis=1)[:, 0, :]
            rel_ik = jnp.take_along_axis(rel_b, k_star, axis=1)
            rel_kj = rel[k_star, j_idx]
            return new_lat, rel_ik * rel_kj

        blocks_lat = lat.reshape(nb, block, g)
        blocks_rel = rel.reshape(nb, block, g)
        new_lat, new_rel = jax.lax.map(do_block, (blocks_lat, blocks_rel))
        return (new_lat.reshape(g, g), new_rel.reshape(g, g)), None

    (lat, rel), _ = jax.lax.scan(one_squaring, (lat, rel), None, length=steps)
    return lat, rel


def apsp_device(latency_ns: np.ndarray, reliability: np.ndarray,
                block: int = 16) -> tuple[np.ndarray, np.ndarray]:
    """Device APSP. Input: (G, G) int64 one-hop latency with INF_I64
    sentinels and 0 diagonal, float32 one-hop reliability with 1.0 diagonal.
    Output in the same convention (int64 with INF_I64 where unreachable).

    Requires every finite edge latency < INF_I32 (~1.07 s) — validated.
    """
    g = latency_ns.shape[0]
    finite = latency_ns[latency_ns < INF_I64]
    if finite.size and finite.max() >= int(INF_I32):
        raise ValueError("edge latency >= ~1.07s: device APSP unsupported")
    # pad to a multiple of block with unreachable rows/cols
    gp = max(block, ((g + block - 1) // block) * block)
    lat32 = np.full((gp, gp), INF_I32, dtype=np.int32)
    rel32 = np.zeros((gp, gp), dtype=np.float32)
    lat32[:g, :g] = np.minimum(latency_ns, np.int64(INF_I32)).astype(np.int32)
    rel32[:g, :g] = reliability
    idx = np.arange(g, gp)
    lat32[idx, idx] = 0
    rel32[idx, idx] = 1.0

    steps = max(1, int(np.ceil(np.log2(max(g, 2)))))
    out_lat, out_rel = _apsp_kernel(jnp.asarray(lat32), jnp.asarray(rel32),
                                    steps=steps, block=block)
    out_lat = np.asarray(out_lat)[:g, :g].astype(np.int64)
    out_rel = np.asarray(out_rel)[:g, :g]
    out_lat[out_lat >= int(INF_I32)] = INF_I64
    return out_lat, out_rel
