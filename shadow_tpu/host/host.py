"""Simulated hosts.

A Host mirrors the reference's ``Host`` (SURVEY.md §2 "Host"): a simulated
machine with its own clock view, RNG stream, NIC token-bucket state (held in
the engine's arrays, indexed by host id), socket namespace, event queue, and
processes. All Host state is host-local: scheduler policies may execute
different hosts' events on different threads within a round; cross-host
interaction happens only through the network engine at round boundaries.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional

from shadow_tpu.core.events import BAND_APP, EventQueue
from shadow_tpu.core.rng import host_rng
from shadow_tpu.core.time import SimTime
from shadow_tpu.network import unit as U
from shadow_tpu.network.transport import (
    CONGESTION_CONTROLS, DatagramSocket, StreamEndpoint, ESTABLISHED)
from shadow_tpu.network.unit import Unit
from shadow_tpu.utils.counters import Counters

EPHEMERAL_BASE = 49152
LOG_LEVELS = ("error", "warning", "info", "debug", "trace")


class Host:
    def __init__(self, host_id: int, name: str, ip: str, node_id: int,
                 seed: int, controller, cc: Optional[str] = None) -> None:
        self.id = host_id
        self.name = name
        self.ip = ip
        self.node_id = node_id
        self.controller = controller
        self.engine = None  # set by controller after engine construction
        from shadow_tpu.network.fluid import HEADER, MTU

        # fluid quantum (experimental.unit_mtus): max stream payload bytes
        # per unit on this host's connections
        self.unit_chunk = (
            controller.cfg.experimental.unit_mtus * MTU - HEADER)
        #: congestion control for this host's stream endpoints
        #: (experimental.congestion_control, overridable per host via
        #: hosts.<name>.congestion_control); cc_id is the C twin's
        #: dispatch integer, read at core bind (colcore.c init_core)
        self.cc_name = cc or controller.cfg.experimental.congestion_control
        self.cc_id = CONGESTION_CONTROLS[self.cc_name].cc_id
        self.rng = host_rng(seed, host_id)
        self.equeue = EventQueue()
        self.counters = Counters()
        self._now: SimTime = 0
        self._uid_counter = 0
        self.egress: list[Unit] = []  # units emitted this round (FIFO)
        #: columnar-plane state (set when the engine is a ColumnarPlane):
        #: egress rows are plain tuples; resolved arrival rows for the
        #: current round land in _inbox (see network/colplane.py)
        self.colplane = None
        self.egress_rows: list[tuple] = []
        self._inbox = None
        self.ingress_deferred_rows: list[tuple] = []
        #: columnar transport engine (network/devtransport.py) when
        #: experimental.device_transport is on and the plane is the
        #: Python columnar one; ack-dominated rounds defer to the
        #: barrier and advance as one batched kernel (bit-identical)
        self.devt = None
        # hot-path counters kept as plain ints (Counter.__getitem__ per
        # unit measurably drags at 1M+ units); folded in fold_counters()
        self._n_emitted = 0
        self._n_delivered = 0
        self._n_dgrams = 0
        self._n_dgrams_recv = 0
        self._n_events = 0
        #: fault injection (shadow_tpu/faults.py): crashed-host flag, and
        #: per-host accounting for units dropped by teardown (arrivals at
        #: a down host + parked units cleared at crash) and units the
        #: engine blackholed for this source (cut links / no route)
        self.down = False
        self.faults_active = False  # set when a faults: section exists
        self._n_teardown = 0
        self._n_blackholed = 0
        self.ingress_deferred: list[Unit] = []  # ingress-bucket backlog
        self.processes: list = []
        # sockets
        self._listeners: dict[int, Callable] = {}  # port -> on_accept
        self._udp: dict[int, DatagramSocket] = {}
        self._conns: dict[tuple[int, int, int], StreamEndpoint] = {}
        self._next_ephemeral = EPHEMERAL_BASE
        self._log_lines: list[str] = []
        #: running hash over the log lines (determinism sentinel): updated
        #: per append so a digest sample costs O(new bytes), not a re-hash
        #: of the whole history every sampled round
        self._log_sha = hashlib.sha256()
        self._ack_eps: dict = {}  # endpoints owing a coalesced barrier ack
        self.pcap = None  # PcapWriter when hosts.<name>.pcap_enabled
        self.log_level = "info"  # per-host override (hosts.<name>.log_level)
        #: telemetry (shadow_tpu/telemetry/): the run's collector when a
        #: telemetry: section exists, else None (models check this ONCE at
        #: start, so the off path costs nothing per event). Flow records
        #: buffer host-locally (_flow_buf) and flush at round ends in
        #: host-id order — canonical regardless of scheduler policy.
        self.telemetry = None
        self._flow_buf: list = []

    # -- time & events ----------------------------------------------------
    @property
    def now(self) -> SimTime:
        return self._now

    def schedule(self, time: SimTime, fn: Callable[[], None],
                 band: int = BAND_APP, key: int = -1) -> int:
        return self.equeue.push(time, fn, band=band, key=key)

    def schedule_in(self, delay: SimTime, fn: Callable[[], None]) -> int:
        return self.equeue.push(self._now + delay, fn)

    def cancel(self, handle: int) -> None:
        self.equeue.cancel(handle)

    def fold_counters(self) -> None:
        """Fold the int-attribute hot counters into the Counter object
        (called once at finalize, before the controller merges)."""
        if self._n_emitted:
            self.counters.add("units_emitted", self._n_emitted)
        if self._n_delivered:
            self.counters.add("units_delivered", self._n_delivered)
        if self._n_dgrams:
            self.counters.add("dgrams_sent", self._n_dgrams)
        if self._n_dgrams_recv:
            self.counters.add("dgrams_received", self._n_dgrams_recv)
        if self._n_events:
            self.counters.add("events", self._n_events)
        if self._n_teardown:
            self.counters.add("units_teardown_dropped", self._n_teardown)
        if self._n_blackholed:
            self.counters.add("units_blackholed", self._n_blackholed)
        if self._n_teardown or self._n_blackholed:
            # per-host observability for fault experiments: the counts land
            # in this host's own log file beside the merged totals
            self.log(f"fault accounting: blackholed={self._n_blackholed} "
                     f"teardown_dropped={self._n_teardown}")
        self._n_emitted = self._n_delivered = self._n_dgrams = 0
        self._n_dgrams_recv = 0
        self._n_events = 0
        self._n_teardown = self._n_blackholed = 0

    def run_events(self, end: SimTime) -> int:
        """Execute all pending events with time < end (one round's worth).
        Under the columnar plane, resolved network rows (net_rows) merge
        with the heap in canonical (time, band, key) order — identical
        execution order to the per-unit plane's heap-only flow."""
        n = 0
        rows = self._inbox
        if rows is None:
            while (ev := self.equeue.pop_until(end)) is not None:
                self._now, task = ev
                task()
                n += 1
            self._n_events += n
            return n
        self._inbox = None
        devt = self.devt
        if devt is not None and devt.intercept(self, rows, end):
            # device transport: the whole round (inbox AND due timers)
            # defers to the barrier, where cohorts of clean acks across
            # hosts advance as ONE batched kernel and everything replays
            # through this method's exact merge discipline — the event
            # count reports through DeviceTransport.take_executed
            return 0
        eq = self.equeue
        heap = eq._heap
        dispatch = self.dispatch_row
        pos = 0
        ln = len(rows)
        # fast path: no heap events at all (common for workload hosts with
        # no pending timers) — straight row drain, re-checking only the
        # cheap emptiness bit in case a dispatch scheduled something
        while pos < ln and not heap:
            dispatch(rows[pos])
            pos += 1
            n += 1
        if heap:
            head = eq.head
            pop = eq.pop_until
            # the inbox<->heap merge with a CACHED head: while heap[0]
            # is still the validated head object (our local ref keeps it
            # alive, so identity is sound), its (t, band, key) is a
            # lower bound on the live head — a later cancel only moves
            # the live head LATER — so a row that beats it dispatches
            # without re-running head()'s cancelled-head scan. Anything
            # else re-validates. One identity check + tuple compare per
            # hot row instead of a method call.
            h0 = None
            while True:
                if h0 is not None and pos < ln and heap and heap[0] is h0:
                    row = rows[pos]
                    ti = row[0]
                    if (ti < h0[0]
                            or (ti == h0[0]
                                and (0, row[1]) < (h0[1], h0[2]))):
                        dispatch(row)
                        pos += 1
                        n += 1
                        continue
                h0 = head()
                hv = h0 is not None and h0[0] < end
                if pos < ln:
                    row = rows[pos]
                    ti = row[0]
                    # inbox rows are BAND_NET (0): they win same-time ties
                    # unless a heap net event carries a smaller key
                    if (not hv or ti < h0[0]
                            or (ti == h0[0]
                                and (0, row[1]) < (h0[1], h0[2]))):
                        dispatch(row)
                        pos += 1
                        n += 1
                        continue
                if hv:
                    self._now, task = pop(end)
                    task()
                    n += 1
                    continue
                break
        self._n_events += n
        return n

    def dispatch_row(self, row) -> None:
        """Columnar-plane arrival dispatch: the field-level twin of the
        per-unit plane's arrival event (engine.ingress_arrival + deliver).
        Charges the ingress token bucket at event time, in event order —
        exactly like the per-unit plane — parking the whole row into the
        deferred backlog when tokens run short."""
        (t, _key, _tgt, kind, peer, aport, bport, nbytes, seq, frag,
         nfrags, size, payload) = row
        if t > self._now:
            self._now = t
        if self.down:
            # crashed host: the arrival is consumed by the dead NIC — no
            # token charge, no delivery, no response; peers discover the
            # failure through their own RTO machinery (faults.py)
            self._n_teardown += 1
            return
        eng = self.engine
        if t >= eng.bootstrap_end:
            tokens = eng.tokens_down
            if tokens[self.id] >= size:
                tokens[self.id] -= size
            else:
                self.ingress_deferred_rows.append(row)
                eng._deferred.add(self)
                return
        self._deliver_row(t, kind, peer, aport, bport, nbytes, seq, frag,
                          nfrags, payload)

    def _deliver_row(self, t: SimTime, kind: int, peer: int, aport: int,
                     bport: int, nbytes: int, seq: int, frag: int,
                     nfrags: int, payload) -> None:
        """The row cleared the ingress bucket: dispatch to a socket."""
        if t > self._now:
            self._now = t
        self._n_delivered += 1
        if self.pcap is not None:
            self.pcap.capture_fields(
                kind, aport, bport, nbytes, seq, payload, t,
                self.controller.hosts[peer].ip, self.ip)
        if kind == U.DGRAM:
            sock = self._udp.get(bport)
            if sock is None:
                self.counters.add("units_unroutable", 1)
                return
            sock.handle_fields(nbytes, payload, (peer, aport), seq, frag,
                               nfrags, t)
            return
        key = (bport, peer, aport)
        ep = self._conns.get(key)
        if ep is None:
            if kind == U.SYN:
                on_accept = self._listeners.get(bport)
                if on_accept is None:
                    self.counters.add("units_unroutable", 1)
                    return
                ep = self._make_endpoint(bport, peer, aport,
                                         initiator=False)
                ep.state = ESTABLISHED
                ep.sender.adv_wnd = seq  # client window rides the SYN
                self._conns[key] = ep
                ep.emit(U.SYNACK, wnd=ep.receiver.window())
                on_accept(ep, t)
                return
            self.counters.add("units_unroutable", 1)
            return
        ep.handle_fields(kind, nbytes, payload, seq, t)

    def record_flow(self, kind: str, peer, t_open: SimTime,
                    ttfb: Optional[SimTime], nbytes: int, status: str,
                    retx: int = 0, x: Optional[int] = None) -> None:
        """One application-flow lifecycle record (telemetry/collector.py),
        called at flow close from model code. ``ttfb`` is absolute sim
        time of the first payload byte (None if none arrived); close time
        is the host clock now. ``x`` is an optional model-defined integer
        riding the record (the ABR model stores the segment's selected
        bitrate there; the summary and metrics_report reduce it to a
        mean). No-op when telemetry is off."""
        tel = self.telemetry
        if tel is None:
            return
        buf = self._flow_buf
        if not buf:
            tel.note_flow_host(self)
        buf.append((kind, peer, t_open, self._now,
                    (ttfb - t_open if ttfb is not None else None),
                    nbytes, status, retx, x))

    def mark_ack(self, ep) -> None:
        """Queue a coalesced barrier ack for this endpoint (transport's
        _ack); the columnar plane tracks owing hosts in a list instead of
        scanning all hosts at the barrier."""
        aeps = self._ack_eps
        if not aeps and self.colplane is not None:
            self.colplane.ack_hosts.append(self)
        aeps[ep] = None

    # -- units ------------------------------------------------------------
    def next_uid(self) -> int:
        uid = (self.id << 32) | self._uid_counter
        self._uid_counter += 1
        return uid

    def emit_unit(self, u: Unit) -> None:
        self.egress.append(u)
        self._n_emitted += 1
        if self.pcap is not None:
            ctl = self.controller
            self.pcap.capture(u, u.t_emit, self.ip, ctl.hosts[u.dst].ip)

    def emit_msg(self, kind: int, dst: int, size: int, nbytes: int,
                 payload, seq: int, sport: int, dport: int,
                 frag_idx: int = 0, nfrags: int = 1) -> None:
        """Field-level emission API shared by the transport and datagram
        layers. Columnar plane: one tuple append, no Unit object, no uid
        mint (uids are assigned vectorized at the barrier in the same
        per-host emission order). Per-unit plane: materialize a Unit, the
        reference-architecture data path."""
        cp = self.colplane
        if cp is not None:
            if self.pcap is not None:
                self.pcap.capture_fields(
                    kind, sport, dport, nbytes, seq, payload, self._now,
                    self.ip, self.controller.hosts[dst].ip)
            c = cp._c
            if c is not None:
                # C engine: packed egress row, no tuple (the C side also
                # tracks the emitters list and the emitted counter)
                c.emit_row(self.id, kind, dst, size, self._now, sport,
                           dport, nbytes, seq, frag_idx, nfrags, payload)
                return
            eg = self.egress_rows
            if not eg:
                cp.emitters.append(self)
            eg.append((kind, dst, size, self._now, sport, dport, nbytes,
                       seq, frag_idx, nfrags, payload))
            self._n_emitted += 1
            return
        self.emit_unit(Unit(
            uid=self.next_uid(),
            src=self.id,
            dst=dst,
            size=size,
            t_emit=self._now,
            kind=kind,
            src_port=sport,
            dst_port=dport,
            nbytes=nbytes,
            payload=payload,
            seq=seq,
            frag_idx=frag_idx,
            nfrags=nfrags,
        ))

    def deliver(self, u: Unit, now: SimTime) -> None:
        """A unit cleared the ingress token bucket: dispatch to a socket."""
        self._now = max(self._now, now)
        self._n_delivered += 1
        if self.pcap is not None:
            self.pcap.capture(u, now, self.controller.hosts[u.src].ip, self.ip)
        if u.kind == U.DGRAM:
            sock = self._udp.get(u.dst_port)
            if sock is not None:
                sock.handle(u, now)
            else:
                self.counters.add("units_unroutable", 1)
            return
        key = (u.dst_port, u.src, u.src_port)
        ep = self._conns.get(key)
        if ep is None and u.kind == U.SYN:
            on_accept = self._listeners.get(u.dst_port)
            if on_accept is None:
                self.counters.add("units_unroutable", 1)
                return
            ep = self._make_endpoint(u.dst_port, u.src, u.src_port,
                                     initiator=False)
            ep.state = ESTABLISHED
            ep.sender.adv_wnd = u.seq  # client window rides the SYN
            self._conns[key] = ep
            ep.emit(U.SYNACK, wnd=ep.receiver.window())
            on_accept(ep, now)
            return
        if ep is None:
            self.counters.add("units_unroutable", 1)
            return
        ep.handle(u, now)

    # -- determinism sentinel (shadow_tpu/checkpoint.py) ------------------
    def state_fingerprint(self) -> dict:
        """Plane-independent observable state for the per-round state
        digest. Everything listed is identical across the per-unit and
        columnar planes (and every scheduler policy) at a round boundary;
        BAND_NET heap entries are deliberately excluded — the planes
        represent in-flight arrivals differently (host heap vs pending
        store), and their effects surface through the counters and
        endpoint machines below."""
        from shadow_tpu.core.events import BAND_NET

        conns = []
        for key in sorted(self._conns):
            ep = self._conns[key]
            fp = getattr(ep, "fingerprint", None)
            conns.append((list(key),
                          fp() if fp is not None else type(ep).__name__))
        return {
            "now": self._now,
            "uid": self._uid_counter,
            "down": self.down,
            "emitted": self._n_emitted,
            "delivered": self._n_delivered,
            "dgrams": self._n_dgrams,
            "dgrams_recv": self._n_dgrams_recv,
            "events": self._n_events,
            "teardown": self._n_teardown,
            "blackholed": self._n_blackholed,
            # shim_fast_* class counters are censuses of WHERE managed
            # syscalls completed (in-shim vs worker) — mode-dependent by
            # design (SHADOW_TPU_SHIM_FASTPATH A/B), so the digest must
            # not see them; the "syscalls" total itself stays invariant
            # (the shim fold adds in-shim completions to it)
            "counters": {k: v for k, v in self.counters.c.items()
                         if not k.startswith("shim_fast_")},
            "rng": self.rng.bit_generator.state,
            "timers": self.equeue.live_times(exclude_band=BAND_NET),
            "conns": conns,
            "listeners": sorted(self._listeners),
            "udp": sorted(self._udp),
            "ephemeral": self._next_ephemeral,
            "log_lines": len(self._log_lines),
            "log_sha": (self._log_sha.hexdigest()
                        if self._log_lines else ""),
        }

    # -- checkpoint/restore (shadow_tpu/checkpoint.py) --------------------
    def __getstate__(self):
        d = self.__dict__.copy()
        del d["_log_sha"]  # hashlib objects cannot pickle; rebuilt below
        # runtime-only columnar-transport engine (holds a jax kernel
        # handle); reattached by Controller._reattach_runtime on restore
        d["devt"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.devt = None
        self._log_sha = hashlib.sha256()
        for ln in self._log_lines:
            self._log_sha.update(ln.encode() + b"\n")

    # -- fault lifecycle (shadow_tpu/faults.py) ---------------------------
    def crash(self, now: SimTime) -> None:
        """Host crash: instant power loss at a round start. Sockets and
        parked ingress units are torn down, application timers die with
        the host; queued BAND_NET arrivals stay queued and are discarded
        at delivery (event-count parity with the columnar plane, whose
        resolved arrivals live outside the heap). Processes are killed
        without exit status; reboot() respawns fresh instances."""
        from shadow_tpu.core.events import BAND_APP, BAND_FAULT

        self.down = True
        core = getattr(self.colplane, "_c", None)
        if core is not None:
            # C-side half of the teardown: mark the CHost down (its row
            # dispatch discards arrivals at the dead NIC, counting them
            # like dispatch_row does) and drop the C-registered gossip
            # handlers — a reboot re-registers fresh state. The endpoint
            # loop below works on C endpoints unchanged: CEp exposes the
            # same _cancel_ctl/_cancel_rto/state surface.
            core.host_crash(self.id)
        self.counters.add("host_crashes", 1)
        torn = 0
        for ep in list(self._conns.values()):
            cancel_ctl = getattr(ep, "_cancel_ctl", None)
            if cancel_ctl is not None:
                cancel_ctl()
                ep.sender._cancel_rto()
                ep.state = 0  # CLOSED — a lingering reference can't emit
            torn += 1
        if torn:
            self.counters.add("conns_torn_down", torn)
        self._conns.clear()
        self._listeners.clear()
        self._udp.clear()
        self._ack_eps.clear()
        parked = len(self.ingress_deferred) + len(self.ingress_deferred_rows)
        if parked:
            self._n_teardown += parked
            self.ingress_deferred.clear()
            self.ingress_deferred_rows.clear()
        self.equeue.clear_band(BAND_APP)
        # also clear BAND_FAULT: churn's minimum-1ns downtime draws can
        # quantize a reboot and the next crash into the SAME round start,
        # and the reboot's pending respawn must die with the host too
        self.equeue.clear_band(BAND_FAULT)
        for p in self.processes:
            kill = getattr(p, "kill", None)
            if kill is not None:
                kill()
        self.log(f"{now} host crashed")

    def reboot(self, now: SimTime) -> None:
        """Host reboot: processes that neither exited nor are running
        respawn as fresh instances, in BAND_FAULT so listeners exist
        before any same-instant network arrival."""
        from shadow_tpu.core.events import BAND_FAULT

        self.down = False
        core = getattr(self.colplane, "_c", None)
        if core is not None:
            core.host_boot(self.id)
        self.counters.add("host_boots", 1)
        self.log(f"{now} host rebooted")
        for p in self.processes:
            if p.exit_code is None and not p.running:
                # a process the crash caught BEFORE its configured start
                # (its spawn event died with the host) still honors its
                # start_time; everything else restarts at boot
                t = now if getattr(p, "spawned", True) \
                    else max(now, p.opts.start_time)
                self.schedule(t, p.spawn, band=BAND_FAULT)

    # -- sockets ----------------------------------------------------------
    def ephemeral_port(self) -> int:
        p = self._next_ephemeral
        self._next_ephemeral += 1
        return p

    def listen(self, port: int, on_accept: Callable) -> None:
        if port in self._listeners:
            raise ValueError(f"{self.name}: port {port} already listening")
        self._listeners[port] = on_accept

    def unlisten(self, port: int) -> None:
        self._listeners.pop(port, None)

    def _make_endpoint(self, local_port: int, remote_host: int,
                       remote_port: int, initiator: bool) -> StreamEndpoint:
        exp = self.controller.cfg.experimental
        core = getattr(self.colplane, "_c", None)
        if core is not None and self.pcap is None:
            # C stream endpoint (native/colcore): the exact protocol twin
            # of StreamEndpoint, bit-identical under the cross-plane and
            # colcore A/B suites; Python remains the oracle (and serves
            # pcap hosts, whose dispatch stays on the Python path)
            return core.make_endpoint(
                self.id, local_port, remote_host, remote_port,
                initiator, exp.socket_send_buffer, exp.socket_recv_buffer,
                self.cc_id)
        return StreamEndpoint(
            self, local_port, remote_host, remote_port, initiator=initiator,
            send_buffer=exp.socket_send_buffer,
            recv_buffer=exp.socket_recv_buffer,
            cc=self.cc_name,
        )

    def connect(self, remote_host: int, remote_port: int) -> StreamEndpoint:
        ep = self._make_endpoint(self.ephemeral_port(), remote_host,
                                 remote_port, initiator=True)
        self._conns[(ep.local_port, remote_host, remote_port)] = ep
        return ep  # caller sets callbacks, then calls ep.connect()

    def udp_socket(self, port: Optional[int] = None) -> DatagramSocket:
        if port is None:
            port = self.ephemeral_port()
        if port in self._udp:
            raise ValueError(f"{self.name}: UDP port {port} already bound")
        sock = DatagramSocket(self, port)
        self._udp[port] = sock
        return sock

    def find_endpoint(self, local_port: int, remote_host: int,
                      remote_port: int) -> Optional[StreamEndpoint]:
        return self._conns.get((local_port, remote_host, remote_port))

    def drop_endpoint(self, ep: StreamEndpoint) -> None:
        self._conns.pop((ep.local_port, ep.remote_host, ep.remote_port), None)

    # -- logging ----------------------------------------------------------
    def log(self, msg: str, level: str = "info") -> None:
        if LOG_LEVELS.index(level) <= LOG_LEVELS.index(self.log_level):
            self._log_lines.append(msg)
            self._log_sha.update(msg.encode() + b"\n")

    def flush_logs(self, data_dir) -> None:
        if not self._log_lines:
            return
        d = data_dir / "hosts" / self.name
        d.mkdir(parents=True, exist_ok=True)
        with open(d / f"{self.name}.log", "w") as f:
            f.write("\n".join(self._log_lines) + "\n")
