"""Host & process emulation layer (SURVEY.md §1 layer 5).

Phase-1 hosts run *plugin* workloads (Python apps over simulated sockets);
phase 4 adds real managed processes behind the same Host abstraction via the
native shim/IPC path (SURVEY.md §7 phase 4).
"""
