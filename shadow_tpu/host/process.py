"""Plugin processes: Python workloads running inside the simulation.

The reference runs real, unmodified Linux binaries as managed processes via
LD_PRELOAD + seccomp (SURVEY.md §2 "Process / ManagedThread", §3.2). That
native path is phase 4 (shadow_tpu/native/, SURVEY.md §7); this module is
the phase-1 plugin path: a workload is a Python class driven by simulated
callbacks, declared in config as ``path: pyapp:<module>:<Class>``.

Plugin apps see only the ProcessAPI facade — simulated sockets, simulated
time, per-host RNG — never real OS resources, so a plugin run is fully
deterministic and policy-independent.
"""

from __future__ import annotations

import importlib
from typing import Callable, Optional

from shadow_tpu.config.schema import ProcessOptions
from shadow_tpu.core.time import SimTime, emulated
from shadow_tpu.network.transport import DatagramSocket, StreamEndpoint


class ProcessAPI:
    """The world as a plugin app sees it."""

    def __init__(self, host, proc: "PluginProcess") -> None:
        self._host = host
        self._proc = proc

    # identity / environment
    @property
    def host_name(self) -> str:
        return self._host.name

    @property
    def host_id(self) -> int:
        return self._host.id

    @property
    def rng(self):
        return self._host.rng

    # time
    @property
    def now(self) -> SimTime:
        return self._host.now

    @property
    def wallclock_ns(self) -> int:
        return emulated(self._host.now)

    def after(self, delay_ns: SimTime, fn: Callable[[], None]) -> int:
        return self._host.schedule_in(delay_ns, fn)

    def cancel(self, handle: int) -> None:
        self._host.cancel(handle)

    # naming
    def resolve(self, name_or_ip: str) -> int:
        """Resolve a host name or IP string to a host id (simulated DNS)."""
        return self._host.controller.resolve(name_or_ip)

    # sockets
    def listen(self, port: int, on_accept: Callable[[StreamEndpoint, SimTime], None]) -> None:
        self._host.listen(port, on_accept)

    def connect(self, remote: str, port: int) -> StreamEndpoint:
        """Create a stream connection. Set callbacks on the returned endpoint,
        then call .connect() on it."""
        return self._host.connect(self._host.controller.resolve(remote), port)

    def udp_socket(self, port: Optional[int] = None) -> DatagramSocket:
        return self._host.udp_socket(port)

    # logging & lifecycle
    def log(self, msg: str) -> None:
        self._host.log(f"{self._host.now} [{self._proc.name}] {msg}")

    def exit(self, code: int = 0) -> None:
        self._proc.finish(code)


class ProcessLifecycle:
    """Shared exit accounting + expected_final_state validation for both
    plugin processes and native managed processes (native/managed.py)."""

    def finish(self, code: int) -> None:
        self.running = False
        if self.exit_code is None:
            self.exit_code = code
            self.host.counters.add("processes_exited", 1)

    def check_final_state(self) -> Optional[str]:
        """Validate expected_final_state at sim end; returns an error or None."""
        exp = self.opts.expected_final_state
        if exp is None:
            return None
        if exp == "running":
            if not self.running:
                return (f"{self.host.name}/{self.name}: expected running, "
                        f"exited {self.exit_code}")
            return None
        if isinstance(exp, dict) and "exited" in exp:
            want = int(exp["exited"])
            if self.running:
                return f"{self.host.name}/{self.name}: expected exit {want}, still running"
            if self.exit_code != want:
                return (f"{self.host.name}/{self.name}: expected exit {want}, "
                        f"got {self.exit_code}")
            return None
        if isinstance(exp, dict) and "signaled" in exp:
            # native managed processes record signal deaths as -signum
            want = -int(exp["signaled"])
            if self.running:
                return (f"{self.host.name}/{self.name}: expected signal "
                        f"{-want}, still running")
            if self.exit_code != want:
                return (f"{self.host.name}/{self.name}: expected signal "
                        f"{-want}, got exit code {self.exit_code}")
            return None
        return f"{self.host.name}/{self.name}: unrecognized expected_final_state {exp!r}"


class PluginProcess(ProcessLifecycle):
    """Lifecycle wrapper for one configured plugin-process instance."""

    PYAPP_PREFIX = "pyapp:"

    def __init__(self, host, opts: ProcessOptions, index: int) -> None:
        self.host = host
        self.opts = opts
        self.name = f"{_basename(opts.path)}.{index}"
        self.exit_code: Optional[int] = None
        self.running = False
        self.spawned = False  # ever spawned (host reboot respects start_time)
        self.app = None

    #: spec -> app class; import_module per spawn costs an import-lock
    #: round trip, which 100k same-model clients pay 100k times
    _app_classes: dict = {}

    @classmethod
    def is_plugin_path(cls, path: str) -> bool:
        return path.startswith(cls.PYAPP_PREFIX)

    def spawn(self) -> None:
        """The process start event (reference analog: SURVEY.md §3.2)."""
        spec = self.opts.path[len(self.PYAPP_PREFIX):]
        app_cls = self._app_classes.get(spec)
        if app_cls is None:
            try:
                mod_name, cls_name = spec.rsplit(":", 1)
            except ValueError as exc:
                raise ValueError(
                    f"bad pyapp path {self.opts.path!r} "
                    f"(want pyapp:module:Class)"
                ) from exc
            mod = importlib.import_module(mod_name)
            app_cls = getattr(mod, cls_name)
            self._app_classes[spec] = app_cls
        api = ProcessAPI(self.host, self)
        self.app = app_cls(api, list(self.opts.args), dict(self.opts.environment))
        self.running = True
        self.spawned = True
        self.host.counters.add("processes_spawned", 1)
        self.app.start()

    def shutdown(self) -> None:
        """The configured shutdown_time fired (graceful stop request)."""
        if self.running and self.app is not None:
            stop = getattr(self.app, "stop", None)
            if stop is not None:
                stop()
            if self.running:  # app didn't exit itself
                self.finish(0)

    def kill(self) -> None:
        """Host crash (shadow_tpu/faults.py): the process dies instantly —
        no stop() callback, no exit code (it neither exited nor was
        signaled in the simulated world). A reboot respawns a fresh
        instance via spawn()."""
        if self.running:
            self.running = False
            self.app = None



def _basename(path: str) -> str:
    if path.startswith(PluginProcess.PYAPP_PREFIX):
        return path.rsplit(":", 1)[-1].lower()
    return path.rsplit("/", 1)[-1]
