"""Utilities: unit parsing, logging, counters, heartbeat, pcap, status."""
