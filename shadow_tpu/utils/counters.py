"""Counters — global and per-host object/event counters with an end-of-run
summary, mirroring the reference's counter subsystem (SURVEY.md §2
"Counters / heartbeat", §5.1c)."""

from __future__ import annotations

from collections import Counter


class Counters:
    __slots__ = ("c",)

    def __init__(self) -> None:
        self.c: Counter = Counter()

    def add(self, name: str, n: int = 1) -> None:
        self.c[name] += n

    def get(self, name: str) -> int:
        return self.c.get(name, 0)

    def merge(self, other: "Counters") -> None:
        self.c.update(other.c)

    def summary(self) -> str:
        if not self.c:
            return "counters: (none)"
        items = ", ".join(f"{k}={v}" for k, v in sorted(self.c.items()))
        return f"counters: {items}"

    def as_dict(self) -> dict:
        return dict(sorted(self.c.items()))
