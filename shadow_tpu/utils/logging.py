"""Simulator logging: a main shadow.log plus per-host logs.

Mirrors the reference's logger + output-tree layout (SURVEY.md §2 "Logger",
§5.5): main log to ``<data_dir>/shadow.log`` (and mirrored to stderr),
per-host lines to ``<data_dir>/hosts/<name>/``. Log content that feeds
determinism tests contains sim time only — wall-clock appears only in
heartbeat lines, which determinism tests exclude.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Optional

LEVELS = {"error": 40, "warning": 30, "info": 20, "debug": 10, "trace": 5}


class SimLogger:
    def __init__(self, level: str = "info", path: Optional[Path] = None,
                 mirror_stderr: bool = True) -> None:
        self.level = LEVELS[level]
        self.lines: list[str] = []
        self.path = path
        self.mirror = mirror_stderr

    def log(self, level: str, msg: str) -> None:
        if LEVELS[level] < self.level:
            return
        line = f"[{level}] {msg}"
        self.lines.append(line)
        if self.mirror:
            print(line, file=sys.stderr)

    def error(self, msg: str) -> None:
        self.log("error", msg)

    def warning(self, msg: str) -> None:
        self.log("warning", msg)

    def info(self, msg: str) -> None:
        self.log("info", msg)

    def debug(self, msg: str) -> None:
        self.log("debug", msg)

    def flush(self) -> None:
        if self.path is not None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "w") as f:
                f.write("\n".join(self.lines) + ("\n" if self.lines else ""))
