"""Per-host pcap capture of simulated traffic.

Reference analog: SURVEY.md §2 "Pcap capture" (optional per-host pcap files
for wireshark analysis). Classic pcap format (not pcapng), LINKTYPE_RAW
(101): each record is a synthesized IPv4 packet — TCP for stream units, UDP
for datagrams — sized to the unit's wire size and truncated to the
configured capture size. One record per *unit* (a unit models up to
MAX_PKTS MTU packets travelling together; the record's orig_len reports the
full wire size, so byte accounting in analysis tools stays exact).
"""

from __future__ import annotations

import socket
import struct
from typing import Optional

from shadow_tpu.core.time import NS_PER_SEC
from shadow_tpu.network import unit as U

LINKTYPE_RAW = 101

_TCP_FLAGS = {
    U.SYN: 0x02, U.SYNACK: 0x12, U.DATA: 0x18,  # PSH|ACK
    U.ACK: 0x10, U.FIN: 0x11, U.FINACK: 0x11,
}


class PcapWriter:
    def __init__(self, path, snaplen: int = 65535) -> None:
        self.snaplen = int(snaplen)
        self._f = open(path, "wb")
        self._f.write(struct.pack(
            "<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, self.snaplen, LINKTYPE_RAW))
        self.records = 0

    def capture(self, unit, t_ns: int, src_ip: str, dst_ip: str) -> None:
        self.capture_fields(unit.kind, unit.src_port, unit.dst_port,
                            unit.nbytes, unit.seq, unit.payload, t_ns,
                            src_ip, dst_ip)

    def capture_fields(self, kind: int, src_port: int, dst_port: int,
                       nbytes: int, seq: int, payload, t_ns: int,
                       src_ip: str, dst_ip: str) -> None:
        if kind == U.DGRAM:
            l4 = struct.pack(">HHHH", src_port, dst_port, 8 + nbytes, 0)
            proto = socket.IPPROTO_UDP
        else:
            l4 = struct.pack(">HHIIBBHHH", src_port, dst_port,
                             seq & 0xFFFFFFFF, 0, 5 << 4,
                             _TCP_FLAGS.get(kind, 0x10), 65535, 0, 0)
            proto = socket.IPPROTO_TCP
        payload = payload or b"\0" * nbytes
        total = 20 + len(l4) + len(payload)
        ip = struct.pack(">BBHHHBBH4s4s", 0x45, 0, total, self.records & 0xFFFF,
                         0, 64, proto, 0, socket.inet_aton(src_ip),
                         socket.inet_aton(dst_ip))
        pkt = (ip + l4 + payload)[: self.snaplen]
        self._f.write(struct.pack("<IIII", t_ns // NS_PER_SEC,
                                  (t_ns % NS_PER_SEC) // 1000, len(pkt), total))
        self._f.write(pkt)
        self.records += 1

    def close(self) -> None:
        self._f.close()


def read_packet_count(path) -> int:
    """Count records in a classic pcap file (tests/tooling helper)."""
    with open(path, "rb") as f:
        f.read(24)
        n = 0
        while True:
            hdr = f.read(16)
            if len(hdr) < 16:
                return n
            incl = struct.unpack("<IIII", hdr)[2]
            f.seek(incl, 1)
            n += 1
