"""Unit parsing for config values (bandwidth, sizes).

The reference's YAML uses human-unit strings like ``1 Gbit`` for host
bandwidths and ``16 MiB`` for buffer sizes (SURVEY.md §5.6).  We normalize:

- bandwidth -> bytes per second (int)
- sizes     -> bytes (int)

Bit units are decimal (1 Gbit = 1e9 bit); byte units support both decimal
(kB/MB/GB) and binary (KiB/MiB/GiB) prefixes.
"""

from __future__ import annotations

_BIT_PREFIX = {
    "": 1, "k": 10**3, "kilo": 10**3, "m": 10**6, "mega": 10**6,
    "g": 10**9, "giga": 10**9, "t": 10**12, "tera": 10**12,
    # base-1024 bit prefixes (tornettools emits "... Kibit" bandwidths)
    "ki": 2**10, "kibi": 2**10, "mi": 2**20, "mebi": 2**20,
    "gi": 2**30, "gibi": 2**30, "ti": 2**40, "tebi": 2**40,
}

_BYTE_UNITS = {
    "b": 1, "byte": 1, "bytes": 1,
    "kb": 10**3, "kilobyte": 10**3, "kilobytes": 10**3,
    "mb": 10**6, "megabyte": 10**6, "megabytes": 10**6,
    "gb": 10**9, "gigabyte": 10**9, "gigabytes": 10**9,
    "tb": 10**12, "terabyte": 10**12, "terabytes": 10**12,
    "kib": 2**10, "kibibyte": 2**10, "kibibytes": 2**10,
    "mib": 2**20, "mebibyte": 2**20, "mebibytes": 2**20,
    "gib": 2**30, "gibibyte": 2**30, "gibibytes": 2**30,
    "tib": 2**40, "tebibyte": 2**40, "tebibytes": 2**40,
}


def _split_num_unit(s: str) -> tuple[float, str, str]:
    """Returns (number, lowercased unit, raw-case unit)."""
    s = s.strip()
    i = 0
    while i < len(s) and (s[i].isdigit() or s[i] in ".+-eE"):
        # guard against consuming the 'e' of a unit like "eb": require the
        # char after 'e'/'E' to be a digit or sign for it to count as exponent
        if s[i] in "eE" and not (i + 1 < len(s) and (s[i + 1].isdigit() or s[i + 1] in "+-")):
            break
        i += 1
    num = s[:i].strip()
    raw = s[i:].strip().replace(" ", "")
    if not num:
        raise ValueError(f"no numeric part in {s!r}")
    return float(num), raw.lower(), raw


def parse_bandwidth(value) -> int:
    """Parse a bandwidth config value into bytes/second.

    Accepts ints (bits/s? no — the reference convention is unit-suffixed
    strings; a bare int is taken as bytes/second), or strings:
    "1 Gbit" (per second implied), "10 Mbit/s", "125 MB/s", "1000 kibibyte/s".
    """
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return int(value)
    num, unit, raw = _split_num_unit(str(value))
    if raw.endswith("Bps"):  # capital B: bytes/sec (MBps = megabytes/s)
        base = unit[:-3]
        if base in _BIT_PREFIX:
            return int(num * _BIT_PREFIX[base])
    if unit.endswith("bps"):  # Mbps/Gbps/kbps are bit units
        base = unit[:-3]
        if base in _BIT_PREFIX:
            return int(num * _BIT_PREFIX[base] / 8)
    for suffix in ("/s", "ps", "persec", "persecond"):
        if unit.endswith(suffix) and unit not in _BYTE_UNITS:
            unit = unit[: -len(suffix)]
            break
    if unit.endswith("bit") or unit.endswith("bits"):
        base = unit[: unit.rindex("bit")]
        if base not in _BIT_PREFIX:
            raise ValueError(f"unknown bandwidth unit in {value!r}")
        return int(num * _BIT_PREFIX[base] / 8)
    if unit in _BYTE_UNITS:
        return int(num * _BYTE_UNITS[unit])
    raise ValueError(f"unknown bandwidth unit in {value!r}")


def parse_size(value) -> int:
    """Parse a size config value into bytes. Bare numbers are bytes."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return int(value)
    num, unit, _raw = _split_num_unit(str(value))
    if unit in _BYTE_UNITS:
        return int(num * _BYTE_UNITS[unit])
    if unit == "":
        return int(num)
    if unit.endswith("bit") or unit.endswith("bits"):
        base = unit[: unit.rindex("bit")]
        if base in _BIT_PREFIX:
            return int(num * _BIT_PREFIX[base] / 8)
    raise ValueError(f"unknown size unit in {value!r}")
