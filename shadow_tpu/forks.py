"""Scenario multiverse: checkpoint-forked what-if trees + a comparative reducer.

One expensive trunk run becomes N cheap counterfactuals: restore ONE
boundary checkpoint (shadow_tpu/checkpoint.py, format v5) into M fleet
workers (shadow_tpu/fleet.py) and diverge each branch AFTER the fork
point — by injected live commands replayed through the existing
``commands.jsonl`` machinery, by a volatile config overlay, or (for
divergence axes that are part of the checkpoint's config identity: seed,
fault timeline, congestion control) by an honest cold re-run inside the
same fleet. "Once is Never Enough" (Jansen/Tracey/Goldberg, USENIX
Security '21 — PAPERS.md) supplies the statistics discipline the reducer
applies: the per-branch statistic first, the t-based CI across branches.

The honesty gate (what makes forked results citable): every branch's
output tree and streams are byte-identical to a cold-start run of the
same (config, commands, seed) tuple. For a restore branch that holds
because (a) the trunk's stream prefixes are copied into the branch
directory truncated at the fork boundary by exactly the
``supervise.rollback_streams`` keep rules, (b) the restored pickle
continues them bit-exactly (the checkpoint contract), and (c) the merged
replay log — trunk command history at or before the fork point plus the
branch's injected commands strictly after it — re-applies through the
round loop's replay plane, which skips the prefix on resume and logs the
suffix identically to a cold replay. For a managed (reexec) trunk the
prefix re-executes once per branch from round 0 with digest + guest
cursor verification at the fork boundary, so the branch IS a cold run.
Cold branches (seed/faults/congestion-control divergence) run from
scratch by construction and their manifests say so by name.

Every branch directory carries ``fork_manifest.json``: the trunk
checkpoint digest, the divergence spec, the mode (restore/cold, with the
cold reason named), and the output tree/stream sha256s.

The reducer (``reduce_fork`` / ``tools/compare.py`` / ``fleet report
--compare``) k-way merges per-branch ``LogHistogram`` states, groups
branches (``group:`` in branches.yaml; default = the branch name), and
renders per-group flow percentiles diffed against the trunk with t-based
CI95 over per-branch percentile deltas, marking deltas whose CI excludes
zero. ``tools/bisect_divergence.py --a DIR --b DIR`` names the first
round where two branches' digest streams diverge.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import re
import shutil
import sys
import time as _walltime  # detlint: ok(wallclock): branch wall accounting
from pathlib import Path

FORK_MANIFEST = "fork_manifest.json"
FORK_SUMMARY = "fork_summary.json"
BRANCH_FORMAT = "shadow_tpu-fork-branch"
FORK_SUMMARY_FORMAT = "shadow_tpu-fork-summary"
PLAN_FORMAT = "shadow_tpu-fork-plan"
#: the merged replay log written into each branch directory (trunk
#: command history <= fork point + injected commands > fork point): the
#: "commands" leg of the (config, commands, seed) tuple the honesty gate
#: compares against
REPLAY_FILE = "fork_replay_commands.jsonl"

#: volatile config keys a branch overlay may set: run-shape policy that
#: checkpoint restore honors (VOLATILE_CONFIG_KEYS) *minus* the keys the
#: fork runner itself manages and the keys that would change the
#: already-started output streams mid-run
OVERLAY_ALLOWED = frozenset({
    "general.log_level",
    "general.progress",
    "general.heartbeat_interval",
    "general.checkpoint_every",
    "general.checkpoint_dir",
    "experimental.native_colcore",
    "experimental.device_transport",
})
#: volatile keys the fork runner owns per branch — an overlay naming one
#: is refused with its own wording (not the generic non-volatile error)
OVERLAY_FORK_MANAGED = frozenset({
    "general.data_directory",
    "general.replay_commands",
    "general.live_endpoint",
})
#: volatile, but changing it at the fork point re-cadences a stream that
#: is already half-written — the branch would no longer be byte-identical
#: to its cold twin
OVERLAY_STREAM_KEYS = frozenset({"general.state_digest_every"})

_BRANCH_KEYS = ("name", "group", "seed", "faults", "congestion_control",
                "overlay", "commands", "command_script")
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class ForkError(ValueError):
    """A fork plan could not be built or a branch could not run."""


def _dumps(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def branch_dir(fork_dir, name: str) -> Path:
    return Path(fork_dir) / f"branch_{name}"


# -- branches.yaml ------------------------------------------------------------

def load_branches(path) -> list:
    """Parse + validate a branches.yaml: a top-level ``branches:`` list
    of divergence specs. Each entry needs a filesystem-safe unique
    ``name``; everything else is a divergence axis (``seed``, ``faults``,
    ``congestion_control``, ``overlay``, ``commands``,
    ``command_script``) plus an optional ``group`` for the reducer."""
    import yaml

    try:
        doc = yaml.safe_load(Path(path).read_text())
    except OSError as exc:
        raise ForkError(f"cannot read branches file {path}: {exc}")
    branches = (doc or {}).get("branches") if isinstance(doc, dict) else None
    if not isinstance(branches, list) or not branches:
        raise ForkError(
            f"{path}: want a top-level 'branches:' list with at least "
            f"one entry")
    seen = set()
    for i, b in enumerate(branches):
        if not isinstance(b, dict):
            raise ForkError(f"{path}: branches[{i}] must be a mapping")
        name = b.get("name")
        if not isinstance(name, str) or not _NAME_RE.match(name):
            raise ForkError(
                f"{path}: branches[{i}]: 'name' must be a filesystem-"
                f"safe string ([A-Za-z0-9._-], <= 64 chars), got {name!r}")
        if name in seen:
            raise ForkError(f"{path}: duplicate branch name {name!r}")
        seen.add(name)
        unknown = sorted(set(b) - set(_BRANCH_KEYS))
        if unknown:
            raise ForkError(
                f"{path}: branch {name!r}: unknown keys {unknown} "
                f"(want {list(_BRANCH_KEYS)})")
    return branches


# -- the fork plan ------------------------------------------------------------

def _validate_overlay(name: str, overlay: dict) -> None:
    for k in overlay:
        if k in OVERLAY_FORK_MANAGED:
            raise ForkError(
                f"branch {name!r}: overlay key {k!r} is managed by the "
                f"fork runner (each branch gets its own directory, replay "
                f"log, and no endpoint) — it cannot be overlaid")
        if k in OVERLAY_STREAM_KEYS:
            raise ForkError(
                f"branch {name!r}: overlay key {k!r} would re-cadence the "
                f"digest stream at the fork point, so the branch would no "
                f"longer be byte-identical to its cold-start twin — set "
                f"it on the trunk run instead")
        if k.startswith("telemetry"):
            raise ForkError(
                f"branch {name!r}: overlay key {k!r} would re-cadence the "
                f"telemetry streams mid-run and break the branch's "
                f"byte-identity with its cold-start twin — set it on the "
                f"trunk run instead")
        if k not in OVERLAY_ALLOWED:
            raise ForkError(
                f"branch {name!r}: overlay key {k!r} is not volatile — a "
                f"branch that changes simulation semantics is a different "
                f"simulation, not a fork of this one; diverge by 'seed:', "
                f"'faults:', or 'congestion_control:' (an honest cold "
                f"re-run), or overlay one of {sorted(OVERLAY_ALLOWED)}")


def _branch_commands(name: str, spec: dict, fork_t: int) -> list:
    """Normalize a branch's injected command script into replay records
    (strictly after the fork point; refused otherwise by name)."""
    from shadow_tpu import live as _live
    from shadow_tpu.core.time import parse_time

    recs = []
    for j, c in enumerate(spec.get("commands") or ()):
        if not isinstance(c, dict) or "t" not in c:
            raise ForkError(
                f"branch {name!r}: commands[{j}] must be a mapping with "
                f"a 't' (sim time) and a 'cmd'")
        try:
            t = int(parse_time(c["t"]))
            norm = _live.normalize_command(
                {k: v for k, v in c.items() if k != "t"})
        except ValueError as exc:
            raise ForkError(f"branch {name!r}: commands[{j}]: {exc}")
        recs.append({"cmd": norm, "round": 0, "seq": 0, "t": t})
    script = spec.get("command_script")
    if script:
        try:
            loaded = _live.load_command_log(script)
        except (OSError, ValueError) as exc:
            raise ForkError(
                f"branch {name!r}: command_script {script}: {exc}")
        recs.extend({"cmd": r["cmd"], "round": 0, "seq": 0,
                     "t": int(r["t"])} for r in loaded)
    recs.sort(key=lambda r: r["t"])
    for r in recs:
        if r["t"] <= fork_t:
            raise ForkError(
                f"branch {name!r}: injected command at t={r['t']} ns is "
                f"at or before the fork point (sim {fork_t} ns) — the "
                f"trunk prefix is already decided; inject commands "
                f"strictly after the checkpoint boundary")
    return recs


def plan_fork(config_path, ckpt_path, branches: list, fork_dir,
              overrides: dict = None, trunk_dir=None) -> dict:
    """Validate a fork up front and return the JSON-safe plan document
    the fleet ships to its workers: trunk checkpoint identity, per-branch
    divergence (restore vs. cold, with cold reasons named), and the
    merged replay records. Every refusal names its cause here, before a
    single worker spawns."""
    from shadow_tpu import checkpoint as _ckpt
    from shadow_tpu import live as _live
    from shadow_tpu.config import load_config

    ckpt = Path(ckpt_path)
    header = _ckpt.read_header(ckpt)  # CheckpointError on non-checkpoints
    ver = int(header.get("version") or 0)
    if header.get("managed") and ver < _ckpt.VERSION:
        raise ForkError(
            f"{ckpt}: managed guests require checkpoint format v5 "
            f"(deterministic re-execution cursors); this file claims "
            f"version {ver} — re-checkpoint the trunk with a current "
            f"build before forking")
    if ver != _ckpt.VERSION:
        raise ForkError(
            f"{ckpt}: cannot fork a version-{ver} checkpoint — forking "
            f"needs format v{_ckpt.VERSION} (re-checkpoint the trunk "
            f"with a current build)")
    reexec = header.get("mode") == "reexec"
    fork_t = int(header["sim_time_ns"])
    fork_rounds = int(header["rounds"])

    # fork-level overrides apply to EVERY branch — including telemetry
    # flags, which must reproduce the trunk invocation's (the same way
    # --resume-from re-passes them): the restored collector continues
    # its streams bit-exactly when the section matches. Per-BRANCH
    # telemetry divergence is refused (_validate_overlay).
    over = dict(overrides or {})
    base_cfg = load_config(str(config_path), over, cache_doc=True)
    want, got = header["config_digest"], _ckpt.config_digest(base_cfg)
    if want != got:
        raise ForkError(
            f"{ckpt}: config mismatch — the checkpoint was written under "
            f"a different simulation config (digest {want[:12]} vs "
            f"{got[:12]}); a fork trunk must be restored under the exact "
            f"configuration that produced it (volatile keys excepted). "
            f"Per-branch divergence goes in branches.yaml, not the base "
            f"config.")
    if base_cfg.telemetry is not None and base_cfg.telemetry.metrics_dir:
        raise ForkError(
            "telemetry.metrics_dir is set: every branch would append to "
            "one shared metrics directory — forking needs per-run stream "
            "locations (the default: the run's data_directory)")

    if trunk_dir is None and ckpt.parent.name == "checkpoints":
        # the default layout: <trunk>/checkpoints/ckpt_t*.ckpt
        trunk_dir = ckpt.parent.parent
    trunk_dir = Path(trunk_dir) if trunk_dir is not None else None

    # the trunk's command history: every branch inherits it (<= fork
    # point); a reexec snapshot embeds it, a pickle trunk recorded it in
    # the run directory's commands.jsonl
    trunk_cmds = []
    if reexec:
        with open(ckpt, "rb") as f:
            f.readline()
            try:
                payload = json.loads(f.readline())
            except ValueError as exc:
                raise ForkError(
                    f"{ckpt}: corrupt re-execution snapshot payload "
                    f"({exc})")
        trunk_cmds = [r for r in (payload.get("commands") or ())
                      if int(r["t"]) <= fork_t]
    elif trunk_dir is not None and (trunk_dir / "commands.jsonl").is_file():
        trunk_cmds = [r for r in
                      _live.load_command_log(trunk_dir / "commands.jsonl")
                      if int(r["t"]) <= fork_t]
    next_seq = max((int(r["seq"]) for r in trunk_cmds), default=0) + 1

    plans: dict = {}
    order: list = []
    for spec in branches:
        name = spec["name"]
        divergence = {k: spec[k] for k in _BRANCH_KEYS[2:] if k in spec}
        b_over = dict(over)
        cold_reason = None
        if "seed" in spec:
            b_over["general.seed"] = int(spec["seed"])
            cold_reason = ("general.seed is part of the checkpoint's "
                           "config identity")
        if "faults" in spec:
            b_over["faults"] = spec["faults"]
            cold_reason = ("the fault timeline is part of the "
                           "checkpoint's config identity")
        if "congestion_control" in spec:
            b_over["experimental.congestion_control"] = \
                str(spec["congestion_control"])
            cold_reason = ("experimental.congestion_control is part of "
                           "the checkpoint's config identity")
        _validate_overlay(name, spec.get("overlay") or {})
        b_over.update(spec.get("overlay") or {})
        injected = _branch_commands(name, spec, fork_t)
        for i, rec in enumerate(injected):
            rec["seq"] = next_seq + i
        mode = "cold" if cold_reason else "restore"
        if mode == "restore" and not reexec and trunk_dir is None:
            raise ForkError(
                f"branch {name!r} restores the trunk checkpoint, which "
                f"needs the trunk run directory (stream prefixes + "
                f"command history), but none could be derived from "
                f"{ckpt} — pass --trunk-dir")
        # the branch's (config, commands, seed) tuple: trunk history plus
        # this branch's injected suffix. A cold branch replays the whole
        # log from round 0; a restore branch resumes past the prefix.
        replay = trunk_cmds + injected
        plans[name] = {
            "name": name,
            "group": str(spec.get("group") or name),
            "mode": mode,
            "cold_reason": cold_reason,
            "overrides": b_over,
            "replay": replay,
            "divergence": divergence,
            "seed": int(b_over.get("general.seed",
                                   base_cfg.general.seed)),
        }
        order.append(name)
    return {
        "format": PLAN_FORMAT,
        "config": str(config_path),
        "overrides": over,
        "fork_dir": str(fork_dir),
        "ckpt": str(ckpt),
        "ckpt_sha256": hashlib.sha256(ckpt.read_bytes()).hexdigest(),
        "config_digest": want,
        "ckpt_t": fork_t,
        "ckpt_rounds": fork_rounds,
        "reexec": bool(reexec),
        "trunk_dir": str(trunk_dir) if trunk_dir is not None else None,
        "seed": int(base_cfg.general.seed),
        "branches": plans,
        "order": order,
    }


# -- branch execution (fleet worker side) -------------------------------------

def _copy_filtered(src: Path, dst: Path, keep) -> None:
    """Copy ``src`` to ``dst`` keeping only records ``keep`` accepts —
    the copying twin of supervise._filter_jsonl (unparseable lines are
    kept; an empty result writes no file, matching a run that never
    created the stream)."""
    if not src.is_file():
        return
    out = []
    with open(src) as f:
        for line in f:
            s = line.rstrip("\n")
            if not s:
                continue
            try:
                rec = json.loads(s)
            except ValueError:
                out.append(s)
                continue
            if keep(rec):
                out.append(s)
    if out:
        dst.write_text("".join(x + "\n" for x in out))


def _copy_prefix_streams(fork: dict, dst: Path) -> None:
    """Seed a restore branch's directory with the trunk's stream
    prefixes truncated at the fork boundary — the exact keep rules
    ``supervise.rollback_streams`` applies when truncating in place, so
    the restored run's appends continue them byte-identically."""
    from shadow_tpu.supervise import stream_prefix_keep

    src = Path(fork["trunk_dir"])
    keeps = stream_prefix_keep(fork["ckpt_rounds"], fork["ckpt_t"])
    for name, keep in keeps.items():
        _copy_filtered(src / name, dst / name, keep)
    for sidecar in ("state_digests.shard*.jsonl", "flows.shard*.jsonl"):
        base = sidecar.split(".", 1)[0] + ".jsonl"
        for p in sorted(src.glob(sidecar)):
            _copy_filtered(p, dst / p.name, keeps[base])


def _branch_stream_digests(d: Path) -> dict:
    from shadow_tpu.fleet import _stream_digests

    out = _stream_digests(d)
    p = Path(d) / "commands.jsonl"
    if p.is_file():
        out["commands.jsonl"] = hashlib.sha256(p.read_bytes()).hexdigest()
    return out


def run_branch(fork: dict, name: str) -> dict:
    """Run one branch of a fork plan into its directory and write its
    ``fork_manifest.json`` + mergeable telemetry state. Raises on
    failure (the fleet worker loop converts that into a failed manifest
    + retry accounting, exactly like a seed)."""
    from shadow_tpu import checkpoint as _ckpt
    from shadow_tpu import fleet as _fleet
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import (VOLATILE_SUMMARY_KEYS,
                                            Controller)

    plan = fork["branches"][name]
    d = branch_dir(fork["fork_dir"], name)
    _fleet._reap_stale_guests(d)
    shutil.rmtree(d, ignore_errors=True)
    d.mkdir(parents=True, exist_ok=True)
    t0 = _walltime.perf_counter()
    base = {
        "format": BRANCH_FORMAT,
        "branch": name,
        "group": plan["group"],
        "mode": plan["mode"],
        "cold_reason": plan["cold_reason"],
        "seed": plan["seed"],
        "divergence": plan["divergence"],
        "trunk_checkpoint": fork["ckpt"],
        "trunk_checkpoint_sha256": fork["ckpt_sha256"],
        "trunk_config_digest": fork["config_digest"],
        "fork_t": fork["ckpt_t"],
        "fork_rounds": fork["ckpt_rounds"],
    }
    # mark the attempt in-flight BEFORE spawning anything (the fleet
    # manifest discipline: a worker that dies mid-run leaves "running",
    # never a trusted partial)
    _fleet._write_json(d / FORK_MANIFEST, {**base, "status": "running"})
    over = dict(plan["overrides"])
    over["general.data_directory"] = str(d)
    over["general.live_endpoint"] = None
    replay = plan.get("replay") or ()
    if replay:
        rp = d / REPLAY_FILE
        with open(rp, "w") as f:
            for rec in replay:
                f.write(_dumps(rec) + "\n")
        over["general.replay_commands"] = str(rp)
    cfg = load_config(fork["config"], over, cache_doc=True)
    if plan["mode"] == "restore":
        if not fork["reexec"]:
            _copy_prefix_streams(fork, d)
        ctl, resume_at = _ckpt.load_checkpoint(fork["ckpt"], cfg,
                                               mirror_log=False)
        result = ctl.run(resume_at=resume_at)
    else:
        ctl = Controller(cfg, mirror_log=False)
        result = ctl.run()
    if ctl.telemetry is not None:
        (d / _fleet.TEL_STATE_FILE).write_text(
            ctl.telemetry.export_state_json())
    wall = _walltime.perf_counter() - t0
    man = {
        **base,
        "status": "ok",
        "wall_seconds": round(wall, 3),
        "loop_wall_seconds": round(result["wall_seconds"], 3),
        "events": result["events"],
        "rounds": result["rounds"],
        "exit_reason": result["exit_reason"],
        "process_errors": result["process_errors"],
        "tree_sha256": _fleet.output_tree_digest(d),
        "streams_sha256": _branch_stream_digests(d),
        "summary": {k: v for k, v in result.items()
                    if k not in VOLATILE_SUMMARY_KEYS},
    }
    _fleet._write_json(d / FORK_MANIFEST, man)
    return man


def write_failed_branch_manifest(fork_dir, name: str, error: str,
                                 tb: str = "") -> dict:
    d = branch_dir(fork_dir, name)
    d.mkdir(parents=True, exist_ok=True)
    from shadow_tpu.fleet import _write_json

    man = {
        "format": BRANCH_FORMAT,
        "branch": name,
        "status": "failed",
        "error": error,
        "traceback": tb,
    }
    _write_json(d / FORK_MANIFEST, man)
    return man


# -- the comparative reducer --------------------------------------------------

_LABELS = ("p50_ms", "p90_ms", "p99_ms", "p99_9_ms")


def _trunk_state(trunk_dir):
    """The trunk's mergeable telemetry state: the fleet sidecar when the
    trunk was a fleet member, else rebuilt from its flows.jsonl (a plain
    run records every flow; the histogram is a pure function of them)."""
    from shadow_tpu.fleet import TEL_STATE_FILE
    from shadow_tpu.telemetry.histogram import LogHistogram

    if trunk_dir is None:
        return None
    trunk_dir = Path(trunk_dir)
    p = trunk_dir / TEL_STATE_FILE
    if p.is_file():
        try:
            return json.loads(p.read_text())
        except ValueError:
            pass
    fp = trunk_dir / "flows.jsonl"
    if not fp.is_file():
        return None
    hist: dict = {}
    counts: dict = {}
    with open(fp) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            kind = rec.get("flow")
            if kind is None:
                continue
            c = counts.setdefault(kind, {"ok": 0, "failed": 0})
            if rec.get("status") == "ok":
                c["ok"] += 1
                h = hist.get(kind)
                if h is None:
                    h = hist[kind] = LogHistogram()
                h.add(int(rec["latency_ns"]))
            else:
                c["failed"] += 1
    return {"hist": {k: h.state() for k, h in hist.items()},
            "flow_counts": counts}


def reduce_fork(fork_dir, extra: dict = None) -> dict:
    """K-way merge the per-branch manifests + histogram states under
    ``fork_dir`` into ``fork_summary.json``: per-branch flow percentiles,
    per-group pooled percentiles, and per-group percentile DELTAS vs the
    trunk with t-based CI95 across the group's branches (significant =
    the CI excludes zero; n=1 groups carry the delta without a CI).
    Idempotent — a pure function of the on-disk artifacts."""
    from shadow_tpu.fleet import TEL_STATE_FILE, _write_json, t_ci95
    from shadow_tpu.telemetry.histogram import LogHistogram

    fork_dir = Path(fork_dir)
    if extra is None:
        # re-reduction (the report subcommand): carry the original run's
        # orchestration metadata forward instead of erasing it
        try:
            prev = json.loads((fork_dir / FORK_SUMMARY).read_text())
            extra = {k: prev[k] for k in
                     ("config", "jobs", "branches_planned", "trunk_dir",
                      "trunk_checkpoint", "fork_wall_seconds",
                      "draw_service")
                     if k in prev}
        except (OSError, ValueError):
            extra = None
    roster = set((extra or {}).get("branches_planned") or ()) or None
    manifests = []
    for p in sorted(fork_dir.glob("branch_*/" + FORK_MANIFEST)):
        try:
            man = json.loads(p.read_text())
        except ValueError:
            continue
        if man.get("format") != BRANCH_FORMAT:
            continue
        if roster is not None and man.get("branch") not in roster:
            continue
        manifests.append(man)
    completed = [m for m in manifests if m.get("status") == "ok"]
    failed = {m["branch"]: m.get("error", "unknown")
              for m in manifests if m.get("status") != "ok"}
    trunk_dir = (extra or {}).get("trunk_dir") or (
        completed[0].get("trunk_dir") if completed else None)
    tstate = _trunk_state(trunk_dir)
    trunk_q: dict = {}
    if tstate:
        for kind, hs in sorted(tstate.get("hist", {}).items()):
            h = LogHistogram.from_state(hs)
            if h.total:
                c = tstate.get("flow_counts", {}).get(kind, {})
                trunk_q[kind] = {"ok": c.get("ok", 0),
                                 "failed": c.get("failed", 0),
                                 **h.quantiles_ns_to_ms()}
    states = []  # (manifest, state)
    branches_out: dict = {}
    groups: dict = {}
    for m in completed:
        branches_out[m["branch"]] = {
            "group": m["group"], "mode": m["mode"],
            "cold_reason": m.get("cold_reason"), "seed": m.get("seed"),
            "divergence": m.get("divergence") or {},
            "flows": {},
        }
        p = branch_dir(fork_dir, m["branch"]) / TEL_STATE_FILE
        if p.is_file():
            try:
                states.append((m, json.loads(p.read_text())))
            except ValueError:
                pass
    for m, st in states:
        flows = {}
        for kind in sorted(st.get("flow_counts", {})):
            c = st["flow_counts"][kind]
            row = {"count": c["ok"] + c["failed"], "ok": c["ok"],
                   "failed": c["failed"]}
            hs = st["hist"].get(kind)
            if hs is not None:
                h = LogHistogram.from_state(hs)
                if h.total:
                    row.update(h.quantiles_ns_to_ms())
            flows[kind] = row
        branches_out[m["branch"]]["flows"] = flows
        groups.setdefault(m["group"], []).append((m, st))
    groups_out: dict = {}
    for group in sorted(groups):
        members = groups[group]
        kinds = sorted({k for _m, st in members
                        for k in st.get("hist", {})})
        gflows: dict = {}
        for kind in kinds:
            pooled = LogHistogram.merged(
                [st["hist"][kind] for _m, st in members
                 if kind in st.get("hist", {})])
            per_branch = {}
            deltas = {lab: [] for lab in _LABELS}
            for m, st in members:
                hs = st.get("hist", {}).get(kind)
                if hs is None:
                    continue
                h = LogHistogram.from_state(hs)
                if not h.total:
                    continue
                q = h.quantiles_ns_to_ms()
                per_branch[m["branch"]] = q
                if kind in trunk_q:
                    for lab in _LABELS:
                        deltas[lab].append(
                            round(q[lab] - trunk_q[kind][lab], 3))
            row = {"pooled": pooled.quantiles_ns_to_ms(),
                   "per_branch": per_branch}
            if kind in trunk_q and any(deltas[lab] for lab in _LABELS):
                dvt = {}
                for lab in _LABELS:
                    ci = t_ci95(deltas[lab])
                    ci["deltas"] = deltas[lab]
                    # significant: the 95% CI over per-branch deltas
                    # excludes zero (needs n >= 2 — a single branch has
                    # no spread to bound)
                    ci["significant"] = bool(
                        ci.get("n", 0) >= 2
                        and (ci["lo"] > 0 or ci["hi"] < 0))
                    dvt[lab] = ci
                row["delta_vs_trunk"] = dvt
            gflows[kind] = row
        groups_out[group] = {
            "branches": sorted(m["branch"] for m, _st in members),
            "flows": gflows,
        }
    doc = {
        "format": FORK_SUMMARY_FORMAT,
        "n_branches": len(manifests),
        "completed": [m["branch"] for m in completed],
        "failed": failed,
        "per_branch_wall_seconds": {
            m["branch"]: m.get("wall_seconds") for m in completed},
        "events_total": sum(m.get("events", 0) for m in completed),
        "trunk_flows": trunk_q,
        "branches": branches_out,
        "groups": groups_out,
        **(extra or {}),
    }
    _write_json(fork_dir / FORK_SUMMARY, doc)
    return doc


def render_compare(summary: dict) -> str:
    """The comparison table: per flow kind, the trunk percentiles and
    each group's mean percentile delta with its CI95, starred when the
    CI excludes zero."""
    lines = []
    n_ok = len(summary.get("completed", []))
    failed = summary.get("failed", {})
    trunk = summary.get("trunk_checkpoint") or "?"
    lines.append(
        f"fork: {summary.get('n_branches', n_ok)} branch(es), {n_ok} ok, "
        f"{len(failed)} failed — trunk {trunk}")
    for b, err in sorted(failed.items()):
        lines.append(f"  FAILED branch {b}: {err}")
    trunk_q = summary.get("trunk_flows", {})
    groups = summary.get("groups", {})
    if not trunk_q:
        lines.append("  (no trunk flow telemetry — enable telemetry on "
                     "the trunk run for percentile diffs)")
        return "\n".join(lines)
    branches = summary.get("branches", {})
    for kind in sorted(trunk_q):
        tq = trunk_q[kind]
        lines.append("")
        lines.append(
            f"  flow {kind!r}: trunk p50 {tq['p50_ms']:.1f} / "
            f"p90 {tq['p90_ms']:.1f} / p99 {tq['p99_ms']:.1f} ms "
            f"({tq['ok']} ok, {tq['failed']} failed)")
        hdr = (f"    {'group':<20} {'n':>3} "
               f"{'Δp50 ms (CI95)':>22} {'Δp99 ms (CI95)':>22}")
        lines.append(hdr)
        lines.append("    " + "-" * (len(hdr) - 4))
        for group in sorted(groups):
            row = groups[group]["flows"].get(kind)
            if row is None or "delta_vs_trunk" not in row:
                continue
            modes = {branches.get(b, {}).get("mode")
                     for b in groups[group]["branches"]}
            tag = "" if modes == {"restore"} else " [cold]"

            def d_str(ci):
                if ci.get("n", 0) < 2:
                    return f"{ci.get('mean', 0):+.1f} (n=1)"
                star = " *" if ci.get("significant") else "  "
                return (f"{ci['mean']:+.1f} ± {ci['half_width']:.1f}"
                        f"{star}")

            dvt = row["delta_vs_trunk"]
            lines.append(
                f"    {group + tag:<20} {dvt['p50_ms'].get('n', 0):>3} "
                f"{d_str(dvt['p50_ms']):>22} {d_str(dvt['p99_ms']):>22}")
    lines.append("")
    lines.append("  Δ = group mean of per-branch (branch − trunk) "
                 "percentiles; CI95 is t-based across the group's "
                 "branches; * = the CI excludes zero. [cold] groups "
                 "re-ran the prefix (their divergence axis is part of "
                 "the config identity).")
    return "\n".join(lines)


def render_fork_report(summary: dict) -> str:
    """Branch-level fork report (the sweep report's lineage), ending in
    the comparison table."""
    lines = []
    n_ok = len(summary.get("completed", []))
    failed = summary.get("failed", {})
    lines.append(
        f"fork: {summary.get('n_branches', n_ok)} branch(es), {n_ok} ok, "
        f"{len(failed)} failed"
        + (f", jobs={summary['jobs']}" if "jobs" in summary else "")
        + (f", wall {summary['fork_wall_seconds']}s"
           if "fork_wall_seconds" in summary else ""))
    for b in summary.get("completed", []):
        info = summary.get("branches", {}).get(b, {})
        mode = info.get("mode", "?")
        why = (f" ({info.get('cold_reason')})"
               if mode == "cold" and info.get("cold_reason") else "")
        lines.append(f"  branch {b}: {mode}{why}, group "
                     f"{info.get('group', b)}")
    for b, err in sorted(failed.items()):
        lines.append(f"  FAILED branch {b}: {err}")
    return "\n".join(lines) + "\n" + render_compare(summary)


# -- CLI (the `python -m shadow_tpu fork` verb) -------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shadow_tpu fork",
        description="fork one trunk checkpoint into a tree of what-if "
                    "branches and compare them against the trunk")
    p.add_argument("config", help="the trunk's simulation YAML config")
    p.add_argument("--from", dest="fork_from", required=True,
                   metavar="CKPT",
                   help="the trunk checkpoint to fork (a live "
                   "checkpoint_now response names the path)")
    p.add_argument("--branches", required=True, metavar="FILE",
                   help="branches.yaml: the divergence spec per branch")
    p.add_argument("--fork-dir", default=None,
                   help="fork output root (default: <config-stem>.fork)")
    p.add_argument("--trunk-dir", default=None,
                   help="the trunk run directory (default: derived from "
                   "the checkpoint path's <trunk>/checkpoints/ layout)")
    p.add_argument("--jobs", type=int, default=2, metavar="M",
                   help="concurrent branch simulations (default 2)")
    p.add_argument("--set", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="override a config option for EVERY branch by "
                   "dotted path (must keep the trunk's config digest); "
                   "repeatable")
    p.add_argument("--retries", type=int, default=1, metavar="N",
                   help="bounded retry budget per branch (default 1)")
    p.add_argument("--no-device-service", action="store_true",
                   help="branches attach the device individually")
    p.add_argument("--live-endpoint", metavar="PATH",
                   help="bind a STATUS-ONLY endpoint streaming per-branch "
                   "lifecycle records; 'auto' = <fork-dir>/live.sock")
    p.add_argument("--quiet", action="store_true",
                   help="no progress lines on stderr")
    p.add_argument("--json", action="store_true",
                   help="print the fork summary as one JSON line instead "
                   "of the comparison report")
    return p


def main(argv=None) -> int:
    from shadow_tpu import fleet as _fleet

    args = build_parser().parse_args(argv)
    over: dict = {}
    for item in args.set:
        if "=" not in item:
            print(f"fork: --set expects KEY=VALUE, got {item!r}",
                  file=sys.stderr)
            return 2
        import yaml as _yaml

        k, v = item.split("=", 1)
        over[k] = _yaml.safe_load(v)
    fork_dir = args.fork_dir or (Path(args.config).stem + ".fork")
    try:
        branches = load_branches(args.branches)
        plan = plan_fork(args.config, args.fork_from, branches, fork_dir,
                         overrides=over, trunk_dir=args.trunk_dir)
        runner = _fleet.FleetRunner(
            args.config, plan["order"], args.jobs, fork_dir,
            overrides=over, fork=plan,
            device_service=not args.no_device_service, quiet=args.quiet,
            live_endpoint=args.live_endpoint, retries=args.retries)
        summary = runner.run()
    except FileNotFoundError as exc:
        print(f"fork: file not found: "
              f"{getattr(exc, 'filename', None) or exc}", file=sys.stderr)
        return 2
    except (ValueError, OSError) as exc:
        print(f"fork: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(summary) if args.json
          else render_fork_report(summary))
    if summary.get("exit_reason") == "interrupted":
        return 130
    return 0 if not summary["failed"] else 1


if __name__ == "__main__":
    sys.exit(main())
