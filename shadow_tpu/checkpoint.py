"""Checkpoint/restore with byte-identical resume + the determinism sentinel.

Shadow's contract is bit-deterministic conservative round-based DES; this
module makes the *simulator itself* fail well. Any run can be snapshotted at
a round boundary and resumed so that the continuation is byte-identical to
the uninterrupted run, and any run can emit a canonical per-round state
digest stream that turns "whole-run hash mismatch" debugging into a
bisection (tools/bisect_divergence.py).

Why whole-graph serialization works here: at a round *boundary* the entire
simulation is quiescent Python state — host event heaps, transport endpoint
machines, fluid bucket arrays, the columnar pending-arrival store,
counter-based RNG generators, the fault-timeline cursor. The only
non-snapshottable state is runtime plumbing (scheduler threads, the JAX
device plane, the C engine, open pcap streams, real managed-process OS
state), which is either rebuilt on restore (scheduler, device — both
result-transparent by existing invariants) or refused up front with a clear
error (managed processes, pcap).

Before the state walk, ``engine.flush_all()`` materializes every in-flight
loss-draw batch. Resolving draws early is result-identical by construction
(flags are pure functions of unit identity and event order is canonicalized
by per-unit keys), so a checkpointing run stays byte-identical to a
non-checkpointing run — the property tests/test_checkpoint.py gates.

Closures: event heaps and endpoint callbacks hold nested functions and
lambdas (model code), which stdlib pickle refuses. ``_SimPickler`` reduces
any non-importable function to (marshaled code object, module, defaults,
closure cells); cells are reconstructed empty and filled via a state setter
so shared cells keep their identity and recursive closures cannot loop the
pickler. Marshal ties a checkpoint to the interpreter that wrote it, so the
header records the (major, minor) Python version and loading refuses a
mismatch — a stale checkpoint fails fast instead of resuming subtly wrong.

SECURITY: a checkpoint is a pickle — loading one executes code. Treat
checkpoint files like the simulation configs that produced them: trusted
local artifacts, never untrusted input.

The determinism sentinel (``general.state_digest_every``) reuses the same
quiescent-boundary walk, but hashes only *plane-independent* observables
(per-host clocks, uid/event/delivery counters, transport state machines,
application timer multisets, RNG states, host log content; global unit and
byte counters, token-bucket arrays, the effective latency/loss matrices,
the fault cursor). BAND_NET heap entries and the columnar pending store are
deliberately excluded — the two data planes represent in-flight arrivals
differently — so one digest stream is comparable across all scheduler
policies. A divergence in in-flight traffic still surfaces within a round
or two through the delivery counters and endpoint state it must touch.
"""

from __future__ import annotations

import hashlib
import importlib
import io
import json
import marshal
import mmap
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import types
from pathlib import Path

import numpy as np

FORMAT = "shadow_tpu-checkpoint"
VERSION = 1
#: config keys that may legitimately differ between the checkpointing run
#: and the resuming invocation (run-location and snapshot policy, never
#: simulation semantics)
VOLATILE_CONFIG_KEYS = (
    ("general", "data_directory"),
    ("general", "checkpoint_every"),
    ("general", "checkpoint_dir"),
    ("general", "state_digest_every"),
    ("general", "progress"),
    ("general", "heartbeat_interval"),
    ("general", "log_level"),
)

DIGEST_FILE = "state_digests.jsonl"


class CheckpointError(ValueError):
    """A checkpoint could not be written, read, or applied."""


# -- closure-capable pickling -------------------------------------------------

def _rebuild_function(code_bytes, module, name, defaults, kwdefaults,
                      closure):
    """Reconstruct a nested function/lambda from its marshaled code object.
    Globals are the (re-imported) defining module's dict — all model and
    simulator code is importable, which the save path verified."""
    glb = importlib.import_module(module).__dict__ if module else {}
    fn = types.FunctionType(marshal.loads(code_bytes), glb, name,
                            defaults, closure)
    if kwdefaults:
        fn.__kwdefaults__ = kwdefaults
    return fn


def _make_cell():
    return types.CellType()


def _cell_set(cell, state):
    if state:  # () = the cell was empty (declared but never bound)
        cell.cell_contents = state[0]


#: live runtime objects that must never appear in a checkpoint; hitting one
#: means a snapshot-preparation bug, and the error should say WHAT leaked
#: instead of pickle's opaque complaint
_FORBIDDEN = (
    (threading.Thread, "thread"),
    (io.IOBase, "open file"),
    (socket.socket, "socket"),
    (mmap.mmap, "memory map"),
    (subprocess.Popen, "subprocess"),
)


class _SimPickler(pickle.Pickler):
    def reducer_override(self, obj):
        if isinstance(obj, types.FunctionType):
            qn = getattr(obj, "__qualname__", "")
            if "<locals>" not in qn and "<lambda>" not in qn:
                return NotImplemented  # importable: pickle by reference
            mod = obj.__module__
            if mod is None or mod not in sys.modules:
                raise CheckpointError(
                    f"cannot checkpoint closure {qn!r}: defining module "
                    f"{mod!r} is not importable")
            return (_rebuild_function,
                    (marshal.dumps(obj.__code__), mod, obj.__name__,
                     obj.__defaults__, obj.__kwdefaults__, obj.__closure__))
        if isinstance(obj, types.CellType):
            try:
                state = (obj.cell_contents,)
            except ValueError:
                state = ()
            # contents ride as post-creation state (not a constructor arg)
            # so cells shared between closures dedupe through the memo and
            # self-referential closures terminate
            return (_make_cell, (), state, None, None, _cell_set)
        for t, label in _FORBIDDEN:
            if isinstance(obj, t):
                raise CheckpointError(
                    f"cannot checkpoint a live {label} ({obj!r}) — "
                    f"snapshot preparation should have detached it")
        return NotImplemented


# -- config identity ----------------------------------------------------------

def config_digest(cfg) -> str:
    """Canonical digest of the simulation-semantic part of a config: a
    resume under a *different* config would not be the same simulation, so
    load refuses it. Keys in VOLATILE_CONFIG_KEYS are excluded."""
    import dataclasses

    doc = {
        "general": dataclasses.asdict(cfg.general),
        "network": cfg.network,
        "experimental": dataclasses.asdict(cfg.experimental),
        "hosts": [dataclasses.asdict(h) for h in cfg.hosts],
        "faults": (dataclasses.asdict(cfg.faults)
                   if cfg.faults is not None else None),
    }
    for section, key in VOLATILE_CONFIG_KEYS:
        doc[section].pop(key, None)
    # checkpointing forces the pure-Python planes (same coercion faults
    # apply), so the flag's incoming value is not semantic either
    doc["experimental"].pop("native_colcore", None)
    blob = json.dumps(doc, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()


# -- save / load --------------------------------------------------------------

def checkpoint_path(ckpt_dir: Path, sim_time: int) -> Path:
    return Path(ckpt_dir) / f"ckpt_t{sim_time:020d}.ckpt"


def save_checkpoint(controller, now: int) -> Path:
    """Serialize the complete simulation state at the round boundary
    ``now``. Must be called from the controller's round loop (or after it),
    when no scheduler worker is mid-round."""
    validate_config_checkpointable(controller.cfg)  # direct-API callers get
    #                                 the same clear refusal the CLI gets
    eng = controller.engine
    eng.flush_all()  # resolve in-flight draws: result-identical, device-free
    if eng.outstanding:
        raise CheckpointError(
            "engine still holds outstanding draw batches after flush_all()")
    ckpt_dir = Path(controller.ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    path = checkpoint_path(ckpt_dir, now)
    header = {
        "format": FORMAT,
        "version": VERSION,
        "python": list(sys.version_info[:2]),
        "sim_time_ns": now,
        "rounds": controller.rounds,
        "events": controller.events,
        "config_digest": config_digest(controller.cfg),
    }
    tmp = path.with_suffix(".tmp")
    try:
        # stream the pickle straight into the temp file: a checkpoint at
        # north-star scale is GBs, and a BytesIO staging copy would hold
        # the whole thing in RAM twice on top of the live state
        with open(tmp, "wb") as f:
            f.write(json.dumps(header, sort_keys=True).encode() + b"\n")
            _SimPickler(f, protocol=4).dump(
                {"now": now, "controller": controller})
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    os.replace(tmp, path)  # a torn write can never look like a checkpoint
    return path


def read_header(path) -> dict:
    with open(path, "rb") as f:
        line = f.readline()
    try:
        header = json.loads(line)
    except ValueError as exc:
        raise CheckpointError(f"{path}: not a shadow_tpu checkpoint") from exc
    if header.get("format") != FORMAT:
        raise CheckpointError(f"{path}: not a shadow_tpu checkpoint")
    return header


def load_checkpoint(path, cfg=None, mirror_log: bool = True):
    """Restore a checkpoint; returns ``(controller, resume_at)``.

    ``cfg`` is the current invocation's parsed config: its semantic digest
    must match the checkpoint's (VOLATILE_CONFIG_KEYS excepted — so the
    resume may redirect data_directory or change snapshot cadence), and its
    volatile keys are applied to the restored controller.
    """
    header = read_header(path)
    if header.get("version") != VERSION:
        raise CheckpointError(
            f"{path}: checkpoint version {header.get('version')} != "
            f"supported {VERSION}")
    if tuple(header.get("python", ())) != tuple(sys.version_info[:2]):
        raise CheckpointError(
            f"{path}: written by Python "
            f"{'.'.join(map(str, header.get('python', ())))}, running "
            f"{sys.version_info[0]}.{sys.version_info[1]} — marshaled "
            f"closures are not portable across interpreter versions")
    if cfg is not None:
        want, got = header["config_digest"], config_digest(cfg)
        if want != got:
            raise CheckpointError(
                f"{path}: config mismatch — the checkpoint was written "
                f"under a different simulation config (digest {want[:12]} "
                f"vs {got[:12]}). Resume with the original config; only "
                f"data_directory / checkpoint / digest / logging keys may "
                f"differ.")
    with open(path, "rb") as f:
        f.readline()
        try:
            # stream-unpickle from the positioned handle: no staging copy
            # of a potentially multi-GB payload beside the object graph
            obj = pickle.load(f)
        except CheckpointError:
            raise
        except Exception as exc:
            raise CheckpointError(
                f"{path}: corrupt or unreadable checkpoint payload "
                f"({type(exc).__name__}: {exc})") from exc
    controller, now = obj["controller"], obj["now"]
    if cfg is not None:
        # apply the resume invocation's volatile keys — driven off
        # VOLATILE_CONFIG_KEYS so exclusion (config_digest) and
        # application can never drift apart
        for section, key in VOLATILE_CONFIG_KEYS:
            setattr(getattr(controller.cfg, section), key,
                    getattr(getattr(cfg, section), key))
        # the telemetry section is volatile too (result-transparent, not
        # in the config digest) but is a whole subsystem, not a scalar:
        # honor the resume invocation's section — enable, disable, or
        # re-cadence — instead of silently keeping the pickled state
        _apply_telemetry_resume(controller, cfg.telemetry, now)
    controller._reattach_runtime(mirror_log=mirror_log)
    controller.log.info(
        f"resumed from {path}: sim time {now} ns, round {controller.rounds}, "
        f"{controller.events} events")
    return controller, now


def _apply_telemetry_resume(controller, want, now: int) -> None:
    """Reconcile the restored controller's telemetry state with the
    resume invocation's ``telemetry:`` section (the volatile-key rule,
    section-shaped). Same section -> the pickled collector continues its
    streams bit-exactly; absent -> telemetry is disabled; newly present
    or re-cadenced -> a fresh/retimed collector starts sampling at the
    next grid point after ``now``. Caveat (documented in MIGRATION.md):
    flow records come from model code that captures the collector at
    process spawn, so ENABLING telemetry on resume covers samplers and
    fault annotations immediately but only processes spawned after the
    resume point produce flow records."""
    have = controller.telemetry
    if want is None:
        if have is not None:
            controller.telemetry = None
            for h in controller.hosts:
                h.telemetry = None
            if controller.faults is not None:
                controller.faults.on_apply = None
        controller.cfg.telemetry = None
        return
    from shadow_tpu.telemetry import TelemetryCollector

    if have is None:
        tel = TelemetryCollector(want)
        tel.next_sample = ((now // tel.sample_every) + 1) * tel.sample_every
        controller.telemetry = tel
        for h in controller.hosts:
            h.telemetry = tel
        if controller.faults is not None:
            controller.faults.on_apply = tel.record_fault
    else:
        if int(want.sample_every) != have.sample_every:
            have.sample_every = int(want.sample_every)
            have.next_sample = (
                (now // have.sample_every) + 1) * have.sample_every
        have.metrics_dir = want.metrics_dir
    controller.cfg.telemetry = want


# -- determinism sentinel -----------------------------------------------------

def _feed(h, obj) -> None:
    """Canonical byte encoding of the digest structure (type-tagged,
    length-prefixed; dict keys sorted) — stable across runs, policies,
    and platforms."""
    if obj is None:
        h.update(b"N")
    elif isinstance(obj, bool):
        h.update(b"T" if obj else b"F")
    elif isinstance(obj, (int, np.integer)):
        h.update(b"i%d;" % int(obj))
    elif isinstance(obj, float):
        h.update(b"f" + struct.pack("<d", obj))
    elif isinstance(obj, str):
        b = obj.encode()
        h.update(b"s%d:" % len(b) + b)
    elif isinstance(obj, (bytes, bytearray)):
        h.update(b"b%d:" % len(obj) + bytes(obj))
    elif isinstance(obj, (list, tuple)):
        h.update(b"[%d;" % len(obj))
        for x in obj:
            _feed(h, x)
    elif isinstance(obj, dict):
        h.update(b"{%d;" % len(obj))
        for k in sorted(obj):
            _feed(h, k)
            _feed(h, obj[k])
    elif isinstance(obj, np.ndarray):
        h.update(b"a" + str(obj.dtype).encode() + b"|"
                 + str(obj.shape).encode() + b"|")
        h.update(np.ascontiguousarray(obj).tobytes())
    else:
        raise CheckpointError(
            f"state digest: unhashable field type {type(obj).__name__}")


def _digest(obj) -> str:
    h = hashlib.sha256()
    _feed(h, obj)
    return h.hexdigest()


def state_digest(controller, sim_now: int):
    """Returns ``(global_digest_hex, {host_name: digest_hex})`` over the
    plane-independent state at the round boundary ``sim_now``.

    Calls ``engine.flush_all()`` first so both data planes (and the lazy
    draw coalescing inside each) sit at the same resolution frontier —
    early resolution is result-identical, so a digesting run stays
    byte-identical to a non-digesting one.
    """
    eng = controller.engine
    eng.flush_all()
    hosts = {}
    for h in controller.hosts:
        hosts[h.name] = _digest(h.state_fingerprint())
    g = {
        "t": sim_now,
        "rounds": controller.rounds,
        "events": controller.events,
        "units_sent": eng.units_sent,
        "units_dropped": eng.units_dropped,
        "units_blackholed": eng.units_blackholed,
        "bytes_sent": eng.bytes_sent,
        "ev_key": eng._ev_key,
        "tokens_down": eng.tokens_down,
        # egress buckets: hash the canonical observable, not the raw
        # (t_base, tokens, debt) triple — capped available-at-now
        # (fluid.TokenBuckets.levels, shared with the telemetry samplers)
        # is identical across planes: any divergence in actual bucket
        # BEHAVIOR must show here or in the unit counters.
        "bucket_avail": eng.buckets.levels(sim_now),
        "last_refill": eng._last_refill,
        # the effective latency/loss/rate matrices are deliberately NOT
        # hashed: they are pure functions of the config (pinned by
        # config_digest) and the applied-action cursor below, and at 10k+
        # graph nodes re-hashing O(nodes^2) matrices every sample would
        # dominate sentinel cost. A corrupted matrix without a moved
        # cursor still surfaces within a round or two through the arrival
        # times, unit counters, and endpoint state it must perturb.
        "faults": ((controller.faults.idx, controller.faults.applied)
                   if controller.faults is not None else None),
        "hosts": hosts,
    }
    return _digest(g), hosts


def emit_digest(controller, sim_now: int) -> None:
    """Append one sentinel record to <data_dir>/state_digests.jsonl."""
    g, hosts = state_digest(controller, sim_now)
    controller.data_dir.mkdir(parents=True, exist_ok=True)
    rec = {"round": controller.rounds, "t": sim_now, "digest": g,
           "hosts": hosts}
    with open(controller.data_dir / DIGEST_FILE, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")


def validate_config_checkpointable(cfg) -> None:
    """THE checkpointability policy, single source of truth — pure config
    inspection, so it can fail at build time before anything is
    constructed. Refused: real managed-process guests (live OS process
    state cannot be snapshotted) and pcap hosts (captures stream to disk
    mid-run). See README 'Checkpoint & resume'."""
    from shadow_tpu.host.process import PluginProcess

    for hopts in cfg.hosts:
        if hopts.pcap_enabled:
            raise ValueError(
                f"checkpoint_every is unsupported with pcap capture: host "
                f"{hopts.name!r} has pcap_enabled (captures stream to disk "
                f"mid-run); disable one of the two")
        for popts in hopts.processes:
            if not PluginProcess.is_plugin_path(popts.path):
                raise ValueError(
                    f"checkpoint_every is unsupported with managed native "
                    f"processes: host {hopts.name!r} runs {popts.path!r} "
                    f"(real OS process state cannot be snapshotted — see "
                    f"README 'Checkpoint & resume'); use pyapp: workloads "
                    f"or disable checkpointing")
