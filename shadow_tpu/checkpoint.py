"""Checkpoint/restore with byte-identical resume + the determinism sentinel.

Shadow's contract is bit-deterministic conservative round-based DES; this
module makes the *simulator itself* fail well. Any run can be snapshotted at
a round boundary and resumed so that the continuation is byte-identical to
the uninterrupted run, and any run can emit a canonical per-round state
digest stream that turns "whole-run hash mismatch" debugging into a
bisection (tools/bisect_divergence.py).

Why whole-graph serialization works here: at a round *boundary* the entire
simulation is quiescent state — host event heaps, transport endpoint
machines, fluid bucket arrays, the columnar pending-arrival store,
counter-based RNG generators, the fault-timeline cursor. State held in C
extension objects (native/colcore endpoints, tor sinks/relays, gossip
states, packed store batches) exports to plain Python structures through
per-type ``_export_state`` reducers and rebuilds on load (the header's
``colcore`` ABI fingerprint refuses a mismatched build by name). The only
non-snapshottable state is runtime plumbing (scheduler threads, the JAX
device plane, the Core object itself, open pcap streams), which is rebuilt
on restore (scheduler, device, C core — all result-transparent by existing
invariants) or refused up front with a clear error (pcap).

Managed (real-binary) configs cannot ride the pickle path — a guest is a
live OS process whose kernel state (memory image, file table, thread
stacks) no userspace snapshot can capture. Format v5 covers them anyway by
leaning on the determinism contract instead: a managed checkpoint is a
**re-execution snapshot** — a small JSON record of the round boundary
(sim time, round count, canonical state digest, and a per-guest cursor
into the journaled observation stream, ``guest_oplogs/``) plus the live
commands applied so far. Restore rebuilds the controller from the config
and re-executes deterministically from round 0; at the recorded boundary
the recomputed state digest and guest journal cursors are verified against
the snapshot (mismatch fails by name), after which the run simply
continues — the guests are already live on the transport, so no splice is
needed. The continuation is byte-identical to the uninterrupted run
because the whole prefix is. Restore cost is O(prefix re-execution), not
O(state) — the honest trade for real-binary fidelity.

Before the state walk, ``engine.flush_all()`` materializes every in-flight
loss-draw batch. Resolving draws early is result-identical by construction
(flags are pure functions of unit identity and event order is canonicalized
by per-unit keys), so a checkpointing run stays byte-identical to a
non-checkpointing run — the property tests/test_checkpoint.py gates.

Closures: event heaps and endpoint callbacks hold nested functions and
lambdas (model code), which stdlib pickle refuses. ``_SimPickler`` reduces
any non-importable function to (marshaled code object, module, defaults,
closure cells); cells are reconstructed empty and filled via a state setter
so shared cells keep their identity and recursive closures cannot loop the
pickler. Marshal ties a checkpoint to the interpreter that wrote it, so the
header records the (major, minor) Python version and loading refuses a
mismatch — a stale checkpoint fails fast instead of resuming subtly wrong.

SECURITY: a checkpoint is a pickle — loading one executes code. Treat
checkpoint files like the simulation configs that produced them: trusted
local artifacts, never untrusted input.

The determinism sentinel (``general.state_digest_every``) reuses the same
quiescent-boundary walk, but hashes only *plane-independent* observables
(per-host clocks, uid/event/delivery counters, transport state machines,
application timer multisets, RNG states, host log content; global unit and
byte counters, token-bucket arrays, the effective latency/loss matrices,
the fault cursor). BAND_NET heap entries and the columnar pending store are
deliberately excluded — the two data planes represent in-flight arrivals
differently — so one digest stream is comparable across all scheduler
policies. A divergence in in-flight traffic still surfaces within a round
or two through the delivery counters and endpoint state it must touch.
"""

from __future__ import annotations

import hashlib
import importlib
import io
import json
import marshal
import mmap
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import types
from pathlib import Path

import numpy as np

FORMAT = "shadow_tpu-checkpoint"
#: version 2: the header gained the ``colcore`` build/ABI fingerprint and
#: checkpoints may carry C-engine state (exported to plain structures by
#: the reducers below). Version 3: the pickled StreamSender layout grew
#: the SACK scoreboard + CongestionControl fields (the Python-plane twin
#: of the colcore ABI 2 -> 3 bump), so version-2 checkpoints — whose
#: senders lack those attributes and would crash on the first ack after
#: resume — are refused by the version gate like version-1 before them.
#: Version 4: the StreamSender SACK/rtx scoreboards became SORTED LISTS
#: (canonical by construction for the columnar transport export,
#: network/devtransport.py); a version-3 checkpoint would restore sets
#: where the bisect-based scoreboard code expects lists. Version 5:
#: managed (real-binary) configs are checkpointable as re-execution
#: snapshots (header ``mode: "reexec"`` + a JSON payload of round cursor,
#: state digest, per-guest journal cursors, and applied live commands —
#: no pickle); pure-pyapp configs keep the pickle payload unchanged. A
#: pre-v5 checkpoint can never describe a managed run (older builds
#: refused managed configs at save), so a managed-marked header below v5
#: is refused by name. See MIGRATION.md.
VERSION = 5
#: config keys that may legitimately differ between the checkpointing run
#: and the resuming invocation (run-location, snapshot policy, and the
#: data-plane implementation toggle — never simulation semantics:
#: native_colcore is bit-identical on and off, and the resume HONORS the
#: invocation's value by rebuilding — or not — the C core)
VOLATILE_CONFIG_KEYS = (
    ("general", "data_directory"),
    ("general", "checkpoint_every"),
    ("general", "checkpoint_dir"),
    ("general", "state_digest_every"),
    ("general", "progress"),
    ("general", "heartbeat_interval"),
    ("general", "log_level"),
    # the live-operations plane (shadow_tpu/live.py) is pure wall-clock:
    # the endpoint streams records and accepts commands, but commands only
    # touch sim state via the recorded commands.jsonl, which replays via
    # replay_commands — so both keys are run-location policy, not
    # simulation semantics
    ("general", "live_endpoint"),
    ("general", "replay_commands"),
    ("experimental", "native_colcore"),
    # the columnar transport engine is the same kind of toggle: every
    # path is bit-identical (tests/test_devtransport.py), engagement is
    # wall-clock policy, and _reattach_runtime rebuilds — or not — the
    # engine from the resume invocation's value
    ("experimental", "device_transport"),
)

DIGEST_FILE = "state_digests.jsonl"


class CheckpointError(ValueError):
    """A checkpoint could not be written, read, or applied."""


# -- closure-capable pickling -------------------------------------------------

def _rebuild_function(code_bytes, module, name, defaults, kwdefaults,
                      closure, qualname=None):
    """Reconstruct a nested function/lambda from its marshaled code object.
    Globals are the (re-imported) defining module's dict — all model and
    simulator code is importable, which the save path verified. The
    original ``__qualname__`` is restored explicitly: on Python < 3.11
    FunctionType derives it from ``co_name``, and a rebuilt closure that
    lost its ``<locals>`` marker would fool the reducer's importability
    test at the NEXT checkpoint (a resumed run that checkpoints again)."""
    glb = importlib.import_module(module).__dict__ if module else {}
    fn = types.FunctionType(marshal.loads(code_bytes), glb, name,
                            defaults, closure)
    if kwdefaults:
        fn.__kwdefaults__ = kwdefaults
    if qualname:
        fn.__qualname__ = qualname
    return fn


def _make_cell():
    return types.CellType()


def _cell_set(cell, state):
    if state:  # () = the cell was empty (declared but never bound)
        cell.cell_contents = state[0]


# -- C-engine state (native/colcore) ------------------------------------------
#
# A run with the C engine attached holds live state in C extension objects:
# stream endpoints (CEp), tor relays/sinks/exit streams, gossip states, and
# packed store batches. Each exports its COMPLETE state as plain Python
# structures via ``_export_state`` and rebuilds from them — the pickler
# reduces every C object to (shell, (), state, _colcore_setstate), so
# shared references and reference cycles ride the memo exactly like Python
# objects. Core pointers are never pickled: ``Controller._reattach_runtime``
# rebuilds the core and binds the restored objects via ``Core.adopt``
# (finish_colcore_adopt below). Packed store batches reduce to the plain
# StoreBatch row-list form — the plane-neutral representation either plane
# can resume from.

#: restored C objects awaiting a core binding; drained by
#: finish_colcore_adopt after _reattach_runtime rebuilds the C engine
_PENDING_ADOPT: list = []
#: colcore type names whose instances need a core pointer at adopt time
_ADOPT_KINDS = frozenset(("Endpoint", "GossipState", "Relay"))


def _colcore_shell(kind):
    from shadow_tpu.native import _colcore

    return _colcore.shell(kind)


def _colcore_setstate(obj, state):
    obj._restore_state(state)
    if type(obj).__name__ in _ADOPT_KINDS:
        _PENDING_ADOPT.append(obj)


def _rebuild_storebatch(rows, pos):
    from shadow_tpu.network.colplane import StoreBatch

    b = StoreBatch(rows)
    b.pos = pos
    return b


class _DeadCoreHandle:
    """Stands in for a pickled reference to the old C core (reachable only
    through activation-hook closures): _reattach_runtime rewires every
    hook to the fresh core before the simulation resumes, so any call that
    reaches this object is a wiring bug — fail by name."""

    def __getattr__(self, name):
        def _dead(*_a, **_k):
            raise CheckpointError(
                f"stale C-core reference called ({name}) — "
                f"_reattach_runtime did not rewire an activation hook")

        return _dead


def _dead_core():
    return _DeadCoreHandle()


def finish_colcore_adopt(controller) -> None:
    """Bind every checkpoint-restored C object to the rebuilt core
    (called by Controller._reattach_runtime after attach_colcore)."""
    global _PENDING_ADOPT
    pend, _PENDING_ADOPT = _PENDING_ADOPT, []
    if not pend:
        return
    core = getattr(controller.engine, "_c", None)
    if core is None:
        raise CheckpointError(
            "checkpoint contains C-engine state but no C core was rebuilt "
            "— resume with experimental.native_colcore enabled on a tpu "
            "policy (or re-checkpoint from a Python-plane run)")
    core.adopt(pend)


#: live runtime objects that must never appear in a checkpoint; hitting one
#: means a snapshot-preparation bug, and the error should say WHAT leaked
#: instead of pickle's opaque complaint
_FORBIDDEN = (
    (threading.Thread, "thread"),
    (io.IOBase, "open file"),
    (socket.socket, "socket"),
    (mmap.mmap, "memory map"),
    (subprocess.Popen, "subprocess"),
)


class _SimPickler(pickle.Pickler):
    def reducer_override(self, obj):
        tp = type(obj)
        if getattr(tp, "__module__", None) == "_colcore":
            name = tp.__name__
            if name == "CBatch":
                pos, rows = obj.export_rows()
                return (_rebuild_storebatch, (rows, pos))
            if name == "Core":
                # only reachable through activation-hook closures; the
                # restore path rebuilds a fresh core and rewires them
                return (_dead_core, ())
            if name in ("Endpoint", "Relay", "TorSink", "GossipState",
                        "ExitStream"):
                # shell first, state second: cycles (endpoint <-> relay
                # <-> model callbacks) resolve through the pickle memo
                return (_colcore_shell, (name,), obj._export_state(),
                        None, None, _colcore_setstate)
            raise CheckpointError(
                f"cannot checkpoint colcore object of type {name!r}")
        if isinstance(obj, types.FunctionType):
            qn = getattr(obj, "__qualname__", "")
            if "<locals>" not in qn and "<lambda>" not in qn:
                # importable IF the module really exposes this object under
                # its name (a checkpoint-rebuilt closure can carry a bare
                # qualname on Python < 3.11); otherwise marshal it
                m = sys.modules.get(obj.__module__ or "")
                if m is not None and getattr(m, obj.__name__, None) is obj:
                    return NotImplemented  # pickle by reference
            mod = obj.__module__
            if mod is None or mod not in sys.modules:
                raise CheckpointError(
                    f"cannot checkpoint closure {qn!r}: defining module "
                    f"{mod!r} is not importable")
            return (_rebuild_function,
                    (marshal.dumps(obj.__code__), mod, obj.__name__,
                     obj.__defaults__, obj.__kwdefaults__, obj.__closure__,
                     qn))
        if isinstance(obj, types.CellType):
            try:
                state = (obj.cell_contents,)
            except ValueError:
                state = ()
            # contents ride as post-creation state (not a constructor arg)
            # so cells shared between closures dedupe through the memo and
            # self-referential closures terminate
            return (_make_cell, (), state, None, None, _cell_set)
        for t, label in _FORBIDDEN:
            if isinstance(obj, t):
                raise CheckpointError(
                    f"cannot checkpoint a live {label} ({obj!r}) — "
                    f"snapshot preparation should have detached it")
        return NotImplemented


# -- config identity ----------------------------------------------------------

def config_digest(cfg) -> str:
    """Canonical digest of the simulation-semantic part of a config: a
    resume under a *different* config would not be the same simulation, so
    load refuses it. Keys in VOLATILE_CONFIG_KEYS are excluded."""
    import dataclasses

    doc = {
        "general": dataclasses.asdict(cfg.general),
        "network": cfg.network,
        "experimental": dataclasses.asdict(cfg.experimental),
        "hosts": [dataclasses.asdict(h) for h in cfg.hosts],
        "faults": (dataclasses.asdict(cfg.faults)
                   if cfg.faults is not None else None),
    }
    for section, key in VOLATILE_CONFIG_KEYS:
        doc[section].pop(key, None)
    # sim_shards is excluded from the digest but is NOT volatile: the
    # header records it explicitly and load refuses a mismatched count BY
    # NAME (a shard checkpoint is one piece of an N-way partition — it
    # can only resume into the same partition)
    doc["general"].pop("sim_shards", None)
    blob = json.dumps(doc, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()


def config_has_managed(cfg) -> bool:
    """True when any configured process is a real managed executable (not
    ``pyapp:``) — such configs checkpoint as re-execution snapshots."""
    from shadow_tpu.host.process import PluginProcess

    return any(not PluginProcess.is_plugin_path(popts.path)
               for hopts in cfg.hosts for popts in hopts.processes)


# -- save / load --------------------------------------------------------------

def checkpoint_path(ckpt_dir: Path, sim_time: int,
                    shard: int = None) -> Path:
    if shard is not None:
        return Path(ckpt_dir) / f"ckpt_t{sim_time:020d}.shard{shard}.ckpt"
    return Path(ckpt_dir) / f"ckpt_t{sim_time:020d}.ckpt"


def save_checkpoint(controller, now: int) -> Path:
    """Serialize the complete simulation state at the round boundary
    ``now``. Must be called from the controller's round loop (or after it),
    when no scheduler worker is mid-round."""
    validate_config_checkpointable(controller.cfg)  # direct-API callers get
    #                                 the same clear refusal the CLI gets
    eng = controller.engine
    eng.flush_all()  # resolve in-flight draws: result-identical, device-free
    if eng.outstanding:
        raise CheckpointError(
            "engine still holds outstanding draw batches after flush_all()")
    ckpt_dir = Path(controller.ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    n_shards = int(getattr(controller, "n_shards", 1))
    path = checkpoint_path(
        ckpt_dir, now,
        shard=controller.shard_id if n_shards > 1 else None)
    if config_has_managed(controller.cfg):
        if n_shards > 1:
            raise CheckpointError(
                "managed re-execution checkpoints are single-process only "
                "(sim_shards=1); sharded managed runs cannot checkpoint")
        return _save_reexec(controller, now, path)
    # colcore build/ABI fingerprint: when the C engine is attached the
    # payload carries C-exported state, and resuming it on a mismatched
    # colcore build must fail fast by name instead of diverging silently
    colcore_abi = None
    if getattr(eng, "_c", None) is not None:
        from shadow_tpu.native import _colcore

        colcore_abi = int(_colcore.ABI)
    header = {
        "format": FORMAT,
        "version": VERSION,
        "python": list(sys.version_info[:2]),
        "sim_time_ns": now,
        "rounds": controller.rounds,
        "events": controller.events,
        "config_digest": config_digest(controller.cfg),
        "colcore": colcore_abi,
        # multi-process sharding: the shard count is part of the state's
        # identity — a shard checkpoint holds 1/N of the host partition
        # and can only resume into an N-way run (load refuses by name)
        "sim_shards": n_shards,
        **({"shard": controller.shard_id} if n_shards > 1 else {}),
    }
    tmp = path.with_suffix(".tmp")
    try:
        # stream the pickle straight into the temp file: a checkpoint at
        # north-star scale is GBs, and a BytesIO staging copy would hold
        # the whole thing in RAM twice on top of the live state
        with open(tmp, "wb") as f:
            f.write(json.dumps(header, sort_keys=True).encode() + b"\n")
            _SimPickler(f, protocol=4).dump(
                {"now": now, "controller": controller})
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    os.replace(tmp, path)  # a torn write can never look like a checkpoint
    return path


def _save_reexec(controller, now: int, path: Path) -> Path:
    """Write a v5 re-execution snapshot for a managed config: a JSON
    header + JSON payload (no pickle). The payload pins everything the
    restore must reproduce and verify — the round cursor, the canonical
    state digest at this boundary, each guest's journal cursor, and the
    live commands applied so far (embedded so the restore re-applies them
    at the same boundaries without needing the original run directory)."""
    g, hosts = state_digest(controller, now)
    commands = []
    cmd_log = Path(controller.data_dir) / "commands.jsonl"
    if cmd_log.is_file():
        from shadow_tpu.live import load_command_log

        commands = [r for r in load_command_log(cmd_log) if r["t"] <= now]
    header = {
        "format": FORMAT,
        "version": VERSION,
        "mode": "reexec",
        "managed": True,
        "python": list(sys.version_info[:2]),
        "sim_time_ns": now,
        "rounds": controller.rounds,
        "events": controller.events,
        "config_digest": config_digest(controller.cfg),
        "colcore": None,  # no exported C state rides a reexec snapshot
        "sim_shards": 1,
    }
    payload = {
        "digest": g,
        "hosts": hosts,
        "cursors": controller.guest_journal_cursors(),
        "commands": commands,
    }
    tmp = path.with_suffix(".tmp")
    try:
        with open(tmp, "wb") as f:
            f.write(json.dumps(header, sort_keys=True).encode() + b"\n")
            f.write(json.dumps(payload, sort_keys=True).encode() + b"\n")
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    os.replace(tmp, path)
    return path


def read_header(path) -> dict:
    with open(path, "rb") as f:
        line = f.readline()
    try:
        header = json.loads(line)
    except ValueError as exc:
        raise CheckpointError(f"{path}: not a shadow_tpu checkpoint") from exc
    if header.get("format") != FORMAT:
        raise CheckpointError(f"{path}: not a shadow_tpu checkpoint")
    return header


def load_checkpoint(path, cfg=None, mirror_log: bool = True):
    """Restore a checkpoint; returns ``(controller, resume_at)``.

    ``cfg`` is the current invocation's parsed config: its semantic digest
    must match the checkpoint's (VOLATILE_CONFIG_KEYS excepted — so the
    resume may redirect data_directory or change snapshot cadence), and its
    volatile keys are applied to the restored controller.
    """
    header = read_header(path)
    if header.get("managed") and int(header.get("version") or 0) < 5:
        # can only be a hand-rolled or corrupted artifact: every build
        # that could SAVE a managed checkpoint already wrote format v5
        # re-execution cursors. Name the real requirement instead of the
        # generic version complaint.
        raise CheckpointError(
            f"{path}: managed guests require checkpoint format v5 "
            f"(deterministic re-execution cursors); this file claims "
            f"version {header.get('version')} — re-checkpoint the run "
            f"with a current build")
    if header.get("version") != VERSION:
        raise CheckpointError(
            f"{path}: checkpoint version {header.get('version')} != "
            f"supported {VERSION}")
    if header.get("mode") == "reexec":
        return _load_reexec(path, header, cfg, mirror_log)
    if tuple(header.get("python", ())) != tuple(sys.version_info[:2]):
        raise CheckpointError(
            f"{path}: written by Python "
            f"{'.'.join(map(str, header.get('python', ())))}, running "
            f"{sys.version_info[0]}.{sys.version_info[1]} — marshaled "
            f"closures are not portable across interpreter versions")
    if cfg is not None:
        have_sh = int(header.get("sim_shards", 1))
        want_sh = int(getattr(cfg.general, "sim_shards", 1))
        if have_sh != want_sh:
            raise CheckpointError(
                f"{path}: checkpoint written with sim_shards={have_sh} "
                f"but this invocation has sim_shards={want_sh} — the host "
                f"partition is part of the snapshot's identity; resume "
                f"with general.sim_shards={have_sh} (results are "
                f"byte-identical at any shard count, so re-running from "
                f"scratch at the new count reproduces the same "
                f"simulation)")
    want_abi = header.get("colcore")
    if want_abi is not None:
        # the payload carries C-engine state: the resume needs a colcore
        # build with a matching state-format ABI, and the invocation must
        # not disable the C engine (C tor/tgen sink state has no Python
        # rebuild path — re-checkpoint from a Python-plane run to demote)
        try:
            from shadow_tpu.native import _colcore
        except ImportError as exc:
            raise CheckpointError(
                f"{path}: checkpoint carries C-engine state (colcore ABI "
                f"{want_abi}) but shadow_tpu.native._colcore is not "
                f"importable here — build it first: make -C native") from exc
        if int(_colcore.ABI) != int(want_abi):
            raise CheckpointError(
                f"{path}: checkpoint written by colcore ABI {want_abi}, "
                f"this build is ABI {_colcore.ABI} — the C state formats "
                f"are incompatible; resume on the writing build or "
                f"re-checkpoint from a Python-plane run")
        if cfg is not None and not cfg.experimental.native_colcore:
            raise CheckpointError(
                f"{path}: checkpoint carries C-engine state but the "
                f"resume invocation disables it "
                f"(experimental.native_colcore=false); C endpoint/sink "
                f"state cannot be demoted to the Python plane — resume "
                f"with the C engine enabled, or re-checkpoint from a "
                f"Python-plane run")
    if cfg is not None:
        want, got = header["config_digest"], config_digest(cfg)
        if want != got:
            raise CheckpointError(
                f"{path}: config mismatch — the checkpoint was written "
                f"under a different simulation config (digest {want[:12]} "
                f"vs {got[:12]}). Resume with the original config; only "
                f"data_directory / checkpoint / digest / logging keys may "
                f"differ.")
    global _PENDING_ADOPT
    _PENDING_ADOPT = []  # a failed earlier load must not leak stale objects
    with open(path, "rb") as f:
        f.readline()
        try:
            # stream-unpickle from the positioned handle: no staging copy
            # of a potentially multi-GB payload beside the object graph
            obj = pickle.load(f)
        except CheckpointError:
            raise
        except Exception as exc:
            raise CheckpointError(
                f"{path}: corrupt or unreadable checkpoint payload "
                f"({type(exc).__name__}: {exc})") from exc
    controller, now = obj["controller"], obj["now"]
    if cfg is not None:
        # apply the resume invocation's volatile keys — driven off
        # VOLATILE_CONFIG_KEYS so exclusion (config_digest) and
        # application can never drift apart
        for section, key in VOLATILE_CONFIG_KEYS:
            setattr(getattr(controller.cfg, section), key,
                    getattr(getattr(cfg, section), key))
        # the telemetry section is volatile too (result-transparent, not
        # in the config digest) but is a whole subsystem, not a scalar:
        # honor the resume invocation's section — enable, disable, or
        # re-cadence — instead of silently keeping the pickled state
        _apply_telemetry_resume(controller, cfg.telemetry, now)
    controller._reattach_runtime(mirror_log=mirror_log)
    controller.log.info(
        f"resumed from {path}: sim time {now} ns, round {controller.rounds}, "
        f"{controller.events} events")
    return controller, now


def _load_reexec(path, header, cfg, mirror_log: bool):
    """Restore a managed re-execution snapshot: rebuild the controller
    from the config and hand back ``(controller, None)`` — the caller's
    ``run(resume_at=None)`` then re-executes the deterministic prefix from
    round 0. The snapshot's round cursor, state digest, and per-guest
    journal cursors are armed on the controller and verified when the
    round loop reaches the recorded boundary (divergence fails by name);
    the run keeps going from there, byte-identical to the uninterrupted
    run. Live commands recorded up to the boundary ride the snapshot and
    are re-applied at their original boundaries via the replay plane."""
    if cfg is None:
        raise CheckpointError(
            f"{path}: a managed re-execution snapshot rebuilds the "
            f"simulation from its config — pass the config to "
            f"load_checkpoint (the CLI's --resume-from does)")
    if int(getattr(cfg.general, "sim_shards", 1)) != 1:
        raise CheckpointError(
            f"{path}: managed re-execution snapshots resume at "
            f"sim_shards=1 only")
    want, got = header["config_digest"], config_digest(cfg)
    if want != got:
        raise CheckpointError(
            f"{path}: config mismatch — the checkpoint was written "
            f"under a different simulation config (digest {want[:12]} "
            f"vs {got[:12]}). Resume with the original config; only "
            f"data_directory / checkpoint / digest / logging keys may "
            f"differ.")
    with open(path, "rb") as f:
        f.readline()
        try:
            payload = json.loads(f.readline())
        except ValueError as exc:
            raise CheckpointError(
                f"{path}: corrupt re-execution snapshot payload") from exc
    commands = payload.get("commands") or []
    if commands and not cfg.general.replay_commands:
        # the resume invocation has no command log of its own: replay the
        # embedded records so runtime faults land on the same boundaries
        ddir = Path(cfg.general.data_directory)
        ddir.mkdir(parents=True, exist_ok=True)
        replay = ddir / "reexec_commands.jsonl"
        with open(replay, "w") as f:
            for rec in commands:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        cfg.general.replay_commands = str(replay)
    from shadow_tpu.core.controller import Controller

    controller = Controller(cfg, mirror_log=mirror_log)
    if payload.get("cursors") and controller.guest_journal_dir is None:
        # the resume invocation may not itself checkpoint, but cursor
        # verification needs the re-executed guests journaled
        controller.guest_journal_dir = controller.data_dir / "guest_oplogs"
    controller._reexec_verify = {
        "path": str(path),
        "t": int(header["sim_time_ns"]),
        "rounds": int(header["rounds"]),
        "digest": payload["digest"],
        "cursors": payload.get("cursors") or {},
    }
    controller.log.info(
        f"restoring {path} by deterministic re-execution: re-running "
        f"rounds 0..{header['rounds']} (sim {header['sim_time_ns']} ns), "
        f"digest-verified at the snapshot boundary")
    return controller, None


def _apply_telemetry_resume(controller, want, now: int) -> None:
    """Reconcile the restored controller's telemetry state with the
    resume invocation's ``telemetry:`` section (the volatile-key rule,
    section-shaped). Same section -> the pickled collector continues its
    streams bit-exactly; absent -> telemetry is disabled; newly present
    or re-cadenced -> a fresh/retimed collector starts sampling at the
    next grid point after ``now``. Caveat (documented in MIGRATION.md):
    flow records come from model code that captures the collector at
    process spawn, so ENABLING telemetry on resume covers samplers and
    fault annotations immediately but only processes spawned after the
    resume point produce flow records."""
    have = controller.telemetry
    if want is None:
        if have is not None:
            controller.telemetry = None
            for h in controller.hosts:
                h.telemetry = None
            if controller.faults is not None:
                controller.faults.on_apply = None
        controller.cfg.telemetry = None
        return
    from shadow_tpu.telemetry import TelemetryCollector

    if have is None:
        tel = TelemetryCollector(want)
        tel.next_sample = ((now // tel.sample_every) + 1) * tel.sample_every
        controller.telemetry = tel
        for h in controller.hosts:
            h.telemetry = tel
        if controller.faults is not None:
            controller.faults.on_apply = tel.record_fault
    else:
        if int(want.sample_every) != have.sample_every:
            have.sample_every = int(want.sample_every)
            have.next_sample = (
                (now // have.sample_every) + 1) * have.sample_every
        have.metrics_dir = want.metrics_dir
    controller.cfg.telemetry = want


# -- determinism sentinel -----------------------------------------------------

def _feed(h, obj) -> None:
    """Canonical byte encoding of the digest structure (type-tagged,
    length-prefixed; dict keys sorted) — stable across runs, policies,
    and platforms."""
    if obj is None:
        h.update(b"N")
    elif isinstance(obj, bool):
        h.update(b"T" if obj else b"F")
    elif isinstance(obj, (int, np.integer)):
        h.update(b"i%d;" % int(obj))
    elif isinstance(obj, float):
        h.update(b"f" + struct.pack("<d", obj))
    elif isinstance(obj, str):
        b = obj.encode()
        h.update(b"s%d:" % len(b) + b)
    elif isinstance(obj, (bytes, bytearray)):
        h.update(b"b%d:" % len(obj) + bytes(obj))
    elif isinstance(obj, (list, tuple)):
        h.update(b"[%d;" % len(obj))
        for x in obj:
            _feed(h, x)
    elif isinstance(obj, dict):
        h.update(b"{%d;" % len(obj))
        for k in sorted(obj):
            _feed(h, k)
            _feed(h, obj[k])
    elif isinstance(obj, np.ndarray):
        h.update(b"a" + str(obj.dtype).encode() + b"|"
                 + str(obj.shape).encode() + b"|")
        h.update(np.ascontiguousarray(obj).tobytes())
    else:
        raise CheckpointError(
            f"state digest: unhashable field type {type(obj).__name__}")


def _digest(obj) -> str:
    h = hashlib.sha256()
    _feed(h, obj)
    return h.hexdigest()


def state_digest(controller, sim_now: int):
    """Returns ``(global_digest_hex, {host_name: digest_hex})`` over the
    plane-independent state at the round boundary ``sim_now``.

    Calls ``engine.flush_all()`` first so both data planes (and the lazy
    draw coalescing inside each) sit at the same resolution frontier —
    early resolution is result-identical, so a digesting run stays
    byte-identical to a non-digesting one.
    """
    eng = controller.engine
    eng.flush_all()
    hosts = {}
    for h in controller.hosts:
        hosts[h.name] = _digest(h.state_fingerprint())
    g = {
        "t": sim_now,
        "rounds": controller.rounds,
        "events": controller.events,
        "units_sent": eng.units_sent,
        "units_dropped": eng.units_dropped,
        "units_blackholed": eng.units_blackholed,
        "bytes_sent": eng.bytes_sent,
        "ev_key": eng._ev_key,
        "tokens_down": eng.tokens_down,
        # egress buckets: hash the canonical observable, not the raw
        # (t_base, tokens, debt) triple — capped available-at-now
        # (fluid.TokenBuckets.levels, shared with the telemetry samplers)
        # is identical across planes: any divergence in actual bucket
        # BEHAVIOR must show here or in the unit counters.
        "bucket_avail": eng.buckets.levels(sim_now),
        "last_refill": eng._last_refill,
        # the effective latency/loss/rate matrices are deliberately NOT
        # hashed: they are pure functions of the config (pinned by
        # config_digest) and the applied-action cursor below, and at 10k+
        # graph nodes re-hashing O(nodes^2) matrices every sample would
        # dominate sentinel cost. A corrupted matrix without a moved
        # cursor still surfaces within a round or two through the arrival
        # times, unit counters, and endpoint state it must perturb.
        "faults": ((controller.faults.idx, controller.faults.applied)
                   if controller.faults is not None else None),
        "hosts": hosts,
    }
    return _digest(g), hosts


def shard_digest_partial(controller, sim_now: int) -> dict:
    """One shard worker's contribution to a sentinel record: fingerprints
    of its OWNED hosts plus its slice of the global observables. The
    parent merges partials (merge_shard_digests) into the byte-exact
    single-process record — per-host state lives wholly on its owning
    shard, the counters are disjoint sums, and the bucket/token arrays
    are valid exactly at the owned indices."""
    eng = controller.engine
    eng.flush_all()
    own = [h for h in controller.hosts if controller.owns(h.id)]
    ids = [h.id for h in own]
    return {
        "hosts": {h.name: _digest(h.state_fingerprint()) for h in own},
        "ids": ids,
        "events": controller.events,
        "units_sent": eng.units_sent,
        "units_dropped": eng.units_dropped,
        "units_blackholed": eng.units_blackholed,
        "bytes_sent": eng.bytes_sent,
        "ev_key": eng._ev_key,
        "tokens_down": eng.tokens_down[ids],
        "bucket_avail": eng.buckets.levels(sim_now)[ids],
        "last_refill": eng._last_refill,
        "faults": ((controller.faults.idx, controller.faults.applied)
                   if controller.faults is not None else None),
    }


def merge_shard_digests(parts: list, sim_now: int, rounds: int,
                        n_hosts: int):
    """Combine per-shard partials into the exact state_digest() result of
    the equivalent single-process run: ``(global_digest_hex, hosts)``."""
    tokens = np.zeros(n_hosts, dtype=np.int64)
    bucket = np.zeros(n_hosts, dtype=np.int64)
    hosts: dict = {}
    for p in parts:
        ids = p["ids"]
        tokens[ids] = p["tokens_down"]
        bucket[ids] = p["bucket_avail"]
        hosts.update(p["hosts"])
    g = {
        "t": sim_now,
        "rounds": rounds,
        "events": sum(p["events"] for p in parts),
        "units_sent": sum(p["units_sent"] for p in parts),
        "units_dropped": sum(p["units_dropped"] for p in parts),
        "units_blackholed": sum(p["units_blackholed"] for p in parts),
        "bytes_sent": sum(p["bytes_sent"] for p in parts),
        "ev_key": sum(p["ev_key"] for p in parts),
        "tokens_down": tokens,
        "bucket_avail": bucket,
        "last_refill": parts[0]["last_refill"],
        "faults": parts[0]["faults"],
        "hosts": hosts,
    }
    return _digest(g), hosts


def emit_digest(controller, sim_now: int) -> None:
    """Append one sentinel record to <data_dir>/state_digests.jsonl."""
    g, hosts = state_digest(controller, sim_now)
    controller.data_dir.mkdir(parents=True, exist_ok=True)
    rec = {"round": controller.rounds, "t": sim_now, "digest": g,
           "hosts": hosts}
    with open(controller.data_dir / DIGEST_FILE, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")


def validate_config_checkpointable(cfg) -> None:
    """THE checkpointability policy, single source of truth — pure config
    inspection, so it can fail at build time before anything is
    constructed. Refused: pcap hosts (captures stream to disk mid-run).
    Managed (real-binary) configs are checkpointable since format v5 —
    they snapshot as re-execution cursors, not pickles — but only at
    sim_shards=1. See README 'Checkpoint & resume'."""
    for hopts in cfg.hosts:
        if hopts.pcap_enabled:
            raise ValueError(
                f"checkpoint_every is unsupported with pcap capture: host "
                f"{hopts.name!r} has pcap_enabled (captures stream to disk "
                f"mid-run); disable one of the two")
    if config_has_managed(cfg) \
            and int(getattr(cfg.general, "sim_shards", 1)) != 1:
        raise ValueError(
            "checkpoint_every with managed native processes requires "
            "sim_shards=1: a re-execution snapshot re-runs the whole "
            "simulation prefix in one process")
