# Top-level developer/CI entry points.
#
#   make native   - build the preload shim + native test programs
#   make test     - full pytest suite (CPU JAX, 8 virtual devices)
#   make ci       - the full gate: native build, tests, multichip dry run,
#                   and the 1k-host twice-run determinism check
#   make bench    - the benchmark harness (one JSON line on stdout)

.PHONY: native test ci bench clean

native:
	$(MAKE) -C native

test: native
	python -m pytest tests/ -q

ci: native
	bash tools/ci.sh

bench:
	python bench.py

clean:
	$(MAKE) -C native clean
	rm -rf shadow.data
