"""Checkpoint/restore, graceful shutdown, and the determinism sentinel
(shadow_tpu/checkpoint.py).

The load-bearing property: a run resumed from ANY checkpoint produces an
output tree (and summary) identical to the uninterrupted run — across every
scheduler policy and with the C engine on or off (checkpointing runs keep
whatever plane they were configured with: C-held state exports to plain
structures through the colcore reducers and rebuilds on load). On top of
the same state walk, the per-round digest stream must be identical across
policies and data planes, and tools/bisect_divergence.py must name the
exact first divergent round of a perturbed run.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
from pathlib import Path

import pytest
import yaml

from shadow_tpu import checkpoint as ckpt
from shadow_tpu.config import parse_config
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.time import NS_PER_SEC

ROOT = Path(__file__).resolve().parents[1]

BASE = """
general:
  stop_time: 60s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 1 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        edge [ source 0 target 1 latency "25 ms" packet_loss 0.01 ]
        edge [ source 0 target 0 latency "5 ms" ]
        edge [ source 1 target 1 latency "5 ms" ]
      ]
hosts:
  server:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.tgen:TGenServer
        args: ["8080"]
  client:
    network_node_id: 1
    processes:
      - path: pyapp:shadow_tpu.models.tgen:TGenClient
        args: ["8 MB", "2", serial, "8080", server]
        start_time: 1s
"""

#: partition 2s..5s: a checkpoint taken with cadence 3s lands mid-partition,
#: so the resume must replay the heal (link_up) identically
FAULTS = """
events:
  - {time: 2s, kind: link_down, src_nodes: [0], dst_nodes: [1], duration: 3s}
"""

from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as VOLATILE


def _strip(summary):
    for k in VOLATILE:
        summary.pop(k, None)
    return summary


def _tree(data_dir) -> dict:
    out = {}
    hosts_dir = Path(data_dir) / "hosts"
    if hosts_dir.is_dir():
        for root, _, files in os.walk(hosts_dir):
            for f in sorted(files):
                p = os.path.join(root, f)
                rel = os.path.relpath(p, data_dir)
                out[rel] = hashlib.sha256(open(p, "rb").read()).hexdigest()
    assert out, f"no host output under {data_dir}"
    return out


def _cfg(tmp_path, tag, doc=BASE, faults=None, **overrides):
    d = yaml.safe_load(doc)
    if faults:
        d["faults"] = yaml.safe_load(faults)
    ov = {"general.data_directory": str(tmp_path / tag)}
    ov.update(overrides)
    return parse_config(d, ov)


def _run(tmp_path, tag, doc=BASE, faults=None, **overrides):
    cfg = _cfg(tmp_path, tag, doc, faults, **overrides)
    summary = Controller(cfg, mirror_log=False).run()
    return _strip(summary), _tree(tmp_path / tag)


def _checkpoints(tmp_path, tag):
    paths = sorted((tmp_path / tag / "checkpoints").glob("*.ckpt"))
    assert paths, "no checkpoints written"
    return paths


def _resume(tmp_path, tag, path, doc=BASE, faults=None, **overrides):
    cfg = _cfg(tmp_path, tag, doc, faults, **overrides)
    ctl, resume_at = ckpt.load_checkpoint(path, cfg, mirror_log=False)
    summary = ctl.run(resume_at=resume_at)
    return _strip(summary), _tree(tmp_path / tag)


# -- resume equivalence ------------------------------------------------------

def test_resume_matches_uninterrupted_smoke(tmp_path):
    """tpu_batch: tree + summary of (checkpoint run, resume-from-first-
    checkpoint run) both equal the uninterrupted run — checkpointing is
    transparent AND resume is byte-identical."""
    ov = {"experimental.scheduler_policy": "tpu_batch"}
    full_s, full_t = _run(tmp_path, "full", **ov)
    src_s, src_t = _run(tmp_path, "src",
                        **{"general.checkpoint_every": "5s", **ov})
    assert src_s == full_s  # checkpointing run itself is unperturbed
    assert src_t == full_t
    res_s, res_t = _resume(tmp_path, "res", _checkpoints(tmp_path, "src")[0],
                           **ov)
    assert res_s == full_s
    assert res_t == full_t


@pytest.mark.slow
@pytest.mark.parametrize("policy", ["thread_per_core", "thread_per_host",
                                    "tpu_batch"])
@pytest.mark.parametrize("colcore", [True, False])
def test_resume_equivalence_matrix(tmp_path, policy, colcore):
    """The full guarantee: for every scheduler policy, with the C engine
    on and off in the CHECKPOINTING run itself, a resume from EVERY
    checkpoint reproduces the uninterrupted output tree hash exactly.
    With colcore on (tpu_batch), the checkpoints carry C-exported
    endpoint state and the resume rebuilds + adopts it; with colcore
    off, resuming with the default (C on) exercises the cross-plane
    path — Python-written state continues under a freshly attached C
    core (plain StoreBatches convert to packed CBatches, Python
    endpoints keep dispatching through the C loop's fallback)."""
    ov = {"experimental.scheduler_policy": policy}
    full_s, full_t = _run(tmp_path, "full", **ov)
    _run(tmp_path, "src",
         **{"general.checkpoint_every": "10s",
            "experimental.native_colcore": colcore, **ov})
    paths = _checkpoints(tmp_path, "src")
    for i, p in enumerate(paths):
        res_s, res_t = _resume(tmp_path, f"res{i}", p, **ov)
        assert res_t == full_t, f"tree mismatch resuming {p.name}"
        assert res_s == full_s, f"summary mismatch resuming {p.name}"


def test_resumed_run_can_checkpoint_again(tmp_path):
    """A resumed run that keeps checkpointing must produce loadable
    checkpoints of its own (second-generation resume is byte-identical).
    Regression: checkpoint-rebuilt closures lost their <locals> qualname
    marker on Python < 3.11 and broke the NEXT save's reducer."""
    ov = {"experimental.scheduler_policy": "tpu_batch"}
    _, full_t = _run(tmp_path, "full", **ov)
    _run(tmp_path, "src", **{"general.checkpoint_every": "10s", **ov})
    first = _checkpoints(tmp_path, "src")[0]
    # resume WITH checkpointing still on: the continuation writes its own
    res_s, res_t = _resume(tmp_path, "res", first,
                           **{"general.checkpoint_every": "10s", **ov})
    assert res_t == full_t
    gen2 = [p for p in _checkpoints(tmp_path, "res")
            if ckpt.read_header(p)["sim_time_ns"]
            > ckpt.read_header(first)["sim_time_ns"]]
    assert gen2, "resumed run wrote no later checkpoints"
    _, res2_t = _resume(tmp_path, "res2", gen2[0], **ov)
    assert res2_t == full_t


def test_resume_under_active_fault_timeline(tmp_path):
    """A checkpoint taken mid-partition: the resumed run must replay the
    heal (link_up) and every later transition identically."""
    full_s, full_t = _run(tmp_path, "full", faults=FAULTS)
    assert full_s["fault_transitions_applied"] == 2
    assert full_s["units_blackholed"] > 0
    _run(tmp_path, "src", faults=FAULTS,
         **{"general.checkpoint_every": "3s"})
    paths = _checkpoints(tmp_path, "src")
    mid = [p for p in paths
           if 2 * NS_PER_SEC <= ckpt.read_header(p)["sim_time_ns"]
           < 5 * NS_PER_SEC]
    assert mid, "no checkpoint landed inside the partition window"
    res_s, res_t = _resume(tmp_path, "res", mid[0], faults=FAULTS)
    assert res_t == full_t
    assert res_s == full_s


def test_resume_rejects_config_mismatch(tmp_path):
    _run(tmp_path, "src", **{"general.checkpoint_every": "5s"})
    path = _checkpoints(tmp_path, "src")[0]
    other = _cfg(tmp_path, "res", **{"general.seed": 99})
    with pytest.raises(ckpt.CheckpointError, match="config mismatch"):
        ckpt.load_checkpoint(path, other, mirror_log=False)
    # volatile keys (data_directory, cadence) may differ: loads fine
    ok = _cfg(tmp_path, "res2", **{"general.checkpoint_every": "30s"})
    ctl, t = ckpt.load_checkpoint(path, ok, mirror_log=False)
    assert t == ckpt.read_header(path)["sim_time_ns"]


def test_load_rejects_garbage_and_wrong_python(tmp_path):
    junk = tmp_path / "junk.ckpt"
    junk.write_bytes(b"not a checkpoint\n")
    with pytest.raises(ckpt.CheckpointError, match="not a shadow_tpu"):
        ckpt.load_checkpoint(junk)
    bad = tmp_path / "badpy.ckpt"
    header = {"format": ckpt.FORMAT, "version": ckpt.VERSION,
              "python": [2, 7], "config_digest": "x", "sim_time_ns": 0}
    bad.write_bytes(json.dumps(header).encode() + b"\n")
    with pytest.raises(ckpt.CheckpointError, match="Python"):
        ckpt.load_checkpoint(bad)
    trunc = tmp_path / "trunc.ckpt"
    header["python"] = list(sys.version_info[:2])
    trunc.write_bytes(json.dumps(header).encode() + b"\n" + b"\x80\x04K")
    with pytest.raises(ckpt.CheckpointError, match="corrupt"):
        ckpt.load_checkpoint(trunc)


def test_checkpoint_rejects_pcap_and_sharded_managed(tmp_path):
    """Managed configs are checkpointable since format v5 (re-execution
    snapshots) — but only single-process: the sharded combination is
    refused up front. The pcap refusal is unchanged."""
    d = yaml.safe_load(BASE)
    d["hosts"]["server"]["processes"][0]["path"] = "/bin/sh"
    cfg = parse_config(d, {
        "general.data_directory": str(tmp_path / "mg"),
        "general.checkpoint_every": "1s"})
    ckpt.validate_config_checkpointable(cfg)  # no longer refused
    shard = parse_config(d, {
        "general.data_directory": str(tmp_path / "mg2"),
        "general.checkpoint_every": "1s",
        "general.sim_shards": 2})
    with pytest.raises(ValueError, match="sim_shards=1"):
        ckpt.validate_config_checkpointable(shard)
    cfg = _cfg(tmp_path, "pc", **{"general.checkpoint_every": "1s",
                                  "hosts.server.pcap_enabled": True})
    with pytest.raises(ValueError, match="pcap"):
        Controller(cfg, mirror_log=False)


def test_load_refuses_pre_v5_managed_checkpoint_by_name(tmp_path):
    """A managed-marked header below format v5 predates re-execution
    cursors: refused with a message naming the required version, before
    the generic version gate gets a chance to confuse the story."""
    old = tmp_path / "old_managed.ckpt"
    header = {"format": ckpt.FORMAT, "version": 4, "managed": True,
              "python": list(sys.version_info[:2]), "config_digest": "x",
              "sim_time_ns": 0}
    old.write_bytes(json.dumps(header).encode() + b"\n")
    with pytest.raises(ckpt.CheckpointError, match="v5"):
        ckpt.load_checkpoint(old)


# -- graceful shutdown -------------------------------------------------------

def test_sigint_finishes_round_writes_summary_and_final_checkpoint(tmp_path):
    """SIGINT mid-run: the loop finishes the current round, writes a final
    checkpoint, and finalizes a VALID summary with exit_reason=interrupted
    and partial=true; resuming the final checkpoint completes the run with
    the uninterrupted run's exact output tree."""
    _, full_t = _run(tmp_path, "full")
    cfg = _cfg(tmp_path, "int", **{"general.checkpoint_every": "5s"})
    ctl = Controller(cfg, mirror_log=False)
    # deliver a real SIGINT from inside the simulation (deterministic
    # instant, real handler path — we are the main thread)
    ctl.hosts[0].schedule(3 * NS_PER_SEC,
                          lambda: os.kill(os.getpid(), signal.SIGINT))
    summary = ctl.run()
    assert summary["exit_reason"] == "interrupted"
    assert summary["partial"] is True
    assert summary["interrupt_signal"] == "SIGINT"
    assert 0 < summary["sim_seconds"] < 60
    assert summary["rounds"] > 0 and summary["counters"]
    final = _checkpoints(tmp_path, "int")[-1]
    assert ckpt.read_header(final)["sim_time_ns"] >= 3 * NS_PER_SEC
    _, res_t = _resume(tmp_path, "res", final)
    assert res_t == full_t


# -- determinism sentinel ----------------------------------------------------

def test_digest_stream_identical_across_policies(tmp_path):
    """The sentinel gate: one config, three scheduler policies (spanning
    both data planes), byte-identical digest streams."""
    streams = {}
    for pol in ("thread_per_core", "thread_per_host", "tpu_batch"):
        _run(tmp_path, f"dg-{pol}",
             **{"experimental.scheduler_policy": pol,
                "general.state_digest_every": 50})
        streams[pol] = (tmp_path / f"dg-{pol}"
                        / ckpt.DIGEST_FILE).read_bytes()
    ref = streams["thread_per_core"]
    assert ref.count(b"\n") >= 3, "too few sentinel records to mean much"
    for pol, s in streams.items():
        assert s == ref, f"digest stream diverges under {pol}"


def test_digest_emission_is_transparent(tmp_path):
    """Digesting flushes in-flight draw batches early — result-identical
    by construction; assert the output tree does not move."""
    _, plain_t = _run(tmp_path, "plain")
    _, dg_t = _run(tmp_path, "dg", **{"general.state_digest_every": 25})
    assert dg_t == plain_t


def test_digest_stream_truncated_on_rerun(tmp_path):
    """Re-running into the same data_directory must not concatenate
    sentinel streams (duplicate rounds would confuse the bisect tool)."""
    ov = {"general.state_digest_every": 50}
    _run(tmp_path, "rr", **ov)
    once = (tmp_path / "rr" / ckpt.DIGEST_FILE).read_bytes()
    _run(tmp_path, "rr", **ov)  # same tag -> same data_directory
    again = (tmp_path / "rr" / ckpt.DIGEST_FILE).read_bytes()
    assert again == once


def _bisect(*paths):
    r = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "bisect_divergence.py"),
         *map(str, paths)],
        capture_output=True, text=True, timeout=60)
    return r.returncode, r.stdout


def test_bisect_divergence_names_round_and_host(tmp_path):
    recs = [{"round": r, "t": r * 10, "digest": f"d{r}",
             "hosts": {"alice": f"a{r}", "bob": f"b{r}"}}
            for r in range(5, 55, 5)]
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    a.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    recs2 = json.loads(json.dumps(recs))  # deep copy
    for r in recs2:
        if r["round"] >= 35:  # diverges at round 35, host bob only
            r["digest"] += "X"
            r["hosts"]["bob"] += "X"
    b.write_text("\n".join(json.dumps(r) for r in recs2) + "\n")
    rc, out = _bisect(a, b)
    assert rc == 1
    assert "FIRST DIVERGENT ROUND: 35" in out
    assert "last matching round: 30" in out
    assert "bob" in out and "alice" not in out
    rc, out = _bisect(a, a)
    assert rc == 0 and "identical" in out


def test_bisect_on_real_seed_perturbation(tmp_path):
    """Two real runs differing only in seed: the tool's answer must equal
    the first record where the streams actually differ."""
    for tag, seed in (("p3", 3), ("p4", 4)):
        _run(tmp_path, tag, **{"general.seed": seed,
                               "general.state_digest_every": 20})
    fa = tmp_path / "p3" / ckpt.DIGEST_FILE
    fb = tmp_path / "p4" / ckpt.DIGEST_FILE
    ra = [json.loads(l) for l in open(fa)]
    rb = [json.loads(l) for l in open(fb)]
    first = next((x["round"] for x, y in zip(ra, rb)
                  if x["digest"] != y["digest"]), None)
    assert first is not None, "different seeds produced identical streams?"
    rc, out = _bisect(fa, fb)
    assert rc == 1
    assert f"FIRST DIVERGENT ROUND: {first}" in out


# -- guest watchdog (native/managed.py) --------------------------------------

def test_watchdog_converts_held_turn_to_host_down(tmp_path):
    """A managed guest that holds its turn past guest_turn_timeout without
    a syscall (userspace spin livelock) is killed and the host downed,
    with a diagnostic log line — instead of hanging the simulator. Driven
    with a stand-in guest (a socketpair that never speaks + a real child
    process), so it runs without the native shim."""
    import socket as socklib

    from shadow_tpu.config.schema import ProcessOptions
    from shadow_tpu.native.managed import GuestThread, ManagedProcess

    cfg = _cfg(tmp_path, "wd",
               **{"experimental.guest_turn_timeout": 0.2})
    ctl = Controller(cfg, mirror_log=False)
    host = ctl.hosts[0]

    def stub(path):
        p = ManagedProcess(host, ProcessOptions(path=path), 0)
        p.proc = subprocess.Popen(["sleep", "30"])
        p.threads = {0: GuestThread(0, None)}
        p.running = True
        host.processes.append(p)
        return p

    proc = stub("/bin/spinner")
    assert proc._turn_timeout == pytest.approx(0.2)
    sibling = stub("/bin/sibling")  # second managed guest on the host
    worker, guest = socklib.socketpair(socklib.AF_UNIX, socklib.SOCK_STREAM)
    proc.sock = worker
    proc.threads[0].sock = worker
    try:
        proc._pump(proc.threads[0])  # guest never speaks -> watchdog
    finally:
        guest.close()
    assert proc.running is False
    assert proc.exit_code == -9
    assert proc.proc.poll() is not None  # really killed + reaped
    # the sibling's live OS process must not outlive its 'down' host
    assert sibling.running is False
    assert sibling.proc.poll() is not None
    assert host.down is True
    assert host.counters.get("guest_watchdog_kills") == 1
    assert host.counters.get("host_crashes") == 1
    assert any("guest watchdog" in ln for ln in host._log_lines)


# -- managed guests: re-execution checkpoints (format v5) --------------------

BUILD = ROOT / "native" / "build"


def _managed_missing() -> list:
    """Why the real-binary matrix legs cannot run here (empty = they can):
    the same kernel-capability probe the shim suite uses, plus the build
    artifacts themselves."""
    missing = []
    for b in ("libshadow_shim.so", "tgen_srv", "ring_probe"):
        if not (BUILD / b).is_file():
            missing.append(f"native/build/{b}")
    if not missing:
        try:
            from test_native_shim import _env_caps_missing
            missing += _env_caps_missing()
        except ImportError as e:
            missing.append(f"capability probe unavailable ({e})")
    return missing


_MANAGED_MISSING = _managed_missing()
managed_only = pytest.mark.skipif(
    bool(_MANAGED_MISSING),
    reason="managed guest plane unavailable: "
           + ", ".join(map(str, _MANAGED_MISSING)))

#: real unmodified binaries mid-transfer: tgen_srv streams 300 kB to
#: ring_probe (the shim fast plane's dedicated client), finishing around
#: sim 1.7s — so a 500ms checkpoint cadence lands snapshots squarely
#: inside the transfer
MANAGED_BASE = f"""
general:
  stop_time: 30s
  seed: 11
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 1 latency "20 ms" ]
        edge [ source 0 target 0 latency "5 ms" ]
        edge [ source 1 target 1 latency "5 ms" ]
      ]
hosts:
  server:
    network_node_id: 0
    ip_addr: 11.0.0.1
    processes:
      - path: {BUILD / "tgen_srv"}
        args: ["8080", "1"]
        expected_final_state: {{exited: 0}}
  client:
    network_node_id: 1
    processes:
      - path: {BUILD / "ring_probe"}
        args: ["11.0.0.1", "8080", "300000"]
        start_time: 1s
        expected_final_state: {{exited: 0}}
"""


@pytest.fixture(params=["fastpath_on", "fastpath_off"])
def shim_fastpath(request, monkeypatch):
    """Both sides of the shim fast plane: the module global gates the
    worker side (read per-process at spawn), the env var gates the C shim
    inside the child."""
    on = request.param == "fastpath_on"
    import shadow_tpu.native.managed as managed

    monkeypatch.setenv("SHADOW_TPU_SHIM_FASTPATH", "1" if on else "0")
    monkeypatch.setattr(managed, "_FASTPATH_ON", on)
    return on


@managed_only
def test_managed_reexec_checkpoint_resume_identity(tmp_path, shim_fastpath):
    """The headline v5 property, with real binaries on both fast-plane
    legs: a checkpoint taken mid-transfer resumes by re-execution into
    the uninterrupted run's exact host tree, summary, and digest stream —
    and the snapshot boundary is digest- and journal-cursor-verified."""
    dig = {"general.state_digest_every": 5}
    full_s, full_t = _run(tmp_path, "full", doc=MANAGED_BASE, **dig)
    assert full_s["process_errors"] == []
    src_s, src_t = _run(tmp_path, "src", doc=MANAGED_BASE,
                        **{"general.checkpoint_every": "500 ms", **dig})
    assert src_s == full_s  # journaling + snapshots are transparent
    assert src_t == full_t
    paths = _checkpoints(tmp_path, "src")
    hdr = ckpt.read_header(paths[0])
    assert hdr["mode"] == "reexec" and hdr["managed"] is True
    assert hdr["version"] == 5
    # the mid-transfer snapshot carries a journal cursor per live guest
    assert list((tmp_path / "src" / "guest_oplogs").glob("*.jsonl"))
    res_s, res_t = _resume(tmp_path, "res", paths[0], doc=MANAGED_BASE,
                           **dig)
    assert res_t == full_t
    assert res_s == full_s
    assert ((tmp_path / "res" / ckpt.DIGEST_FILE).read_bytes()
            == (tmp_path / "full" / ckpt.DIGEST_FILE).read_bytes())


@managed_only
def test_managed_reexec_detects_divergence(tmp_path):
    """A reexec snapshot resumed under a DIFFERENT observation stream
    must fail loudly at the boundary, not silently continue: corrupt the
    recorded state digest and expect the by-name divergence error."""
    _run(tmp_path, "src", doc=MANAGED_BASE,
         **{"general.checkpoint_every": "500 ms"})
    p = _checkpoints(tmp_path, "src")[0]
    header, payload = p.read_text().splitlines()[:2]
    doc = json.loads(payload)
    doc["digest"] = "0" * len(doc["digest"])
    tampered = tmp_path / "tampered.ckpt"
    tampered.write_text(header + "\n" + json.dumps(doc) + "\n")
    cfg = _cfg(tmp_path, "res", doc=MANAGED_BASE)
    ctl, resume_at = ckpt.load_checkpoint(tampered, cfg, mirror_log=False)
    try:
        with pytest.raises(ckpt.CheckpointError, match="diverged"):
            ctl.run(resume_at=resume_at)
    finally:
        # the abort path skips _finalize: reap the real guests ourselves
        for p in ctl.processes:
            p.kill()
        ctl.scheduler.shutdown()


@managed_only
def test_managed_host_down_respawns_and_stays_deterministic(tmp_path):
    """Live host lifecycle on a managed host (the old by-name refusal):
    a replayed host_down mid-transfer SIGKILLs the real guest, host_up
    respawns a fresh instance, and the whole faulted run is byte-stable
    under --replay-commands."""
    cmds = tmp_path / "cmds.jsonl"
    cmds.write_text(json.dumps(
        {"cmd": {"cmd": "host_down", "hosts": ["client"],
                 "duration": "300000000 ns"},
         "round": 0, "seq": 0, "t": 1_200_000_000}) + "\n")
    ov = {"general.replay_commands": str(cmds),
          "general.state_digest_every": 5}
    runs = []
    for tag in ("a", "b"):
        s, t = _run(tmp_path, tag, doc=MANAGED_BASE, **ov)
        assert s["counters"]["host_crashes"] == 1
        assert s["counters"]["host_boots"] == 1
        # 3 spawns = server + client + the post-reboot client respawn
        assert s["counters"]["processes_spawned"] == 3
        runs.append((s, t))
    assert runs[0] == runs[1]
    assert ((tmp_path / "a" / ckpt.DIGEST_FILE).read_bytes()
            == (tmp_path / "b" / ckpt.DIGEST_FILE).read_bytes())
    # and a checkpoint taken AFTER the fault embeds the command stream:
    # resuming it replays the crash/respawn prefix identically
    _run(tmp_path, "src", doc=MANAGED_BASE,
         **{"general.checkpoint_every": "500 ms", **ov})
    late = _checkpoints(tmp_path, "src")[-1]
    res_s, res_t = _resume(tmp_path, "res", late, doc=MANAGED_BASE,
                           **{"general.state_digest_every": 5})
    assert res_t == runs[0][1]
    assert res_s == runs[0][0]


# -- schema --------------------------------------------------------------

def test_schema_validates_new_keys(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_every"):
        _cfg(tmp_path, "s1", **{"general.checkpoint_every": 0})
    with pytest.raises(ValueError, match="state_digest_every"):
        _cfg(tmp_path, "s2", **{"general.state_digest_every": -1})
    with pytest.raises(ValueError, match="guest_turn_timeout"):
        _cfg(tmp_path, "s3", **{"experimental.guest_turn_timeout": -1})
    cfg = _cfg(tmp_path, "s4", **{"general.checkpoint_every": "250 ms",
                                  "general.checkpoint_dir": "/tmp/x",
                                  "general.state_digest_every": 7,
                                  "experimental.guest_turn_timeout": 1.5})
    assert cfg.general.checkpoint_every == 250_000_000
    assert cfg.general.checkpoint_dir == "/tmp/x"
    assert cfg.general.state_digest_every == 7
    assert cfg.experimental.guest_turn_timeout == 1.5
