"""Multi-process shard determinism (shadow_tpu/parallel/shards.py).

THE acceptance gate of the sharding PR: sim_shards=1/2/4 produce
byte-identical output trees, flows.jsonl, metrics.jsonl, and digest
streams on the fault-injection config (gossip flood + bulk stream under
partition/degrade/churn), with the C engine on and off — shards=1 being
the unchanged single-process controller. Plus: same-count checkpoint
resume reproduces the uninterrupted tree, and a mismatched-count resume
refuses by name.

The wire/ring primitives get direct unit tests (payload round-trip,
ring wrap, spill signaling) since a subtle packing bug would surface as
a distant divergence otherwise.
"""

from __future__ import annotations

import hashlib
import json
import shutil
from pathlib import Path

import pytest
import yaml

from shadow_tpu.config.schema import parse_config
from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS, Controller
from shadow_tpu.parallel import shards as sh

ROOT = Path(__file__).resolve().parent.parent
CHURN_YAML = ROOT / "examples" / "gossip_churn.yaml"

#: shortened churn config: covers the partition (4s), its heal (9s is
#: beyond), the degrade window start, and seeded churn from 2s
STOP = "10s"


def _cfg(tag: str, shards: int, colcore: bool = True, stop: str = STOP,
         extra: dict = None):
    doc = yaml.safe_load(CHURN_YAML.read_text())
    over = {
        "general.data_directory": f"/tmp/st-shards-{tag}",
        "general.stop_time": stop,
        "general.sim_shards": shards,
        "general.state_digest_every": 50,
        "telemetry.sample_every": "5s",
        "experimental.scheduler_policy": "tpu_batch",
        "experimental.native_colcore": colcore,
        **(extra or {}),
    }
    # an extra of {key: None} removes the override (e.g. disable the
    # telemetry section for the checkpoint legs)
    over = {k: v for k, v in over.items() if v is not None}
    shutil.rmtree(f"/tmp/st-shards-{tag}", ignore_errors=True)
    return parse_config(doc, over)


def _run(tag: str, shards: int, colcore: bool = True, stop: str = STOP,
         extra: dict = None) -> dict:
    cfg = _cfg(tag, shards, colcore, stop, extra)
    if shards == 1:
        return Controller(cfg, mirror_log=False).run()
    return sh.run_sharded(cfg, mirror_log=False)


def _tree(tag: str) -> dict:
    out = {}
    base = Path(f"/tmp/st-shards-{tag}")
    for p in sorted((base / "hosts").rglob("*")):
        if p.is_file():
            out[str(p.relative_to(base))] = hashlib.sha256(
                p.read_bytes()).hexdigest()
    assert out
    return out


def _streams(tag: str) -> dict:
    base = Path(f"/tmp/st-shards-{tag}")
    out = {}
    for name in ("flows.jsonl", "metrics.jsonl", "state_digests.jsonl"):
        out[name] = hashlib.sha256((base / name).read_bytes()).hexdigest()
    return out


def _clean_summary(s: dict) -> dict:
    s = dict(s)
    for k in VOLATILE_SUMMARY_KEYS:
        s.pop(k, None)
    return s


# -- the identity matrix ------------------------------------------------------

def test_shard_identity_c_engine():
    """shards=1 (plain controller) vs 2 vs 4 with the C engine: trees,
    flow/metric/digest streams, and non-volatile summaries all byte-
    identical under faults + churn + telemetry + the sentinel."""
    s1 = _run("c1", 1)
    t1, st1 = _tree("c1"), _streams("c1")
    assert s1["counters"].get("host_crashes", 0) > 0  # adversity ran
    assert s1["units_blackholed"] > 0
    assert st1["flows.jsonl"] and st1["state_digests.jsonl"]
    for n in (2, 4):
        sn = _run(f"c{n}", n)
        assert _tree(f"c{n}") == t1, f"tree diverged at shards={n}"
        assert _streams(f"c{n}") == st1, f"streams diverged at shards={n}"
        assert _clean_summary(sn) == _clean_summary(s1), \
            f"summary diverged at shards={n}"
        assert sn["sim_shards"] == n
        assert len(sn["shards"]["per_shard"]) == n


def test_shard_identity_python_plane():
    """Same gate with the C engine OFF (pure-Python columnar plane):
    shards=2 vs the single-process Python run — and the Python tree must
    equal the C tree (the planes are twins, sharded or not)."""
    s1 = _run("py1", 1, colcore=False)
    t1, st1 = _tree("py1"), _streams("py1")
    s2 = _run("py2", 2, colcore=False)
    assert _tree("py2") == t1
    assert _streams("py2") == st1
    assert _clean_summary(s2) == _clean_summary(s1)


@pytest.mark.slow
def test_shard_identity_python_plane_4():
    s1 = _run("py41", 1, colcore=False)
    _run("py44", 4, colcore=False)
    assert _tree("py44") == _tree("py41")
    assert _streams("py44") == _streams("py41")


def test_shard_identity_thread_policy():
    """The per-unit plane (thread_per_core) shards too: same divert/
    ingest contract, heap arrivals instead of a pending store."""
    extra = {"experimental.scheduler_policy": "thread_per_core"}
    _run("tp1", 1, extra=extra)
    _run("tp2", 2, extra=extra)
    assert _tree("tp2") == _tree("tp1")
    assert _streams("tp2") == _streams("tp1")


# -- checkpoint/resume --------------------------------------------------------

def test_shard_checkpoint_resume_and_refusal():
    """Same-count resume from a mid-churn shard manifest reproduces the
    uninterrupted tree and continues the digest stream; a mismatched
    shard count refuses by name; a single-process checkpoint refuses a
    sharded resume (and vice versa)."""
    from shadow_tpu import checkpoint as ckpt

    full = _run("ckf", 2, extra={"telemetry.sample_every": None})
    t_full = _tree("ckf")
    dig_full = Path(
        "/tmp/st-shards-ckf/state_digests.jsonl").read_text().splitlines()
    _run("cks", 2, extra={"telemetry.sample_every": None,
                          "general.checkpoint_every": "4s"})
    manifests = sorted(Path("/tmp/st-shards-cks/checkpoints")
                       .glob("*" + sh.MANIFEST_SUFFIX))
    assert manifests, "sharded run wrote no manifest"
    mani = manifests[0]
    doc = json.loads(mani.read_text())
    assert doc["sim_shards"] == 2
    assert len(doc["files"]) == 2
    for f in doc["files"]:
        h = ckpt.read_header(mani.parent / f)
        assert h["sim_shards"] == 2
        assert h["shard"] in (0, 1)

    # resume at the same count: tree identity + digest-stream suffix
    cfg = _cfg("ckr", 2, extra={"telemetry.sample_every": None})
    res = sh.run_sharded(cfg, mirror_log=False, resume_from=str(mani))
    assert _tree("ckr") == t_full
    dig_res = Path(
        "/tmp/st-shards-ckr/state_digests.jsonl").read_text().splitlines()
    assert dig_res == dig_full[-len(dig_res):]
    assert _clean_summary(res)["counters"] == \
        _clean_summary(full)["counters"]

    # mismatched count refuses by name (manifest path and shard path)
    cfg4 = _cfg("ckbad", 4, extra={"telemetry.sample_every": None})
    with pytest.raises(ckpt.CheckpointError, match="sim_shards=2"):
        sh.run_sharded(cfg4, mirror_log=False, resume_from=str(mani))
    shard_file = mani.parent / doc["files"][0]
    cfg4b = _cfg("ckbad2", 4, extra={"telemetry.sample_every": None})
    with pytest.raises(ckpt.CheckpointError, match="sim_shards=2"):
        sh.run_sharded(cfg4b, mirror_log=False,
                       resume_from=str(shard_file))
    # a shard checkpoint cannot resume into the single-process controller
    cfg1 = _cfg("ckbad3", 1, extra={"telemetry.sample_every": None})
    with pytest.raises(ckpt.CheckpointError, match="sim_shards"):
        ckpt.load_checkpoint(str(shard_file), cfg1, mirror_log=False)


# -- signal-delivery races ----------------------------------------------------

@pytest.mark.parametrize("signame", ["SIGINT", "SIGTERM"])
def test_sharded_signal_mid_round(signame):
    """SIGINT/SIGTERM landing mid-round in a sharded run: a valid
    PARTIAL json summary (exit_reason interrupted, the signal named,
    rounds counted), the conventional 128+N exit status, and no leaked
    worker processes — never a hang or a traceback."""
    import os
    import signal as _signal
    import subprocess
    import sys
    import time

    tag = f"sig{signame[3].lower()}"
    d = f"/tmp/st-shards-{tag}"
    shutil.rmtree(d, ignore_errors=True)
    proc = subprocess.Popen(
        [sys.executable, "-m", "shadow_tpu", str(CHURN_YAML),
         "--shards", "2", "--stop-time", "120s",
         "--data-directory", d, "--state-digest-every", "20",
         "--quiet", "--json-summary"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=str(ROOT))
    try:
        # wait for real mid-run progress (the merged digest stream is
        # flowing), so the signal races an active round, not startup
        digp = Path(d) / "state_digests.jsonl"
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if digp.is_file() and digp.stat().st_size > 0:
                break
            assert proc.poll() is None, proc.stderr.read().decode()
            time.sleep(0.05)
        else:
            pytest.fail("no round progress before the deadline")
        os.kill(proc.pid, getattr(_signal, signame))
        out, err = proc.communicate(timeout=90)
    finally:
        if proc.poll() is None:
            proc.kill()
    res = json.loads(out)
    assert res["exit_reason"] == "interrupted", err.decode()
    assert res["interrupt_signal"] == signame
    assert res["rounds"] > 0
    assert res["sim_shards"] == 2
    assert proc.returncode == 128 + int(getattr(_signal, signame))


# -- refusals -----------------------------------------------------------------

def test_shard_config_refusals():
    cfg = _cfg("ref1", 2)
    cfg.experimental.scheduler_policy = "tpu_mesh"
    with pytest.raises(ValueError, match="tpu_mesh"):
        sh.validate_config_shardable(cfg)
    cfg = _cfg("ref2", 2)
    cfg.hosts[0].pcap_enabled = True
    with pytest.raises(ValueError, match="pcap"):
        sh.validate_config_shardable(cfg)
    cfg = _cfg("ref3", 2)
    cfg.hosts[0].processes[0].path = "/bin/true"
    with pytest.raises(ValueError, match="managed"):
        sh.validate_config_shardable(cfg)


# -- wire format + rings ------------------------------------------------------

def test_pack_unpack_roundtrip():
    rows = [
        (100, (3 << 40) | 7, 5, 2, 3, 4000, 80, 1234, 99, 0, 1, 1500,
         b"payload-bytes"),
        (200, (1 << 40) | 0, 2, 1, 1, 50000, 7000, 0, 7, 2, 3, 560,
         ("inv", (1, 2, 3), "tx-id")),
        (300, 42, 9, 4, 8, 1, 2, -5, -17, 0, 1, 40, None),
        (2**62, 2**57, 11, 7, 10, 65535, 65535, 2**61, 2**60, 63, 64,
         15000, "unicode-π"),
    ]
    assert sh.unpack_rows(sh.pack_rows(rows)) == rows
    assert sh.unpack_rows(sh.pack_rows([])) == []


class _OddPayload:
    """Module-level so the pickle fallback can serialize it."""

    def __init__(self, v):
        self.v = v

    def __eq__(self, other):
        return isinstance(other, _OddPayload) and other.v == self.v


def test_pack_pickle_fallback():
    rows = [(1, 2, 3, 4, 5, 6, 7, 8, 9, 0, 1, 2, _OddPayload("x"))]
    assert sh.unpack_rows(sh.pack_rows(rows)) == rows


def test_shm_ring_wrap_and_spill():
    import os

    name = f"stpu_test_{os.getpid()}"
    ring = sh.ShmRing(name, size=256, create=True)
    try:
        blocks = [bytes([i]) * (40 + i) for i in range(4)]
        # fill/drain cycles force the wrap path several times
        for cycle in range(10):
            wrote = []
            for b in blocks:
                if ring.write(b):
                    wrote.append(b)
            assert wrote, "ring accepted nothing"
            assert ring.read_all() == wrote
        # a block larger than capacity signals a spill
        assert not ring.write(b"x" * 300)
        # writer-side blocks interleaved with partial drains
        assert ring.write(b"a" * 100)
        assert ring.read_all() == [b"a" * 100]
        assert ring.write(b"b" * 100)
        assert ring.write(b"c" * 100)
        assert ring.read_all() == [b"b" * 100, b"c" * 100]
    finally:
        ring.close()
        ring.unlink()


def test_keys_are_uids():
    """The canonical-key scheme the shard plane rests on: BAND_NET event
    keys equal unit uids in every plane (placement-independent ordering).
    Guarded here so a future key-scheme change cannot silently break
    cross-shard ordering."""
    from shadow_tpu.network import colplane as cp

    doc = yaml.safe_load(CHURN_YAML.read_text())
    cfg = parse_config(doc, {
        "general.data_directory": "/tmp/st-shards-keys",
        "general.stop_time": "4s",
        "experimental.scheduler_policy": "tpu_batch"})
    ctl = Controller(cfg, mirror_log=False)
    eng = ctl.engine
    assert isinstance(eng, cp.ColumnarPlane)
    eng.bind_shard(0, 2)
    ctl.run()
    # every diverted row's key must be a well-formed uid of its src.
    # With the C engine the rows sit in the core's packed send buffers:
    # drain them as wire blocks and parse them back with the Python
    # unpacker — which also round-trips the C packer against the wire
    # format the receive side (cbatch_from_packed) expects.
    packed = eng.take_xout_packed(1 << 30)
    if packed is not None:
        rows_by_shard = [
            [r for blob in blocks for r in sh.unpack_rows(blob)]
            for blocks in packed]
    else:
        rows_by_shard = eng.xout
    moved = 0
    for rows in rows_by_shard:
        tks = [(r[0], r[1]) for r in rows]
        assert tks == sorted(tks)  # the packer ships (t, key)-sorted
        for r in rows:
            assert r[1] >> 32 == r[4], (r[1], r[4])  # key's src == peer
            moved += 1
    # xout was drained nowhere (no parent): rows for shard-1 hosts stayed
    assert moved > 0
    assert eng.shard_n == 2
