"""Live operations plane (shadow_tpu/live.py).

The load-bearing property: a run driven interactively through the live
endpoint — runtime fault commands, pause/resume, checkpoint_now, stop —
is REPLAYABLE byte-identically from its config plus the recorded
commands.jsonl, across scheduler policies, the C/Python twin planes,
and shard counts; and the endpoint itself (streaming + an attached
follower) never perturbs the simulation. On top: the time-travel
debugger (``python -m shadow_tpu.live jump``) reproduces recorded state
digests, and ``bisect_divergence --json`` feeds it a divergent round.
"""

from __future__ import annotations

import hashlib
import json
import shutil
import threading
import time as _walltime  # detlint: ok(wallclock): test harness pacing only
from pathlib import Path

import pytest
import yaml

from shadow_tpu import live as lv
from shadow_tpu.config.schema import parse_config
from shadow_tpu.core.controller import Controller
from shadow_tpu.parallel import shards as sh

ROOT = Path(__file__).resolve().parents[1]
CHURN_YAML = ROOT / "examples" / "gossip_churn.yaml"

#: two-node bulk stream: long enough (sim seconds) that an immediately
#: sent command always lands mid-transfer, short enough to run a matrix
BASE = """
general:
  stop_time: 120s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        edge [ source 0 target 1 latency "25 ms" ]
        edge [ source 0 target 0 latency "5 ms" ]
        edge [ source 1 target 1 latency "5 ms" ]
      ]
hosts:
  server:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.tgen:TGenServer
        args: ["8080"]
  client:
    network_node_id: 1
    processes:
      - path: pyapp:shadow_tpu.models.tgen:TGenClient
        args: ["16 MB", "1", serial, "8080", server]
        start_time: 1s
"""

LINK_DOWN = {"cmd": "link_down", "src_nodes": [0], "dst_nodes": [1],
             "duration": "3s"}


def _base_cfg(tag: str, over: dict = None):
    doc = yaml.safe_load(BASE)
    dd = f"/tmp/st-live-{tag}"
    shutil.rmtree(dd, ignore_errors=True)
    return parse_config(doc, {"general.data_directory": dd,
                              "general.state_digest_every": 50,
                              "telemetry.sample_every": "5s",
                              **(over or {})})


def _tree(tag: str, require=True) -> dict:
    out = {}
    base = Path(f"/tmp/st-live-{tag}")
    for p in sorted((base / "hosts").rglob("*")):
        if p.is_file():
            out[str(p.relative_to(base))] = hashlib.sha256(
                p.read_bytes()).hexdigest()
    if require:
        assert out, f"no host artifacts under /tmp/st-live-{tag}"
    return out


def _stream(tag: str, name: str) -> str:
    return Path(f"/tmp/st-live-{tag}/{name}").read_text()


def _sim_cmds(log_text: str) -> list:
    """The sim-visible command records: replay skips (and does not
    re-log) wall_only pause/resume entries, so replay logs must equal
    the live log FILTERED to these."""
    return [ln for ln in log_text.splitlines()
            if not json.loads(ln).get("wall_only")]


def _live_run(tag: str, cmds: list, over: dict = None,
              collect_stream: bool = False):
    """Run BASE with a live endpoint; a sibling thread sends ``cmds``
    in order as soon as the socket binds. Returns (summary, acks,
    records) — records only populated when ``collect_stream``."""
    sock = f"/tmp/st-live-{tag}.sock"
    cfg = _base_cfg(tag, {"general.live_endpoint": sock, **(over or {})})
    acks: list = []
    records: list = []

    def _drive():
        for c in cmds:
            acks.append(lv.send_command(sock, c, timeout=60))

    def _follow():
        for rec in lv.stream_records(sock, timeout=60):
            records.append(rec)

    threads = [threading.Thread(target=_drive, daemon=True)]
    if collect_stream:
        threads.append(threading.Thread(target=_follow, daemon=True))
    for t in threads:
        t.start()
    summary = Controller(cfg, mirror_log=False).run()
    for t in threads:
        t.join(timeout=10)
    return summary, acks, records


def _replay_run(tag: str, log_path: str, over: dict = None) -> dict:
    cfg = _base_cfg(tag, {"general.replay_commands": log_path,
                          **(over or {})})
    return Controller(cfg, mirror_log=False).run()


# -- command validation + the canonical log -----------------------------------

def test_normalize_command():
    n = lv.normalize_command(dict(LINK_DOWN))
    assert n["cmd"] == "link_down"
    # canonical durations are explicit-unit strings: a bare int would be
    # re-parsed as SECONDS by parse_time on the replay side
    assert n["duration"] == "3000000000 ns"
    # idempotent: normalizing the canonical form is a fixed point
    assert lv.normalize_command(dict(n)) == n
    with pytest.raises(ValueError, match="unknown command"):
        lv.normalize_command({"cmd": "reboot_host"})
    with pytest.raises(ValueError, match="unknown keys"):
        lv.normalize_command({**LINK_DOWN, "sneaky": 1})
    with pytest.raises(ValueError, match="unknown command"):
        lv.normalize_command({"src_nodes": [0]})
    with pytest.raises(ValueError, match="no parameters"):
        lv.normalize_command({"cmd": "pause", "duration": "1s"})
    assert lv.normalize_command({"cmd": "pause"}) == {"cmd": "pause"}


def test_command_log_roundtrip(tmp_path):
    n = lv.normalize_command(dict(LINK_DOWN))
    lines = [lv.format_command_record(n, 1, 10, 50_000_000),
             lv.format_command_record({"cmd": "pause"}, 2, 20, 90_000_000,
                                      wall_only=True)]
    p = tmp_path / "commands.jsonl"
    p.write_text("\n".join(lines) + "\n")
    recs = lv.load_command_log(p)
    assert [r["seq"] for r in recs] == [1, 2]
    assert recs[0]["cmd"] == n
    assert recs[1]["wall_only"] is True
    # application order is file order; t must be non-decreasing
    p.write_text("\n".join(reversed(lines)) + "\n")
    with pytest.raises(ValueError, match="goes backwards"):
        lv.load_command_log(p)


def test_server_refuse_ack_and_broadcast(tmp_path):
    sock = str(tmp_path / "s.sock")
    srv = lv.LiveServer(sock, refuse=lambda n: (
        "not here" if n["cmd"] == "pause" else None))
    try:
        got = []
        t = threading.Thread(
            target=lambda: got.extend(lv.stream_records(sock, timeout=10)),
            daemon=True)
        t.start()
        for _ in range(500):  # wait for the follower's hello
            if got:
                break
            _walltime.sleep(0.01)
        assert got and got[0]["type"] == "hello"
        assert lv.send_command(sock, {"cmd": "pause"})["type"] == "error"
        assert lv.send_command(sock, {"cmd": "bogus"})["type"] == "error"
        ack = lv.send_command(sock, dict(LINK_DOWN))
        assert ack["type"] == "ack"
        assert ack["cmd"]["duration"] == "3000000000 ns"
        # the refused + malformed commands never reached the queue
        assert [c["cmd"] for c in srv.poll_commands()] == ["link_down"]
        srv.publish({"type": "hb", "t": 1})
        srv.publish_stream("metrics.jsonl", ['{"kind":"sample"}'])
    finally:
        srv.close()
    t.join(timeout=10)
    kinds = [r["type"] for r in got]
    assert "hb" in kinds and "stream" in kinds


def test_endpoint_path_too_long(tmp_path):
    with pytest.raises(ValueError, match="AF_UNIX"):
        lv.LiveServer(str(tmp_path / ("x" * 120) / "live.sock"))


# -- live run vs replay: the byte-identity matrix -----------------------------

def test_live_replay_identity_matrix():
    """One interactively driven run (pause + link_down + resume, streamed
    to a follower), replayed from its commands.jsonl across scheduler
    policies and the C/Python twin planes: trees, digest streams,
    metrics, and the sim-visible command log are all byte-identical."""
    live_over = {"experimental.scheduler_policy": "tpu_batch",
                 "experimental.native_colcore": True,
                 "general.heartbeat_interval": "2s"}
    s1, acks, recs = _live_run(
        "mx-live", [{"cmd": "pause"}, dict(LINK_DOWN), {"cmd": "resume"}],
        over=live_over, collect_stream=True)
    assert [a["type"] for a in acks] == ["ack"] * 3
    # the command plane reached the sim: down + scheduled heal applied
    assert s1["fault_transitions_applied"] >= 2
    log = "/tmp/st-live-mx-live/commands.jsonl"
    cl = Path(log).read_text()
    recs_log = [json.loads(x) for x in cl.splitlines()]
    assert [r["cmd"]["cmd"] for r in recs_log] == \
        ["pause", "link_down", "resume"]
    assert recs_log[0].get("wall_only") and recs_log[2].get("wall_only")
    # pause wall-blocked the boundary: all three landed on the same one
    assert len({r["t"] for r in recs_log}) == 1
    # the follower saw the lifecycle: hello, heartbeats, the commands,
    # stream tees, and the end record
    kinds = {r["type"] for r in recs}
    assert {"hello", "hb", "command", "stream", "end"} <= kinds
    t1 = _tree("mx-live")
    d1 = _stream("mx-live", "state_digests.jsonl")
    m1 = _stream("mx-live", "metrics.jsonl")
    for tag, over in (
            ("mx-r-pyplane", {"experimental.scheduler_policy": "tpu_batch",
                              "experimental.native_colcore": False}),
            ("mx-r-tpc", {"experimental.scheduler_policy":
                          "thread_per_core",
                          "experimental.native_colcore": True}),
            ("mx-r-tpc-py", {"experimental.scheduler_policy":
                             "thread_per_core",
                             "experimental.native_colcore": False})):
        s2 = _replay_run(tag, log, over)
        assert _tree(tag) == t1, f"tree diverged: {tag}"
        assert _stream(tag, "state_digests.jsonl") == d1, tag
        assert _stream(tag, "metrics.jsonl") == m1, tag
        assert _sim_cmds(_stream(tag, "commands.jsonl")) == \
            _sim_cmds(cl), tag
        assert s2["fault_transitions_applied"] == \
            s1["fault_transitions_applied"]


def test_live_noop_endpoint_is_transparent():
    """A bound endpoint with no commands (follower attached) changes
    nothing: tree and digests equal the detached run, and no
    commands.jsonl is written."""
    s_live, _, _ = _live_run("noop-live", [], collect_stream=True)
    cfg = _base_cfg("noop-off")
    s_off = Controller(cfg, mirror_log=False).run()
    assert _tree("noop-live") == _tree("noop-off")
    assert _stream("noop-live", "state_digests.jsonl") == \
        _stream("noop-off", "state_digests.jsonl")
    assert not Path("/tmp/st-live-noop-live/commands.jsonl").exists()
    assert s_live["rounds"] == s_off["rounds"]


def test_live_stop_command_and_replay():
    """A live ``stop`` ends the run gracefully at a round boundary
    (interrupt_signal live_stop, partial summary) and is recorded —
    replaying the log reproduces the same truncated run."""
    s1, acks, _ = _live_run("stop-live", [{"cmd": "stop"}])
    assert acks[0]["type"] == "ack"
    assert s1["exit_reason"] == "interrupted"
    assert s1["interrupt_signal"] == "live_stop"
    cl = _stream("stop-live", "commands.jsonl")
    assert json.loads(cl)["cmd"]["cmd"] == "stop"
    s2 = _replay_run("stop-replay", "/tmp/st-live-stop-live/commands.jsonl")
    assert s2["exit_reason"] == "interrupted"
    assert s2["rounds"] == s1["rounds"]
    assert _tree("stop-replay", require=False) == \
        _tree("stop-live", require=False)
    assert _stream("stop-replay", "commands.jsonl") == cl
    # the stop may land before the first digest sample; the two runs
    # must agree on whether one was written
    p1 = Path("/tmp/st-live-stop-live/state_digests.jsonl")
    p2 = Path("/tmp/st-live-stop-replay/state_digests.jsonl")
    assert p1.exists() == p2.exists()
    if p1.exists():
        assert p1.read_text() == p2.read_text()


def test_checkpoint_now_and_mid_command_resume():
    """checkpoint_now + a 6s link_down: the on-demand checkpoint lands
    inside the fault window (scheduled heal pending in the snapshot).
    Resuming from it with the recorded log replays nothing (every
    recorded boundary <= the snapshot) yet the heal still fires — tree
    and digest suffix are identical to the uninterrupted live run."""
    from shadow_tpu import checkpoint as ckpt

    down = {**LINK_DOWN, "duration": "6s"}
    # pause pins every command to ONE boundary B: the snapshot is taken
    # at B (wall timing decides B, and that choice is recorded)
    s1, acks, _ = _live_run("ck-live", [{"cmd": "pause"}, down,
                                        {"cmd": "checkpoint_now"},
                                        {"cmd": "resume"}])
    assert [a["type"] for a in acks] == ["ack"] * 4
    ckpts = sorted(Path("/tmp/st-live-ck-live/checkpoints").glob("*.ckpt"))
    assert ckpts, "checkpoint_now wrote nothing"
    h = ckpt.read_header(str(ckpts[0]))
    t_down = next(json.loads(ln)["t"]
                  for ln in _stream("ck-live", "commands.jsonl").splitlines()
                  if json.loads(ln)["cmd"]["cmd"] == "link_down")
    # the snapshot was taken at the fault's own boundary, 6s before the
    # scheduled heal: the fault window brackets it
    assert int(h["sim_time_ns"]) == t_down < t_down + 6_000_000_000
    t1 = _tree("ck-live")
    d1 = _stream("ck-live", "state_digests.jsonl").splitlines()
    log = "/tmp/st-live-ck-live/commands.jsonl"
    cfg = _base_cfg("ck-res", {"general.replay_commands": log})
    ctl, resume_at = ckpt.load_checkpoint(str(ckpts[0]), cfg,
                                          mirror_log=False)
    s2 = ctl.run(resume_at=resume_at)
    assert _tree("ck-res") == t1
    d2 = _stream("ck-res", "state_digests.jsonl").splitlines()
    assert d2 == d1[-len(d2):], "resumed digest stream diverged"
    assert s2["exit_reason"] == "completed"
    # no commands re-logged on resume: every recorded boundary <= the
    # snapshot had already applied before it was taken
    assert not Path("/tmp/st-live-ck-res/commands.jsonl").exists()


# -- sharded: live fault at shards=2, replayed at 2 and 1 ---------------------

def _churn_cfg(tag: str, over: dict):
    doc = yaml.safe_load(CHURN_YAML.read_text())
    dd = f"/tmp/st-live-{tag}"
    shutil.rmtree(dd, ignore_errors=True)
    return parse_config(doc, {
        "general.data_directory": dd,
        "general.stop_time": "8s",
        "general.state_digest_every": 50,
        "telemetry.sample_every": "5s",
        "experimental.scheduler_policy": "tpu_batch",
        "experimental.native_colcore": True,
        **over})


def test_live_sharded_replay_identity():
    """THE acceptance leg: a live fault injected into a sharded (N=2, C
    engine) churn run — on top of the config's own fault timeline —
    replays byte-identically at shards=2 AND shards=1; pause is refused
    by name on the sharded endpoint."""
    sock = "/tmp/st-live-sh.sock"
    acks: list = []

    def _drive():
        acks.append(lv.send_command(sock, {**LINK_DOWN, "duration": "2s"},
                                    timeout=60))
        acks.append(lv.send_command(sock, {"cmd": "pause"}, timeout=60))

    t = threading.Thread(target=_drive, daemon=True)
    t.start()
    s1 = sh.run_sharded(_churn_cfg("sh-live",
                                   {"general.live_endpoint": sock,
                                    "general.sim_shards": 2}),
                        mirror_log=False)
    t.join(timeout=10)
    assert acks[0]["type"] == "ack"
    assert acks[1]["type"] == "error"
    assert "single-process" in acks[1]["error"]
    log = "/tmp/st-live-sh-live/commands.jsonl"
    cl = Path(log).read_text()
    assert json.loads(cl)["cmd"]["cmd"] == "link_down"
    t1, d1 = _tree("sh-live"), _stream("sh-live", "state_digests.jsonl")
    m1 = _stream("sh-live", "metrics.jsonl")
    s2 = sh.run_sharded(_churn_cfg("sh-r2",
                                   {"general.replay_commands": log,
                                    "general.sim_shards": 2}),
                        mirror_log=False)
    s3 = Controller(_churn_cfg("sh-r1", {"general.replay_commands": log}),
                    mirror_log=False).run()
    for tag in ("sh-r2", "sh-r1"):
        assert _tree(tag) == t1, f"tree diverged: {tag}"
        assert _stream(tag, "state_digests.jsonl") == d1, tag
        assert _stream(tag, "metrics.jsonl") == m1, tag
        assert _stream(tag, "commands.jsonl") == cl, tag
    assert s2["rounds"] == s1["rounds"] == s3["rounds"]


# -- time travel + bisect -----------------------------------------------------

def test_jump_reproduces_recorded_digest(tmp_path):
    """jump --round R: restore the nearest checkpoint, re-execute to R,
    and digest-verify against the recorded stream — then again with the
    checkpoints hidden (from-scratch fallback), on a commanded run."""
    s1, acks, _ = _live_run("jump-live", [dict(LINK_DOWN)],
                            over={"general.checkpoint_every": "4s"})
    assert acks[0]["type"] == "ack"
    run_dir = Path("/tmp/st-live-jump-live")
    digs = [json.loads(x) for x in
            _stream("jump-live", "state_digests.jsonl").splitlines()]
    target = digs[-1]["round"]  # after the heal, deep into the run
    cfg_path = tmp_path / "jump.yaml"
    cfg_path.write_text(BASE)
    out: list = []
    rc = lv.jump(run_dir, target, cfg_path, out=out.append,
                 inspect_dir=tmp_path / "jump-ck")
    assert rc == 0, "\n".join(out)
    assert any("restored" in ln for ln in out), out  # used a checkpoint
    assert any("[MATCH]" in ln for ln in out)
    # hide the checkpoints: the jump re-executes from round 0 instead
    # (replaying the same command log) and still reproduces the digest
    hidden = run_dir / "checkpoints.hidden"
    (run_dir / "checkpoints").rename(hidden)
    try:
        out2: list = []
        rc2 = lv.jump(run_dir, target, cfg_path, out=out2.append,
                      inspect_dir=tmp_path / "jump-scratch")
        assert rc2 == 0, "\n".join(out2)
        assert any("re-executing from round 0" in ln for ln in out2)
        assert any("[MATCH]" in ln for ln in out2)
    finally:
        hidden.rename(run_dir / "checkpoints")


def test_bisect_json_and_jump_handoff(tmp_path, capsys):
    """bisect_divergence --json names the first divergent round as one
    machine-readable record, and the jump CLI's --from-bisect reader
    accepts it."""
    import sys
    sys.path.insert(0, str(ROOT / "tools"))
    try:
        import bisect_divergence as bd
    finally:
        sys.path.pop(0)
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    recs = [{"round": r, "t": r * 10, "digest": f"d{r}",
             "hosts": {"h0": f"x{r}", "h1": f"y{r}"}} for r in (50, 100, 150)]
    a.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    recs[1] = {**recs[1], "digest": "DIFF",
               "hosts": {"h0": "DIFF", "h1": "y100"}}
    b.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    assert bd.main(["--json", str(a), str(b)]) == 1
    rec = json.loads(capsys.readouterr().out)
    assert rec == {"kind": "digest", "round": 100, "t": 1000,
                   "hosts": ["h0"], "last_match": 50}
    assert bd.main(["--json", str(a), str(a)]) == 0
    ident = json.loads(capsys.readouterr().out)
    assert ident["kind"] == "identical" and ident["last_round"] == 150
    # the handoff: _read_bisect picks the record out of mixed output
    src = tmp_path / "bisect.out"
    src.write_text("noise line\n" + json.dumps(rec) + "\n")
    assert lv._read_bisect(str(src))["round"] == 100


# -- config plumbing ----------------------------------------------------------

def test_live_config_keys_are_volatile():
    """live_endpoint/replay_commands must never enter checkpoint config
    identity (a replay resume would refuse otherwise), and the schema
    accepts both keys."""
    from shadow_tpu.checkpoint import VOLATILE_CONFIG_KEYS

    assert ("general", "live_endpoint") in VOLATILE_CONFIG_KEYS
    assert ("general", "replay_commands") in VOLATILE_CONFIG_KEYS
    cfg = _base_cfg("schema", {"general.live_endpoint": "auto",
                               "general.replay_commands": "/tmp/x.jsonl"})
    assert cfg.general.live_endpoint == "auto"
    assert cfg.general.replay_commands == "/tmp/x.jsonl"
    assert lv.resolve_endpoint("auto", "/data/run") == "/data/run/live.sock"
    assert lv.resolve_endpoint("/tmp/s.sock", "/data/run") == "/tmp/s.sock"


def test_fleet_members_never_bind():
    """_member_config forces live_endpoint off: M concurrent seeds must
    not race on one socket path."""
    from shadow_tpu.fleet import _member_config

    cfg_path = Path("/tmp/st-live-fleet.yaml")
    cfg_path.write_text(BASE)
    cfg = _member_config(str(cfg_path),
                         {"general.live_endpoint": "/tmp/x.sock"},
                         Path("/tmp/st-live-fleet-sweep"), 3)
    assert cfg.general.live_endpoint is None
    assert cfg.general.seed == 3
