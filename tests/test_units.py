import pytest

from shadow_tpu.utils.units import parse_bandwidth, parse_size


def test_bandwidth_bits():
    assert parse_bandwidth("1 Gbit") == 125_000_000
    assert parse_bandwidth("10 Mbit") == 1_250_000
    assert parse_bandwidth("100 kbit") == 12_500
    assert parse_bandwidth("1 Gbit/s") == 125_000_000
    assert parse_bandwidth("100 Mbps") == 12_500_000


def test_bandwidth_bytes():
    assert parse_bandwidth("125 MB") == 125_000_000
    assert parse_bandwidth("1 MiB") == 2**20
    assert parse_bandwidth(1000) == 1000


def test_sizes():
    assert parse_size("16 MiB") == 16 * 2**20
    assert parse_size("64 kB") == 64_000
    assert parse_size(512) == 512
    assert parse_size("131072") == 131072


def test_bad_units():
    with pytest.raises(ValueError):
        parse_bandwidth("10 parsecs")
    with pytest.raises(ValueError):
        parse_size("1 lightyear")
