import numpy as np
import pytest

from shadow_tpu.core.time import NS_PER_MS
from shadow_tpu.network.gml import parse_gml
from shadow_tpu.network.graph import INF_I64, from_gml, load_graph, one_gbit_switch

TRIANGLE = """
graph [
  directed 0
  node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  node [ id 1 ]
  node [ id 2 ]
  edge [ source 0 target 1 latency "10 ms" packet_loss 0.01 ]
  edge [ source 1 target 2 latency "20 ms" packet_loss 0.02 ]
  edge [ source 0 target 2 latency "50 ms" packet_loss 0.0 ]
]
"""


def test_parse_gml_basics():
    g = parse_gml(TRIANGLE)
    assert not g.directed
    assert len(g.nodes) == 3
    assert len(g.edges) == 3
    assert g.nodes[0]["host_bandwidth_up"] == "1 Gbit"


def test_apsp_prefers_shorter_path():
    ng = from_gml(parse_gml(TRIANGLE))
    # 0 -> 2 direct is 50ms; via 1 it's 30ms: APSP must pick 30ms
    assert ng.latency(0, 2) == 30 * NS_PER_MS
    assert ng.latency(2, 0) == 30 * NS_PER_MS
    # reliability along chosen path: (1-.01)*(1-.02)
    assert ng.reliability_of(0, 2) == pytest.approx(0.99 * 0.98, rel=1e-6)
    assert ng.latency(0, 1) == 10 * NS_PER_MS
    # node defaults
    assert ng.node_defaults[0].bandwidth_up == 125_000_000
    assert ng.node_defaults[1].bandwidth_up is None


def test_self_latency_defaults_to_min_adjacent():
    ng = from_gml(parse_gml(TRIANGLE))
    assert ng.latency(0, 0) == 10 * NS_PER_MS
    assert ng.latency(1, 1) == 10 * NS_PER_MS


def test_min_latency_lookahead():
    ng = from_gml(parse_gml(TRIANGLE))
    assert ng.min_latency_ns == 10 * NS_PER_MS


def test_directed_graph_unreachable():
    g = parse_gml(
        """
        graph [ directed 1
          node [ id 0 ] node [ id 1 ]
          edge [ source 0 target 1 latency "5 ms" ]
        ]
        """
    )
    ng = from_gml(g)
    assert ng.latency(0, 1) == 5 * NS_PER_MS
    assert not ng.reachable(1, 0)
    assert ng.latency_ns[1, 0] == INF_I64


def test_switch_shorthand():
    ng = one_gbit_switch()
    assert ng.n_nodes == 1
    assert ng.latency(0, 0) == NS_PER_MS
    assert ng.node_defaults[0].bandwidth_up == 125_000_000


def test_load_graph_inline():
    ng = load_graph({"type": "gml", "inline": TRIANGLE})
    assert ng.n_nodes == 3


def test_long_chain_apsp():
    # chain of 12 nodes, 1ms per hop: tests repeated-squaring depth
    n = 12
    nodes = "\n".join(f"node [ id {i} ]" for i in range(n))
    edges = "\n".join(
        f'edge [ source {i} target {i+1} latency "1 ms" ]' for i in range(n - 1)
    )
    ng = from_gml(parse_gml(f"graph [ directed 0\n{nodes}\n{edges}\n]"))
    assert ng.latency(0, n - 1) == (n - 1) * NS_PER_MS
    assert np.all(ng.latency_ns < INF_I64)


def test_tornettools_format_fixture():
    """BASELINE #3's committed topology is in the tornettools output
    schema: city labels, country codes, base-1024 Kibit bandwidths,
    microsecond latencies, float packet_loss — all parsed, with the
    config's relative file reference resolving against the config dir."""
    from pathlib import Path

    from shadow_tpu.config import load_config
    from shadow_tpu.network.graph import load_graph
    from shadow_tpu.utils.units import parse_bandwidth

    root = Path(__file__).resolve().parents[1]
    g = load_graph({"type": "gml",
                    "file": str(root / "examples/topology_tornet400.gml")})
    assert g.n_nodes == 30
    assert g.min_latency_ns == 2_000_000  # the 2000 us self-edges
    # node defaults came from the Kibit strings (base-1024 bits)
    d = g.node_defaults[0]
    assert d.bandwidth_up == int(710022 * 1024 / 8)
    cfg = load_config(str(root / "examples/tor_400relay.yaml"))
    assert cfg.network["graph"]["file"].endswith("topology_tornet400.gml")
    assert parse_bandwidth("1 Mibit") == 2**20 // 8
