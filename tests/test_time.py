from shadow_tpu.core.time import (
    EMULATED_EPOCH,
    NS_PER_MS,
    NS_PER_SEC,
    NS_PER_US,
    emulated,
    format_time,
    parse_time,
)


def test_parse_bare_numbers_are_seconds():
    assert parse_time(10) == 10 * NS_PER_SEC
    assert parse_time(0.5) == NS_PER_SEC // 2
    assert parse_time("2") == 2 * NS_PER_SEC


def test_parse_units():
    assert parse_time("10 ms") == 10 * NS_PER_MS
    assert parse_time("10ms") == 10 * NS_PER_MS
    assert parse_time("500 us") == 500 * NS_PER_US
    assert parse_time("100 ns") == 100
    assert parse_time("3 s") == 3 * NS_PER_SEC
    assert parse_time("10 seconds") == 10 * NS_PER_SEC
    assert parse_time("1 min") == 60 * NS_PER_SEC
    assert parse_time("2 hours") == 7200 * NS_PER_SEC
    assert parse_time("1.5s") == NS_PER_SEC * 3 // 2


def test_emulated_clock_offset():
    assert emulated(0) == EMULATED_EPOCH
    assert emulated(5 * NS_PER_SEC) - EMULATED_EPOCH == 5 * NS_PER_SEC


def test_format_roundtrippish():
    assert format_time(999) == "999ns"
    assert "us" in format_time(1500)
    assert "ms" in format_time(2 * NS_PER_MS)
    assert "s" in format_time(3 * NS_PER_SEC)
