"""Correctness tests for the gossip workload model (BASELINE.md config #4;
VERDICT.md round-1 weak #7: the model previously had zero tests)."""

import os

import pytest
import yaml

from shadow_tpu.config import parse_config
from shadow_tpu.core.controller import Controller

GOSSIP_CFG = """
general:
  stop_time: 40s
  seed: 9
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 1 latency "30 ms" ]
        edge [ source 0 target 0 latency "10 ms" ]
        edge [ source 1 target 1 latency "10 ms" ]
      ]
hosts:
  origin:
    network_node_id: 0
    quantity: 2
    processes:
      - path: pyapp:shadow_tpu.models.gossip:GossipNode
        args: ["7000", "30", "4", "2", "1.0"]
  member:
    network_node_id: 1
    quantity: 28
    processes:
      - path: pyapp:shadow_tpu.models.gossip:GossipNode
        args: ["7000", "30", "4", "0", "1.0"]
"""


def run(seed=9, loss_line=None):
    text = GOSSIP_CFG
    if loss_line:
        # loss on every edge (member<->member traffic rides the 10 ms edges)
        text = text.replace('latency "30 ms"', f'latency "30 ms" {loss_line}')
        text = text.replace('latency "10 ms"', f'latency "10 ms" {loss_line}')
    cfg = parse_config(yaml.safe_load(text), {
        "general.seed": seed,
        "general.data_directory": f"/tmp/st-gossip-{seed}-{bool(loss_line)}",
    })
    c = Controller(cfg, mirror_log=False)
    result = c.run()
    return c, result


def test_flood_reaches_every_node_without_loss():
    c, result = run()
    apps = [p.app for p in c.processes]
    all_txids = set()
    for a in apps:
        all_txids.update(f"{a.api.host_id}:{k}".encode()
                         for k in range(1, a.originated + 1))
    assert len(all_txids) == 4  # 2 origins x 2 txs
    # peer graph with k=4 over 30 nodes is connected w.h.p.; every node
    # must have learned every tx (INV -> GETDATA -> TX converges well
    # within 40 sim-seconds at these latencies)
    for a in apps:
        assert a.seen == all_txids, a.api.host_id
    # each tx is received exactly once per non-originating node
    total_rx = sum(a.received_tx for a in apps)
    assert total_rx == sum(len(all_txids - {
        f"{a.api.host_id}:{k}".encode() for k in range(1, a.originated + 1)})
        for a in apps)
    assert result["units_dropped"] == 0


def test_flood_deterministic_and_seed_sensitive():
    _, r1 = run(seed=9)
    _, r2 = run(seed=9)
    for k in ("events", "units_sent", "counters"):
        assert r1[k] == r2[k]
    c3, _ = run(seed=10)
    # different seed -> different peer graphs (host RNG drives peer choice)
    assert any(a.peers != b.peers
               for a, b in zip([p.app for p in c3.processes],
                               [p.app for p in run(seed=9)[0].processes]))


def test_flood_with_loss_still_converges_mostly():
    c, result = run(loss_line="packet_loss 0.01")
    assert result["units_dropped"] > 0
    apps = [p.app for p in c.processes]
    # redundancy (k=4 peers) makes the flood robust: the vast majority of
    # nodes still learn every tx despite 1% packet loss on the backbone
    full = sum(1 for a in apps if len(a.seen) == 4)
    assert full >= 25, full


@pytest.mark.skipif(os.environ.get("SHADOW_TPU_FAST_TESTS") == "1",
                    reason="scale test skipped in fast mode")
def test_scale_20k_hosts_full_coverage():
    """A 20k-host slice of the 100k-host scale demo (tools/scale_100k.py):
    quantity-templated hosts on a 64-node graph, 2 originators flooding to
    FULL coverage — nothing materializes host^2 state (SURVEY §7 hard
    part #5). The full 100k run is the script's documented measurement."""
    import subprocess
    import sys
    from pathlib import Path

    root = Path(__file__).resolve().parents[1]
    r = subprocess.run(
        [sys.executable, "tools/scale_100k.py", "--hosts", "20000",
         "--stop", "6", "--data-directory", "/tmp/st-scale20k"],
        capture_output=True, text=True, timeout=300, cwd=str(root))
    assert r.returncode == 0, r.stderr[-500:]
    got = int(r.stdout.split("tx_deliveries=")[1].split()[0])
    # 2 tx x 19999 hosts, minus the few deliveries edge loss genuinely
    # eats (gossip redundancy recovers most, not all)
    assert got >= 0.999 * 2 * 19999, r.stdout
