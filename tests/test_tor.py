"""Tor-shaped onion-routing workload tests (BASELINE.md config #3 model)."""

import yaml

from shadow_tpu.config import parse_config
from shadow_tpu.core.controller import Controller

TOR_CFG = """
general:
  stop_time: 60s
  seed: 12
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 2 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 1 latency "20 ms" ]
        edge [ source 0 target 2 latency "40 ms" ]
        edge [ source 1 target 2 latency "30 ms" ]
        edge [ source 0 target 0 latency "5 ms" ]
        edge [ source 1 target 1 latency "5 ms" ]
        edge [ source 2 target 2 latency "5 ms" ]
      ]
hosts:
  relay:
    network_node_id: 1
    quantity: 6
    processes:
      - path: pyapp:shadow_tpu.models.tor:TorExit
        args: ["9001"]
  web:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.tgen:TGenServer
        args: ["80"]
  user:
    network_node_id: 2
    quantity: 4
    processes:
      - path: pyapp:shadow_tpu.models.tor:TorClient
        args: ["6", "9001", web, "80", "200 kB", "2"]
        start_time: 1s
        expected_final_state: {exited: 0}
"""


def run(**over):
    cfg = parse_config(yaml.safe_load(TOR_CFG), {
        "general.data_directory": "/tmp/st-tor", **over})
    c = Controller(cfg, mirror_log=False)
    return c, c.run()


def test_circuits_complete_through_three_hops():
    c, result = run()
    assert result["process_errors"] == [], result["process_errors"]
    clients = [p.app for p in c.processes if p.name.startswith("torclient")]
    assert len(clients) == 4
    for cl in clients:
        assert cl.completed == 2 and cl.failed == 0
        # 3 hops + exit fetch: at least 4 one-way latencies each direction
        # plus telescoping handshakes; must be well over one direct RTT
        for t in cl.completion_times:
            assert t > 100_000_000, t
    # relays actually relayed: total relayed bytes >= 2 hops' worth of the
    # 8 fetches (the exit hop re-frames rather than relays)
    relays = [p.app for p in c.processes if p.name.startswith("torexit")]
    total = sum(r.bytes_relayed for r in relays)
    assert total >= 2 * 8 * 200_000, total
    for h in c.hosts:
        assert h._conns == {}, h.name


def test_tor_deterministic():
    _, r1 = run(**{"general.data_directory": "/tmp/st-tor-d1"})
    _, r2 = run(**{"general.data_directory": "/tmp/st-tor-d2"})
    for k in ("events", "units_sent", "units_dropped", "bytes_sent", "counters"):
        assert r1[k] == r2[k], k
