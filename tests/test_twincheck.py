"""Mutation fixtures for the static twin-contract auditor + determinism
linter (tools/twincheck/).

The discipline: copy the contract-bearing sources into a scratch tree,
perturb EXACTLY ONE twin surface, and assert the named finding fires —
then assert the real tree produces zero findings with every waiver
carrying a written reason.  If a check can't catch its seeded drift, it
isn't a gate.
"""

from __future__ import annotations

import shutil
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools" / "twincheck"))

import det_lint  # noqa: E402
import twin_audit  # noqa: E402

COLCORE = REPO / "native" / "colcore" / "colcore.c"


# -- scratch twin tree --------------------------------------------------------

@pytest.fixture()
def tree(tmp_path):
    """A minimal copy of the audited surfaces: shadow_tpu/ (sans caches),
    colcore.c, shring.h + shim.c, MIGRATION.md."""
    shutil.copytree(REPO / "shadow_tpu", tmp_path / "shadow_tpu",
                    ignore=shutil.ignore_patterns("__pycache__", "*.so"))
    (tmp_path / "native" / "colcore").mkdir(parents=True)
    shutil.copy(COLCORE, tmp_path / "native" / "colcore" / "colcore.c")
    (tmp_path / "native" / "shim").mkdir(parents=True)
    shutil.copy(REPO / "native" / "shring.h",
                tmp_path / "native" / "shring.h")
    shutil.copy(REPO / "native" / "shim" / "shim.c",
                tmp_path / "native" / "shim" / "shim.c")
    shutil.copy(REPO / "MIGRATION.md", tmp_path / "MIGRATION.md")
    return tmp_path


def mutate(tree: Path, relpath: str, old: str, new: str):
    p = tree / relpath
    src = p.read_text()
    assert src.count(old) >= 1, "mutation anchor %r missing in %s" % (
        old, relpath)
    p.write_text(src.replace(old, new, 1))


def append(tree: Path, relpath: str, code: str):
    p = tree / relpath
    p.write_text(p.read_text() + "\n" + code + "\n")


def rules(findings):
    return [f.rule for f in findings]


# -- the clean tree is clean --------------------------------------------------

def test_real_tree_audit_zero_findings():
    findings = twin_audit.audit(REPO)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_real_tree_detlint_zero_findings_and_reasoned_waivers():
    findings, waivers = det_lint.lint_with_waivers(REPO)
    assert findings == [], "\n".join(str(f) for f in findings)
    assert waivers, "the tree documents its deliberate wall-clock uses"
    for path, line, rule, reason in waivers:
        assert reason.strip(), "%s:%d waives %r with no reason" % (
            path, line, rule)


def test_scratch_copy_is_clean(tree):
    assert twin_audit.audit(tree) == []


# -- twin-contract mutations --------------------------------------------------

def test_abi_bump_without_migration_entry(tree):
    mutate(tree, "native/colcore/colcore.c",
           'PyModule_AddIntConstant(m, "ABI", 4)',
           'PyModule_AddIntConstant(m, "ABI", 5)')
    assert "abi-migration" in rules(twin_audit.audit(tree))


def test_version_bump_without_migration_entry(tree):
    mutate(tree, "shadow_tpu/checkpoint.py", "VERSION = 5", "VERSION = 9")
    assert "version-migration" in rules(twin_audit.audit(tree))


def test_rto_max_drift_python_side(tree):
    mutate(tree, "shadow_tpu/network/transport.py",
           "RTO_MAX_NS = 60_000 * NS_PER_MS",
           "RTO_MAX_NS = 61_000 * NS_PER_MS")
    assert "const-drift:RTO_MAX_NS" in rules(twin_audit.audit(tree))


def test_rto_max_drift_c_side(tree):
    mutate(tree, "native/colcore/colcore.c",
           "#define RTO_MAX_NS_C 60000000000LL",
           "#define RTO_MAX_NS_C 59000000000LL")
    assert "const-drift:RTO_MAX_NS" in rules(twin_audit.audit(tree))


def test_export_field_drop_is_caught(tree):
    # drop the final field code from the CEp export format — a checkpoint
    # written by such a build could not restore
    mutate(tree, "native/colcore/colcore.c",
           '"(iiiiOiiiOLOLLLLLLLLLLiiONNLLLLLiNOOOOOOiLLOLOLOiLLiLLNN)"',
           '"(iiiiOiiiOLOLLLLLLLLLLiiONNLLLLLiNOOOOOOiLLOLOLOiLLiLLN)"')
    assert "export-arity" in rules(twin_audit.audit(tree))


def test_fingerprint_field_drop_is_caught(tree):
    mutate(tree, "shadow_tpu/network/transport.py",
           "1 if s.in_recovery else 0, s.recover, s.sack_high,",
           "1 if s.in_recovery else 0, s.recover,")
    assert "fingerprint-arity" in rules(twin_audit.audit(tree))


def test_folded_counter_rename_is_caught(tree):
    mutate(tree, "native/colcore/colcore.c",
           '"stream_sack_retransmits"};', '"stream_sack_retx"};')
    assert "counter-name:stream_sack_retx" in rules(twin_audit.audit(tree))


def test_cubic_beta_drift_is_caught(tree):
    # beta 0.7 -> 0.8 on the C side only: the integer-literal sets of the
    # on_loss twins diverge
    mutate(tree, "native/colcore/colcore.c",
           "int64_t nc = e->cwnd * 7 / 10;",
           "int64_t nc = e->cwnd * 8 / 10;")
    assert "cubic-arith:on_loss" in rules(twin_audit.audit(tree))


# -- the columnar kernel twin (third surface, PR 11) --------------------------

def test_kernel_const_drift_is_caught(tree):
    mutate(tree, "shadow_tpu/ops/transport_kernels.py",
           "MSS = 1460", "MSS = 1500")
    assert "kernel-const-drift:MSS" in rules(twin_audit.audit(tree))


def test_kernel_cc_id_drift_is_caught(tree):
    mutate(tree, "shadow_tpu/ops/transport_kernels.py",
           "CC_CUBIC = 1", "CC_CUBIC = 2")
    assert "kernel-const-drift:CC_CUBIC" in rules(twin_audit.audit(tree))


def test_kernel_cc_literal_drift_is_caught(tree):
    # cubic C constant 0.4 -> 0.5 seeded in the KERNEL side only: the
    # on_ack literal sets of the scalar twins and the batched kernel
    # diverge (10_000 -> 8_000 in the delta scaling)
    mutate(tree, "shadow_tpu/ops/transport_kernels.py",
           "(a * a * a // 1_000_000) * (4 * MSS) // 10_000",
           "(a * a * a // 1_000_000) * (4 * MSS) // 8_000")
    assert "kernel-cc-drift:on_ack" in rules(twin_audit.audit(tree))


def test_kernel_cc_literal_drift_scalar_side_is_caught(tree):
    # the same drift seeded on the SCALAR side fails too — the check is
    # symmetric, so neither twin can move without the other
    mutate(tree, "shadow_tpu/network/transport.py",
           "nn = min(newly, 1 << 20)", "nn = min(newly, 1 << 21)")
    found = rules(twin_audit.audit(tree))
    assert "kernel-cc-drift:on_ack" in found
    # ... and the C twin diverges with it (the PR 10 check still fires)
    assert "cubic-arith:on_ack" in found


def test_new_struct_field_without_export_is_caught(tree):
    mutate(tree, "native/colcore/colcore.c",
           "int64_t recover, sack_high, w_max, epoch_start;",
           "int64_t recover, sack_high, w_max, epoch_start, new_knob;")
    assert "struct-export:new_knob" in rules(twin_audit.audit(tree))


def test_interned_attr_rename_is_caught(tree):
    # rename the Python-side attribute out from under the C intern table
    for py in (tree / "shadow_tpu").rglob("*.py"):
        src = py.read_text()
        if "_uid_counter" in src:
            py.write_text(src.replace("_uid_counter", "_uid_ctr"))
    found = rules(twin_audit.audit(tree))
    assert "attr-name:_uid_counter" in found


def test_intern_call_outside_init_is_caught(tree):
    mutate(tree, "native/colcore/colcore.c",
           "ok = attr_i64(params, S_seed, &seed) == 0;",
           'ok = attr_i64(params, PyUnicode_InternFromString("seed"), '
           "&seed) == 0;")
    found = rules(twin_audit.audit(tree))
    assert any(r.startswith("c-intern:") for r in found)


def test_cc_registry_drift_is_caught(tree):
    mutate(tree, "shadow_tpu/config/schema.py",
           'CONGESTION_CONTROL_NAMES = ("newreno", "cubic")',
           'CONGESTION_CONTROL_NAMES = ("newreno", "cubic", "bbr")')
    assert "cc-enum" in rules(twin_audit.audit(tree))


# -- the shim fast-plane ABI (fourth surface, PR 13) --------------------------

def test_shim_page_word_drift_c_side_is_caught(tree):
    # shim would fold in-shim ring reads from the wrong clock-page word
    mutate(tree, "native/shring.h",
           "#define SHIM_PAGE_CLS_RING_R 7",
           "#define SHIM_PAGE_CLS_RING_R 12")
    assert "shim-abi-drift:SHIM_PAGE_CLS_RING_R" in rules(
        twin_audit.audit(tree))


def test_shim_ready_off_drift_python_side_is_caught(tree):
    # worker would publish readiness bytes where the shim doesn't look
    mutate(tree, "shadow_tpu/native/managed.py",
           "SHIM_READY_OFF = 256", "SHIM_READY_OFF = 264")
    assert "shim-abi-drift:SHIM_READY_OFF" in rules(twin_audit.audit(tree))


def test_shim_vfd_base_drift_is_caught(tree):
    # the hex-literal sentinel that separates simulated fds from real ones
    mutate(tree, "native/shim/shim.c",
           "#define SHIM_VFD_BASE 0x100000",
           "#define SHIM_VFD_BASE 0x200000")
    assert "shim-abi-drift:VFD_BASE" in rules(twin_audit.audit(tree))


def test_shim_ring_magic_drift_is_caught(tree):
    mutate(tree, "native/shring.h",
           "#define SHRING_MAGIC 0x53524E47u",
           "#define SHRING_MAGIC 0x53524E48u")
    assert "shim-abi-drift:SHRING_MAGIC" in rules(twin_audit.audit(tree))


def test_shim_epoch_drift_is_caught(tree):
    # realtime family would disagree with core/time.EMULATED_EPOCH
    mutate(tree, "native/shim/shim.c",
           "#define SHIM_EMULATED_EPOCH_NS 946684800000000000LL",
           "#define SHIM_EMULATED_EPOCH_NS 946684800000000001LL")
    assert "shim-abi-drift:EMULATED_EPOCH" in rules(twin_audit.audit(tree))


def test_shim_wbudget_offset_drift_is_caught(tree):
    # worker would arm the tx write budget at the wrong struct offset
    mutate(tree, "shadow_tpu/native/managed.py",
           "SHRING_OFF_WBUDGET = 56", "SHRING_OFF_WBUDGET = 48")
    assert "shim-abi-drift:SHRING_OFF_WBUDGET" in rules(
        twin_audit.audit(tree))


# -- determinism-lint mutations -----------------------------------------------

def _lint(tree):
    return det_lint.lint(tree)


def test_wallclock_injection_is_caught(tree):
    mutate(tree, "shadow_tpu/models/gossip.py",
           "TX_SIZE = 400",
           "import time\nTX_SIZE = 400\n_T0 = time.time()")
    found = _lint(tree)
    assert any(f.rule == "wallclock"
               and f.path.endswith("models/gossip.py") for f in found)


def test_wallclock_waiver_with_reason_passes(tree):
    mutate(tree, "shadow_tpu/models/gossip.py",
           "TX_SIZE = 400",
           "import time as _walltime  "
           "# detlint: ok(wallclock): test-only wall probe\nTX_SIZE = 400")
    assert not any(f.rule == "wallclock" for f in _lint(tree))


def test_waiver_without_reason_is_itself_a_finding(tree):
    mutate(tree, "shadow_tpu/models/gossip.py",
           "TX_SIZE = 400",
           "import time as _walltime  # detlint: ok(wallclock)\n"
           "TX_SIZE = 400")
    found = _lint(tree)
    assert any(f.rule == "waiver-reason" for f in found)
    assert not any(f.rule == "wallclock" for f in found)


def test_stdlib_random_is_caught(tree):
    append(tree, "shadow_tpu/models/echo.py", "import random")
    assert any(f.rule == "modrandom" for f in _lint(tree))


def test_foreign_env_read_is_caught(tree):
    append(tree, "shadow_tpu/models/echo.py",
           "import os\n_H = os.environ.get(\"HOME\")")
    assert any(f.rule == "envread" for f in _lint(tree))


def test_id_ordering_is_caught(tree):
    append(tree, "shadow_tpu/models/echo.py",
           "_ORDER = sorted([object()], key=id)")
    assert any(f.rule == "idorder" for f in _lint(tree))


def test_unsorted_set_iteration_in_digest_path_is_caught(tree):
    append(tree, "shadow_tpu/models/echo.py",
           "def _digest_probe(xs):\n"
           "    return [x for x in set(xs)]")
    assert any(f.rule == "unordered-iter" for f in _lint(tree))


def test_set_materialization_in_digest_path_is_caught(tree):
    append(tree, "shadow_tpu/models/echo.py",
           "def _export_state_probe(xs):\n"
           "    return list(set(xs))")
    assert any(f.rule == "unordered-iter" for f in _lint(tree))


def test_sorted_set_iteration_in_digest_path_passes(tree):
    append(tree, "shadow_tpu/models/echo.py",
           "def _digest_probe(xs):\n"
           "    return [x for x in sorted(set(xs))]")
    assert not any(f.rule == "unordered-iter" for f in _lint(tree))
