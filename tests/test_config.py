import pytest
import yaml

from shadow_tpu.config import parse_config
from shadow_tpu.core.time import NS_PER_SEC

MINIMAL = """
general:
  stop_time: 10s
network:
  graph:
    type: 1_gbit_switch
hosts:
  client:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.echo:EchoClient
        args: [server, "9000"]
        start_time: 1s
  server:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.echo:EchoServer
        args: ["9000"]
"""


def test_minimal_config():
    cfg = parse_config(yaml.safe_load(MINIMAL))
    assert cfg.general.stop_time == 10 * NS_PER_SEC
    assert cfg.general.seed == 1
    assert [h.name for h in cfg.hosts] == ["client", "server"]
    assert cfg.hosts[0].processes[0].start_time == NS_PER_SEC
    assert cfg.hosts[0].processes[0].args == ["server", "9000"]
    assert cfg.experimental.scheduler_policy == "thread_per_core"


def test_overrides():
    cfg = parse_config(
        yaml.safe_load(MINIMAL),
        overrides={
            "general.stop_time": "30s",
            "general.seed": 7,
            "experimental.scheduler_policy": "tpu_batch",
        },
    )
    assert cfg.general.stop_time == 30 * NS_PER_SEC
    assert cfg.general.seed == 7
    assert cfg.experimental.scheduler_policy == "tpu_batch"


def test_quantity_expansion():
    doc = yaml.safe_load(MINIMAL)
    doc["hosts"]["peer"] = {"network_node_id": 0, "quantity": 3, "processes": []}
    cfg = parse_config(doc)
    names = [h.name for h in cfg.hosts]
    assert names == ["client", "server", "peer0", "peer1", "peer2"]


def test_validation_errors():
    with pytest.raises(ValueError, match="stop_time"):
        parse_config({"hosts": {"a": {}}})
    with pytest.raises(ValueError, match="scheduler_policy"):
        parse_config(
            yaml.safe_load(MINIMAL),
            overrides={"experimental.scheduler_policy": "gpu_batch"},
        )
    with pytest.raises(ValueError, match="at least one host"):
        parse_config({"general": {"stop_time": "1s"}, "hosts": {}})


def test_bandwidth_override_parsing():
    doc = yaml.safe_load(MINIMAL)
    doc["hosts"]["client"]["bandwidth_up"] = "10 Mbit"
    doc["hosts"]["client"]["bandwidth_down"] = "100 Mbit"
    cfg = parse_config(doc)
    assert cfg.hosts[0].bandwidth_up == 1_250_000
    assert cfg.hosts[0].bandwidth_down == 12_500_000


def test_schema_rosters_track_their_sources_of_truth():
    """CONGESTION_CONTROL_NAMES and MODEL_REGISTRY are duplicated into
    the schema (import-avoidance: parse_config must not pull in the
    transport or every model module); this pins them to the real
    rosters so adding an algorithm or a model without updating the
    schema fails here instead of rejecting valid configs at parse
    time."""
    import pkgutil

    import shadow_tpu.models
    from shadow_tpu.config.schema import (CONGESTION_CONTROL_NAMES,
                                          MODEL_REGISTRY)
    from shadow_tpu.network.transport import CONGESTION_CONTROLS

    assert set(CONGESTION_CONTROL_NAMES) == set(CONGESTION_CONTROLS)
    assert set(MODEL_REGISTRY) == {
        m.name for m in pkgutil.iter_modules(shadow_tpu.models.__path__)}
