"""Cross-plane equality: the columnar data plane (network/colplane.py,
behind scheduler_policy tpu_batch/tpu_mesh) must produce BIT-IDENTICAL
simulations to the per-unit reference plane (network/engine.py, behind the
thread policies) on every workload family — unit identity, event keys,
bucket charge order, and (time, band, key) execution order are reproduced
exactly, so any divergence is a bug in one of the planes.

Each test runs the same config under thread_per_core (per-unit plane) and
tpu_batch (columnar plane, numpy twin under the tests' forced-CPU JAX) and
asserts the summaries AND the full host output trees match.
"""

import filecmp
from pathlib import Path

import yaml

from shadow_tpu.config import parse_config
from shadow_tpu.core.controller import Controller
from shadow_tpu.network import unit as U

EQ_KEYS = ("sim_seconds", "rounds", "events", "units_sent", "units_dropped",
           "bytes_sent", "counters")


def _run(doc, policy, tag, overrides=None):
    over = {"experimental.scheduler_policy": policy,
            "general.data_directory": f"/tmp/colplane-{tag}-{policy}"}
    if overrides:
        over.update(overrides)
    cfg = parse_config(yaml.safe_load(doc) if isinstance(doc, str) else doc,
                       over)
    ctl = Controller(cfg, mirror_log=False)
    res = ctl.run()
    return ctl, res


def _assert_equal(doc, tag, overrides=None):
    ctl_a, a = _run(doc, "thread_per_core", tag, overrides)
    ctl_b, b = _run(doc, "tpu_batch", tag, overrides)
    for k in EQ_KEYS:
        assert a[k] == b[k], (tag, k, a[k], b[k])
    da = Path(f"/tmp/colplane-{tag}-thread_per_core/hosts")
    db = Path(f"/tmp/colplane-{tag}-tpu_batch/hosts")
    if da.is_dir():
        cmp = filecmp.dircmp(da, db)
        assert not cmp.diff_files and not cmp.left_only and not cmp.right_only
    return ctl_a, ctl_b, a


TGEN_LOSSY = """
general:
  stop_time: 30s
  seed: 7
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        node [ id 1 host_bandwidth_up "10 Mbit" host_bandwidth_down "5 Mbit" ]
        edge [ source 0 target 1 latency "25 ms" packet_loss 0.02 ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.01 ]
        edge [ source 1 target 1 latency "5 ms" ]
      ]
hosts:
  server:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.tgen:TGenServer
        args: ["8080"]
  client:
    network_node_id: 1
    quantity: 4
    processes:
      - path: pyapp:shadow_tpu.models.tgen:TGenClient
        args: ["400 kB", "2", serial, "8080", server]
        start_time: 500 ms
"""


def test_stream_transfers_with_loss_identical():
    """Bulk TCP-like transfers under per-packet loss: retransmits, cwnd
    evolution, loss notifications, and ack coalescing all bit-match."""
    _, _, res = _assert_equal(TGEN_LOSSY, "tgen")
    assert res["units_dropped"] > 0  # the loss machinery actually engaged
    assert res["units_sent"] > 500


GOSSIP = """
general:
  stop_time: 25s
  seed: 11
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 1 host_bandwidth_up "20 Mbit" host_bandwidth_down "20 Mbit" ]
        edge [ source 0 target 1 latency "20 ms" packet_loss 0.005 ]
        edge [ source 0 target 0 latency "8 ms" ]
        edge [ source 1 target 1 latency "8 ms" ]
      ]
hosts:
  node:
    network_node_id: 0
    quantity: 24
    processes:
      - path: pyapp:shadow_tpu.models.gossip:GossipNode
        args: ["7000", "40", "5", "2", "0.5"]
  edge:
    network_node_id: 1
    quantity: 16
    processes:
      - path: pyapp:shadow_tpu.models.gossip:GossipNode
        args: ["7000", "40", "5", "1", "0.7"]
"""


def test_datagram_gossip_identical():
    """High-fanout datagram flood (the columnar fast path) bit-matches."""
    _, _, res = _assert_equal(GOSSIP, "gossip")
    assert res["units_sent"] > 2000


def test_gossip_ingress_pressure_identical():
    """Tight down-links force the ingress token bucket to defer arrivals:
    the columnar deferred-drain order must match the per-unit plane's."""
    doc = yaml.safe_load(GOSSIP)
    text = GOSSIP.replace('"20 Mbit" host_bandwidth_down "20 Mbit"',
                          '"20 Mbit" host_bandwidth_down "120 Kbit"')
    doc = yaml.safe_load(text)
    _assert_equal(doc, "ingress")


TOR = """
general:
  stop_time: 25s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 1 latency "40 ms" packet_loss 0.01 ]
        edge [ source 0 target 0 latency "15 ms" ]
        edge [ source 1 target 1 latency "15 ms" ]
      ]
hosts:
  relay:
    network_node_id: 0
    quantity: 6
    processes:
      - path: pyapp:shadow_tpu.models.tor:TorExit
        args: ["9001"]
  web0:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.tgen:TGenServer
        args: ["80"]
  client:
    network_node_id: 1
    quantity: 4
    processes:
      - path: pyapp:shadow_tpu.models.tor:TorClient
        args: ["6", "9001", web0, "80", "50 kB", "2"]
        start_time: 1s
"""


def test_tor_onion_circuits_identical():
    """Multi-hop framed relaying over streams bit-matches."""
    _assert_equal(TOR, "tor")


def test_round_robin_qdisc_identical():
    """interface_qdisc round_robin reorders egress AFTER uid assignment on
    the per-unit plane; the columnar plane must assign the same uids to
    the same logical units (emission order), or loss draws diverge."""
    _assert_equal(TGEN_LOSSY, "rr", {
        "experimental.interface_qdisc": "round_robin"})


def test_multifrag_datagrams_identical():
    """Datagrams larger than the unit quantum fragment and reassemble;
    losing any fragment loses the datagram — both planes agree."""
    doc = yaml.safe_load(GOSSIP)
    # widen gossip TX payloads past one unit (~15 kB) via a smaller quantum
    _assert_equal(doc, "frag", {"experimental.unit_mtus": 1})


def test_fault_injection_identical():
    """Targeted fault injection (force-dropped units) takes the vector
    barrier path with _RowView adapters — same drops, same recovery."""
    def run_with_fault(policy):
        over = {"experimental.scheduler_policy": policy,
                "general.data_directory": f"/tmp/colplane-fault-{policy}"}
        cfg = parse_config(yaml.safe_load(TGEN_LOSSY), over)
        ctl = Controller(cfg, mirror_log=False)
        remaining = {"n": 3}

        def fault(u):
            # exercises the _RowView surface the per-unit plane's Unit has
            if (u.kind == U.DATA and u.nbytes > 0 and u.nfrags == 1
                    and u.t_emit >= 0 and remaining["n"] > 0):
                remaining["n"] -= 1
                return True
            return False

        ctl.engine.fault_filter = fault
        res = ctl.run()
        assert remaining["n"] == 0, policy
        return res

    a = run_with_fault("thread_per_core")
    b = run_with_fault("tpu_batch")
    for k in EQ_KEYS:
        assert a[k] == b[k], (k, a[k], b[k])


def test_dynamic_runahead_identical():
    """Dynamic runahead widens rounds from observed latencies — the
    min_used_latency bookkeeping must agree across planes."""
    _assert_equal(TGEN_LOSSY, "dyn", {
        "experimental.use_dynamic_runahead": True})


def test_phase_wall_breakdown_present():
    """The run summary carries the per-phase wall breakdown (VERDICT r2
    item #7) for both planes: 'events' always, engine phases columnar."""
    _, a = _run(TGEN_LOSSY, "thread_per_core", "pw")
    assert "events" in a["phase_wall"]
    _, b = _run(TGEN_LOSSY, "tpu_batch", "pw")
    for k in ("events", "barrier", "draw_flush", "extract",
              "ingress_deferred"):
        assert k in b["phase_wall"], k


PARTITIONED = """
general:
  stop_time: 20s
  seed: 13
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 2 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 1 latency "15 ms" ]
        edge [ source 0 target 0 latency "5 ms" ]
        edge [ source 1 target 1 latency "5 ms" ]
        edge [ source 2 target 2 latency "5 ms" ]
      ]
hosts:
  main:
    network_node_id: 0
    quantity: 10
    processes:
      - path: pyapp:shadow_tpu.models.gossip:GossipNode
        args: ["7000", "16", "5", "1", "0.5"]
  island:
    network_node_id: 2
    quantity: 6
    processes:
      - path: pyapp:shadow_tpu.models.gossip:GossipNode
        args: ["7000", "16", "5", "1", "0.5"]
"""


def test_partitioned_topology_blackholes_identical():
    """A partitioned topology (island nodes with NO route to the rest):
    unroutable units blackhole — counted, discarded, buckets still
    charged — identically on the per-unit plane, the columnar plane, AND
    the mesh plane (which previously hard-rejected such topologies)."""
    ctl_a, a = _run(PARTITIONED, "thread_per_core", "bh")
    ctl_b, b = _run(PARTITIONED, "tpu_batch", "bh")
    ctl_c, c = _run(PARTITIONED, "tpu_mesh", "bh")
    for k in EQ_KEYS:
        assert a[k] == b[k] == c[k], (k, a[k], b[k], c[k])
    assert ctl_a.engine.units_blackholed > 0
    assert (ctl_a.engine.units_blackholed == ctl_b.engine.units_blackholed
            == ctl_c.engine.units_blackholed)


def test_mesh_e2e_matches_host_planes():
    """tpu_mesh end-to-end (async exchange readback at the g_min barrier)
    bit-matches both host planes on a lossy stream workload."""
    _, a = _run(TGEN_LOSSY, "thread_per_core", "mesheq")
    _, b = _run(TGEN_LOSSY, "tpu_mesh", "mesheq")
    for k in EQ_KEYS:
        assert a[k] == b[k], (k, a[k], b[k])
