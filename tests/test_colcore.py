"""C engine (native/colcore) bit-identity gates.

The C fast path accelerates functions, not structures (see the module
docstring in native/colcore/colcore.c), so its correctness obligation is
exact: with ``experimental.native_colcore`` toggled, every summary field
and every byte of the output tree must match the pure-Python columnar
plane — which the cross-plane suite (test_colplane.py) already holds
bit-identical to the per-unit reference plane. Transitively the C engine
is therefore pinned to all three Python implementations.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from shadow_tpu.config.schema import load_config
from shadow_tpu.core.controller import Controller

import pathlib
import subprocess

subprocess.run(
    ["make", "-C", str(pathlib.Path(__file__).resolve().parent.parent
                       / "native")],
    check=True, capture_output=True)
from shadow_tpu.native import _colcore  # noqa: E402

from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as VOLATILE


def _run(tmp_path, cfg_path, colcore, overrides=None, policy="tpu_batch"):
    dd = tmp_path / ("c" if colcore else "py")
    ov = {
        "experimental.scheduler_policy": policy,
        "experimental.native_colcore": colcore,
        "general.data_directory": str(dd),
    }
    ov.update(overrides or {})
    cfg = load_config(cfg_path, ov)
    ctl = Controller(cfg, mirror_log=False)
    assert (ctl.engine._c is not None) == colcore
    summary = ctl.run()
    for k in VOLATILE:
        summary.pop(k, None)
    tree = {}
    hosts_dir = dd / "hosts"
    if hosts_dir.is_dir():
        for root, _, files in os.walk(hosts_dir):
            for f in sorted(files):
                p = os.path.join(root, f)
                rel = os.path.relpath(p, dd)
                tree[rel] = hashlib.sha256(open(p, "rb").read()).hexdigest()
    return summary, tree


def _assert_identical(tmp_path, cfg_path, overrides=None):
    a, ta = _run(tmp_path, cfg_path, True, overrides)
    b, tb = _run(tmp_path, cfg_path, False, overrides)
    assert a == b
    assert ta == tb


def test_threefry_twin_exact():
    """C unit_dropped == fluid.loss_flags on randomized units."""
    from shadow_tpu.network.fluid import loss_flags

    rng = np.random.default_rng(7)
    n = 5000
    uid = rng.integers(0, 1 << 62, n, dtype=np.uint64)
    npk = rng.integers(1, 64, n).astype(np.uint32)
    th = rng.integers(0, 1 << 24, n).astype(np.uint32)
    th[rng.random(n) < 0.25] = 0
    seed = 0xDEADBEEF1234
    ref = loss_flags(
        seed,
        (uid & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        (uid >> np.uint64(32)).astype(np.uint32),
        npk,
        th,
    )
    got = np.array(
        [_colcore.unit_dropped(seed, int(u), int(k), int(t))
         for u, k, t in zip(uid, npk, th)]
    )
    assert (ref == got).all()


def test_stream_workload_identical(tmp_path):
    """tgen (stream transport runs through the Python dispatch fallback,
    barrier/store/extract through C)."""
    _assert_identical(tmp_path, "examples/tgen_100host.yaml")


def test_gossip_workload_identical(tmp_path):
    """gossip (full C path: dispatch, datagram delivery, the C model)."""
    _assert_identical(
        tmp_path, "examples/gossip_10k.yaml", {"general.stop_time": "3s"}
    )


def test_tor_workload_identical(tmp_path):
    """tor model: streams + datagrams mixed, loss notifications."""
    _assert_identical(
        tmp_path, "examples/tor_400relay.yaml", {"general.stop_time": "10s"}
    )


def test_pcap_host_python_fallback(tmp_path):
    """A pcap-enabled host forces the per-host Python dispatch path; the C
    engine must keep the rest of the simulation on the C path and stay
    bit-identical (including the pcap file itself)."""
    _assert_identical(
        tmp_path,
        "examples/echo.yaml",
        {"hosts.server.pcap_enabled": True},
    )


def test_fault_filter_python_barrier(tmp_path):
    """fault_filter set -> the barrier falls back to the Python path
    per-round while emission/extraction stay shared; results must match a
    pure-Python run with the same filter."""

    def go(colcore):
        dd = tmp_path / ("fc" if colcore else "fpy")
        cfg = load_config(
            "examples/tgen_100host.yaml",
            {
                "experimental.scheduler_policy": "tpu_batch",
                "experimental.native_colcore": colcore,
                "general.data_directory": str(dd),
                "general.stop_time": "20s",
            },
        )
        ctl = Controller(cfg, mirror_log=False)
        ctl.engine.fault_filter = lambda u: u.dst == 3 and u.kind == 2
        s = ctl.run()
        for k in VOLATILE:
            s.pop(k, None)
        return s

    assert go(True) == go(False)


def test_blackhole_compaction_identical(tmp_path):
    """Partitioned topology: blackholed units exercise the C barrier's
    in-place compaction (review r4 finding #1 — refcount discipline of
    skipped rows). Summaries, counters, and trees must match the Python
    twin, and units_blackholed must be nonzero so the path really ran."""
    import yaml

    from shadow_tpu.config import parse_config
    from tests.test_colplane import PARTITIONED

    def go(colcore):
        dd = tmp_path / ("bc" if colcore else "bpy")
        cfg = parse_config(yaml.safe_load(PARTITIONED), {
            "experimental.scheduler_policy": "tpu_batch",
            "experimental.native_colcore": colcore,
            "general.data_directory": str(dd),
        })
        ctl = Controller(cfg, mirror_log=False)
        s = ctl.run()
        assert ctl.engine.units_blackholed > 0
        for k in VOLATILE:
            s.pop(k, None)
        return s

    assert go(True) == go(False)


def test_deferred_ingress_reentry(tmp_path):
    """Tight down-links force ingress deferral: the C dispatch parks rows
    in the Python backlog and the drain path re-enters the C gossip state
    (GossipState.on_msg). Equality proves the two entry points share one
    state."""
    _assert_identical(
        tmp_path,
        "examples/gossip_10k.yaml",
        {"general.stop_time": "2s", "general.bootstrap_end_time": 0},
    )
