"""Device-resident columnar transport gates (PR 11).

The acceptance matrix for network/devtransport.py +
ops/transport_kernels.py + the colcore column snapshot/adopt ABI:

- identity: device-transport on/off x colcore on/off x scheduler
  policies on the web and tor families — output trees, flows.jsonl,
  metrics.jsonl, digest streams hash-equal, with a vacuity guard (the
  on-leg must actually have advanced cohorts through the batched
  kernel);
- checkpoint/resume mid-run with the columnar transport live;
- the wrong-kernel-guess discipline (PR 3's speculative-window rule,
  applied to transport): force the stage-time classifier to lie and
  assert replay verification rejects every bad row to the scalar twin
  with byte-identical results;
- the three-surface column contract: Core.transport_columns (C) ==
  export_columns (Python) for twin runs, adopt round-trips on both
  planes, refusal on a row naming no live endpoint;
- kernel unit twins: vectorized cc_on_ack/icbrt bit-equal to the
  scalar CongestionControl classes over a randomized input sweep.
"""

from pathlib import Path

import numpy as np
import pytest
import yaml

from shadow_tpu.config import parse_config
from shadow_tpu.core.controller import Controller
from shadow_tpu.network.devtransport import (
    COLUMNS, KEY_COLUMNS, DeviceTransport, adopt_columns, export_columns)
from shadow_tpu.ops import transport_kernels as TK

from tests.test_checkpoint import _strip, _tree
from tests.test_tor_cplane import TOR_CFG

#: a scaled-down web_cdn (clients -> edges -> origin + DNS chain) with
#: enough concurrent bulk transfer that ack-dominated rounds exist —
#: loss-free, so every ack is a clean cumulative advance (the kernel's
#: target regime); the tor leg covers the lossy/SACK interleavings
WEB_CFG = """
general:
  stop_time: 16s
  seed: 23
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "200 Mbit" host_bandwidth_down "200 Mbit" ]
        node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        node [ id 2 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 1 latency "20 ms" ]
        edge [ source 0 target 2 latency "35 ms" ]
        edge [ source 1 target 2 latency "15 ms" ]
        edge [ source 0 target 0 latency "2 ms" ]
        edge [ source 1 target 1 latency "2 ms" ]
        edge [ source 2 target 2 latency "2 ms" ]
      ]
telemetry:
  sample_every: 5s
hosts:
  origin0:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.web:WebOrigin
        args: ["80"]
  dnsroot:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.dns:DnsAuth
        args: ["53"]
  dnsauth:
    network_node_id: 0
    processes:
      - path: pyapp:shadow_tpu.models.dns:DnsAuth
        args: ["53"]
  resolver0:
    network_node_id: 1
    processes:
      - path: pyapp:shadow_tpu.models.dns:DnsResolver
        args: ["53", dnsroot, dnsauth]
  edge0:
    network_node_id: 1
    processes:
      - path: pyapp:shadow_tpu.models.web:WebEdge
        args: ["80", origin0, "80", "60"]
  edge1:
    network_node_id: 2
    processes:
      - path: pyapp:shadow_tpu.models.web:WebEdge
        args: ["80", origin0, "80", "60"]
  c0_:
    network_node_id: 1
    quantity: 5
    processes:
      - path: pyapp:shadow_tpu.models.web:WebClient
        args: ["4", "3", "200 kB", "60 kB", "80", resolver0, edge0, edge1]
        start_time: 300 ms
        environment: {WEB_RETRIES: "2", WEB_THINK_SEC: "1"}
  c1_:
    network_node_id: 2
    quantity: 5
    processes:
      - path: pyapp:shadow_tpu.models.web:WebClient
        args: ["4", "3", "200 kB", "60 kB", "80", resolver0, edge0, edge1]
        start_time: 700 ms
        environment: {WEB_RETRIES: "2", WEB_THINK_SEC: "1"}
"""


def _run(tmp_path, tag, cfg_text, **overrides):
    dd = tmp_path / tag
    ov = {"general.data_directory": str(dd),
          "general.state_digest_every": 50,
          "telemetry": {}}
    ov.update(overrides)
    cfg = parse_config(yaml.safe_load(cfg_text), ov)
    ctl = Controller(cfg, mirror_log=False)
    summary = ctl.run()
    return ctl, _strip(summary), _tree(dd)


DEVT_ON = {"experimental.scheduler_policy": "tpu_batch",
           "experimental.native_colcore": False,
           "experimental.device_transport": True}
DEVT_OFF = {"experimental.scheduler_policy": "tpu_batch",
            "experimental.native_colcore": False}


def test_identity_matrix_web(tmp_path):
    """THE acceptance gate: device-transport on/off x colcore on/off x
    thread policies on the web family — trees, flows, metrics, digests
    hash-equal; the devt leg really advanced cohorts (vacuity guard)."""
    legs = {
        "tpc": {"experimental.scheduler_policy": "thread_per_core",
                "experimental.device_transport": True},  # per-unit: no-op
        "tph": {"experimental.scheduler_policy": "thread_per_host"},
        "c-on": {"experimental.scheduler_policy": "tpu_batch",
                 "experimental.native_colcore": True,
                 "experimental.device_transport": True},  # C twin: no-op
        "py-off": DEVT_OFF,
        "py-on": DEVT_ON,
    }
    runs = {tag: _run(tmp_path, tag, WEB_CFG, **ov)
            for tag, ov in legs.items()}
    base = runs["tpc"][2]
    assert base, "empty output tree"
    for tag in legs:
        assert runs[tag][2] == base, f"{tag} diverged from thread_per_core"
        assert runs[tag][1] == runs["tpc"][1], f"{tag} summary diverged"
    # vacuity guards: the Python devt leg advanced real cohorts through
    # the batched kernel; the C and per-unit legs correctly did not
    ctl_on = runs["py-on"][0]
    devt = ctl_on.engine.devt
    assert devt is not None and devt.cohorts > 0 and devt.acks_batched > 0
    assert runs["c-on"][0].engine.devt is None
    assert getattr(runs["tpc"][0].engine, "devt", None) is None
    # attribution satellite: the columnar path's wall is split out
    assert "transport_tick" in ctl_on.engine.phase_wall


def test_identity_tor(tmp_path):
    """The lossy/SACK-bearing family: tor_400-shaped config with packet
    loss — recovery episodes, dup acks, and SACK payloads interleave
    with clean advances, so the verifier's fallback paths are exercised
    for real (misguesses may or may not occur; identity must hold)."""
    runs = {tag: _run(tmp_path, tag, TOR_CFG, **ov,
                      **{"general.stop_time": "20s"})
            for tag, ov in (("off", DEVT_OFF), ("on", DEVT_ON))}
    assert runs["on"][2] == runs["off"][2], "tor devt on/off diverged"
    assert runs["on"][1] == runs["off"][1]
    devt = runs["on"][0].engine.devt
    assert devt is not None and devt.cohorts > 0


def test_wrong_kernel_guess_is_verified(tmp_path, monkeypatch):
    """PR 3's speculative-window discipline, applied to transport: force
    the stage-time classifier to stage EVERYTHING (dup acks, recovery
    acks, non-advances) and assert replay verification rejects every bad
    row to the scalar twin — misguesses counted, results byte-identical."""
    monkeypatch.setattr(DeviceTransport, "_stageable",
                        staticmethod(lambda ep, s, cum: True))
    _ctl_off, s_off, t_off = _run(tmp_path, "g-off", WEB_CFG, **DEVT_OFF)
    ctl_on, s_on, t_on = _run(tmp_path, "g-on", WEB_CFG, **DEVT_ON)
    assert t_on == t_off and s_on == s_off
    devt = ctl_on.engine.devt
    assert devt is not None and devt.cohorts > 0
    # the lying classifier stages non-advances (e.g. window-update acks
    # whose cum does not move); every one must have been rejected
    assert devt.misguesses > 0, \
        "the forced mis-stage produced no rejected rows — the test is " \
        "vacuous (classifier not consulted?)"


def test_checkpoint_resume_with_devt_live(tmp_path):
    """Mid-run checkpoint + resume with the columnar transport on: the
    resumed run reproduces the uninterrupted run's host tree and digest
    suffix; the engine reattaches (volatile key, like native_colcore)."""
    from shadow_tpu.checkpoint import load_checkpoint

    _c, s_full, full = _run(tmp_path, "ck-full", WEB_CFG, **DEVT_ON)
    _run(tmp_path, "ck-src", WEB_CFG, **DEVT_ON,
         **{"general.checkpoint_every": "6s",
            "general.checkpoint_dir": str(tmp_path / "cks")})
    cks = sorted((tmp_path / "cks").glob("ckpt_*.ckpt"))
    assert cks, "no checkpoint written"
    dd = tmp_path / "ck-res"
    cfg = parse_config(yaml.safe_load(WEB_CFG), {
        "general.data_directory": str(dd),
        "general.state_digest_every": 50,
        "telemetry": {}, **DEVT_ON})
    ctl, resume_at = load_checkpoint(str(cks[0]), cfg, mirror_log=False)
    assert ctl.engine.devt is not None, "devt did not reattach on resume"
    assert all(h.devt is ctl.engine.devt for h in ctl.hosts)
    r = ctl.run(resume_at=resume_at)
    resumed = _tree(dd)
    full_hosts = {k: v for k, v in full.items() if k.startswith("hosts")}
    res_hosts = {k: v for k, v in resumed.items() if k.startswith("hosts")}
    assert res_hosts == full_hosts, "resumed host tree diverged"
    full_dig = (tmp_path / "ck-full" / "state_digests.jsonl").read_text()
    res_dig = (dd / "state_digests.jsonl").read_text()
    assert res_dig and full_dig.endswith(res_dig)
    assert _strip(r) == s_full


def test_columns_cross_surface(tmp_path):
    """The three-surface column contract: the C snapshot ABI
    (Core.transport_columns) produces the exact arrays the Python
    export produces for twin runs; adopt round-trips on both planes and
    refuses rows naming no live endpoint."""
    stop = {"general.stop_time": "6s"}
    ctl_py, _s1, _t1 = _run(tmp_path, "col-py", WEB_CFG, **DEVT_OFF,
                            **stop)
    ctl_c, _s2, _t2 = _run(
        tmp_path, "col-c", WEB_CFG,
        **{"experimental.scheduler_policy": "tpu_batch",
           "experimental.native_colcore": True}, **stop)
    core = ctl_c.engine._c
    if core is None:
        pytest.skip("colcore not built")
    cols_py = export_columns(ctl_py.hosts)
    cols_c = core.transport_columns()
    names = KEY_COLUMNS + COLUMNS
    assert set(cols_c) == set(names)
    n = len(cols_py["hid"])
    assert n > 0, "no live endpoints at the snapshot instant"
    for name in names:
        assert np.array_equal(cols_py[name], cols_c[name]), name
    # adopt round-trips (identity writeback changes nothing)
    core.adopt_transport_columns(cols_c)
    after = core.transport_columns()
    for name in names:
        assert np.array_equal(after[name], cols_c[name]), name
    assert adopt_columns(ctl_py.hosts, cols_py) == n
    after_py = export_columns(ctl_py.hosts)
    for name in names:
        assert np.array_equal(after_py[name], cols_py[name]), name
    # a genuine writeback lands: halve one endpoint's cwnd via the ABI
    mutated = {k: v.copy() for k, v in cols_c.items()}
    mutated["cwnd"][0] = max(int(mutated["cwnd"][0]) // 2, 2920)
    core.adopt_transport_columns(mutated)
    assert core.transport_columns()["cwnd"][0] == mutated["cwnd"][0]
    # refusal: a row naming no live endpoint fails by name, and refusal
    # is ATOMIC — earlier rows must not have been half-adopted (the bad
    # row is placed LAST and an earlier row carries a sentinel value a
    # non-atomic writeback would have landed)
    bogus = {k: v.copy() for k, v in mutated.items()}
    bogus["cwnd"][0] = 123456789
    bogus["local_port"][-1] = 1  # no such connection key
    with pytest.raises(ValueError, match="no live C endpoint"):
        core.adopt_transport_columns(bogus)
    after_refusal = core.transport_columns()
    for name in names:
        assert np.array_equal(after_refusal[name], mutated[name]), name
    # the Python twin refuses atomically too
    bogus_py = {k: v.copy() for k, v in after_py.items()}
    bogus_py["cwnd"][0] = 123456789
    bogus_py["local_port"][-1] = 1
    with pytest.raises(ValueError, match="no live Python endpoint"):
        adopt_columns(ctl_py.hosts, bogus_py)
    for name in names:
        assert np.array_equal(export_columns(ctl_py.hosts)[name],
                              after_py[name]), name
    # ... and a length-mismatched adopt column refuses up front (the
    # atomicity contract covers malformed snapshots too)
    short = {k: v.copy() for k, v in after_py.items()}
    short["cwnd"] = short["cwnd"][:0]
    with pytest.raises(ValueError, match="missing or not length"):
        adopt_columns(ctl_py.hosts, short)
    for name in names:
        assert np.array_equal(export_columns(ctl_py.hosts)[name],
                              after_py[name]), name


def test_kernel_twins_bit_exact():
    """Randomized sweep: the vectorized cc_on_ack equals the scalar
    CongestionControl classes field for field, and icbrt equals
    transport._icbrt — the numpy half of the third-surface contract
    (twincheck pins the literals; this pins the arithmetic)."""
    from shadow_tpu.network.transport import (
        MIN_CWND, CubicLike, NewReno, _icbrt)

    class _H:
        pass

    class _Ep:
        pass

    class _S:
        pass

    rng = np.random.default_rng(11)
    n = 5000
    cc_id = rng.integers(0, 2, n).astype(np.int64)
    cwnd = rng.integers(MIN_CWND, 1 << 34, n).astype(np.int64)
    ssthresh = np.where(rng.random(n) < 0.5,
                        rng.integers(MIN_CWND, 1 << 34, n),
                        1 << 62).astype(np.int64)
    w_max = rng.integers(0, 1 << 33, n).astype(np.int64)
    eps = np.where(rng.random(n) < 0.3, 0,
                   rng.integers(1, 10 ** 12, n)).astype(np.int64)
    newly = rng.integers(1, 1 << 21, n).astype(np.int64)
    now = rng.integers(10 ** 12, 10 ** 13, n).astype(np.int64)
    kc, kw, ke = TK.cc_on_ack(cc_id, cwnd, ssthresh, w_max, eps, newly,
                              now)
    for i in range(n):
        s = _S()
        s.cwnd, s.ssthresh = int(cwnd[i]), int(ssthresh[i])
        s.w_max, s.epoch_start = int(w_max[i]), int(eps[i])
        s.ep = _Ep()
        s.ep.host = _H()
        s.ep.host._now = int(now[i])
        cc = NewReno() if cc_id[i] == 0 else CubicLike()
        cc.on_ack(s, int(newly[i]))
        assert (s.cwnd, s.w_max, s.epoch_start) == (
            int(kc[i]), int(kw[i]), int(ke[i])), i
    xs = np.concatenate([
        rng.integers(0, 1 << 60, 2000),
        [0, 1, 7, 8, 26, 27, (1 << 20) ** 3 - 1, (1 << 20) ** 3],
    ]).astype(np.int64)
    kv = TK.icbrt(xs)
    for i, x in enumerate(xs):
        assert _icbrt(int(x)) == int(kv[i]), x
    # rto_min_scan: the vectorized expiry scan names the earliest slot
    dl = rng.integers(1, 1 << 60, 64).astype(np.int64)
    t, i = TK.rto_min_scan(dl)
    assert t == int(dl.min()) and int(dl[i]) == t


def test_device_kernel_matches_numpy_if_available():
    """The jax.jit twin (pinned bucket shapes, x64) returns the numpy
    twin's exact results — routing between them is pure wall policy."""
    devk = TK.DeviceAckKernel.attach()
    if devk is None:
        pytest.skip("no usable jax x64 device path")
    rng = np.random.default_rng(3)
    n = 1000  # pads to the 1024 bucket
    from shadow_tpu.network.transport import MIN_CWND

    cols = (
        rng.integers(0, 2, n), rng.integers(MIN_CWND, 1 << 34, n),
        np.full(n, 1 << 62), rng.integers(0, 1 << 33, n),
        rng.integers(0, 10 ** 12, n), rng.integers(0, 1 << 30, n),
        rng.integers(0, 1 << 40, n),
    )
    cols = tuple(c.astype(np.int64) for c in cols)
    cum = (cols[5] + rng.integers(1, 1 << 20, n)).astype(np.int64)
    now = rng.integers(10 ** 12, 10 ** 13, n).astype(np.int64)
    ref = TK.ack_advance(*cols, cum, now)
    dev = devk.run(*cols, cum, now=now)
    for a, b in zip(ref, dev):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # oversized cohorts CHUNK at the largest pinned bucket (rows are
    # independent — boundaries cannot change results) instead of
    # compiling a fresh shape mid-run
    big = tuple(np.tile(c, 70) for c in cols)  # 70k rows > 65536
    big_cum = np.tile(cum, 70)
    big_now = np.tile(now, 70)
    ref2 = TK.ack_advance(*big, big_cum, big_now)
    dev2 = devk.run(*big, big_cum, now=big_now)
    shapes = set(devk._fns)
    assert shapes <= {2, 256, 1024, 4096, 16384, 65536}, shapes
    for a, b in zip(ref2, dev2):
        assert np.array_equal(np.asarray(a), np.asarray(b))
